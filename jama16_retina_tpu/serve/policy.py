"""Frontier-derived serving policy: bucket sizes, coalescing wait, and
shed thresholds chosen from a MEASURED ``serve_frontier`` sweep instead
of hand-set constants (ISSUE 12 tentpole, with "Batch Size Influence on
GPU/TPU Performance", PAPERS.md, as the motivation: the throughput/
latency frontier of an accelerator is an empirical curve, and policy
read off the curve beats policy guessed from folklore).

The flow:

  1. ``bench.py`` (not --skip_frontier) sweeps serve.bucket_sizes x
     offered concurrency and lands the frontier as the
     ``serve_frontier`` list in its JSON output;
  2. ``scripts/derive_serve_policy.py`` turns that JSON into a
     VERSIONED policy artifact (``derive_policy`` + ``save_policy``
     here): a small JSON file carrying the chosen knobs, a content-hash
     version string, and the model fingerprint the sweep described;
  3. ``serve.policy_from=<path>`` loads the artifact
     (``load_policy`` + ``apply_policy``) at router/predict
     construction. Hand-set knobs STILL WIN: the policy only fills
     fields the config carries at their dataclass defaults, so an
     operator override is never silently clobbered.

Staleness is refused, not absorbed: an artifact derived for a different
(arch, image_size, head, device-count) raises typed
:class:`PolicyStale` naming the re-derive command — the same discipline
the rawshard manifest and the compile cache apply to their fingerprints.

Derivation heuristics (each documented inline; all deterministic —
``derive_policy`` is a pure function of the sweep, so the same bench
JSON always yields the same artifact and version hash):

  * ``max_batch``: the smallest swept bucket reaching >= KNEE_FRAC of
    the sweep's best throughput — past the knee, bigger buckets buy
    latency, not images/sec;
  * ``bucket_sizes``: every swept bucket <= max_batch (the ladder the
    sweep actually measured, so partial windows run a measured shape);
  * ``max_wait_ms``: half the chosen bucket's p50 at its best
    concurrency, clamped to [1, 25] ms — waiting longer than ~half a
    service time to fill a window trades latency for nothing;
  * ``shed_in_flight`` / ``shed_queue_depth``: multiples of the
    concurrency where the chosen bucket's throughput peaked — offered
    load beyond the peak only grows the queue.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os

from absl import logging as absl_logging

from jama16_retina_tpu.integrity import artifact as artifact_lib

FORMAT = "jama16.serve_policy"
# v2 (ISSUE 16): adds per-priority-class knobs (``classes``) derived
# from p99-under-SLO at a target offered load — the interactive class
# opts into the int8 student, speculative escalation, batch fusion and
# the fused preprocess — plus the per-bucket p99 ledger the choice was
# made from. v1 artifacts still load (their class table is empty, so
# they apply exactly the knobs they always did).
VERSION = 2
COMPAT_VERSIONS = (1, VERSION)
# Interactive class rule: a bucket this small is single-request
# territory — the derived class rides the cheap path (int8 student +
# speculation + fusion) there; bigger interactive buckets keep the
# engine dtype.
INTERACTIVE_SMALL_BUCKET = 8

# The knee rule: the smallest bucket within this fraction of the
# sweep's best throughput is chosen as max_batch (module-level so the
# tests pin against the shipped value).
KNEE_FRAC = 0.90
# Shed thresholds as multiples of the peak-throughput concurrency:
# in-flight requests beyond SHED_IN_FLIGHT_X * peak add queueing, not
# throughput; the queue cap is looser to absorb bursts.
SHED_IN_FLIGHT_X = 4
SHED_QUEUE_X = 8


class PolicyStale(RuntimeError):
    """The policy artifact was derived for a different model/mesh
    fingerprint (or an incompatible artifact version): serving with it
    would apply a frontier measured on different shapes. Re-derive with
    scripts/derive_serve_policy.py against a fresh sweep."""


@dataclasses.dataclass(frozen=True)
class ServePolicy:
    """One derived, versioned serving policy (the artifact's typed
    form). ``version`` is a content hash — two artifacts with the same
    knobs and fingerprint carry the same version string, so provenance
    survives copying the file around."""

    bucket_sizes: tuple
    max_batch: int
    max_wait_ms: float
    shed_in_flight: int
    shed_queue_depth: int
    fingerprint: dict
    source: dict
    version: str = ""
    # v2: per-priority-class knob table ({"interactive": {...},
    # "batch": {...}}) and the per-bucket p99 ledger (bucket -> best
    # point's p99_ms) the interactive choice was made from. Both empty
    # on a loaded v1 artifact.
    classes: dict = dataclasses.field(default_factory=dict)
    per_bucket_p99: dict = dataclasses.field(default_factory=dict)

    def payload(self) -> dict:
        return {
            "format": FORMAT,
            "version": VERSION,
            "bucket_sizes": [int(b) for b in self.bucket_sizes],
            "max_batch": int(self.max_batch),
            "max_wait_ms": float(self.max_wait_ms),
            "shed_in_flight": int(self.shed_in_flight),
            "shed_queue_depth": int(self.shed_queue_depth),
            "fingerprint": dict(self.fingerprint),
            "source": dict(self.source),
            "classes": {
                k: dict(v) for k, v in self.classes.items()
            },
            "per_bucket_p99": {
                str(k): v for k, v in self.per_bucket_p99.items()
            },
        }


def _content_version(payload: dict) -> str:
    blob = json.dumps(payload, sort_keys=True).encode()
    return f"sp{VERSION}-{hashlib.sha256(blob).hexdigest()[:10]}"


def policy_fingerprint(cfg, n_devices: int = 1) -> dict:
    """What a frontier sweep is a function of: the model's compiled
    shapes and the device count the rates were normalized by. A policy
    carries this; loading it under a different value is refused."""
    return {
        "arch": cfg.model.arch,
        "image_size": int(cfg.model.image_size),
        "head": cfg.model.head,
        "n_devices": int(n_devices),
    }


def frontier_from_bench_json(obj: dict) -> list:
    """Extract the ``serve_frontier`` list from a bench JSON — either
    bench.py's own output (top-level key) or the archived wrapper form
    (nested under ``parsed``). Raises when the JSON carries no sweep:
    deriving policy from nothing must be loud."""
    for holder in (obj, obj.get("parsed") or {}, obj.get("extras") or {}):
        if isinstance(holder, dict) and holder.get("serve_frontier"):
            return list(holder["serve_frontier"])
    raise ValueError(
        "bench JSON carries no 'serve_frontier' sweep — run "
        "bench.py WITHOUT --skip_frontier (and --skip_serve) first"
    )


def _interactive_class(points: list, slo_p99_ms: float,
                       target_images_per_sec: float) -> dict:
    """The v2 interactive-class rule: optimize p99 UNDER the SLO at the
    target offered load, not knee throughput — among all swept points
    with p99 <= SLO and rate >= target, take the LOWEST p99 (ties to
    the smaller bucket). Unsatisfiable constraints relax loudly: first
    the target is dropped, then the SLO, so the class always derives
    (the knee rule already guards the batch class). A small chosen
    bucket opts the class into the whole interactive fast path —
    int8 student, speculative escalation, batch fusion, fused
    preprocess — which ``apply_policy`` only applies to config fields
    still at their defaults."""
    with_p99 = [p for p in points if p.get("p99_ms") is not None]
    if not with_p99:
        return {}
    pool = with_p99
    if slo_p99_ms > 0:
        under = [p for p in pool if p["p99_ms"] <= slo_p99_ms]
        if under:
            pool = under
        else:
            absl_logging.warning(
                "no frontier point meets interactive p99 <= %g ms; "
                "interactive class minimizes p99 unconstrained",
                slo_p99_ms,
            )
    if target_images_per_sec > 0:
        loaded = [
            p for p in pool
            if p["images_per_sec"] >= target_images_per_sec
        ]
        if loaded:
            pool = loaded
        else:
            absl_logging.warning(
                "no frontier point under the SLO sustains %g img/s; "
                "interactive class drops the load target",
                target_images_per_sec,
            )
    chosen = min(pool, key=lambda p: (p["p99_ms"], int(p["bucket"])))
    bucket = int(chosen["bucket"])
    p50 = float(chosen.get("p50_ms") or 2.0)
    cls = {
        "bucket": bucket,
        "max_wait_ms": round(min(25.0, max(1.0, p50 / 2.0)), 2),
        "p99_ms": float(chosen["p99_ms"]),
        "concurrency": int(chosen.get("concurrency") or 1),
        "speculative": True,
        "fusion": True,
        "fused_preprocess": True,
    }
    if bucket <= INTERACTIVE_SMALL_BUCKET:
        cls["dtype"] = "int8"
    return cls


def derive_policy(frontier: list, fingerprint: dict,
                  slo_p99_ms: float = 0.0,
                  source: "dict | None" = None,
                  target_images_per_sec: float = 0.0) -> ServePolicy:
    """Pure derivation of a ServePolicy from frontier sweep rows
    (``{bucket, concurrency, images_per_sec, p50_ms, p99_ms}``; rows
    whose rate the physics guard withheld — images_per_sec None — are
    skipped). ``slo_p99_ms`` > 0 additionally restricts the bucket
    choice to buckets whose best-throughput point keeps p99 under the
    SLO; if none qualifies the SLO is ignored, loudly.

    v2: also derives the per-priority-class table — the batch class
    keeps this knee rule, the interactive class optimizes p99-under-SLO
    at ``target_images_per_sec`` (``_interactive_class``) — and records
    every bucket's best-point p99 so a future re-derivation (or an
    operator) can audit the choice without re-running the sweep."""
    points = [
        p for p in frontier
        if p.get("images_per_sec") is not None and p.get("bucket")
    ]
    if not points:
        raise ValueError(
            "serve_frontier sweep has no usable points (all rates "
            "withheld?) — cannot derive a policy"
        )
    # Best (rate, concurrency, p50, p99) per bucket.
    best: dict = {}
    for p in points:
        b = int(p["bucket"])
        if b not in best or p["images_per_sec"] > best[b]["images_per_sec"]:
            best[b] = p
    # SLO first, knee second: restrict to buckets whose best-throughput
    # point keeps p99 under the SLO, THEN take the smallest bucket
    # within KNEE_FRAC of that eligible set's peak. An unsatisfiable
    # SLO falls back to the whole sweep, loudly.
    eligible = dict(best)
    if slo_p99_ms > 0:
        under_slo = {
            b: p for b, p in best.items()
            if p.get("p99_ms") is not None and p["p99_ms"] <= slo_p99_ms
        }
        if under_slo:
            eligible = under_slo
        else:
            absl_logging.warning(
                "no frontier bucket meets p99 <= %g ms at its best "
                "throughput; deriving policy from the knee rule alone",
                slo_p99_ms,
            )
    peak_rate = max(p["images_per_sec"] for p in eligible.values())
    candidates = sorted(
        b for b, p in eligible.items()
        if p["images_per_sec"] >= KNEE_FRAC * peak_rate
    )
    max_batch = candidates[0]
    chosen = best[max_batch]
    buckets = tuple(sorted(b for b in best if b <= max_batch))
    p50 = float(chosen.get("p50_ms") or 2.0)
    max_wait_ms = round(min(25.0, max(1.0, p50 / 2.0)), 2)
    peak_conc = max(1, int(chosen.get("concurrency") or 1))
    classes = {
        "batch": {
            "bucket": int(max_batch),
            "max_wait_ms": max_wait_ms,
        },
    }
    interactive = _interactive_class(
        points, slo_p99_ms, target_images_per_sec
    )
    if interactive:
        classes["interactive"] = interactive
    policy = ServePolicy(
        bucket_sizes=buckets,
        max_batch=int(max_batch),
        max_wait_ms=max_wait_ms,
        shed_in_flight=SHED_IN_FLIGHT_X * peak_conc,
        shed_queue_depth=SHED_QUEUE_X * peak_conc,
        fingerprint=dict(fingerprint),
        source=dict(source or {}),
        classes=classes,
        per_bucket_p99={
            str(b): (float(p["p99_ms"])
                     if p.get("p99_ms") is not None else None)
            for b, p in sorted(best.items())
        },
    )
    return dataclasses.replace(
        policy, version=_content_version(policy.payload())
    )


def save_policy(path: str, policy: ServePolicy) -> str:
    """Sealed atomic write of the artifact (integrity/artifact.py —
    ISSUE 13: a torn policy file must never parse, and a bit-flipped
    one must fail its content checksum on load)."""
    payload = policy.payload()
    payload["policy_version"] = (
        policy.version or _content_version(payload)
    )
    return artifact_lib.write_sealed_json(
        path, payload, schema="serve.policy", version=VERSION
    )


def load_policy(path: str) -> ServePolicy:
    """Load + validate an artifact; refuses unknown formats/versions
    with :class:`PolicyStale` (an artifact this code cannot interpret
    must not silently half-apply)."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        raise PolicyStale(
            f"cannot read policy artifact {path}: "
            f"{type(e).__name__}: {e} — re-derive with "
            "scripts/derive_serve_policy.py"
        ) from e
    if (obj.get("format") != FORMAT
            or obj.get("version") not in COMPAT_VERSIONS):
        raise PolicyStale(
            f"policy artifact {path} is "
            f"{obj.get('format')!r} v{obj.get('version')!r}, this code "
            f"reads {FORMAT!r} v{sorted(COMPAT_VERSIONS)} — re-derive "
            "with scripts/derive_serve_policy.py"
        )
    expected = {
        "bucket_sizes", "max_batch", "max_wait_ms", "shed_in_flight",
        "shed_queue_depth", "fingerprint",
    }
    missing = expected - set(obj)
    if missing:
        raise PolicyStale(
            f"policy artifact {path} is torn/incomplete (missing "
            f"{sorted(missing)}) — re-derive with "
            "scripts/derive_serve_policy.py"
        )
    # Content checksum last (after the typed staleness refusals keep
    # their own errors): bit rot raises ArtifactCorrupt, counted.
    artifact_lib.verify_payload(obj, path, artifact="policy")
    return ServePolicy(
        bucket_sizes=tuple(int(b) for b in obj["bucket_sizes"]),
        max_batch=int(obj["max_batch"]),
        max_wait_ms=float(obj["max_wait_ms"]),
        shed_in_flight=int(obj["shed_in_flight"]),
        shed_queue_depth=int(obj["shed_queue_depth"]),
        fingerprint=dict(obj["fingerprint"]),
        source=dict(obj.get("source") or {}),
        version=str(obj.get("policy_version") or ""),
        # Absent on a v1 artifact: it keeps loading (version bump
        # contract) and applies exactly the knobs it always did.
        classes={
            k: dict(v) for k, v in (obj.get("classes") or {}).items()
        },
        per_bucket_p99=dict(obj.get("per_bucket_p99") or {}),
    )


def check_fingerprint(policy: ServePolicy, cfg,
                      n_devices: int = 1, path: str = "") -> None:
    """Refuse a policy derived for a different model/mesh: the frontier
    it encodes was measured on other compiled shapes."""
    want = policy_fingerprint(cfg, n_devices)
    if dict(policy.fingerprint) != want:
        raise PolicyStale(
            f"policy artifact {path or '(loaded)'} was derived for "
            f"{policy.fingerprint} but this session runs {want} — "
            "re-derive with scripts/derive_serve_policy.py against a "
            "fresh serve_frontier sweep"
        )


def apply_policy(cfg, policy: ServePolicy) -> "tuple[object, list]":
    """Fill the serving knobs the policy derives into ``cfg.serve``,
    WITHOUT clobbering anything the operator set explicitly: a field is
    policy-filled only while it still carries its ServeConfig dataclass
    default (the "hand-set knobs still win" contract; the applied field
    list is returned for the session's provenance record)."""
    from jama16_retina_tpu.configs import ServeConfig

    defaults = ServeConfig()
    sc = cfg.serve
    updates: dict = {}
    if tuple(sc.bucket_sizes) == tuple(defaults.bucket_sizes):
        updates["bucket_sizes"] = tuple(policy.bucket_sizes)
    if sc.max_batch == defaults.max_batch:
        updates["max_batch"] = policy.max_batch
    if sc.max_wait_ms == defaults.max_wait_ms:
        updates["max_wait_ms"] = policy.max_wait_ms
    if sc.shed_in_flight == defaults.shed_in_flight:
        updates["shed_in_flight"] = policy.shed_in_flight
    if sc.shed_queue_depth == defaults.shed_queue_depth:
        updates["shed_queue_depth"] = policy.shed_queue_depth
    # v2 interactive class: the ONLY way the speculative / fusion /
    # fused-preprocess machinery turns on by policy (they ship off by
    # default; the derived class opts the deployment in) — still under
    # the hand-set-wins rule, knob by knob.
    interactive = policy.classes.get("interactive") or {}
    if interactive:
        if (interactive.get("dtype")
                and sc.dtype == defaults.dtype):
            updates["dtype"] = str(interactive["dtype"])
        if (interactive.get("speculative")
                and sc.cascade_speculative == defaults.cascade_speculative):
            updates["cascade_speculative"] = True
        if (interactive.get("fusion")
                and sc.router_fusion == defaults.router_fusion):
            updates["router_fusion"] = True
        if (interactive.get("fused_preprocess")
                and sc.fused_preprocess == defaults.fused_preprocess):
            updates["fused_preprocess"] = True
    if not updates:
        return cfg, []
    new_cfg = cfg.replace(serve=dataclasses.replace(sc, **updates))
    return new_cfg, sorted(updates)


def maybe_apply_policy(cfg, n_devices: int = 1) -> "tuple[object, dict]":
    """The one entry point sessions call: when ``serve.policy_from``
    names an artifact, load -> fingerprint-check -> apply, and return
    (possibly-updated cfg, provenance dict for reports). A config
    without the knob returns unchanged with empty provenance."""
    path = cfg.serve.policy_from
    if not path:
        return cfg, {}
    policy = load_policy(path)
    check_fingerprint(policy, cfg, n_devices=n_devices, path=path)
    cfg, applied = apply_policy(cfg, policy)
    absl_logging.info(
        "serve policy %s applied from %s (fields: %s)",
        policy.version, path, ", ".join(applied) or "none — all knobs "
        "hand-set",
    )
    return cfg, {
        "path": path,
        "version": policy.version,
        "applied": applied,
        "source": dict(policy.source),
    }
