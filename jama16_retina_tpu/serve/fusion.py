"""Cross-engine batch fusion: one device dispatch for rows bound to
DIFFERENT models (ISSUE 16 tentpole a).

The Router's continuous batching already coalesces rows across request
boundaries — but only within one model, so two tenants each trickling
single-image interactive requests each pay their own b4-class dispatch
(the 5.5x small-batch efficiency cliff, BENCH_r05 device_only_b4 vs
b128). When ``serve.router_fusion`` is on, the dispatch tick is allowed
to cut bins that MIX models, and this module scores them:

  * FUSED: when every engine in the bin lowers the same serving
    program (same ``compilecache.model_fingerprint`` + serving dtype +
    mesh-less), their stacked member states concatenate along the
    member axis into one tree and ONE stacked forward scores the whole
    bin for every member of every model; the demux slices each model's
    member rows back out and ensemble-averages them exactly like
    ``ServingEngine.probs`` (``metrics.ensemble_average``);
  * GROUPED: engines whose programs differ (or stubs/cascades without
    engine internals) fall back to one ``probs`` call per model over
    that model's rows, scattered back by index — still one bin, one
    replica charge, one completion path.

Either way every output row is attributed to its (model, replica,
generation): generation handles are pinned ONCE per model before any
dispatch (the engine's reload-attribution discipline), and the router
records per-part segments with the model name. Row order never
changes — demux writes through the same index sets the mux read.

Observability parity: the grouped path rides ``probs``/
``probs_with_generation`` and so feeds every engine's row hooks for
free; the fused path bypasses them (it steps the concatenated state
directly), so ``_observe_fused`` replays the same hooks — per-
generation row counters, shadow sampling, drift windows, canary
cadence — on each model's slice after the demux. Drift coverage must
not depend on whether engines happened to fuse.
"""

from __future__ import annotations

import threading

import numpy as np

from jama16_retina_tpu.eval import metrics
from jama16_retina_tpu.serve import compilecache


def fusion_token(engine) -> "tuple | None":
    """The program identity under which two engines may share one
    stacked forward: ``model_fingerprint`` (arch/head/size/member
    form/TTA/backend…) plus the serving dtype (the int8 path bakes its
    dequant into the program). None = this engine cannot fuse (no
    engine internals — a stub or cascade — or a sharded mesh engine,
    whose placement this module does not reproduce)."""
    if not (hasattr(engine, "_step") and hasattr(engine, "_gen")
            and hasattr(engine, "cfg")):
        return None
    if getattr(engine, "_batch_sharding", None) is not None:
        return None
    fp = compilecache.model_fingerprint(engine.cfg, mesh=None)
    fp["serve_dtype"] = str(getattr(engine, "dtype", "fp32"))
    return tuple(sorted(fp.items()))


class FusionCache:
    """Concatenated stacked-state cache: re-concatenating k_total
    member trees per dispatch would cost a device copy of every
    parameter every bin. Keyed by the exact (model, engine identity,
    generation) tuple — a reload on ANY fused engine misses and
    rebuilds, so a fused forward never scores a retired generation.
    Holds one entry (the live combination): fused serving churns
    generations, not combinations.

    One Router shares one cache across ALL replica worker threads, and
    score_mixed runs OUTSIDE the router lock — so _key/_state are read
    and swapped under the cache's own lock, and callers get the state
    that was built (or found) FOR THEIR KEY, never a re-read of
    self._state that a concurrent bin with a different key (other
    model subset, or a generation swap from a concurrent reload) may
    have replaced between check and use. Without this, _key could pair
    with the other key's _state and a fused dispatch would silently
    score with the wrong parameters/generation while attributing the
    pinned one."""

    def __init__(self):
        self._lock = threading.Lock()
        self._key = None
        self._state = None

    def fused_state(self, pinned: "list[tuple[str, object, object]]"):
        """``pinned``: [(model, engine, generation-handle), ...] in bin
        member order. Returns the concatenated stacked state plus the
        per-model member spans [(model, k_lo, k_hi), ...]."""
        import jax
        import jax.numpy as jnp

        key = tuple(
            (m, id(e), int(g.gen_id)) for m, e, g in pinned
        )
        spans = []
        k = 0
        for m, _e, g in pinned:
            spans.append((m, k, k + int(g.n_members)))
            k += int(g.n_members)
        # Check, build, and publish atomically; return the LOCAL state
        # so a concurrent miss with a different key can at worst evict
        # the cache entry, never swap the state under this bin. The
        # concat runs under the lock: two racing misses would otherwise
        # both pay the full stacked-params device copy just to have one
        # overwrite the other.
        with self._lock:
            if key == self._key:
                return self._state, spans
            states = [g.state for _m, _e, g in pinned]
            state = jax.tree.map(
                lambda *xs: jnp.concatenate(xs, axis=0), *states
            )
            self._state = state
            self._key = key
        return state, spans


def _model_spans(parts) -> "list[tuple[str, int, int]]":
    """Bin-row spans per part, in bin order: the mux layout
    ``_make_bin_locked`` produced, reused verbatim for the demux."""
    spans = []
    lo = 0
    for req, req_lo, req_hi in parts:
        hi = lo + (req_hi - req_lo)
        spans.append((req.model, lo, hi))
        lo = hi
    return spans


def score_mixed(
    engines_by_model: dict,
    rows: np.ndarray,
    parts,
    bucket: int,
    cache: "FusionCache | None" = None,
) -> "tuple[np.ndarray, dict]":
    """Score one (possibly multi-model) bin: returns
    ``(out [n, ...], {model: generation})`` with row i of ``out``
    scored by the engine of row i's model. Tries the single fused
    dispatch first; engines that cannot fuse take the grouped path.
    """
    spans = _model_spans(parts)
    models = []
    for m, _lo, _hi in spans:
        if m not in models:
            models.append(m)

    tokens = {m: fusion_token(engines_by_model[m]) for m in models}
    if (len(models) > 1
            and all(t is not None for t in tokens.values())
            and len(set(tokens.values())) == 1):
        return _score_fused(engines_by_model, rows, spans, models,
                            bucket, cache)
    return _score_grouped(engines_by_model, rows, spans, models)


def _score_fused(engines_by_model, rows, spans, models, bucket, cache):
    import jax

    # Pin every model's generation handle ONCE, before any device work
    # (the engine's own reload-attribution rule): a concurrent reload
    # swaps the NEXT bin's states, never splits this one. Sorted, not
    # bin order: the member axis must not depend on which tenant's
    # request led the bin, or an a-led / b-led alternation would miss
    # the one-entry FusionCache every dispatch and pay the full
    # stacked-params concat (a device copy of every parameter) per bin.
    pinned = [(m, engines_by_model[m], engines_by_model[m]._gen)
              for m in sorted(models)]
    if cache is None:
        cache = FusionCache()
    state, member_spans = cache.fused_state(pinned)

    n = int(rows.shape[0])
    pad_rows = max(0, int(bucket) - n)
    padded = (np.concatenate(
        [rows, np.zeros((pad_rows, *rows.shape[1:]), rows.dtype)])
        if pad_rows else rows)
    step = pinned[0][1]._step
    placed = jax.device_put(padded, jax.local_devices()[0])
    member = np.asarray(jax.device_get(
        step(state, {"image": placed})
    ))[:, :n]

    out = None
    model_idx = {}
    for m, k_lo, k_hi in member_spans:
        avg = metrics.ensemble_average(list(member[k_lo:k_hi]))
        if out is None:
            out = np.empty((n, *avg.shape[1:]), avg.dtype)
        idx = np.concatenate([
            np.arange(lo, hi) for sm, lo, hi in spans if sm == m
        ])
        out[idx] = avg[idx]
        model_idx[m] = idx
    # The fused dispatch bypassed probs_with_generation, which is where
    # the serial path feeds its per-row observability — replay those
    # hooks here per model, or drift-monitoring coverage would silently
    # depend on whether engines happened to fuse.
    for m, eng, gen in pinned:
        idx = model_idx[m]
        _observe_fused(eng, gen, rows[idx], out[idx])
    gens = {m: int(g.gen_id) for m, _e, g in pinned}
    return out, gens


def _observe_fused(engine, gen, images, scores) -> None:
    """The serve-path row hooks ``probs_with_generation`` would have
    fed, applied to one model's slice of a fused bin: the pinned
    generation's row counter (reload attribution), the staged-rollout
    shadow sampler, and the quality monitor's drift windows + canary
    cadence (canary scored through ``member_probs`` on the SAME pinned
    generation, so canary traffic never pollutes the drift histograms
    and never splits across a concurrent reload)."""
    c_rows = getattr(gen, "c_rows", None)
    if c_rows is not None:
        c_rows.inc(int(images.shape[0]))
    sh = getattr(engine, "_shadow", None)
    if sh is not None and sh.claim():
        engine._shadow_sample(sh, images, scores)
    q = getattr(engine, "quality", None)
    if q is not None:
        q.observe(images, scores)
        if q.canary_claim():
            q.run_canary(
                lambda imgs: metrics.ensemble_average(
                    list(engine.member_probs(imgs, _gen=gen))
                )
            )


def _score_grouped(engines_by_model, rows, spans, models):
    out = None
    gens = {}
    for m in models:
        idx = np.concatenate([
            np.arange(lo, hi) for sm, lo, hi in spans if sm == m
        ])
        eng = engines_by_model[m]
        if hasattr(eng, "probs_with_generation"):
            res, gen = eng.probs_with_generation(rows[idx])
        else:
            res = eng.probs(rows[idx])
            gen = int(getattr(eng, "generation", 0))
        res = np.asarray(res)
        if out is None:
            out = np.empty(
                (int(rows.shape[0]), *res.shape[1:]), res.dtype
            )
        out[idx] = res
        gens[m] = int(gen)
    return out, gens
