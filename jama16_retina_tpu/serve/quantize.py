"""Serving dtype axis: fp32 / bf16 / int8 stacked-state transforms
(ISSUE 10 cheap-path serving).

The serve hot path's arithmetic is already bf16 on TPU
(model.compute_dtype), but the stacked parameter tree restores and
resides in fp32 — every forward streams full-width weights out of HBM.
``serve.dtype`` trades that width for throughput, per engine:

  * ``fp32`` — restored params verbatim. The bit-identity default: every
    parity pin (engine vs sequential path, predict.py byte-identical
    JSONL) rides this mode unchanged.
  * ``bf16`` — float params (and the EMA shadow, when carried) cast to
    bfloat16 at stacking: half the weight HBM traffic. BatchNorm
    statistics stay float32 — stored moments are a numerically
    sensitive sum-of-squares, and casting them buys ~nothing.
  * ``int8`` — rank>=2 kernels quantized to symmetric per-output-channel
    int8 (via AQT when importable — it ships in this container's
    site-packages — else a hand-rolled fallback with identical
    semantics, logged). The device residency is int8 values + float32
    scales wrapped in :class:`Q8Leaf`; ``dequant_transform`` runs INSIDE
    the one serving program (train_lib.make_serving_step
    ``param_transform``), so XLA fuses the dequant into the forward and
    no full-width copy of the tree ever persists.

Quality gate: a non-fp32 engine is REFUSED at construction
(:class:`DtypeRejected`) when its golden-canary deviation exceeds
``serve.dtype_canary_max_dev`` — the same golden-canary +
operating-point parity path every reload candidate passes, applied to
the numerics change instead of a weights change (serve/engine.py).
"""

from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp
from absl import logging as absl_logging

SERVE_DTYPES = ("fp32", "bf16", "int8")


class DtypeRejected(RuntimeError):
    """A non-fp32 serving dtype failed its golden-canary construction
    gate: the quantized engine's scores deviate from the pinned
    reference by more than ``serve.dtype_canary_max_dev``, so it never
    takes a request — rebuild with ``serve.dtype=fp32`` (or loosen the
    bound deliberately, with the deviation in hand)."""


class Q8Leaf(flax.struct.PyTreeNode):
    """One int8-quantized parameter leaf: ``q`` (int8 values) and ``s``
    (float32 per-output-channel scales, broadcastable to ``q``).
    A pytree node — device_put/jit trace it like any array pair —
    deliberately NOT a dict, which flax param trees would descend into.
    """

    q: jnp.ndarray
    s: jnp.ndarray


def check_dtype(dtype: str) -> str:
    if dtype not in SERVE_DTYPES:
        raise ValueError(
            f"unknown serve.dtype {dtype!r}; choose one of "
            f"{'/'.join(SERVE_DTYPES)}"
        )
    return dtype


def _is_q8(x) -> bool:
    return isinstance(x, Q8Leaf)


def _quantize_leaf(p: jnp.ndarray) -> Q8Leaf:
    """Symmetric int8 for one STACKED kernel [k, ..., out_channels]:
    calibration reduces over the middle axes only, keeping the member
    axis (0) and the output-channel axis (-1) — one scale per
    (member, channel) pair. Pooling across members would let the
    largest-magnitude member's amax set every member's scale and
    collapse smaller members to a handful of int8 levels (ensemble
    members train from independent seeds; their kernel magnitudes
    legitimately differ)."""
    axes = tuple(range(1, p.ndim - 1))
    try:
        from aqt.jax.v2 import aqt_quantizer

        qt, _ = aqt_quantizer.quantizer_make(8).quant(
            jnp.asarray(p), calibration_axes=axes
        )
        scale = qt.scale[0]
        for extra in qt.scale[1:]:  # pragma: no cover - single-scale quantizers
            scale = scale * extra
        return Q8Leaf(
            q=jnp.asarray(qt.qvalue, jnp.int8),
            s=jnp.asarray(scale, jnp.float32),
        )
    except ImportError:
        # Container without AQT: same math by hand (symmetric, clip at
        # the int8 range, scale = amax/127 with a zero-guard).
        absl_logging.warning(
            "AQT unavailable; int8 serving dtype using the built-in "
            "symmetric quantizer (identical semantics)"
        )
        p = jnp.asarray(p, jnp.float32)
        amax = jnp.max(jnp.abs(p), axis=axes, keepdims=True)
        scale = jnp.where(amax > 0, amax / 127.0, 1.0)
        q = jnp.clip(jnp.round(p / scale), -127, 127).astype(jnp.int8)
        return Q8Leaf(q=q, s=jnp.asarray(scale, jnp.float32))


def _cast_tree_bf16(tree):
    def cast(p):
        if _is_q8(p):
            return p
        if jnp.issubdtype(jnp.result_type(p), jnp.floating):
            return jnp.asarray(p, jnp.bfloat16)
        return p

    return jax.tree.map(cast, tree, is_leaf=_is_q8)


def _quantize_tree_int8(tree):
    def q(p):
        if _is_q8(p):  # idempotent: a reload of an already-quantized
            return p   # candidate state must not double-quantize
        # ndim >= 3 on the STACKED tree = rank>=2 kernels (conv/dense
        # weights under their leading [k] member axis). Stacked biases
        # and BatchNorm affine params are [k, O] (ndim 2) and stay
        # float — the weights-only contract: quantizing them buys ~no
        # HBM traffic and adds avoidable logit error.
        if (hasattr(p, "ndim") and p.ndim >= 3
                and jnp.issubdtype(jnp.result_type(p), jnp.floating)):
            return _quantize_leaf(p)
        return p

    return jax.tree.map(q, tree, is_leaf=_is_q8)


def state_for_dtype(state, dtype: str):
    """The eager, pre-placement transform of a stacked serving state
    (engine._build_generation): fp32 is identity; bf16 casts the params
    and EMA shadow (BatchNorm statistics stay float32); int8 wraps
    rank>=2 float kernels in :class:`Q8Leaf`. Idempotent — reloading a
    candidate built from an already-transformed state is a no-op."""
    check_dtype(dtype)
    if dtype == "fp32":
        return state
    if dtype == "bf16":
        return state.replace(
            params=_cast_tree_bf16(state.params),
            ema_params=(
                _cast_tree_bf16(state.ema_params)
                if state.ema_params is not None else None
            ),
        )
    return state.replace(
        params=_quantize_tree_int8(state.params),
        ema_params=(
            _quantize_tree_int8(state.ema_params)
            if state.ema_params is not None else None
        ),
    )


def _dequant_tree(tree):
    return jax.tree.map(
        lambda p: (jnp.asarray(p.q, jnp.float32) * p.s) if _is_q8(p) else p,
        tree, is_leaf=_is_q8,
    )


def dequant_transform(dtype: str):
    """The traced half (make_serving_step ``param_transform``): None for
    fp32/bf16 (their params feed the forward directly); for int8 a
    state->state map that dequantizes every Q8Leaf inside the serving
    program, so the dequant fuses and HBM holds int8+scales."""
    check_dtype(dtype)
    if dtype != "int8":
        return None

    def transform(state):
        return state.replace(
            params=_dequant_tree(state.params),
            ema_params=(
                _dequant_tree(state.ema_params)
                if state.ema_params is not None else None
            ),
        )

    return transform
