"""Serving subsystem: persistent micro-batched inference over
device-resident stacked ensembles (the first subsystem on the serving
half of the ROADMAP north star).

Three layers, composable and individually testable:

  * ``engine``  — ServingEngine: restore every ensemble member ONCE,
    stack them into one device-resident [k] parameter tree
    (train_lib.stack_states), and serve a single stacked forward per
    batch (train_lib.make_serving_step) instead of k sequential
    restore+forward passes. Batches pad into a small set of bucketed
    shapes so jit compiles once per bucket, never per request.
  * ``batcher`` — MicroBatcher: a thread-safe request queue that
    coalesces concurrent requests up to serve.max_batch or
    serve.max_wait_ms and returns per-request futures in submission
    order (arXiv:1812.11731's lesson operationalized: accelerator
    inference throughput is won by batching, i.e. by coalescing).
  * ``host``    — the host stage: fundus normalization parallelized
    across a worker pool with worker-count-invariant output order
    (the ParallelDecoder pattern applied to raw photographs).

predict.py rides this stack for --device={tpu,cpu}; bench.py's
``serve_*`` section measures it under the round-3 fenced discipline.
"""

from jama16_retina_tpu.serve.batcher import (
    DeadlineExceeded,
    MicroBatcher,
    Overloaded,
)
from jama16_retina_tpu.serve.engine import (
    ReloadRejected,
    RollbackUnavailable,
    ServingEngine,
    resolve_buckets,
)

__all__ = [
    "DeadlineExceeded",
    "MicroBatcher",
    "Overloaded",
    "ReloadRejected",
    "RollbackUnavailable",
    "ServingEngine",
    "resolve_buckets",
]
