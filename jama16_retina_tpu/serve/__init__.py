"""Serving subsystem: persistent micro-batched inference over
device-resident stacked ensembles (the first subsystem on the serving
half of the ROADMAP north star).

Layers, composable and individually testable:

  * ``assemble`` — EngineSpec -> built engine (ISSUE 14): the ONE
    composable assembly seam where mesh shape, serving dtype, cascade,
    compile cache, and member count compose declaratively instead of
    each constructor site wiring the layers below positionally.
    predict.py, the router's replica factory, and the lifecycle CLI
    all construct through it; a 1-device spec is pinned bit-identical
    to the pre-seam construction path.
  * ``engine``  — ServingEngine: restore every ensemble member ONCE,
    stack them into one device-resident [k] parameter tree
    (train_lib.stack_states), and serve a single stacked forward per
    batch (train_lib.make_serving_step) instead of k sequential
    restore+forward passes. Batches pad into a small set of bucketed
    shapes so jit compiles once per bucket, never per request.
  * ``batcher`` — MicroBatcher: a thread-safe request queue that
    coalesces concurrent requests up to serve.max_batch or
    serve.max_wait_ms and returns per-request futures in submission
    order (arXiv:1812.11731's lesson operationalized: accelerator
    inference throughput is won by batching, i.e. by coalescing).
  * ``host``    — the host stage: fundus normalization parallelized
    across a worker pool with worker-count-invariant output order
    (the ParallelDecoder pattern applied to raw photographs).
  * ``cascade`` — CascadeEngine (ISSUE 10): a distilled student scores
    every row, only scores inside ``serve.cascade_band`` of the
    operating thresholds escalate to the full stacked ensemble —
    gated by golden-canary + operating-point parity before go-live.
  * ``quantize`` — the ``serve.dtype`` axis (fp32/bf16/int8-via-AQT
    stacked-state transforms), canary-gated at engine construction.
  * ``compilecache`` — persistent per-(bucket, mesh, dtype, k) AOT
    executable cache: engine restart deserializes in seconds instead
    of re-paying the ~79 s warmup+compile (docs/PERF.md §Cheap-path).
  * ``router``  — Router (ISSUE 12): the front door above N engine
    replicas — priority-classed admission, continuous batching across
    bucket boundaries, retry-on-sibling replica failover, graceful
    drain, and in-process autoscaling actuation.
  * ``policy``  — frontier-derived serving policy artifacts
    (bucket/wait/shed knobs read off a measured serve_frontier sweep;
    versioned, fingerprint-checked, hand-set knobs win).
  * ``scaler``  — the pure hysteresis-guarded replica autoscaling
    policy behind ``serve.scaler.desired_replicas``.

predict.py rides this stack for --device={tpu,cpu}; bench.py's
``serve_*`` section measures it under the round-3 fenced discipline.
"""

from jama16_retina_tpu.serve.assemble import EngineSpec, assemble
from jama16_retina_tpu.serve.batcher import (
    DeadlineExceeded,
    MicroBatcher,
    Overloaded,
)
from jama16_retina_tpu.serve.cascade import CascadeEngine, CascadeRejected
from jama16_retina_tpu.serve.compilecache import (
    CompileCache,
    CompileCacheStale,
)
from jama16_retina_tpu.serve.engine import (
    DtypeRejected,
    ReloadRejected,
    RollbackUnavailable,
    ServingEngine,
    resolve_buckets,
)
from jama16_retina_tpu.serve.policy import PolicyStale, ServePolicy
from jama16_retina_tpu.serve.router import (
    EscalationPool,
    NoReplicasLeft,
    Router,
)

__all__ = [
    "CascadeEngine",
    "CascadeRejected",
    "CompileCache",
    "CompileCacheStale",
    "DeadlineExceeded",
    "DtypeRejected",
    "EngineSpec",
    "EscalationPool",
    "MicroBatcher",
    "NoReplicasLeft",
    "Overloaded",
    "PolicyStale",
    "ReloadRejected",
    "RollbackUnavailable",
    "Router",
    "ServePolicy",
    "ServingEngine",
    "assemble",
    "resolve_buckets",
]
