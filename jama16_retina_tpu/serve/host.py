"""Serving host stage: parallel fundus normalization of raw photographs.

predict.py's original host loop read and normalized images one at a time
on a single thread — at ~0.1 s per 299px fundus normalization that
stage, not the accelerator, bounds a screening batch. This module is the
ParallelDecoder pattern (data/grain_pipeline.py, PR 1) applied to raw
photograph files: cv2.imread and the OpenCV resize/blur pipeline inside
``resize_and_center_fundus`` release the GIL, so a thread pool scales
without process-spawn cost.

Determinism contract (same as ParallelDecoder): output depends only on
the input path list, never on worker count or scheduling — results are
assembled in input order (``ThreadPoolExecutor.map`` is
order-preserving), so ``workers`` is a pure throughput knob. Pinned by
tests/test_serve.py.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from jama16_retina_tpu.data.grain_pipeline import resolve_decode_workers
from jama16_retina_tpu.obs import faultinject
from jama16_retina_tpu.obs import registry as obs_registry
from jama16_retina_tpu.preprocess import fundus
from jama16_retina_tpu.utils import retry as retry_lib


def reject_reason_slug(why: str) -> str:
    """Skip-reason text -> the bounded counter vocabulary (ISSUE 5
    satellite): a per-reason counter set must not grow one metric per
    distinct error STRING, so free-text reasons map onto a small fixed
    slug space. Unmatched reasons land in ``other`` (still counted)."""
    if why.startswith("unreadable"):
        return "decode_error"
    if "too small" in why:
        return "too_small"
    if "no fundus found" in why:
        return "not_fundus"
    return "other"


def _count_rejects(skipped, registry: "obs_registry.Registry | None") -> None:
    """serve.input_rejected{reason} counters with help strings, so the
    skip ledger surfaces in telemetry records, .prom files, and
    obs_report's quality tables — not just predict.py's stderr JSON.
    The --strict exit-2 contract is untouched (counting is additive)."""
    if not skipped:
        return
    reg = registry if registry is not None else obs_registry.default_registry()
    total = reg.counter(
        "serve.input_rejected",
        help="input images rejected before the forward pass, all reasons",
    )
    helps = {
        "decode_error": "rejected: file unreadable / not a decodable image",
        "too_small": "rejected: detected fundus radius below the minimum",
        "not_fundus": "rejected: no fundus disc found in the frame",
        "other": "rejected: uncategorized preprocessing failure",
    }
    for _, why in skipped:
        slug = reject_reason_slug(why)
        total.inc()
        reg.counter(
            f"serve.input_rejected.{slug}", help=helps.get(slug, "")
        ).inc()


@dataclasses.dataclass
class PreprocessResult:
    """Kept rows in input order + the skip ledger predict.py reports."""

    images: np.ndarray  # uint8 [n_kept, S, S, 3], input order
    kept: list  # paths of the scored rows, aligned with images
    skipped: list  # (path, reason) pairs, input order
    qualities: list  # gradability score per kept row (fundus stats)
    # Paths that hit a transient read error, were retried
    # (utils/retry.py under --max_retries) and then SCORED — a separate
    # ledger from `skipped` so --strict semantics stay exact: a retried
    # success is not an incomplete batch (ISSUE 6 satellite).
    retried: list = dataclasses.field(default_factory=list)


def _load_one(path: str, image_size: int, ben_graham: bool,
              max_retries: int = 0):
    """One path -> (error_reason | None, canvas | None, quality | None,
    retried: bool). Total per row: unreadable files and blank frames
    become reasons, any other exception propagates (a corrupt install
    must stay loud).

    The file read routes through the ``host.decode`` fault seam
    (obs/faultinject.py) and, with ``max_retries`` > 0, through the
    shared bounded-backoff retry (utils/retry.py) — a transient NFS
    flap on one image of a screening batch becomes a retried success,
    not a reject."""
    import cv2

    tries = {"n": 0}

    def _read() -> bytes:
        tries["n"] += 1
        with open(path, "rb") as f:
            data = f.read()
        # Fault seam: error-kind entries raise (the transient-I/O
        # drill --max_retries absorbs), corrupt-kind entries damage
        # the bytes (per-request reject drill).
        return faultinject.corrupt("host.decode", data)

    try:
        if max_retries > 0:
            data = retry_lib.retry_call(
                _read, attempts=max_retries + 1, base_delay=0.02,
                site="host.decode",
            )
        else:
            data = _read()
    except OSError as e:
        return f"unreadable: {e}", None, None, tries["n"] > 1
    retried = tries["n"] > 1
    bgr = cv2.imdecode(np.frombuffer(data, np.uint8), cv2.IMREAD_COLOR)
    if bgr is None:
        return "unreadable", None, None, retried
    try:
        canvas, q = fundus.resize_and_center_fundus(
            bgr[..., ::-1], diameter=image_size,
            ben_graham=ben_graham, with_quality=True,
        )
    except fundus.FundusNotFound as e:
        return f"no fundus found: {e}", None, None, retried
    return None, canvas, float(q["quality"]), retried


def preprocess_paths(
    paths: "list[str]", image_size: int, ben_graham: bool = False,
    workers: int = 0,
    registry: "obs_registry.Registry | None" = None,
    max_retries: int = 0,
) -> PreprocessResult:
    """Normalize ``paths`` across a thread pool; worker-count-invariant.

    ``workers``: 0 auto-derives like data.decode_workers (one thread per
    host core up to 8, leaving a core for device dispatch).
    ``registry``: sink for the per-reason ``serve.input_rejected{reason}``
    data-quality counters (None = process default).
    ``max_retries``: per-image transient-read retries (utils/retry.py;
    predict.py --max_retries). Retried-then-scored paths land in the
    ``retried`` ledger AND the ``serve.input_retried`` counter —
    separate from ``skipped``, so --strict stays exact.
    """
    workers = resolve_decode_workers(workers)

    def one(p):
        return _load_one(p, image_size, ben_graham, max_retries=max_retries)

    if workers <= 1 or len(paths) < 2:
        rows = [one(p) for p in paths]
    else:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
            max_workers=min(workers, len(paths)),
            thread_name_prefix="jama16-serve-host",
        ) as pool:
            # map() yields results in input order regardless of which
            # worker finished first — the whole determinism contract.
            rows = list(pool.map(one, paths))

    kept, skipped, qualities, canvases, retried = [], [], [], [], []
    for p, (why, canvas, quality, was_retried) in zip(paths, rows):
        if why is not None:
            skipped.append((p, why))
            continue
        if was_retried:
            retried.append(p)
        kept.append(p)
        canvases.append(canvas)
        qualities.append(quality)
    images = (
        np.stack(canvases) if canvases
        else np.zeros((0, image_size, image_size, 3), np.uint8)
    )
    _count_rejects(skipped, registry)
    if retried:
        reg = (registry if registry is not None
               else obs_registry.default_registry())
        reg.counter(
            "serve.input_retried",
            help="images that hit a transient read error, were retried "
                 "and then SCORED (not part of the reject ledger)",
        ).inc(len(retried))
    return PreprocessResult(
        images=images, kept=kept, skipped=skipped, qualities=qualities,
        retried=retried,
    )


def prepare_images(
    images_u8: np.ndarray,
    *,
    fused: bool = False,
    interpret: "bool | None" = None,
    registry: "obs_registry.Registry | None" = None,
) -> "tuple[np.ndarray, dict | None]":
    """Device-side serve preprocess for a uint8 batch: returns the
    normalized float32 rows plus (fused path only) the INPUT_STATS dict
    the quality monitor would otherwise recompute with its own
    per-pixel pass.

    ``fused=False`` (the default until serving-policy v2 opts in) runs
    the pure-jnp reference — the bit-reference the Pallas kernel is
    pinned against. ``fused=True`` runs the fused kernel
    (ops/pallas_serve.py); ``interpret`` defaults to interpret mode off
    TPU so tests and CPU smoke paths exercise the same kernel body.
    """
    import jax
    import jax.numpy as jnp

    from jama16_retina_tpu.ops import pallas_serve

    x = jnp.asarray(np.ascontiguousarray(images_u8))
    if not fused:
        norm, stats = pallas_serve.serve_preprocess_reference(x)
        return np.asarray(norm), pallas_serve.input_stats_dict(
            np.asarray(stats))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    norm, stats = pallas_serve.fused_serve_preprocess(
        x, interpret=bool(interpret))
    reg = registry if registry is not None else obs_registry.default_registry()
    reg.counter(
        "serve.preprocess.fused_rows",
        help="rows normalized by the fused Pallas serve preprocess "
             "(normalize + channel stats + layout in one pass; "
             "serve.fused_preprocess)",
    ).inc(int(images_u8.shape[0]))
    return np.asarray(norm), pallas_serve.input_stats_dict(np.asarray(stats))


def stats_only(
    images_u8: np.ndarray,
    *,
    fused: bool = False,
    interpret: "bool | None" = None,
    registry: "obs_registry.Registry | None" = None,
) -> dict:
    """INPUT_STATS dict for a uint8 batch via the (fused or reference)
    preprocess — the drop-in ``QualityMonitor.stats_fn`` replacement
    predict.py installs when ``serve.fused_preprocess`` is on, so the
    monitor's input histograms stop paying a separate host-numpy
    per-pixel pass."""
    _, stats = prepare_images(
        images_u8, fused=fused, interpret=interpret, registry=registry)
    return stats
