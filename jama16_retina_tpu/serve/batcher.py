"""Dynamic micro-batcher: coalesce concurrent requests into engine
batches.

The throughput lever the batch-size study (PAPERS.md, arXiv:1812.11731)
names for accelerator inference: a chip at batch 1 wastes almost all of
its arithmetic, so a server must COALESCE concurrent requests into one
forward. This stage is that server core, kept deliberately small:

  * ``submit(rows)`` is thread-safe, returns a ``Future`` immediately;
  * one worker thread drains the queue, closing each window at
    ``max_batch`` rows or ``max_wait_ms`` after the window's FIRST
    request (whichever comes first — a lone request never waits longer
    than max_wait_ms, a burst never waits at all);
  * the coalesced rows go to ``infer_fn`` (normally
    ServingEngine.probs, which buckets/pads/chunks internally) and the
    result rows are sliced back to their requests in submission order.

Determinism contract: a row's result depends only on the row's content
and the bucket shape it runs at — never on which other rows it happened
to coalesce with (eval-mode forwards are row-independent; pinned by
tests/test_serve.py). With a single-bucket engine every row always runs
at the same compiled shape, making results bit-invariant to arrival
interleaving; with multiple buckets, bf16 models can drift at float-ulp
level across bucket shapes (docs/PERF.md §Serve).
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from jama16_retina_tpu.obs import registry as obs_registry
from jama16_retina_tpu.obs import trace as obs_trace


class Overloaded(RuntimeError):
    """Typed submit-time rejection (ISSUE 6 admission control): the
    batcher is over its configured queue-depth or in-flight threshold.
    Raised BEFORE the request enqueues, so an overloaded server answers
    in microseconds instead of letting p99 collapse — callers retry
    elsewhere/later, exactly the load-shedding contract."""


class DeadlineExceeded(TimeoutError):
    """Typed per-request deadline miss: the request's deadline had
    already passed when its coalescing window closed, so no device work
    was spent on it. Set as the future's exception (never raised on the
    submitter thread — the submit itself succeeded)."""


def _submit_trace_id() -> str:
    """The request's fleet-unique trace id, captured on the submitter
    thread: adopt the ambient TraceContext when the caller (a router
    dispatch) installed one — the batcher's segments then join that
    request's trace — else mint a fresh ``"<pid>-<n>"`` id."""
    ctx = obs_trace.current_context()
    return ctx.trace_id if ctx is not None else obs_trace.new_context().trace_id


@dataclass
class _Request:
    rows: np.ndarray
    future: Future = field(default_factory=Future)
    # monotonic submit time: the end-to-end request latency histogram's
    # start mark (resolved - submitted, including queue wait + coalesce
    # window + inference + result slicing).
    t_submit: float = field(default_factory=time.monotonic)
    # Request-scoped trace id (ISSUE 4): assigned at submit, rides the
    # request through window fill -> flush -> engine forward -> future
    # resolution, so its latency decomposes into named trace segments.
    # Fleet-unique (ISSUE 15): a bare process-local int would alias
    # across pid lanes the moment two servers' exemplars merge in one
    # fleet view.
    trace_id: str = field(default_factory=_submit_trace_id)
    # monotonic time the worker popped this request off the queue (end
    # of its queue-wait segment, start of its window-fill segment).
    t_pop: float = 0.0
    # Absolute monotonic deadline (ISSUE 6), or None. Checked at
    # window close: an expired request is failed with DeadlineExceeded
    # before it burns any device work.
    t_deadline: "float | None" = None


_STOP = object()


class MicroBatcher:
    """Thread-safe coalescing request queue over a row-wise infer_fn.

    ``infer_fn(rows[n, ...]) -> results[n, ...]`` must map row i of its
    input to row i of its output (ServingEngine.probs does). Requests
    larger than ``max_batch`` are accepted; the engine chunks them.

    ``autostart=False`` leaves the worker unstarted until ``start()`` —
    tests use it to stage a deterministic queue before any flush runs.

    ``row_shape``/``row_dtype`` (optional): per-row shape/dtype every
    submission must match, rejected AT SUBMIT otherwise. Without it one
    malformed request would only fail inside its coalesced window,
    taking innocent co-riders' futures down with it
    (ServingEngine.make_batcher pins the model's [S, S, 3] uint8 rows).

    Telemetry (obs/; ``registry=None`` uses the process default):
    ``serve.batcher.queue_depth`` gauge (requests waiting),
    ``serve.batcher.window_fill`` histogram (rows/max_batch per flushed
    window — persistently low fill says max_wait_ms closes windows
    before coalescing pays), ``serve.request_latency_s`` histogram
    (submit -> future resolved, end to end), and the close-path
    counters ``serve.batcher.rejected_at_close`` /
    ``serve.batcher.close_flushed_windows``. Reliability telemetry
    (ISSUE 6): ``serve.batcher.in_flight`` gauge (admitted-unresolved
    requests — the shedding threshold's own gauge, so alert rules and
    the shed decision read the same number),
    ``serve.batcher.window_errors``, and the shed counters
    ``serve.shed.{queue_depth,in_flight,deadline}``.

    Request-scoped tracing (obs/trace.py; ``tracer=None`` uses the
    process default): each submit is assigned a ``trace_id`` and, when
    tracing is enabled, resolves with four complete events —
    ``serve.request.{queue_wait,window_fill,device,resolve}`` — whose
    durations tile the exact monotonic interval the latency histogram
    observed, so any single request's latency decomposes from the
    timeline (pinned by tests/test_trace.py, incl. on an 8-device
    mesh engine).

    ``quality`` (obs/quality.py; ISSUE 5): a QualityMonitor fed each
    flushed window's (rows, results) — for batchers over a BARE
    ``infer_fn``. A batcher built by ``ServingEngine.make_batcher``
    leaves this None: the engine already observes inside ``probs()``,
    and a second hook here would double-count every row.
    """

    def __init__(
        self,
        infer_fn: Callable[[np.ndarray], np.ndarray],
        max_batch: int = 64,
        max_wait_ms: float = 5.0,
        autostart: bool = True,
        row_shape: "tuple[int, ...] | None" = None,
        row_dtype=None,
        registry: "obs_registry.Registry | None" = None,
        tracer: "obs_trace.Tracer | None" = None,
        quality=None,
        shed_queue_depth: int = 0,
        shed_in_flight: int = 0,
        default_deadline_ms: float = 0.0,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._infer = infer_fn
        self._row_shape = tuple(row_shape) if row_shape is not None else None
        self._row_dtype = np.dtype(row_dtype) if row_dtype is not None else None
        self.max_batch = int(max_batch)
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        # Admission control (ISSUE 6): plain ints under self._lock, NOT
        # gauge reads — the shed decision must work with a disabled
        # registry and must not take a metric lock on every submit.
        # 0 = that threshold off (the default; the bench overhead pin
        # measures this disabled path).
        self.shed_queue_depth = int(shed_queue_depth)
        self.shed_in_flight = int(shed_in_flight)
        self.default_deadline_ms = float(default_deadline_ms)
        self._n_queued = 0     # submitted, not yet popped into a window
        self._n_in_flight = 0  # admitted, future not yet resolved/failed
        self._queue: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._closed = False
        self.batches_run = 0
        self.rows_run = 0
        reg = registry if registry is not None else obs_registry.default_registry()
        self._tracer = (
            tracer if tracer is not None else obs_trace.default_tracer()
        )
        self._quality = quality
        self._g_depth = reg.gauge(
            "serve.batcher.queue_depth",
            help="requests waiting to coalesce into a window",
        )
        self._h_fill = reg.histogram(
            "serve.batcher.window_fill",
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0),
            help="rows/max_batch per flushed window (low fill says "
                 "max_wait_ms closes windows before coalescing pays)",
        )
        self._h_latency = reg.histogram(
            "serve.request_latency_s",
            help="end-to-end request latency: submit -> future resolved "
                 "with host probabilities",
        )
        self._c_batches = reg.counter(
            "serve.batcher.batches",
            help="coalesced windows flushed to the engine",
        )
        self._c_rows = reg.counter(
            "serve.batcher.rows",
            help="request rows flushed through coalesced windows",
        )
        self._c_rejected_closed = reg.counter(
            "serve.batcher.rejected_at_close",
            help="submits refused because the batcher was already "
                 "closed",
        )
        self._c_close_flushed = reg.counter(
            "serve.batcher.close_flushed_windows",
            help="in-flight windows flushed (served, not dropped) "
                 "during close()",
        )
        self._g_in_flight = reg.gauge(
            "serve.batcher.in_flight",
            help="requests admitted but not yet resolved (the in-flight "
                 "shedding threshold's gauge — alert rules read this)",
        )
        self._c_window_errors = reg.counter(
            "serve.batcher.window_errors",
            help="coalesced windows whose infer_fn raised; only that "
                 "window's futures failed, the worker survived",
        )
        self._c_shed_queue = reg.counter(
            "serve.shed.queue_depth",
            help="submits rejected Overloaded at the queue-depth "
                 "threshold (serve.shed_queue_depth)",
        )
        self._c_shed_in_flight = reg.counter(
            "serve.shed.in_flight",
            help="submits rejected Overloaded at the in-flight "
                 "threshold (serve.shed_in_flight)",
        )
        self._c_shed_deadline = reg.counter(
            "serve.shed.deadline",
            help="requests whose deadline had passed at window close; "
                 "failed DeadlineExceeded before any device work",
        )
        self._thread = threading.Thread(
            target=self._loop, name="jama16-serve-batcher", daemon=True
        )
        self._started = False
        if autostart:
            self.start()

    def start(self) -> None:
        if not self._started:
            self._started = True
            self._thread.start()

    def submit(self, rows: np.ndarray,
               deadline_ms: "float | None" = None) -> Future:
        """Enqueue ``rows`` ([n, ...], n >= 1); the Future resolves to
        the per-row results for exactly those rows, in row order.

        ``deadline_ms``: relative per-request deadline (None falls back
        to ``default_deadline_ms``; <= 0 = no deadline). An expired
        request is failed with ``DeadlineExceeded`` at window close —
        before any device work — never silently dropped.

        Raises ``Overloaded`` (without enqueueing) when a configured
        shedding threshold is exceeded: fast typed rejection is the
        overload contract (ISSUE 6) — the caller learns in microseconds
        that the server is saturated instead of joining an unbounded
        queue and timing out."""
        rows = np.asarray(rows)
        if rows.ndim < 1 or rows.shape[0] == 0:
            raise ValueError(
                f"submit() wants [n, ...] with n >= 1, got shape {rows.shape}"
            )
        if self._row_shape is not None and rows.shape[1:] != self._row_shape:
            raise ValueError(
                f"submit() rows must be [n, {self._row_shape}], got "
                f"{rows.shape} — rejected at submit so a malformed "
                "request cannot fail its coalesced window's co-riders"
            )
        if self._row_dtype is not None and rows.dtype != self._row_dtype:
            raise ValueError(
                f"submit() rows must be {self._row_dtype}, got {rows.dtype}"
            )
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        with self._lock:
            if self._closed:
                self._c_rejected_closed.inc()
                raise RuntimeError("MicroBatcher is closed")
            if (self.shed_queue_depth > 0
                    and self._n_queued >= self.shed_queue_depth):
                self._c_shed_queue.inc()
                raise Overloaded(
                    f"queue depth {self._n_queued} >= shed threshold "
                    f"{self.shed_queue_depth}; request shed at submit"
                )
            if (self.shed_in_flight > 0
                    and self._n_in_flight >= self.shed_in_flight):
                self._c_shed_in_flight.inc()
                raise Overloaded(
                    f"{self._n_in_flight} requests in flight >= shed "
                    f"threshold {self.shed_in_flight}; request shed at "
                    "submit"
                )
            req = _Request(rows)
            if deadline_ms and deadline_ms > 0:
                req.t_deadline = req.t_submit + deadline_ms / 1e3
            self._n_queued += 1
            self._n_in_flight += 1
            self._queue.put(req)
            self._g_depth.add(1)
            self._g_in_flight.set(self._n_in_flight)
        return req.future

    def _loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is _STOP:
                return
            item.t_pop = time.monotonic()
            window = [item]
            rows = item.rows.shape[0]
            deadline = time.monotonic() + self.max_wait_s
            stop_after = False
            while rows < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stop_after = True
                    break
                nxt.t_pop = time.monotonic()
                window.append(nxt)
                rows += nxt.rows.shape[0]
            if stop_after:
                # This window's flush is part of close(): its requests
                # arrived before the sentinel and are served, not
                # dropped — observable as close_flushed_windows.
                self._c_close_flushed.inc()
            try:
                self._flush(window)
            except BaseException as e:  # noqa: BLE001 - worker survival
                # _flush's own handler already fails the window's
                # futures on infer errors; this outer belt catches a
                # failure in that handler itself (ISSUE 6 satellite:
                # a worker-thread exception must never strand every
                # queued future forever — the worker stays alive for
                # the next window no matter what).
                self._c_window_errors.inc()
                for w in window:
                    try:
                        if not w.future.done():
                            w.future.set_exception(e)
                    except InvalidStateError:
                        pass
            if stop_after:
                return

    def _flush(self, window: "list[_Request]") -> None:
        self._g_depth.add(-len(window))
        admitted = window
        with self._lock:
            self._n_queued -= len(window)
        # Segment timestamps (ISSUE 4): every request's latency is the
        # SAME monotonic interval its trace segments tile — queue-wait
        # [t_submit, t_pop) + window-fill [t_pop, t_flush) + device
        # [t_flush, t_infer_done) + resolve [t_infer_done, now) sum to
        # the serve.request_latency_s observation EXACTLY (one clock).
        t_flush = time.monotonic()
        # Deadline-aware admission at window close (ISSUE 6): a request
        # whose deadline already passed is failed with DeadlineExceeded
        # HERE — before it consumes a slot in the coalesced forward —
        # so under overload the device only ever works on requests
        # whose callers are still waiting.
        expired = [
            w for w in window
            if w.t_deadline is not None and t_flush > w.t_deadline
        ]
        if expired:
            window = [w for w in window if w.t_deadline is None
                      or t_flush <= w.t_deadline]
            for w in expired:
                self._c_shed_deadline.inc()
                try:
                    if not w.future.done():
                        w.future.set_exception(DeadlineExceeded(
                            f"deadline passed "
                            f"{t_flush - w.t_deadline:.3f}s before its "
                            "window closed; no device work was spent"
                        ))
                except InvalidStateError:
                    pass
        if not window:
            with self._lock:
                self._n_in_flight -= len(admitted)
                self._g_in_flight.set(self._n_in_flight)
            return
        try:
            for w in window:
                if w.t_pop == 0.0:  # never-started close() drain
                    w.t_pop = t_flush
            flat = (
                window[0].rows if len(window) == 1
                else np.concatenate([w.rows for w in window])
            )
            out = np.asarray(self._infer(flat))
            if out.shape[0] != flat.shape[0]:
                raise RuntimeError(
                    f"infer_fn returned {out.shape[0]} rows for "
                    f"{flat.shape[0]} inputs — row contract broken"
                )
            t_infer_done = time.monotonic()
            if self._quality is not None:
                # Worker-thread context; the monitor's observe is
                # lock-guarded and O(rows) vectorized. Input statistics
                # only make sense for image-shaped rows; anything else
                # feeds score drift alone.
                imgs = (flat if flat.ndim == 4 and flat.shape[-1] == 3
                        else None)
                self._quality.observe(imgs, out)
            self.batches_run += 1
            self.rows_run += int(flat.shape[0])
            self._c_batches.inc()
            self._c_rows.inc(int(flat.shape[0]))
            self._h_fill.observe(flat.shape[0] / self.max_batch)
            now = time.monotonic()
            tr = self._tracer
            lo = 0
            for w in window:
                hi = lo + w.rows.shape[0]
                # A caller may cancel() after a result() timeout — even
                # CONCURRENTLY with this loop, so a cancelled() check
                # would race; per-future try/except keeps one cancelled
                # request from poisoning its co-riders' futures.
                try:
                    w.future.set_result(out[lo:hi])
                    # Exemplar (ISSUE 15): each flush window's slowest
                    # request rides out through telemetry by trace_id.
                    self._h_latency.observe(now - w.t_submit,
                                            exemplar=w.trace_id)
                    if tr.enabled:
                        args = {
                            "trace_id": w.trace_id,
                            "rows": int(w.rows.shape[0]),
                        }
                        tr.complete("serve.request.queue_wait",
                                    w.t_submit, w.t_pop, args)
                        tr.complete("serve.request.window_fill",
                                    w.t_pop, t_flush, args)
                        tr.complete("serve.request.device",
                                    t_flush, t_infer_done, args)
                        tr.complete("serve.request.resolve",
                                    t_infer_done, now, args)
                except InvalidStateError:
                    pass
                lo = hi
        except BaseException as e:  # noqa: BLE001 - futures carry it
            # Every request of the window learns the failure; the worker
            # survives to serve the next window (including a concurrent
            # cancel() racing these set_exception calls). Counted so an
            # engine that starts failing windows is visible in telemetry
            # (serve.batcher.window_errors) long before anyone reads
            # stderr.
            self._c_window_errors.inc()
            for w in window:
                try:
                    if not w.future.done():
                        w.future.set_exception(e)
                except InvalidStateError:
                    pass
        finally:
            with self._lock:
                self._n_in_flight -= len(admitted)
                self._g_in_flight.set(self._n_in_flight)

    def close(self) -> None:
        """Stop accepting requests, flush everything already queued,
        and join the worker."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._queue.put(_STOP)
        if self._started:
            self._thread.join()
        else:
            # Never-started batcher: drain so queued futures don't hang.
            pending = []
            while True:
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    break
                if item is not _STOP:
                    pending.append(item)
            if pending:
                self._c_close_flushed.inc()
                self._flush(pending)

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
