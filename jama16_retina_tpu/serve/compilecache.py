"""Persistent AOT compilation cache for the serving engine (ISSUE 10
zero cold-start).

Every ``ServingEngine`` restart used to re-pay the full warmup+compile
bill — ~79 s on the bench TPU (BENCH_r01) — because jit's in-process
cache dies with the process. This module makes the compiled serving
program a durable artifact instead: each per-bucket executable is
AOT-compiled once, serialized with ``jax.experimental.
serialize_executable``, and written under a model-fingerprinted cache
directory; the next engine (restart, ``engine.reload()`` candidate
warm-up, another process on the same host) deserializes in milliseconds
instead of recompiling.

Layout, all writes atomic (tmp + os.replace — the rawshard-manifest
discipline, so a concurrent reader never sees a torn entry):

    <serve.compile_cache_dir>/
      MANIFEST.json                      # version + fingerprint + detail
      exec_b{B}_m{mesh}_{dtype}_k{K}.jex # one serialized executable per
                                         # (bucket, mesh shape, dtype,
                                         #  member count) key

Failure semantics, in order of loudness:

  * STALE FINGERPRINT — the directory's manifest names a different
    (model, dtype, jax, backend) tuple than this engine: REFUSED at
    construction with :class:`CompileCacheStale` naming the rebuild
    command. Silently serving executables compiled for another model is
    the one corruption this cache must never absorb.
  * CORRUPT / MISSING ENTRY — degrades to a COUNTED recompile
    (``serve.compile_cache.misses``); a cache problem must never fail a
    request. The load seam carries the ``serve.compile_cache.load``
    fault site so ``bench.py --chaos`` / tests drive exactly this path.
  * SERIALIZATION UNSUPPORTED (exotic backends) — save failures are
    logged and swallowed; the engine keeps its freshly compiled
    executable and simply stays cold across restarts.

Telemetry: ``serve.compile_cache.{hits,misses}`` counters and the
``serve.compile_cache.load_sec`` gauge (summed deserialize seconds of
the last warm-up) — obs_report's Serving-cost section renders the hit
ratio next to the engine's warm-up time.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle

from absl import logging as absl_logging

from jama16_retina_tpu.integrity import artifact as artifact_lib
from jama16_retina_tpu.obs import faultinject

CACHE_VERSION = 1


class CompileCacheStale(RuntimeError):
    """The cache directory was built for a different model fingerprint.
    Refused loudly: deserializing another model's executables would
    serve wrong math or crash mid-request. The message names the
    rebuild command."""


def model_fingerprint(cfg, mesh=None, n_devices: "int | None" = None) -> dict:
    """The identity a cached executable is only valid for: everything
    that changes the lowered serving program — model architecture knobs,
    member form, TTA, the mesh TOPOLOGY (device count, AXIS NAMES, and
    the launch's process count — not just the shape: a resharded pod
    slice with the same device total but a different member/data
    factoring or host split lowers a differently-partitioned program,
    and ISSUE 14's fix is that it must refuse with the typed
    CompileCacheStale rebuild message instead of deserializing a
    mismatched executable), and the jax/backend pair that produced the
    serialization format. The serving DTYPE is deliberately NOT here:
    it is part of every entry key instead, so one cache directory
    serves a model's fp32/bf16/int8 engines side by side."""
    import jax

    from jama16_retina_tpu.parallel import mesh as mesh_lib

    if n_devices is None:
        n_devices = int(mesh.devices.size) if mesh is not None else 1
    mfp = mesh_lib.mesh_fingerprint(mesh)
    m = cfg.model
    return {
        "arch": m.arch,
        "head": m.head,
        "image_size": int(m.image_size),
        "compute_dtype": m.compute_dtype,
        "aux_head": bool(m.aux_head),
        "stem_s2d": bool(m.stem_s2d),
        "member_parallel": bool(cfg.serve.member_parallel),
        "tta": bool(cfg.eval.tta),
        "n_devices": int(n_devices),
        "mesh_axes": "x".join(mfp["axis_names"]) or "none",
        "process_count": int(mfp["process_count"]),
        "jax": jax.__version__,
        "backend": jax.default_backend(),
    }


def fingerprint_hash(fp: dict) -> str:
    blob = json.dumps(fp, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def _atomic_write_bytes(path: str, blob: bytes) -> None:
    # The shared sealed-writer seam (integrity/artifact.py): atomic
    # tmp+fsync+rename plus the integrity.write fault sites, so the
    # --chaos disk-fault drills cover cache entries too.
    artifact_lib.atomic_write_bytes(path, blob)


class CompileCache:
    """One engine's handle on the on-disk executable cache.

    Construction validates (or writes) the manifest; ``load``/``save``
    move individual executables. Counters are registered on the
    engine's registry so cache behavior lands in telemetry snapshots.
    """

    def __init__(self, path: str, fingerprint: dict, registry=None):
        from jama16_retina_tpu.obs import registry as obs_registry

        self.dir = os.path.abspath(path)
        self.fingerprint = dict(fingerprint)
        self.fp_hash = fingerprint_hash(self.fingerprint)
        os.makedirs(self.dir, exist_ok=True)
        reg = (registry if registry is not None
               else obs_registry.default_registry())
        self._reg = reg
        self.c_hits = reg.counter(
            "serve.compile_cache.hits",
            help="per-bucket serving executables deserialized from the "
                 "persistent compile cache instead of compiled",
        )
        self.c_misses = reg.counter(
            "serve.compile_cache.misses",
            help="per-bucket serving compiles the cache could not "
                 "serve (cold entry, corrupt/injected load failure) — "
                 "each one is a real XLA compile",
        )
        self.g_load_sec = reg.gauge(
            "serve.compile_cache.load_sec",
            help="summed deserialize seconds of the last engine "
                 "warm-up's cache loads (the warm-restart bill) "
                 "[fleet:max]",
        )
        self._check_or_write_manifest()

    # -- manifest ----------------------------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.dir, "MANIFEST.json")

    def _check_or_write_manifest(self) -> None:
        path = self._manifest_path()
        if os.path.exists(path):
            try:
                with open(path) as f:
                    manifest = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                raise CompileCacheStale(
                    f"compile cache manifest {path!r} is unreadable "
                    f"({type(e).__name__}: {e}); rebuild: delete the "
                    f"directory (rm -r {self.dir}) and re-warm one "
                    "engine (any predict.py/bench.py run with "
                    "serve.compile_cache_dir set)"
                )
            if manifest.get("version") != CACHE_VERSION:
                raise CompileCacheStale(
                    f"compile cache at {self.dir} is format version "
                    f"{manifest.get('version')!r}; this runtime writes "
                    f"{CACHE_VERSION} — rebuild: rm -r {self.dir} and "
                    "re-warm one engine"
                )
            if manifest.get("fingerprint") != self.fp_hash:
                theirs = manifest.get("detail", {})
                diff = sorted(
                    k for k in set(theirs) | set(self.fingerprint)
                    if theirs.get(k) != self.fingerprint.get(k)
                )
                raise CompileCacheStale(
                    f"compile cache at {self.dir} was built for "
                    f"fingerprint {manifest.get('fingerprint')} but this "
                    f"engine is {self.fp_hash} (differing fields: "
                    f"{', '.join(diff) or 'unknown'}); executables "
                    "compiled for another model must not serve — "
                    f"rebuild: rm -r {self.dir} (or point "
                    "serve.compile_cache_dir at a per-model directory) "
                    "and re-warm one engine construction"
                )
            # Sealed-content check last (the staleness refusals above
            # keep their own typed errors): bit rot in the manifest
            # raises ArtifactCorrupt, counted (ISSUE 13).
            artifact_lib.verify_payload(
                manifest, path, artifact="compile_cache",
                rebuild_key="compile_cache.manifest",
            )
            return
        artifact_lib.write_sealed_json(path, {
            "version": CACHE_VERSION,
            "fingerprint": self.fp_hash,
            "detail": self.fingerprint,
        }, schema="compile_cache.manifest", version=CACHE_VERSION)

    # -- entries -----------------------------------------------------------

    def entry_key(self, bucket: int, mesh_shape, dtype: str,
                  n_members: int) -> str:
        mesh_s = "x".join(str(int(d)) for d in mesh_shape) or "1"
        return f"b{int(bucket)}_m{mesh_s}_{dtype}_k{int(n_members)}"

    def entry_path(self, key: str) -> str:
        return os.path.join(self.dir, f"exec_{key}.jex")

    def load(self, key: str):
        """Deserialize one executable, or None on ANY failure — a
        missing, corrupt, or fault-injected entry is a counted
        recompile (``serve.compile_cache.misses``; the caller compiles
        and saves), never an error that could reach a request. A
        successful deserialize counts a hit."""
        path = self.entry_path(key)
        try:
            # Fault seam (obs/faultinject.py site
            # "serve.compile_cache.load"): one global read + branch
            # unarmed; armed chaos plans fail this load to prove the
            # degrade-to-recompile contract end to end.
            faultinject.check("serve.compile_cache.load")
            if not os.path.exists(path):
                self.c_misses.inc()
                return None
            # Seal-sidecar verification BEFORE unpickling (ISSUE 13):
            # a bit-flipped entry is a counted corruption + counted
            # recompile, never bytes handed to pickle. Entries saved
            # before sealing existed ("unsealed") still load.
            artifact_lib.verify_sidecar(path, artifact="compile_cache",
                                        registry=self._reg)
            from jax.experimental import serialize_executable

            with open(path, "rb") as f:
                entry = pickle.load(f)
            # Entries are (payload, in_tree, out_tree[, meta]): the
            # optional meta dict (ISSUE 19) carries the compile seconds
            # the original miss paid, so a hit can count what it saved
            # (device.compile.saved_sec). Pre-meta 3-tuples still load.
            meta = entry[3] if len(entry) > 3 else {}
            payload, in_tree, out_tree = entry[0], entry[1], entry[2]
            fn = serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree
            )
        except Exception as e:  # noqa: BLE001 - degrade, never fail
            absl_logging.warning(
                "compile cache entry %s unusable (%s: %s); recompiling",
                path, type(e).__name__, e,
            )
            self.c_misses.inc()
            return None
        self.c_hits.inc()
        saved = float(meta.get("compile_sec", 0.0) or 0.0)
        if saved > 0:
            from jama16_retina_tpu.obs import device as device_lib

            device_lib.note_compile_saved(saved, registry=self._reg)
        return fn

    def save(self, key: str, compiled,
             compile_sec: "float | None" = None) -> bool:
        """Serialize one freshly compiled executable; failures are
        logged and swallowed (the engine keeps its in-memory
        executable — it just stays cold across restarts).
        ``compile_sec`` — the measured seconds the compile cost — is
        stored in the entry's meta so a future hit can count the
        seconds it spared."""
        try:
            from jax.experimental import serialize_executable

            payload, in_tree, out_tree = serialize_executable.serialize(
                compiled
            )
            path = self.entry_path(key)
            meta = {}
            if compile_sec is not None and compile_sec > 0:
                meta["compile_sec"] = round(float(compile_sec), 3)
            blob = pickle.dumps((payload, in_tree, out_tree, meta))
            _atomic_write_bytes(path, blob)
            artifact_lib.write_seal_sidecar(
                path, schema="compile_cache.entry",
                version=CACHE_VERSION, extra={"key": key}, blob=blob,
            )
            return True
        except Exception as e:  # noqa: BLE001 - cache is best-effort
            absl_logging.warning(
                "compile cache save failed for %s (%s: %s); engine "
                "stays cold across restarts", key, type(e).__name__, e,
            )
            return False
