"""Front-door router: multi-replica dispatch, priority classes, and
continuous batching over the serving engines (ISSUE 12 tentpole).

Everything below the ROADMAP's "millions of users" line so far
terminates in ONE ``ServingEngine``+``MicroBatcher`` pair. This module
is the layer above — the dataflow front door ("TensorFlow: a system
for large-scale machine learning", PAPERS.md, is the precedent for
decoupling the request-routing graph from per-device execution):

  * a :class:`Router` owns N replica handles (in-process today — each
    wraps its own ``ServingEngine``/``CascadeEngine``; the
    :class:`ReplicaHandle` duck contract is the seam cross-host
    replicas plug into later) and dispatches request BATCHES to them
    by a pluggable policy: ``least_in_flight`` (default) or
    ``bucket_affinity`` (prefer a replica that already compiled/served
    this bucket shape — maximizes per-replica compile-cache reuse);
  * CONTINUOUS BATCHING: submitted requests land in a row queue that
    the dispatch tick re-bins across bucket boundaries — a bin closes
    the moment a full bucket of rows exists (whoever they arrived
    from), and only a partial remainder waits out ``serve.max_wait_ms``
    — instead of every request waiting on its own fixed window. A
    request larger than one bin SPLITS across bins (and possibly
    replicas); its rows never reorder (results reassemble by offset,
    pinned by tests/test_router.py);
  * PRIORITY CLASSES: every request is ``interactive`` or ``batch``.
    Interactive rows bin first each tick, and admission control is
    class-aware — batch submits shed (typed ``Overloaded``, PR 6's
    vocabulary) at ``router_batch_shed_frac`` of the row threshold
    interactive traffic sheds at, so screening batch jobs yield
    capacity to clinicians before clinicians feel anything;
  * REPLICA LIFECYCLE: a failed dispatch marks the replica dead and
    retries its bins on siblings with typed accounting — a mid-storm
    replica death drops ZERO requests and every response stays
    attributable to the (replica, generation) that actually served it.
    ``drain()`` is graceful: a draining replica takes no new bins,
    finishes what it holds, then releases its engine (and with it the
    generation handles);
  * AUTOSCALING: the router samples its own queue/in-flight/latency
    into tumbling windows and runs ``scaler.decide`` (serve/scaler.py
    — pure, hysteresis-guarded) each window, publishing the
    desired-replica gauge ALWAYS and acting on it in-process
    (activate via the replica factory / drain the newest replica)
    when it owns a factory.

Cascade-aware routing (the 1/k-FLOPs twist) composes rather than
nests: build N student-only ``CascadeEngine`` replicas that all share
one :class:`EscalationPool` — a small pool of full-ensemble engines
that only sees rows inside the escalation band — and hand those
cascades to the Router as its replicas. Most replicas then pay student
FLOPs; the expensive pool is shared and load-balanced.

Observability rides the PR-3/4 stack unchanged: ``serve.router.*`` /
``serve.scaler.*`` metrics with help strings (glossary in
docs/OBSERVABILITY.md), a trace span per dispatch tick, and the
``serve.router.dispatch`` fault site (obs/faultinject.py) that the
bench ``--chaos`` replica-death drill injects through.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError

import numpy as np
from absl import logging as absl_logging

from jama16_retina_tpu.obs import faultinject
from jama16_retina_tpu.obs import registry as obs_registry
from jama16_retina_tpu.obs import trace as obs_trace
from jama16_retina_tpu.obs.spans import span
from jama16_retina_tpu.serve import scaler as scaler_lib
from jama16_retina_tpu.serve.batcher import DeadlineExceeded, Overloaded
from jama16_retina_tpu.serve.engine import resolve_buckets

PRIORITIES = ("interactive", "batch")
DISPATCH_POLICIES = ("least_in_flight", "bucket_affinity")

# Replica lifecycle states (ReplicaHandle.state vocabulary; the drain
# semantics are documented in docs/RELIABILITY.md §Router).
ACTIVE = "active"
DRAINING = "draining"
DRAINED = "drained"
FAILED = "failed"

_STOP = object()


class NoReplicasLeft(RuntimeError):
    """Every replica is failed/drained: the router has no dispatch
    target. Requests fail typed (never hang) — the operator condition
    is a dead fleet, not a slow one."""


class ReplicaHandle:
    """The duck contract a Router replica must satisfy — documented as
    a class so the cross-host implementation (ROADMAP item 1) has a
    named seam to fill, but NOT enforced via abc (in-process engines
    and test stubs satisfy it structurally):

      * ``probs(images) -> scores`` with the engine's row contract
        (row i in = row i out), and optionally
        ``probs_with_generation(images) -> (scores, gen_id)`` for
        response attribution;
      * optionally a ``generation`` property (defaults to 0).

    ``ServingEngine`` and ``CascadeEngine`` both qualify as-is.
    """


class EscalationPool:
    """A shared pool of full-ensemble engines behind many student
    cascades (ISSUE 12's cascade-aware routing): satisfies the
    CascadeEngine ``ensemble`` contract (``probs`` row-wise), routing
    each escalation batch to the pool member with the fewest rows in
    flight. Escalated rows are counted (``serve.router.escalations``)
    so the 1/k economics stay measurable.

    Speculative dispatches (ISSUE 16 tentpole c) are accounted apart:
    a speculating cascade scores its WHOLE batch here before the band
    is known, so those rows land in ``serve.router.speculations`` —
    NOT in the escalations ledger, whose help text promises 'rows
    escalated' — and the cascade credits the rows the band actually
    flipped back via :meth:`note_escalated` once the student resolves.
    The 1/k-economics ledger therefore stays exact under speculation
    instead of counting every speculated row as an escalation."""

    def __init__(self, engines, registry: "obs_registry.Registry | None" = None,
                 tracer: "obs_trace.Tracer | None" = None):
        if not engines:
            raise ValueError("EscalationPool needs at least one engine")
        self._engines = list(engines)
        self._in_flight = [0] * len(self._engines)
        self._lock = threading.Lock()
        self._registry = (registry if registry is not None
                          else obs_registry.default_registry())
        self._tracer = (tracer if tracer is not None
                        else obs_trace.default_tracer())
        self._c_rows = self._registry.counter(
            "serve.router.escalations",
            help="rows escalated through the shared full-ensemble pool "
                 "(cascade-aware routing: student replicas everywhere, "
                 "expensive escalations pooled); under speculation "
                 "credited via note_escalated once the band resolves",
        )
        # Registered on FIRST speculative call (the escalations
        # discipline: a speculation-less pool must not export a
        # spurious always-zero series).
        self._c_spec_rows = None

    @property
    def generation(self) -> int:
        """The pool's newest member generation (CascadeEngine reads
        this through its ``ensemble`` half for attribution)."""
        return max(
            int(getattr(e, "generation", 0)) for e in self._engines
        )

    def probs(self, images: np.ndarray) -> np.ndarray:
        return self._probs(images, speculative=False)

    def probs_speculative(self, images: np.ndarray) -> np.ndarray:
        """The speculating cascade's entry point: same routing and row
        contract as ``probs``, but rows count as speculations, not
        escalations — call :meth:`note_escalated` with the rows the
        band actually flipped once the student's scores are in."""
        return self._probs(images, speculative=True)

    def note_escalated(self, n: int) -> None:
        """Credit ``n`` speculated rows as genuine escalations (the
        band flipped them): keeps ``serve.router.escalations`` meaning
        'rows escalated' exactly, speculation on or off."""
        if n > 0:
            self._c_rows.inc(int(n))

    def _probs(self, images: np.ndarray, *, speculative: bool) -> np.ndarray:
        n = int(np.asarray(images).shape[0])
        with self._lock:
            idx = min(
                range(len(self._engines)), key=lambda i: self._in_flight[i]
            )
            # The in-flight ledger charges the WHOLE batch either way:
            # the member genuinely scores every speculated row, and
            # under-charging would steer sibling escalations onto the
            # member busiest with speculative work. Only the ROW
            # counters distinguish speculated from escalated.
            self._in_flight[idx] += n
        # Distributed-trace seam (ISSUE 15): the escalation happens two
        # layers below submit() (replica worker -> CascadeEngine ->
        # here), on whatever thread the replica runs — the AMBIENT
        # context installed by the worker identifies the request, so
        # the escalate event carries its trace_id and the stitched
        # timeline shows exactly which request paid the full ensemble.
        ctx = obs_trace.current_context()
        args = {"rows": n, "pool_member": idx}
        if speculative:
            args["speculative"] = True
        if ctx is not None:
            args["trace_id"] = ctx.trace_id
        try:
            with self._tracer.trace("serve.router.escalate", args=args):
                out = self._engines[idx].probs(images)
        finally:
            with self._lock:
                self._in_flight[idx] -= n
        if speculative:
            c = self._c_spec_rows
            if c is None:
                c = self._c_spec_rows = self._registry.counter(
                    "serve.router.speculations",
                    help="rows scored through the shared full-ensemble "
                         "pool speculatively (whole batches, before the "
                         "cascade band is known); the subset the band "
                         "flips is credited to serve.router.escalations "
                         "via note_escalated",
                )
            c.inc(n)
        else:
            self._c_rows.inc(n)
        return out


class _Replica:
    """One in-process replica handle: an engine, its dispatch queue +
    worker thread, and the accounting the router's policy reads. All
    mutable counters are guarded by the ROUTER's lock (one lock
    hierarchy; the replica only owns its queue).

    Per-replica metric attribution (ISSUE 15 satellite): each replica
    owns a LABELED ``serve.replica{N}.*`` namespace — rows/dispatches/
    failures counters plus an in-flight gauge — instead of muddling
    into the shared ``serve.router.*`` family, so the fleet aggregator
    (and any scraper) can blame a slow or sick replica by name. The
    newest REPLICA_ROWS_KEEP replica namespaces stay exported (the
    scaler churns replicas; the registry must not grow forever)."""

    # The labeled namespace's member metrics, retired together when the
    # replica id ages out of REPLICA_ROWS_KEEP.
    NAMESPACE_METRICS = ("rows", "dispatches", "failures",
                         "in_flight_rows")

    __slots__ = ("rid", "engine", "model", "state", "queue",
                 "in_flight_rows", "rows", "window_rows",
                 "buckets_served", "thread", "c_rows", "c_dispatches",
                 "c_failures", "g_in_flight")

    def __init__(self, rid: int, engine, registry, model: str = "default"):
        self.rid = rid
        self.engine = engine
        self.model = model
        self.state = ACTIVE
        self.queue: "queue.Queue" = queue.Queue()
        self.in_flight_rows = 0   # bins queued or scoring (router lock)
        self.rows = 0             # rows completed, lifetime
        self.window_rows = 0      # rows completed this scaler window
        self.buckets_served: set = set()
        self.thread: "threading.Thread | None" = None
        self.c_rows = registry.counter(
            f"serve.replica{rid}.rows",
            help="rows served by this router replica (per-replica "
                 "ledger; response attribution pairs it with the "
                 "generation id)",
        )
        self.c_dispatches = registry.counter(
            f"serve.replica{rid}.dispatches",
            help="dispatch bins this replica scored",
        )
        self.c_failures = registry.counter(
            f"serve.replica{rid}.failures",
            help="dispatch failures on this replica (nonzero = the "
                 "replica was marked FAILED and its bins moved to "
                 "siblings)",
        )
        self.g_in_flight = registry.gauge(
            f"serve.replica{rid}.in_flight_rows",
            help="rows queued or scoring on this replica right now "
                 "(the least_in_flight policy's per-replica input)",
        )

    def score(self, rows: np.ndarray) -> "tuple[np.ndarray, int]":
        eng = self.engine
        if hasattr(eng, "probs_with_generation"):
            out, gen = eng.probs_with_generation(rows)
            return np.asarray(out), int(gen)
        out = np.asarray(eng.probs(rows))
        return out, int(getattr(eng, "generation", 0))


class _Request:
    """One routed request: its rows, class, deadline, and the
    reassembly state its bins complete into."""

    __slots__ = ("rows", "n", "priority", "model", "future", "t_submit",
                 "t_deadline", "ctx", "trace_id", "offset", "parts",
                 "parts_done", "results", "segments", "failed",
                 "t_first_score", "t_done_score")

    def __init__(self, rows: np.ndarray, priority: str,
                 t_deadline: "float | None", model: str = "default"):
        self.rows = rows
        self.n = int(rows.shape[0])
        self.priority = priority
        self.model = model
        self.future: Future = Future()
        self.t_submit = time.monotonic()
        self.t_deadline = t_deadline
        # Fleet-unique trace context (ISSUE 15): minted at submit,
        # propagated to the replica (ambient, single-request bins) and
        # through it to the EscalationPool — the id the stitched trace
        # and the latency histogram's exemplar both carry.
        self.ctx = obs_trace.new_context()
        self.trace_id = self.ctx.trace_id
        self.offset = 0        # rows binned so far (router lock)
        self.parts = 0         # bins carrying this request's rows
        self.parts_done = 0
        self.results: dict = {}    # req-row offset -> scored rows
        self.segments: list = []   # attribution, in completion order
        self.failed = False
        # Request-segment stamps (router lock): first bin scoring
        # start / last bin scoring end — with t_submit and the resolve
        # time they tile the request's observed latency exactly.
        self.t_first_score: "float | None" = None
        self.t_done_score: "float | None" = None


class _Bin:
    """One dispatch unit: contiguous FIFO rows re-binned from one or
    more requests, bound for one replica (retried on siblings on
    dispatch failure — ``tried`` keeps the exclusion set)."""

    __slots__ = ("rows", "parts", "bucket", "tried", "engines")

    def __init__(self, rows: np.ndarray, parts: list, bucket: int):
        self.rows = rows
        self.parts = parts  # [(request, req_lo, req_hi), ...]
        self.bucket = bucket
        self.tried: set = set()
        # {model: engine} the bin was scored through (_score_bin stashes
        # it) — the audit ledger's lineage source per fused part.
        self.engines: dict = {}


class Router:
    """The front door: ``submit()`` rows with a priority class, get a
    Future; N replica engines serve re-binned batches behind it.

    ``engines``: the initial replica engines (ReplicaHandle contract) —
    a list (one model, named "default") or a dict
    ``{model_name: engine-or-list}`` for multi-tenant routing (ISSUE
    16): requests carry ``submit(..., model=...)`` and only bin onto
    that model's replicas. With ``serve.router_fusion`` on, bins may
    MIX models — rows of different tenants share one device dispatch
    (one stacked forward when the engines' serving programs agree,
    grouped per-model calls otherwise; serve/fusion.py) and demux by
    offset with per-(model, replica, generation) attribution.
    ``replica_factory(rid) -> engine``: how the router builds MORE
    replicas — when present the scaler's decisions are ACTED on
    (activate/drain); without one the scaler only publishes its
    desired-replica gauge. When ``engines`` is None the factory builds
    ``cfg.serve.router_replicas`` replicas up front. A factory is a
    single-model ("default") affair — the scaler has no per-tenant
    signal to act on.

    The policy artifact seam (``serve.policy_from``) is applied by the
    CALLER (``policy.maybe_apply_policy``) before construction — the
    router receives the already-resolved config plus the provenance
    dict for its report, so the fingerprint check happens exactly once
    with the caller's device count.
    """

    def __init__(self, cfg, engines=None, *, replica_factory=None,
                 registry: "obs_registry.Registry | None" = None,
                 policy_provenance: "dict | None" = None):
        sc = cfg.serve
        if sc.router_policy not in DISPATCH_POLICIES:
            raise ValueError(
                f"serve.router_policy must be one of {DISPATCH_POLICIES}, "
                f"got {sc.router_policy!r}"
            )
        if engines is None and replica_factory is None:
            raise ValueError(
                "Router needs engines=[...] and/or a replica_factory"
            )
        if isinstance(engines, dict):
            engines_by_model = {
                str(m): (list(e) if isinstance(e, (list, tuple)) else [e])
                for m, e in engines.items()
            }
            if not engines_by_model or not all(
                    v for v in engines_by_model.values()):
                raise ValueError(
                    "engines dict needs >= 1 engine per model"
                )
            if replica_factory is not None and (
                    len(engines_by_model) > 1
                    or "default" not in engines_by_model):
                raise ValueError(
                    "replica_factory is single-model: use "
                    "engines={'default': [...]} or a plain list with it"
                )
        elif engines is not None:
            engines_by_model = {"default": list(engines)}
        else:
            engines_by_model = None  # factory builds "default" below
        self.cfg = cfg
        self.dispatch_policy = sc.router_policy
        self._buckets = resolve_buckets(sc)
        self.models = (
            tuple(engines_by_model) if engines_by_model is not None
            else ("default",)
        )
        self.fusion = bool(getattr(sc, "router_fusion", False))
        # Prediction provenance (ISSUE 20): predict.py (or any host)
        # attaches an AuditLedger here; _complete_bin then records one
        # audit record PER REQUEST SLICE of every bin — fused
        # cross-request bins attribute row spans to their originating
        # trace ids. None = one attribute read per completed bin.
        self.audit = None
        self._fusion_cache = None
        self._c_fused_bins = None
        self._c_fused_rows = None
        self.max_wait_s = max(0.0, float(sc.max_wait_ms)) / 1e3
        self._tick_s = max(5e-4, float(sc.router_tick_ms) / 1e3)
        self.shed_rows = int(sc.router_shed_rows)
        self.batch_shed_frac = float(sc.router_batch_shed_frac)
        if not (0.0 < self.batch_shed_frac <= 1.0):
            raise ValueError(
                "serve.router_batch_shed_frac must be in (0, 1], got "
                f"{self.batch_shed_frac}"
            )
        self.registry = (
            registry if registry is not None
            else obs_registry.default_registry()
        )
        self._policy_provenance = dict(policy_provenance or {})
        self._factory = replica_factory
        self._limits = scaler_lib.ScalerLimits(
            min_replicas=int(sc.scaler_min_replicas),
            max_replicas=int(sc.scaler_max_replicas),
            slo_p99_s=max(0.0, float(sc.scaler_slo_p99_ms)) / 1e3,
        )
        self._scaler_window_s = max(0.05, float(sc.scaler_window_s))

        reg = self.registry
        self._c_req_interactive = reg.counter(
            "serve.router.requests.interactive",
            help="interactive-class requests admitted by the router",
        )
        self._c_req_batch = reg.counter(
            "serve.router.requests.batch",
            help="batch-class requests admitted by the router",
        )
        self._c_rows = reg.counter(
            "serve.router.rows",
            help="request rows admitted by the router (both classes)",
        )
        self._g_queue_rows = reg.gauge(
            "serve.router.queue_rows",
            help="rows admitted but not yet binned to a replica",
        )
        self._g_in_flight_rows = reg.gauge(
            "serve.router.in_flight_rows",
            help="rows binned to replicas but not yet resolved (queued "
                 "+ in-flight is the class-aware shed backlog)",
        )
        self._c_dispatches = reg.counter(
            "serve.router.dispatches",
            help="bins dispatched to replicas (continuous batching: "
                 "re-binned across request boundaries each tick)",
        )
        self._c_rebins = reg.counter(
            "serve.router.rebins",
            help="requests split across more than one dispatch bin "
                 "(continuous batching across bucket boundaries)",
        )
        if self.fusion:
            # Registered only when fusion is on (the escalations
            # discipline: a fusion-less router must not export a
            # spurious always-zero series from its own construction).
            from jama16_retina_tpu.serve import fusion as fusion_lib

            self._fusion_cache = fusion_lib.FusionCache()
            self._c_fused_bins = reg.counter(
                "serve.router.fused_bins",
                help="dispatch bins that mixed rows from more than one "
                     "model (cross-tenant batch fusion; "
                     "serve.router_fusion)",
            )
            self._c_fused_rows = reg.counter(
                "serve.router.fused_rows",
                help="rows dispatched inside mixed-model bins (each "
                     "demuxed back to its own (model, replica, "
                     "generation) attribution)",
            )
        self._c_retried = reg.counter(
            "serve.router.retried_bins",
            help="bins retried on a sibling after a replica dispatch "
                 "failure (zero-drop contract: typed accounting, the "
                 "request completes elsewhere)",
        )
        self._c_replica_failures = reg.counter(
            "serve.router.replica_failures",
            help="replicas marked failed after a dispatch error; their "
                 "queued bins moved to siblings",
        )
        self._c_request_failures = reg.counter(
            "serve.router.request_failures",
            help="requests failed after every live replica was tried "
                 "(or none remained) — the loud end of the retry path",
        )
        self._c_shed_interactive = reg.counter(
            "serve.router.shed.interactive",
            help="interactive submits rejected Overloaded at the full "
                 "serve.router_shed_rows threshold",
        )
        self._c_shed_batch = reg.counter(
            "serve.router.shed.batch",
            help="batch submits rejected Overloaded at "
                 "router_batch_shed_frac of the row threshold — batch "
                 "sheds first, interactive keeps the headroom",
        )
        self._c_shed_deadline = reg.counter(
            "serve.router.shed.deadline",
            help="requests whose deadline passed before any of their "
                 "rows were binned; failed DeadlineExceeded with no "
                 "device work spent",
        )
        self._c_rejected_closed = reg.counter(
            "serve.router.rejected_at_close",
            help="submits refused because the router was already closed",
        )
        self._g_active = reg.gauge(
            "serve.router.active_replicas",
            help="replicas currently accepting dispatches",
        )
        self._g_draining = reg.gauge(
            "serve.router.draining_replicas",
            help="replicas finishing in-flight work before release",
        )
        self._g_imbalance = reg.gauge(
            "serve.router.imbalance",
            help="per-window max/mean completed-row ratio across active "
                 "replicas (1.0 = perfectly balanced; the "
                 "router_imbalance alert reads this) [fleet:max]",
        )
        self._h_latency = reg.histogram(
            "serve.router.request_latency_s",
            help="routed end-to-end request latency: submit -> future "
                 "resolved (all bins reassembled)",
        )
        # Pre-registered so the span() call in the tick loop reuses a
        # help-carrying histogram (span itself registers help-lessly).
        reg.histogram(
            "serve.router.tick_s",
            help="dispatch-tick duration: deadline sweep + re-binning "
                 "+ replica selection for one tick",
        )
        self._g_desired = reg.gauge(
            "serve.scaler.desired_replicas",
            help="replica count the autoscaling policy wants "
                 "(serve/scaler.py decide(); external autoscalers may "
                 "read this gauge directly)",
        )
        self._g_saturated = reg.gauge(
            "serve.scaler.saturated",
            help="1 while the scaler wants MORE than "
                 "serve.scaler_max_replicas allows (the "
                 "scaler_saturated alert reads this) [fleet:max]",
        )
        self._c_decisions = reg.counter(
            "serve.scaler.decisions",
            help="scaler windows evaluated (every decide() call, "
                 "including holds)",
        )
        self._c_scale_ups = reg.counter(
            "serve.scaler.scale_ups",
            help="scale-up decisions issued by the policy (acted on "
                 "in-process when the router owns a replica factory)",
        )
        self._c_scale_downs = reg.counter(
            "serve.scaler.scale_downs",
            help="scale-down decisions issued by the policy (acted on "
                 "as a graceful replica drain)",
        )

        # One condition guards ALL router mutable state: the request
        # queues, row accounting, the replica table, and the scaler
        # window accumulators. Workers take it briefly per bin.
        self._work = threading.Condition()
        self._q_interactive: deque = deque()
        self._q_batch: deque = deque()
        self._queued_rows = 0
        self._queued_by_model = {m: 0 for m in self.models}
        self._in_flight_rows = 0
        self._closed = False
        self._replicas: "list[_Replica]" = []
        self._next_rid = 0
        self._scaler_state = scaler_lib.ScalerState()
        self._scaler_t0 = time.monotonic()
        self._scaler_samples: list = []   # (queued_rows, in_flight_rows)
        self._window_lat: list = []       # completed latencies (sec)
        # Bounded decision ledger (the REPLICA_ROWS_KEEP discipline): a
        # long-lived front door must not grow one dict per scaler
        # window forever; render/report only ever need the recent tail.
        self._ledger: deque = deque(maxlen=self.SCALER_LEDGER_KEEP)
        # Row shape/dtype pinned from the FIRST submit: rows from
        # different requests concatenate into one bin, so a mismatched
        # submit must be rejected AT SUBMIT (typed, at the caller) —
        # not explode np.concatenate inside the dispatch tick.
        self._row_shape: "tuple | None" = None
        self._row_dtype = None

        if engines_by_model is None:
            n = max(1, int(sc.router_replicas))
            engines_by_model = {
                "default": [replica_factory(r) for r in range(n)]
            }
        n_engines = 0
        with self._work:
            for model, engs in engines_by_model.items():
                for eng in engs:
                    self._add_replica_locked(eng, model=model)
                    n_engines += 1
        self._g_desired.set(n_engines)

        self._tick_thread = threading.Thread(
            target=self._tick_loop, name="jama16-serve-router", daemon=True
        )
        self._tick_thread.start()

    # How many per-replica row ledgers stay exported: a fleet churning
    # replicas through the scaler must not grow one counter per
    # activation forever (the engine's GEN_ROWS_KEEP discipline).
    REPLICA_ROWS_KEEP = 8
    # Scaler decisions retained for the report (obs_report renders the
    # tail; the full history lives in telemetry gauges over time).
    SCALER_LEDGER_KEEP = 256

    # -- replica table (all *_locked: caller holds self._work) -------------

    def _add_replica_locked(self, engine,
                            model: str = "default") -> "_Replica":
        retire = self._next_rid - self.REPLICA_ROWS_KEEP
        if retire >= 0 and not any(
                r.rid == retire and r.state in (ACTIVE, DRAINING)
                for r in self._replicas):
            for metric in _Replica.NAMESPACE_METRICS:
                self.registry.remove(f"serve.replica{retire}.{metric}")
        rep = _Replica(self._next_rid, engine, self.registry, model=model)
        self._next_rid += 1
        self._replicas.append(rep)
        rep.thread = threading.Thread(
            target=self._worker, args=(rep,),
            name=f"jama16-router-replica-{rep.rid}", daemon=True,
        )
        rep.thread.start()
        self._update_replica_gauges_locked()
        return rep

    def _update_replica_gauges_locked(self) -> None:
        self._g_active.set(
            sum(1 for r in self._replicas if r.state == ACTIVE)
        )
        self._g_draining.set(
            sum(1 for r in self._replicas if r.state == DRAINING)
        )

    def _active_locked(self) -> "list[_Replica]":
        return [r for r in self._replicas if r.state == ACTIVE]

    def _maybe_finish_drain_locked(self, rep: "_Replica") -> None:
        """A draining replica with nothing queued and nothing in
        flight is DONE: release its engine (and with it the generation
        handles) and stop its worker."""
        if (rep.state == DRAINING and rep.in_flight_rows == 0
                and rep.queue.empty()):
            rep.state = DRAINED
            rep.engine = None
            rep.queue.put(_STOP)
            self._update_replica_gauges_locked()
            absl_logging.info(
                "router replica %d drained; engine released", rep.rid
            )

    # -- admission (class-aware shedding; ISSUE 12) ------------------------

    def submit(self, rows: np.ndarray, priority: str = "interactive",
               deadline_ms: "float | None" = None,
               model: str = "default") -> Future:
        """Enqueue ``rows`` ([n, ...], n >= 1) under a priority class;
        the Future resolves to the per-row scores in row order (bins
        reassembled by offset). The resolved Future additionally
        carries ``.segments`` —
        ``[{lo, hi, model, replica, generation}, ...]`` — so every
        response row is attributable to the model, replica and
        generation that served it.

        ``model``: which tenant's replicas serve the rows (the names
        the router was constructed with; a plain engines list is the
        single tenant "default"). Rows of different models only share
        a dispatch bin under ``serve.router_fusion``.

        Raises typed ``Overloaded`` (PR 6) at the class-aware row
        threshold: batch sheds at ``router_batch_shed_frac`` of
        ``serve.router_shed_rows``, interactive at the full threshold.
        ``deadline_ms`` falls back to ``serve.default_deadline_ms``; an
        expired request that never binned fails ``DeadlineExceeded``
        with zero device work spent."""
        rows = np.asarray(rows)
        if rows.ndim < 1 or rows.shape[0] == 0:
            raise ValueError(
                f"submit() wants [n, ...] with n >= 1, got {rows.shape}"
            )
        if priority not in PRIORITIES:
            raise ValueError(
                f"priority must be one of {PRIORITIES}, got {priority!r}"
            )
        if model not in self._queued_by_model:
            raise ValueError(
                f"unknown model {model!r}: this router serves "
                f"{self.models} — rejected at submit so a mistargeted "
                "request cannot sit unbinnable in the queue"
            )
        if deadline_ms is None:
            deadline_ms = self.cfg.serve.default_deadline_ms
        n = int(rows.shape[0])
        with self._work:
            if self._closed:
                self._c_rejected_closed.inc()
                raise RuntimeError("Router is closed")
            if self._row_shape is None:
                self._row_shape = rows.shape[1:]
                self._row_dtype = rows.dtype
            elif (rows.shape[1:] != self._row_shape
                  or rows.dtype != self._row_dtype):
                raise ValueError(
                    f"submit() rows must be [n, {self._row_shape}] "
                    f"{self._row_dtype} (pinned by this router's first "
                    f"request), got {rows.shape} {rows.dtype} — "
                    "rejected at submit so a malformed request cannot "
                    "poison the bins it would coalesce into"
                )
            if self.shed_rows > 0:
                threshold = (
                    self.shed_rows if priority == "interactive"
                    else max(1, int(self.shed_rows * self.batch_shed_frac))
                )
                # Backlog = queued + in flight: continuous batching
                # moves rows onto replica queues at tick speed, so the
                # queue alone never shows sustained overload — the
                # admitted-unresolved total does (the batcher's
                # shed_in_flight lesson, in rows).
                backlog = self._queued_rows + self._in_flight_rows
                if backlog + n > threshold:
                    if priority == "interactive":
                        self._c_shed_interactive.inc()
                    else:
                        self._c_shed_batch.inc()
                    raise Overloaded(
                        f"{backlog} rows queued/in-flight + {n} new > "
                        f"{priority} shed threshold {threshold} "
                        f"(serve.router_shed_rows={self.shed_rows}, "
                        f"batch frac {self.batch_shed_frac:g}); request "
                        "shed at submit"
                    )
            req = _Request(
                rows, priority,
                t_deadline=(time.monotonic() + deadline_ms / 1e3
                            if deadline_ms and deadline_ms > 0 else None),
                model=model,
            )
            (self._q_interactive if priority == "interactive"
             else self._q_batch).append(req)
            self._queued_rows += n
            self._queued_by_model[model] += n
            self._g_queue_rows.set(self._queued_rows)
            (self._c_req_interactive if priority == "interactive"
             else self._c_req_batch).inc()
            self._c_rows.inc(n)
            self._work.notify_all()
        return req.future

    def probs(self, images: np.ndarray,
              priority: str = "interactive") -> np.ndarray:
        """Blocking convenience: submit + result."""
        return self.submit(images, priority=priority).result()

    # -- the dispatch tick (continuous batching) ---------------------------

    def _tick_loop(self) -> None:
        while True:
            with self._work:
                if self._closed and not self._queued_rows:
                    return
                if not self._queued_rows:
                    self._work.wait(timeout=self._tick_s)
                if self._closed and not self._queued_rows:
                    return
            with span("serve.router.tick_s", self.registry):
                assignments = []
                with self._work:
                    try:
                        self._expire_deadlines_locked(time.monotonic())
                        assignments = self._pack_locked(time.monotonic())
                    except Exception as e:  # noqa: BLE001 - tick survives
                        # Belt behind the submit-time shape pin: a pack
                        # failure fails the queued requests TYPED and
                        # the tick loop lives on — a wedged dispatch
                        # thread would hang every future forever.
                        absl_logging.error(
                            "router pack failed; failing queued "
                            "requests: %s: %s", type(e).__name__, e,
                        )
                        self._fail_all_queued_locked(e)
                    self._scaler_sample_locked()
                    # Enqueue UNDER the lock: a replica selected above
                    # cannot transition to FAILED (and drain its queue)
                    # between selection and this put — an unlocked put
                    # could strand the bin on a dead replica's queue
                    # forever. queue.put is unbounded, it never blocks.
                    for rep, b in assignments:
                        rep.queue.put(b)
            try:
                self._maybe_scale()
            except Exception as e:  # noqa: BLE001 - tick must survive
                absl_logging.error(
                    "router scaler actuation failed (tick loop "
                    "continues): %s: %s", type(e).__name__, e,
                )
            if not assignments:
                # Nothing dispatchable: a partial is waiting out its
                # coalescing window. Sleep exactly until the OLDEST
                # waiter's window expires (capped at a tick) on the
                # condition — not a fixed fraction of the tick — so a
                # lone interactive request's queue_wait is bounded by
                # its own max_wait_ms, not by tick granularity, and a
                # new submit (notify_all) that completes a bucket wakes
                # the packer immediately.
                with self._work:
                    oldest = None
                    for q in (self._q_interactive, self._q_batch):
                        for req in q:
                            if req.offset < req.n and (
                                    oldest is None
                                    or req.t_submit < oldest):
                                oldest = req.t_submit
                    if oldest is not None:
                        delay = (oldest + self.max_wait_s
                                 - time.monotonic())
                        if delay > 0:
                            self._work.wait(
                                timeout=min(delay, self._tick_s)
                            )

    def _expire_deadlines_locked(self, now: float) -> None:
        """Fail never-binned expired requests typed, before any device
        work; partially-binned requests are past the point of cheap
        refusal and complete normally (late but whole)."""
        for q in (self._q_interactive, self._q_batch):
            kept = deque()
            while q:
                req = q.popleft()
                if (req.offset == 0 and req.t_deadline is not None
                        and now > req.t_deadline):
                    self._queued_rows -= req.n
                    self._queued_by_model[req.model] -= req.n
                    self._c_shed_deadline.inc()
                    try:
                        req.future.set_exception(DeadlineExceeded(
                            f"deadline passed {now - req.t_deadline:.3f}s "
                            "before any row was binned; no device work "
                            "was spent"
                        ))
                    except InvalidStateError:
                        pass
                else:
                    kept.append(req)
            q.extend(kept)
        self._g_queue_rows.set(self._queued_rows)

    def _pack_locked(self, now: float) -> list:
        """Re-bin queued rows across request boundaries into dispatch
        bins (interactive rows first), assign each bin a replica by the
        dispatch policy, and account it in flight. Returns
        [(replica, bin), ...] for the caller to enqueue outside the
        lock.

        Bins are cut per PACK GROUP: without fusion each model packs
        alone (a bin never mixes engines); with ``serve.router_fusion``
        all models share one group, so a trickle of single-row requests
        from different tenants fills one bucket together."""
        if self.fusion or len(self.models) == 1:
            groups = [set(self.models)]
        else:
            groups = [{m} for m in self.models]
        out = []
        for models in groups:
            out.extend(self._pack_group_locked(now, models))
        self._g_queue_rows.set(self._queued_rows)
        self._g_in_flight_rows.set(self._in_flight_rows)
        return out

    def _pack_group_locked(self, now: float, models: set) -> list:
        out = []
        while True:
            # A tenant whose replica set vanished fails typed NOW —
            # its rows must not sit in (or poison) bins nothing can
            # serve. Other tenants in the group keep packing.
            live = {r.model for r in self._active_locked()}
            dead = {
                m for m in models
                if self._queued_by_model[m] > 0 and m not in live
            }
            if dead:
                self._fail_all_queued_locked(NoReplicasLeft(
                    "no active replicas to dispatch to "
                    f"(model(s) {sorted(dead)})"
                ), models=dead)
            total = sum(self._queued_by_model[m] for m in models)
            if total <= 0:
                break
            if total >= self._buckets[-1]:
                take = self._buckets[-1]
            else:
                # Partial remainder: dispatch only once the oldest
                # unbinned request has waited out the coalescing
                # window (or the router is closing and must flush).
                oldest = None
                for q in (self._q_interactive, self._q_batch):
                    for req in q:
                        if (req.model in models and req.offset < req.n
                                and (oldest is None
                                     or req.t_submit < oldest)):
                            oldest = req.t_submit
                if oldest is None:
                    break
                if not self._closed and now - oldest < self.max_wait_s:
                    break
                take = total
            b = self._make_bin_locked(take, models)
            # The bin is charged to ONE replica — the first part's
            # model (FIFO makes that the oldest waiter's tenant); a
            # mixed bin borrows sibling engines at score time.
            primary = b.parts[0][0].model
            reps = [
                r for r in self._active_locked() if r.model == primary
            ]
            rep = self._choose_replica_locked(reps, b)
            b.tried.add(rep.rid)
            rep.in_flight_rows += b.rows.shape[0]
            rep.g_in_flight.set(rep.in_flight_rows)
            self._in_flight_rows += b.rows.shape[0]
            self._c_dispatches.inc()
            if self._c_fused_bins is not None and len(
                    {req.model for req, _lo, _hi in b.parts}) > 1:
                self._c_fused_bins.inc()
                self._c_fused_rows.inc(int(b.rows.shape[0]))
            out.append((rep, b))
        return out

    def _make_bin_locked(self, take: int, models: set) -> "_Bin":
        """Cut ``take`` rows FIFO (interactive queue first, restricted
        to ``models``) into one bin, splitting requests at the
        boundary; fully-binned requests leave their queue."""
        parts = []
        chunks = []
        remaining = take
        for q in (self._q_interactive, self._q_batch):
            if remaining == 0:
                break
            finished = []
            for req in q:
                if remaining == 0:
                    break
                if req.model not in models or req.offset >= req.n:
                    continue
                lo = req.offset
                hi = min(req.n, lo + remaining)
                chunks.append(req.rows[lo:hi])
                parts.append((req, lo, hi))
                req.offset = hi
                req.parts += 1
                if req.parts == 2:  # counted once, at the first split
                    self._c_rebins.inc()
                remaining -= hi - lo
                self._queued_by_model[req.model] -= hi - lo
                if req.offset >= req.n:
                    finished.append(req)
            for r in finished:
                q.remove(r)
        self._queued_rows -= take
        rows = chunks[0] if len(chunks) == 1 else np.concatenate(chunks)
        bucket = next(
            (bk for bk in self._buckets if bk >= rows.shape[0]),
            self._buckets[-1],
        )
        return _Bin(rows, parts, bucket)

    def _choose_replica_locked(self, reps: "list[_Replica]",
                               b: "_Bin") -> "_Replica":
        if self.dispatch_policy == "bucket_affinity":
            warm = [r for r in reps if b.bucket in r.buckets_served]
            if warm:
                reps = warm
        return min(reps, key=lambda r: (r.in_flight_rows, r.rid))

    def _purge_request_locked(self, req: "_Request") -> None:
        """Drop a failed request's still-unbinned remainder from the
        queues (its completed/in-flight bins just no-op at resolution:
        ``req.failed`` gates set_result)."""
        for q in (self._q_interactive, self._q_batch):
            if req in q:
                q.remove(req)
                self._queued_rows -= req.n - req.offset
                self._queued_by_model[req.model] -= req.n - req.offset
        self._g_queue_rows.set(self._queued_rows)

    def _fail_all_queued_locked(self, exc: BaseException,
                                models: "set | None" = None) -> None:
        """Fail queued requests typed — all of them, or (``models``)
        only the tenants whose replica set just vanished; other
        tenants' requests keep their live replicas."""
        for q in (self._q_interactive, self._q_batch):
            kept = deque()
            while q:
                req = q.popleft()
                if models is not None and req.model not in models:
                    kept.append(req)
                    continue
                self._queued_rows -= req.n - req.offset
                self._queued_by_model[req.model] -= req.n - req.offset
                req.failed = True
                self._c_request_failures.inc()
                try:
                    req.future.set_exception(exc)
                except InvalidStateError:
                    pass
            q.extend(kept)
        self._g_queue_rows.set(self._queued_rows)

    # -- replica workers ---------------------------------------------------

    def _worker(self, rep: "_Replica") -> None:
        while True:
            item = rep.queue.get()
            if item is _STOP:
                return
            b: _Bin = item
            t0 = time.monotonic()
            # Ambient trace context (ISSUE 15): a bin carrying exactly
            # one request's rows propagates that request's context into
            # the replica engine (and through a CascadeEngine to the
            # EscalationPool) — a multi-request bin has no single
            # context to claim, so it installs none.
            ctxs = {id(req): req.ctx for req, _lo, _hi in b.parts}
            bin_ctx = (next(iter(ctxs.values()))
                       if len(ctxs) == 1 else None)
            try:
                # Fault seam (obs/faultinject.py "serve.router.dispatch"):
                # one global read + branch unarmed; the --chaos drill
                # injects a replica death here mid-storm.
                faultinject.check("serve.router.dispatch")
                t_score0 = time.perf_counter()
                with obs_trace.use_context(bin_ctx):
                    out, gens = self._score_bin(rep, b)
                # Per-row attribution for FUSED bins (ISSUE 20
                # satellite): a multi-request bin installed no ambient
                # context above, so its stitched trace would otherwise
                # lose the originating ids — one complete event names
                # every part's trace_id and row span instead.
                tr = obs_trace.default_tracer()
                if tr.enabled and len(ctxs) > 1:
                    tr.complete(
                        "serve.router.bin.parts", t_score0,
                        time.perf_counter(),
                        args={
                            "replica": rep.rid,
                            "rows": int(b.rows.shape[0]),
                            "parts": [
                                {"trace_id": req.trace_id,
                                 "model": req.model,
                                 "lo": req_lo, "hi": req_hi}
                                for req, req_lo, req_hi in b.parts
                            ],
                        },
                    )
                if out.shape[0] != b.rows.shape[0]:
                    raise RuntimeError(
                        f"replica {rep.rid} returned {out.shape[0]} rows "
                        f"for {b.rows.shape[0]} inputs — row contract "
                        "broken"
                    )
            except NoReplicasLeft as e:
                # A BORROWED tenant's replicas are gone, not this one:
                # fail the bin typed without blaming the carrier.
                self._fail_bin(rep, b, e)
                continue
            except BaseException as e:  # noqa: BLE001 - retried/typed
                self._on_dispatch_failure(rep, b, e)
                if rep.state == FAILED:
                    return
                continue
            self._complete_bin(rep, b, out, gens, t0)

    def _score_bin(self, rep: "_Replica",
                   b: "_Bin") -> "tuple[np.ndarray, dict]":
        """Score one bin, returning ``(out, {model: generation})``. A
        bin of the replica's own model goes straight through its
        engine; a mixed bin (serve.router_fusion) borrows the
        least-loaded active engine of each other model under the lock
        and scores through serve/fusion.py — one fused stacked forward
        when the engines' programs agree, grouped per-model calls
        otherwise. Rows stay charged to the PRIMARY replica either
        way (its queue carried the bin); a retry on a sibling
        re-borrows from a fresh snapshot."""
        models = []
        for req, _lo, _hi in b.parts:
            if req.model not in models:
                models.append(req.model)
        if len(models) == 1 and models[0] == rep.model:
            b.engines = {rep.model: rep.engine}
            out, gen = rep.score(b.rows)
            return out, {rep.model: gen}
        from jama16_retina_tpu.serve import fusion as fusion_lib

        with self._work:
            engines = {}
            for m in models:
                if m == rep.model and rep.engine is not None:
                    engines[m] = rep.engine
                    continue
                cands = [
                    r for r in self._active_locked()
                    if r.model == m and r.engine is not None
                ]
                if not cands:
                    raise NoReplicasLeft(
                        f"no active replica to borrow an engine for "
                        f"model {m!r}"
                    )
                engines[m] = min(
                    cands, key=lambda r: (r.in_flight_rows, r.rid)
                ).engine
        b.engines = dict(engines)
        out, gens = fusion_lib.score_mixed(
            engines, b.rows, b.parts, b.bucket,
            cache=self._fusion_cache,
        )
        return np.asarray(out), gens

    def _fail_bin(self, rep: "_Replica", b: "_Bin",
                  exc: BaseException) -> None:
        """Fail a bin's requests typed WITHOUT marking the replica
        failed — the bin was unservable (a borrowed tenant's replica
        set vanished), the carrier is healthy."""
        n = int(b.rows.shape[0])
        failed = []
        with self._work:
            rep.in_flight_rows -= n
            rep.g_in_flight.set(max(0, rep.in_flight_rows))
            self._in_flight_rows -= n
            self._g_in_flight_rows.set(self._in_flight_rows)
            for req, _lo, _hi in b.parts:
                if req.failed:
                    continue
                req.failed = True
                self._c_request_failures.inc()
                self._purge_request_locked(req)
                failed.append(req)
            self._maybe_finish_drain_locked(rep)
            self._work.notify_all()
        for req in failed:
            try:
                req.future.set_exception(exc)
            except InvalidStateError:
                pass

    def _complete_bin(self, rep: "_Replica", b: "_Bin",
                      out: np.ndarray, gens: dict, t0: float) -> None:
        n = int(b.rows.shape[0])
        done = []
        t_done = time.monotonic()
        with self._work:
            rep.in_flight_rows -= n
            rep.g_in_flight.set(rep.in_flight_rows)
            rep.rows += n
            rep.window_rows += n
            rep.buckets_served.add(b.bucket)
            self._in_flight_rows -= n
            self._g_in_flight_rows.set(self._in_flight_rows)
            lo = 0
            for req, req_lo, req_hi in b.parts:
                seg = out[lo:lo + (req_hi - req_lo)]
                lo += req_hi - req_lo
                req.results[req_lo] = seg
                if req.t_first_score is None or t0 < req.t_first_score:
                    req.t_first_score = t0
                req.segments.append({
                    "lo": req_lo, "hi": req_hi, "model": req.model,
                    "replica": rep.rid,
                    "generation": int(gens[req.model]),
                })
                req.parts_done += 1
                if (req.offset >= req.n and req.parts_done == req.parts
                        and not req.failed):
                    req.t_done_score = t_done
                    done.append(req)
            self._maybe_finish_drain_locked(rep)
            self._work.notify_all()
        rep.c_rows.inc(n)
        rep.c_dispatches.inc()
        # Audit ledger (ISSUE 20), OUTSIDE the router lock: one record
        # per request slice of the bin — a fused cross-request bin
        # demuxes into per-trace-id records, each carrying the model,
        # replica, pinned generation, and lineage of the engine that
        # actually scored its rows.
        al = self.audit
        if al is not None:
            lo = 0
            for req, req_lo, req_hi in b.parts:
                w = req_hi - req_lo
                al.record(
                    b.rows[lo:lo + w], out[lo:lo + w],
                    trace_id=req.trace_id, model=req.model,
                    replica=rep.rid,
                    generation=int(gens[req.model]),
                    engine=b.engines.get(req.model),
                )
                lo += w
        now = time.monotonic()
        tr = obs_trace.default_tracer()
        for req in done:
            pieces = [req.results[k] for k in sorted(req.results)]
            result = pieces[0] if len(pieces) == 1 else np.concatenate(pieces)
            req.segments.sort(key=lambda s: s["lo"])
            req.future.segments = req.segments
            try:
                req.future.set_result(result)
                lat = now - req.t_submit
                # Exemplar (ISSUE 15): the flush window's slowest
                # request carries its trace_id out through telemetry,
                # so an SLO breach links straight to the trace.
                self._h_latency.observe(lat, exemplar=req.trace_id)
                if tr.enabled:
                    # Three complete events tiling [t_submit, now)
                    # exactly — the router twin of the batcher's
                    # request segments, same monotonic clock as the
                    # latency observation (pinned in tests).
                    args = {"trace_id": req.trace_id, "rows": req.n,
                            "priority": req.priority}
                    tr.complete("serve.router.request.queue_wait",
                                req.t_submit, req.t_first_score, args)
                    tr.complete("serve.router.request.device",
                                req.t_first_score, req.t_done_score,
                                args)
                    tr.complete("serve.router.request.resolve",
                                req.t_done_score, now, args)
                with self._work:
                    self._window_lat.append(lat)
            except InvalidStateError:
                pass

    def _on_dispatch_failure(self, rep: "_Replica", b: "_Bin",
                             exc: BaseException) -> None:
        """A replica died mid-dispatch: mark it failed, move its bins
        (this one + everything still queued behind it) to siblings with
        typed accounting — zero dropped requests as long as one live
        replica remains."""
        moved = [b]
        orphaned_reqs = []
        with self._work:
            if rep.state in (ACTIVE, DRAINING):
                rep.state = FAILED
                self._c_replica_failures.inc()
                rep.c_failures.inc()
                self._update_replica_gauges_locked()
                absl_logging.error(
                    "router replica %d failed dispatching %d rows "
                    "(%s: %s); retrying on siblings",
                    rep.rid, b.rows.shape[0], type(exc).__name__, exc,
                )
            rep.engine = None
            while True:
                try:
                    item = rep.queue.get_nowait()
                except queue.Empty:
                    break
                if item is not _STOP:
                    moved.append(item)
            seen_failed = set()
            for mb in moved:
                n = int(mb.rows.shape[0])
                rep.in_flight_rows -= n
                # Retry siblings must be able to CARRY the bin: same
                # model as its primary part (the engines for any other
                # fused-in models are re-borrowed at score time).
                mb_primary = mb.parts[0][0].model
                reps = [
                    r for r in self._active_locked()
                    if r.rid not in mb.tried and r.model == mb_primary
                ]
                if not reps:
                    # Orphan bin: retries exhausted. Fail each carried
                    # request ONCE (a request may span several orphan
                    # bins) and purge its still-unbinned remainder from
                    # the queues — no more device work is spent on a
                    # caller that already holds an exception.
                    self._in_flight_rows -= n
                    for req, _lo, _hi in mb.parts:
                        if id(req) in seen_failed or req.failed:
                            continue
                        seen_failed.add(id(req))
                        req.failed = True
                        self._c_request_failures.inc()
                        self._purge_request_locked(req)
                        orphaned_reqs.append(req)
                    continue
                target = self._choose_replica_locked(reps, mb)
                mb.tried.add(target.rid)
                target.in_flight_rows += n
                target.g_in_flight.set(target.in_flight_rows)
                self._c_retried.inc()
                # Under the lock for the same reason as the tick-loop
                # puts: the target must not fail-and-drain between
                # selection and enqueue.
                target.queue.put(mb)
            rep.g_in_flight.set(max(0, rep.in_flight_rows))
            self._g_in_flight_rows.set(self._in_flight_rows)
            self._work.notify_all()
        for req in orphaned_reqs:
            try:
                req.future.set_exception(exc)
            except InvalidStateError:
                pass

    # -- autoscaling (serve/scaler.py signals + in-process actuation) ------

    def _scaler_sample_locked(self) -> None:
        self._scaler_samples.append(
            (self._queued_rows, self._in_flight_rows)
        )

    def _maybe_scale(self) -> None:
        now = time.monotonic()
        build_engine_for = None
        drain_rid = None
        with self._work:
            window = now - self._scaler_t0
            if window < self._scaler_window_s:
                return
            samples = self._scaler_samples or [(0, 0)]
            lat = sorted(self._window_lat)
            # Nearest-rank p99: for small windows this is the max — a
            # low-traffic SLO breach must register, not vanish into an
            # interpolated underestimate.
            p99 = lat[
                min(len(lat) - 1,
                    max(0, int(np.ceil(0.99 * len(lat))) - 1))
            ] if lat else 0.0
            stats = scaler_lib.ScalerStats(
                window_sec=window,
                queue_rows=float(np.mean([s[0] for s in samples])),
                in_flight_rows=float(np.mean([s[1] for s in samples])),
                p99_latency_s=float(p99),
            )
            active = len(self._active_locked())
            decision = scaler_lib.decide(
                stats, active, self.cfg.serve.max_batch,
                self._scaler_state, self._limits,
            )
            self._scaler_state = decision.state
            self._scaler_t0 = now
            self._scaler_samples = []
            self._window_lat = []
            self._c_decisions.inc()
            self._g_desired.set(decision.desired)
            self._g_saturated.set(1.0 if decision.saturated else 0.0)
            # Imbalance: completed-row spread across active replicas
            # this window (the router_imbalance alert's gauge).
            window_rows = [
                r.window_rows for r in self._replicas if r.state == ACTIVE
            ]
            mean_rows = float(np.mean(window_rows)) if window_rows else 0.0
            self._g_imbalance.set(
                float(max(window_rows) / mean_rows)
                if mean_rows > 0 else 1.0
            )
            for r in self._replicas:
                r.window_rows = 0
            self._ledger.append({
                "t": time.time(),
                "active": active,
                "desired": decision.desired,
                "reason": decision.reason,
                "queue_rows": round(stats.queue_rows, 1),
                "in_flight_rows": round(stats.in_flight_rows, 1),
                "p99_latency_ms": round(stats.p99_latency_s * 1e3, 2),
            })
            if decision.desired > active:
                self._c_scale_ups.inc()
                if self._factory is not None and not self._closed:
                    build_engine_for = self._next_rid
            elif decision.desired < active:
                self._c_scale_downs.inc()
                if self._factory is not None:
                    # Drain the NEWEST active replica: oldest replicas
                    # hold the warmest compile caches.
                    act = self._active_locked()
                    if len(act) > 1:
                        drain_rid = act[-1].rid
        if build_engine_for is not None:
            try:
                engine = self._factory(build_engine_for)
            except Exception as e:  # noqa: BLE001 - scaling must not kill
                absl_logging.error(
                    "replica factory failed for replica %d: %s: %s",
                    build_engine_for, type(e).__name__, e,
                )
                return
            with self._work:
                if not self._closed:
                    self._add_replica_locked(engine)
        elif drain_rid is not None:
            try:
                self.drain_replica(drain_rid)
            except ValueError as e:
                # A replica failed between the decision and the drain,
                # leaving drain_rid the last active one — hold instead.
                absl_logging.info("scale-down skipped: %s", e)

    def drain_replica(self, rid: int) -> None:
        """Graceful drain: the replica takes no new bins, finishes its
        queued/in-flight work, then releases its engine (generation
        handles included). Refuses to drain the last active replica."""
        with self._work:
            rep = next(
                (r for r in self._replicas if r.rid == rid), None
            )
            if rep is None or rep.state != ACTIVE:
                return
            if len(self._active_locked()) <= 1:
                raise ValueError(
                    "refusing to drain the last active replica — the "
                    "router would have no dispatch target"
                )
            rep.state = DRAINING
            self._update_replica_gauges_locked()
            self._maybe_finish_drain_locked(rep)
            absl_logging.info("router replica %d draining", rid)

    # -- reports / lifecycle -----------------------------------------------

    def replica_states(self) -> list:
        """Snapshot of the replica table (tests + the report)."""
        with self._work:
            return [
                {
                    "replica": r.rid, "state": r.state, "model": r.model,
                    "rows": r.rows, "in_flight_rows": r.in_flight_rows,
                    "buckets": sorted(r.buckets_served),
                    "generation": (
                        int(getattr(r.engine, "generation", 0))
                        if r.engine is not None else None
                    ),
                }
                for r in self._replicas
            ]

    def scaler_ledger(self) -> list:
        with self._work:
            return list(self._ledger)

    def report(self) -> dict:
        """The router's session report — what predict.py journals as a
        ``router`` record and scripts/obs_report.py renders: replica
        ledger, priority/shed split, re-binning + retry accounting, the
        scaler decision ledger, and the policy provenance."""
        return {
            "dispatch_policy": self.dispatch_policy,
            "buckets": [int(b) for b in self._buckets],
            "models": list(self.models),
            "fusion": self.fusion,
            "fused_bins": (
                int(self._c_fused_bins.value)
                if self._c_fused_bins is not None else 0
            ),
            "policy": dict(self._policy_provenance) or None,
            "replicas": self.replica_states(),
            "requests": {
                "interactive": int(self._c_req_interactive.value),
                "batch": int(self._c_req_batch.value),
            },
            "shed": {
                "interactive": int(self._c_shed_interactive.value),
                "batch": int(self._c_shed_batch.value),
                "deadline": int(self._c_shed_deadline.value),
            },
            "rows": int(self._c_rows.value),
            "dispatches": int(self._c_dispatches.value),
            "rebins": int(self._c_rebins.value),
            "retried_bins": int(self._c_retried.value),
            "replica_failures": int(self._c_replica_failures.value),
            # Snapshot read, NOT counter(): a router without an
            # EscalationPool must not register (and so export) a
            # spurious always-zero escalations series as a side effect
            # of its own report.
            "escalations": int(self.registry.snapshot().get(
                "counters", {}
            ).get("serve.router.escalations", 0)),
            "scaler": self.scaler_ledger(),
        }

    def close(self) -> None:
        """Stop accepting, flush everything queued, join workers."""
        with self._work:
            if self._closed:
                return
            self._closed = True
            self._work.notify_all()
        self._tick_thread.join()
        # The tick loop exits only once the queues are empty; every bin
        # is on (or moving between) replica queues. Wait for the last
        # in-flight bin to resolve BEFORE stopping workers: a failure
        # retry re-enqueues on a sibling, and that bin must never land
        # behind the sibling's _STOP.
        with self._work:
            while self._in_flight_rows > 0:
                self._work.wait(timeout=0.05)
            reps = list(self._replicas)
        for rep in reps:
            rep.queue.put(_STOP)
        for rep in reps:
            if rep.thread is not None:
                rep.thread.join()

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
