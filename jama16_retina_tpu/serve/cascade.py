"""Distilled ensemble cascade: cheap by default, expensive by exception
(ISSUE 10 tentpole).

The k-member stacked ensemble pays k member-forwards for EVERY row, yet
almost all screening traffic is nowhere near the operating thresholds —
the region where ensemble averaging actually changes decisions. The
cascade makes that asymmetry structural:

  * a distilled STUDENT (one model trained on the live ensemble's
    averaged soft scores; ``train.distill_from``) scores every request —
    ~1/k the FLOPs of the stacked ensemble;
  * only rows whose student referable score lands within
    ``serve.cascade_band`` of ANY ``serve.cascade_thresholds`` entry
    ESCALATE to the full stacked ensemble, whose scores replace the
    student's for exactly those rows;
  * everything else ships the student score untouched.

With <=20% of traffic in the band, effective ensemble-throughput is
>=2x the always-stacked baseline (benched as ``cascade_speedup``); the
edges degenerate correctly — band 0 escalates only exact threshold
hits, a band covering [0, 1] escalates everything (= the plain
ensemble, bit for bit).

Quality is pinned BEFORE a cascade config can go live, through the
same PR-5/PR-8 gate machinery reload candidates pass
(lifecycle.GateVerdict): ``go_live()`` evaluates the ``golden_canary``
verdict (cascade scores vs the pinned golden set) and the ``auc_floor``
verdict (cascade AUC on labeled rows >= full-ensemble AUC - delta, with
per-operating-threshold sensitivity/specificity in the detail) and
raises typed :class:`CascadeRejected` on any failure — a cascade that
moves the operating points never takes a request.

Lifecycle: the controller treats a CascadeEngine as its ensemble half
(lifecycle/controller.py unwraps it) — drift-triggered retrains swap
the STACKED ensemble under the cascade while the student keeps serving
the cheap path; ``reload``/``rollback``/``release_retained`` delegate.
"""

from __future__ import annotations

import numpy as np
from absl import logging as absl_logging

from jama16_retina_tpu.configs import ExperimentConfig
from jama16_retina_tpu.eval import metrics
from jama16_retina_tpu.obs import registry as obs_registry


class CascadeRejected(RuntimeError):
    """The cascade failed its go-live gate (golden-canary deviation or
    an operating-point AUC floor miss): the student/band pair must not
    serve — retrain the student (train.distill_from), widen the band,
    or serve the plain ensemble."""


def _referable(scores: np.ndarray) -> np.ndarray:
    """Scores -> referable probability [n] for either head (the scalar
    the escalation band and both gates compare on)."""
    s = np.asarray(scores, np.float64)
    if s.ndim == 2:
        s = np.asarray(
            metrics.referable_probs_from_multiclass(s), np.float64
        )
    return s.ravel()


class CascadeEngine:
    """Student-first scoring with band-escalation to the full ensemble.

    ``student`` / ``ensemble``: two ServingEngines (or any objects with
    the engine's ``probs`` row contract — tests stub them); the student
    is normally a k=1 engine over the ``train.distill_from`` product,
    the ensemble the full stacked tree. Engines share one registry so
    the cascade's counters land in the same telemetry snapshots.

    Thresholds/band come from ``cfg.serve.cascade_thresholds`` /
    ``cfg.serve.cascade_band``; empty thresholds default to (0.5,).

    ``quality``: an optional QualityMonitor fed the MERGED scores (the
    distribution the deployment actually serves). When one is passed,
    build the two sub-engines with ``obs.quality`` disabled — otherwise
    each half would double-observe its own partial view (predict.py's
    cascade path wires exactly this).
    """

    def __init__(
        self,
        cfg: ExperimentConfig,
        student,
        ensemble,
        registry: "obs_registry.Registry | None" = None,
        quality=None,
    ):
        self.cfg = cfg
        sc = cfg.serve
        self.band = float(sc.cascade_band)
        if self.band < 0:
            raise ValueError(
                f"serve.cascade_band must be >= 0, got {self.band}"
            )
        self.thresholds = tuple(
            float(t) for t in (sc.cascade_thresholds or (0.5,))
        )
        bad = [t for t in self.thresholds if not 0.0 <= t <= 1.0]
        if bad:
            raise ValueError(
                f"serve.cascade_thresholds must lie in [0, 1]: {bad}"
            )
        self.student = student
        self.ensemble = ensemble
        self.registry = (
            registry if registry is not None
            else getattr(ensemble, "registry",
                         obs_registry.default_registry())
        )
        self._c_student_rows = self.registry.counter(
            "serve.cascade.student_rows",
            help="rows scored by the distilled student (every cascade "
                 "row passes here first)",
        )
        self._c_escalated_rows = self.registry.counter(
            "serve.cascade.escalated_rows",
            help="rows whose student score landed inside the "
                 "escalation band and re-scored through the full "
                 "stacked ensemble (escalation rate = escalated / "
                 "student rows)",
        )
        # Speculative escalation (ISSUE 16 tentpole c): dispatch the
        # ensemble CONCURRENTLY with the student instead of serially,
        # so an escalated row pays max(student, ensemble) latency
        # rather than student + ensemble. Results are bit-equal to the
        # serial cascade (pinned by tests): the ensemble scores at the
        # same bucket shape either way and rows are independent, so
        # esc[mask] == ensemble.probs(images[mask]) row for row. The
        # cost is wasted ensemble work on rows the band never flips —
        # a counted ledger, not a silent one.
        self.speculative = bool(getattr(sc, "cascade_speculative", False))
        self._c_speculated = self.registry.counter(
            "serve.cascade.speculated",
            help="rows scored through the ensemble speculatively "
                 "(concurrently with the student; "
                 "serve.cascade_speculative)",
        )
        self._c_speculated_wasted = self.registry.counter(
            "serve.cascade.speculated.wasted",
            help="speculated rows whose ensemble score was discarded "
                 "because the student landed outside the escalation "
                 "band (the latency-for-FLOPs trade's cost side)",
        )
        self._spec_pool = None
        self.quality = quality
        # Prediction provenance (ISSUE 20): the wiring site attaches
        # ONE ledger at the cascade level (sub-engines stay un-audited
        # — otherwise every escalated row would be recorded twice).
        self.audit = None

    # -- escalation policy -------------------------------------------------

    def escalation_mask(self, referable: np.ndarray) -> np.ndarray:
        """True where a student referable score is within ``band`` of
        any operating threshold — the rows ensemble averaging could
        plausibly flip."""
        r = np.asarray(referable, np.float64).ravel()
        mask = np.zeros(r.shape, bool)
        for thr in self.thresholds:
            mask |= np.abs(r - thr) <= self.band
        return mask

    # -- the serving surface -----------------------------------------------

    def _spec_submit(self, fn, *args):
        """Run ``fn`` on the lazily-created speculation thread (one
        worker: speculative batches are serialized against each other,
        exactly like the serial cascade's ensemble calls were)."""
        if self._spec_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._spec_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="jama16-cascade-spec",
            )
        return self._spec_pool.submit(fn, *args)

    def _probs_raw(self, images: np.ndarray) -> np.ndarray:
        """Score + merge, no quality hook — what the canary scores
        through (canary traffic must never pollute the drift windows,
        the same bypass ServingEngine's member_probs-based canary
        wiring applies)."""
        return self._probs_masked(images)[0]

    def _probs_masked(
        self, images: np.ndarray
    ) -> "tuple[np.ndarray, np.ndarray]":
        """``(_probs_raw output, escalation mask)`` — the mask is the
        path TAKEN per row (student vs ensemble), which the audit
        ledger seals so replay can re-walk the identical cascade."""
        spec_fut = None
        if self.speculative and len(images):
            # Fire the full-ensemble forward for the WHOLE batch before
            # the student runs — by the time the student's scores tell
            # us which rows the band wants, the ensemble is already in
            # flight (or done). Escalated rows then pay
            # max(student, ensemble), not student + ensemble. An
            # EscalationPool ensemble takes its speculative entry point
            # so whole speculated batches don't masquerade as
            # escalations in the pool's 1/k-economics ledger; the rows
            # the band actually flips are credited back below.
            spec_fn = getattr(self.ensemble, "probs_speculative", None)
            spec_fut = self._spec_submit(
                spec_fn if spec_fn is not None else self.ensemble.probs,
                images,
            )
        out = np.asarray(self.student.probs(images))
        n = int(out.shape[0])
        self._c_student_rows.inc(n)
        mask = self.escalation_mask(_referable(out))
        if spec_fut is not None:
            esc_all = np.asarray(spec_fut.result())
            self._c_speculated.inc(n)
            esc_n = int(mask.sum())
            self._c_speculated_wasted.inc(n - esc_n)
            note = getattr(self.ensemble, "note_escalated", None)
            if note is not None:
                note(esc_n)
            if mask.any():
                out = np.array(out)
                out[mask] = esc_all[mask]
                self._c_escalated_rows.inc(esc_n)
        elif mask.any():
            out = np.array(out)
            esc = np.asarray(self.ensemble.probs(images[mask]))
            out[mask] = esc
            self._c_escalated_rows.inc(int(mask.sum()))
        return out, mask

    def probs(self, images: np.ndarray) -> np.ndarray:
        """The cascade's row contract (MicroBatcher-compatible): row i
        of the output is row i's score — the student's, or the full
        ensemble's when the student landed in the escalation band."""
        out, mask = self._probs_masked(images)
        al = self.audit
        if al is not None:
            sgen = getattr(self.student, "_gen", None)
            al.record(
                images, out, engine=self.ensemble,
                generation=self.generation, escalated=mask,
                speculative=self.speculative,
                cascade={"student_dirs": list(sgen.member_dirs)
                         if sgen is not None else None},
            )
        q = self.quality
        if q is not None:
            # Drift windows see the MERGED distribution — the scores the
            # deployment serves; the canary rides the full cascade path
            # so a student/band regression trips it, not just an
            # ensemble one.
            q.observe(images, out)
            if q.canary_claim():
                q.run_canary(self._probs_raw)
        return out

    def make_batcher(self):
        """A MicroBatcher over the cascade under cfg.serve's coalescing
        knobs — the same construction ServingEngine.make_batcher uses,
        with the cascade's probs as the infer_fn."""
        from jama16_retina_tpu.serve.batcher import MicroBatcher

        size = self.cfg.model.image_size
        return MicroBatcher(
            self.probs,
            max_batch=self.cfg.serve.max_batch,
            max_wait_ms=self.cfg.serve.max_wait_ms,
            row_shape=(size, size, 3),
            row_dtype=np.uint8,
            registry=self.registry,
            shed_queue_depth=self.cfg.serve.shed_queue_depth,
            shed_in_flight=self.cfg.serve.shed_in_flight,
            default_deadline_ms=self.cfg.serve.default_deadline_ms,
        )

    # -- lifecycle delegation ----------------------------------------------
    # A drift-triggered retrain replaces the EXPENSIVE model: reload/
    # rollback land on the stacked ensemble while the student keeps
    # serving the cheap path (the controller unwraps a CascadeEngine to
    # its ensemble half; the student is retrained offline via
    # train.distill_from against the new ensemble and swapped by
    # constructing a fresh cascade).

    @property
    def generation(self) -> int:
        return self.ensemble.generation

    def reload(self, member_dirs=None, *, state=None) -> dict:
        return self.ensemble.reload(member_dirs, state=state)

    def rollback(self) -> dict:
        return self.ensemble.rollback()

    def release_retained(self) -> None:
        self.ensemble.release_retained()

    def close(self) -> None:
        """Stop the speculation thread (idempotent). The student and
        ensemble engines stay open — their lifecycle belongs to
        whoever constructed them, same as reload/rollback ownership."""
        pool, self._spec_pool = self._spec_pool, None
        if pool is not None:
            pool.shutdown(wait=True)

    # -- the go-live gate ---------------------------------------------------

    def gate(self, images: "np.ndarray | None" = None,
             grades: "np.ndarray | None" = None) -> list:
        """The named GateVerdicts a cascade config must pass before it
        serves (the PR-8 gate vocabulary, applied to the cascade-vs-
        ensemble comparison):

          * ``golden_canary`` — cascade scores on the pinned golden set
            within ``lifecycle.gate_canary_max_dev`` of the reference
            (skipped, loudly, when no canary is configured/pinned);
          * ``auc_floor`` — on labeled rows, cascade AUC >= full-
            ensemble AUC - ``lifecycle.gate_auc_floor_delta``, and
            sensitivity/specificity at every operating threshold within
            the same delta (the operating-point parity half); skipped
            when no labeled rows are provided.
        """
        from jama16_retina_tpu.lifecycle.controller import GateVerdict

        verdicts = [self._gate_golden_canary(GateVerdict)]
        verdicts.append(
            self._gate_auc_floor(GateVerdict, images, grades)
        )
        return verdicts

    def _gate_golden_canary(self, GateVerdict):
        # The cascade's own monitor (the predict.py wiring) carries the
        # pinned canary when one is injected; a bare cascade over a
        # quality-enabled ensemble engine falls back to that engine's.
        q = (self.quality if self.quality is not None
             else getattr(self.ensemble, "quality", None))
        canary = q.canary if q is not None else None
        if canary is None or canary.reference is None:
            return GateVerdict(
                name="golden_canary", passed=True, skipped=True,
                detail="no canary artifact configured/pinned",
            )
        scores = _referable(self._probs_raw(canary.images))
        ref = _referable(canary.reference)
        if scores.shape != ref.shape:
            return GateVerdict(
                name="golden_canary", passed=False,
                detail=f"score shape {scores.shape} vs pinned {ref.shape}",
            )
        dev = float(np.max(np.abs(scores - ref)))
        thr = float(self.cfg.lifecycle.gate_canary_max_dev)
        return GateVerdict(
            name="golden_canary", passed=dev <= thr, value=dev,
            threshold=thr,
        )

    def _gate_auc_floor(self, GateVerdict, images, grades):
        if images is None or grades is None:
            return GateVerdict(
                name="auc_floor", passed=True, skipped=True,
                detail="no labeled rows provided to score",
            )
        labels = (np.asarray(grades) >= 2).astype(np.float64)
        if not (0.0 < labels.mean() < 1.0):
            return GateVerdict(
                name="auc_floor", passed=True, skipped=True,
                detail="gate rows are single-class; AUC undefined",
            )
        casc = _referable(self._probs_raw(images))
        full = _referable(self.ensemble.probs(images))
        auc_casc = metrics.roc_auc(labels, casc)
        auc_full = metrics.roc_auc(labels, full)
        delta = float(self.cfg.lifecycle.gate_auc_floor_delta)
        # Operating-point parity: at every cascade threshold the
        # decisions' sensitivity/specificity must track the full
        # ensemble within the same delta — AUC alone can hide a local
        # swap exactly at the screening thresholds. (Both classes are
        # non-empty here: the single-class case skipped above.)
        op_ok, op_detail = True, []
        for thr in self.thresholds:
            cm_c = metrics.confusion_at_threshold(labels, casc, thr)
            cm_f = metrics.confusion_at_threshold(labels, full, thr)
            op_ok &= (
                cm_c["sensitivity"] >= cm_f["sensitivity"] - delta
                and cm_c["specificity"] >= cm_f["specificity"] - delta
            )
            op_detail.append(
                f"thr={thr:g}: sens {cm_c['sensitivity']:.4f} vs "
                f"{cm_f['sensitivity']:.4f}, spec "
                f"{cm_c['specificity']:.4f} vs {cm_f['specificity']:.4f}"
            )
        return GateVerdict(
            name="auc_floor",
            passed=bool(auc_casc >= auc_full - delta) and bool(op_ok),
            value=float(auc_casc), threshold=float(auc_full - delta),
            detail=f"full_auc={auc_full:.6f}; " + "; ".join(op_detail),
        )

    def go_live(self, images: "np.ndarray | None" = None,
                grades: "np.ndarray | None" = None) -> list:
        """Run the gates; raise typed :class:`CascadeRejected` naming
        every failing verdict, else return the verdicts (journal-ready
        ``as_dict`` rows). A cascade config that cannot prove operating-
        point parity never serves."""
        verdicts = self.gate(images, grades)
        failed = [v for v in verdicts if not v.passed]
        if failed:
            raise CascadeRejected(
                "cascade refused at go-live: "
                + "; ".join(
                    f"{v.name} (value={v.value}, threshold="
                    f"{v.threshold}, {v.detail})"
                    for v in failed
                )
            )
        absl_logging.info(
            "cascade live: band %.4g around thresholds %s (%s)",
            self.band, self.thresholds,
            ", ".join(
                f"{v.name}={'skip' if v.skipped else 'pass'}"
                for v in verdicts
            ),
        )
        return verdicts
