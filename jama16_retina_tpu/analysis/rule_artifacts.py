"""graftlint rule ``artifacts``: the durable-write contract (ISSUE 13).

``integrity/artifact.py`` is the ONE place durable bytes may reach
disk: its sealed writer carries the atomic tmp+fsync+rename discipline,
the content checksum, and the ``integrity.write`` chaos seams. A
hand-rolled write anywhere else silently opts out of all three — the
exact drift that left ten artifact formats with ten atomicity
conventions before ISSUE 13. This rule makes the discipline a
machine-checked contract like locks/purity:

  * ``artifacts.bare-replace``   — ``os.replace``/``os.rename`` calls
    (publishing or moving a file without the shared seam);
  * ``artifacts.bare-json-dump`` — ``json.dump`` to a file handle
    (use ``artifact.write_sealed_json`` or ``artifact.write_json``);
  * ``artifacts.bare-binary-dump`` — ``np.save``/``np.savez``/
    ``np.savez_compressed``/``pickle.dump`` straight to disk (use
    ``artifact.atomic_write_bytes`` + a seal sidecar).

Scope: the package + scripts + entry scripts (the lint corpus), MINUS
``integrity/artifact.py`` itself. Checkpoint I/O through orbax is
invisible here by construction (orbax owns its own atomicity).
Intentional exceptions go in ``.graftlint.json`` with a justification
— the acceptance bar is <= 3.
"""

from __future__ import annotations

import ast

from jama16_retina_tpu.analysis import core

_OWNER_SUFFIX = "integrity/artifact.py"

# dotted-call suffixes -> finding code
_REPLACE_CALLS = {"os.replace", "os.rename"}
_JSON_CALLS = {"json.dump"}
_BINARY_TAILS = {"save", "savez", "savez_compressed", "dump"}
_BINARY_RECEIVERS = {"np", "numpy", "pickle"}


class ArtifactsRule:
    name = "artifacts"

    def run(self, corpus: "core.Corpus") -> list:
        findings: list = []
        for pf in corpus.py:
            if pf.rel.replace("\\", "/").endswith(_OWNER_SUFFIX):
                continue
            for node in ast.walk(pf.tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = core.dotted(node.func)
                if not fn:
                    continue
                code = self._classify(fn)
                if code is None:
                    continue
                scope = core.scope_of(node)
                findings.append(core.Finding(
                    rule=self.name, code=code, path=pf.rel,
                    line=node.lineno,
                    message=(
                        f"durable write via {fn}() outside "
                        "integrity/artifact.py — it skips the sealed "
                        "atomic-write discipline (tmp+fsync+rename, "
                        "content checksum, integrity.write chaos "
                        "seams); route through artifact.write_sealed_"
                        "json / write_json / atomic_write_bytes / "
                        "rename, or suppress with a justification in "
                        ".graftlint.json"
                    ),
                    key=f"{pf.rel}::{scope}.{fn}",
                ))
        return findings

    @staticmethod
    def _classify(fn: str) -> "str | None":
        parts = fn.split(".")
        tail2 = ".".join(parts[-2:])
        if tail2 in _REPLACE_CALLS:
            return "artifacts.bare-replace"
        if tail2 in _JSON_CALLS:
            return "artifacts.bare-json-dump"
        if (len(parts) >= 2 and parts[-1] in _BINARY_TAILS
                and parts[-2] in _BINARY_RECEIVERS):
            return "artifacts.bare-binary-dump"
        return None
