"""graftlint reporters: text and ``--json`` over one finding list.

Exit-code contract (the CI API): 0 clean, 1 findings, 2 internal
error. The JSON shape is stable: ``{"root", "rules", "findings":
[{rule, code, path, line, message, key}], "counts": {code: n}}``.
"""

from __future__ import annotations

import json


def render_text(findings: list, rules: list) -> str:
    lines = [f.render() for f in findings]
    if findings:
        counts: dict[str, int] = {}
        for f in findings:
            counts[f.code] = counts.get(f.code, 0) + 1
        summary = ", ".join(f"{c} {code}" for code, c in sorted(
            counts.items()))
        lines.append("")
        lines.append(
            f"graftlint: {len(findings)} finding"
            f"{'s' if len(findings) != 1 else ''} ({summary})"
        )
    else:
        lines.append(
            f"graftlint: clean ({', '.join(r.name for r in rules)})"
        )
    return "\n".join(lines)


def render_json(findings: list, rules: list, root: str) -> str:
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.code] = counts.get(f.code, 0) + 1
    return json.dumps(
        {
            "root": root,
            "rules": [r.name for r in rules],
            "findings": [f.as_dict() for f in findings],
            "counts": counts,
        },
        indent=1, sort_keys=True,
    )
