"""graftlint rule ``locks``: lock discipline in threaded classes
(ISSUE 9).

The stack's shared-state classes (MicroBatcher worker vs submitters,
Snapshotter flush thread, ``ServingEngine.reload`` vs the request
path, the lifecycle ``--watch`` supervisor) rely on a convention no
tool verifies: state that is lock-guarded is ALWAYS lock-guarded.
This rule checks exactly that, per class:

  * a class OWNS a lock when some method assigns
    ``self.X = threading.Lock()/RLock()/Condition()``;
  * an attribute is GUARDED when any non-``__init__`` method writes it
    inside a ``with self.<lockfield>:`` block (or inside a method
    whose name ends in ``_locked`` — the caller-holds-the-lock
    convention);
  * a write to a guarded attribute OUTSIDE any lock block, in any
    method except ``__init__``/``__post_init__`` (construction
    happens-before publication) and ``*_locked`` helpers, is a
    finding.

The shape is deliberately low-noise: attributes that are never
lock-guarded anywhere are not judged (plenty of single-writer fields
are legitimately lock-free), but an attribute the class itself says
needs the lock must never be torn by a bare write on another thread's
entry path. Intentional exceptions (e.g. a setup method documented as
single-threaded) go in ``.graftlint.json`` with a justification.
"""

from __future__ import annotations

import ast

from jama16_retina_tpu.analysis import core

LOCK_TYPES = ("Lock", "RLock", "Condition")

_CTOR_METHODS = ("__init__", "__post_init__")


def _lock_fields(cls: ast.ClassDef) -> set:
    """self attributes assigned a threading.Lock/RLock/Condition."""
    fields: set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        if not isinstance(v, ast.Call):
            continue
        fn = core.dotted(v.func) or ""
        if fn.split(".")[-1] not in LOCK_TYPES:
            continue
        for t in node.targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                fields.add(t.attr)
    return fields


def _self_attr_of_target(target) -> "str | None":
    """The self attribute a single assignment target writes (directly,
    or through a subscript on it — ``self.d[k] = v`` mutates ``d``)."""
    node = target
    while isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _writes_in(node, under_lock: bool, lock_fields: set, out: list) -> None:
    """Recursively collect (attr, lineno, under_lock) writes, tracking
    ``with self.<lock>:`` nesting lexically."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # nested defs have their own thread semantics
        locked = under_lock
        if isinstance(child, ast.With):
            for item in child.items:
                ctx = item.context_expr
                if (isinstance(ctx, ast.Attribute)
                        and isinstance(ctx.value, ast.Name)
                        and ctx.value.id == "self"
                        and ctx.attr in lock_fields):
                    locked = True
        targets = []
        if isinstance(child, ast.Assign):
            targets = list(child.targets)
        elif isinstance(child, (ast.AugAssign, ast.AnnAssign)):
            targets = [child.target]
        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for e in elts:
                attr = _self_attr_of_target(e)
                if attr is not None:
                    out.append((attr, child.lineno, locked))
        _writes_in(child, locked, lock_fields, out)


class LocksRule:
    name = "locks"

    def run(self, corpus: "core.Corpus") -> list:
        findings: list = []
        for pf in corpus.py:
            for node in ast.walk(pf.tree):
                if isinstance(node, ast.ClassDef):
                    findings.extend(self._check_class(pf, node))
        return findings

    def _check_class(self, pf, cls: ast.ClassDef) -> list:
        lock_fields = _lock_fields(cls)
        if not lock_fields:
            return []
        # (method, attr, lineno, under_lock) for every self-write.
        writes = []
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            method_writes: list = []
            _writes_in(stmt, False, lock_fields, method_writes)
            for attr, lineno, locked in method_writes:
                writes.append((stmt.name, attr, lineno, locked))
        guarded: set[str] = set()
        for method, attr, _lineno, locked in writes:
            if method in _CTOR_METHODS or attr in lock_fields:
                continue
            if locked or method.endswith("_locked"):
                guarded.add(attr)
        findings = []
        for method, attr, lineno, locked in writes:
            if (attr not in guarded or locked
                    or method in _CTOR_METHODS
                    or method.endswith("_locked")):
                continue
            findings.append(core.Finding(
                rule=self.name, code="locks.unguarded-write",
                path=pf.rel, line=lineno,
                message=(f"{cls.name}.{method} writes self.{attr} without "
                         f"holding the lock, but {cls.name} guards that "
                         "attribute elsewhere (written under "
                         f"`with self.<{'/'.join(sorted(lock_fields))}>`); "
                         "a cross-thread entry path through here can "
                         "tear it — take the lock, or suppress with a "
                         "justification in .graftlint.json"),
                key=f"{pf.rel}::{cls.name}.{method}.{attr}",
            ))
        return findings
