"""graftlint rule ``faults``: the fault-site contract (ISSUE 9).

``obs/faultinject.py`` owns the canonical declared-site registry
(``SITES``: name -> one-line docstring). This rule pins three
populations to it so ``bench --chaos`` and docs/RELIABILITY.md's
failure matrix can never drift from the code:

  * FIRED — literal site names at ``faultinject.check("…")`` /
    ``faultinject.corrupt("…", …)`` seams;
  * ARMED — literal site keys in plan specs handed to ``arm()`` /
    ``plan_from_spec()`` (dict literals and inline JSON strings);
  * DOCUMENTED — site-shaped backtick spans in RELIABILITY.md's
    fault-injection section, plus JSON spec keys in its fenced code
    blocks.

Every fired/armed/documented site must be declared; every declared
site must be fired by at least one real seam (a site nothing calls is
a chaos plan that silently never injects — the one failure mode a
fault harness must not have) and documented in RELIABILITY.md.
"""

from __future__ import annotations

import ast
import json
import re

from jama16_retina_tpu.analysis import core

_SITE_SPAN_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")

_DOC_SECTION = "fault injection"


def declared_sites(pf) -> "dict[str, int] | None":
    """{site: lineno} from the module-level ``SITES`` dict literal;
    None when the module declares no registry."""
    for node in pf.tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "SITES"
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            out = {}
            for k in node.value.keys:
                lit = core.literal_str(k) if k is not None else None
                if lit is not None:
                    out[lit] = k.lineno
            return out
    return None


def _fired_sites(corpus, registry_rel) -> list:
    """[(rel, lineno, site | None)] for every check/corrupt seam."""
    out = []
    for pf in corpus.py:
        if pf.rel == registry_rel:
            continue
        # Bare-name imports: from ...faultinject import check, corrupt
        bare = set()
        for node in ast.walk(pf.tree):
            if (isinstance(node, ast.ImportFrom) and node.module
                    and node.module.split(".")[-1] == "faultinject"):
                for a in node.names:
                    if a.name in ("check", "corrupt"):
                        bare.add(a.asname or a.name)
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            is_seam = False
            if (isinstance(fn, ast.Attribute)
                    and fn.attr in ("check", "corrupt")):
                recv = core.dotted(fn.value) or ""
                is_seam = recv.split(".")[-1] == "faultinject"
            elif isinstance(fn, ast.Name) and fn.id in bare:
                is_seam = True
            if not is_seam:
                continue
            site = (core.literal_str(node.args[0]) if node.args else None)
            out.append((pf.rel, node.lineno, site))
    return out


def _armed_sites(corpus, registry_rel) -> list:
    """[(rel, lineno, site)] for literal spec keys at arm() /
    plan_from_spec() call sites (dict literals and JSON strings)."""
    out = []
    for pf in corpus.py:
        if pf.rel == registry_rel:
            continue
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = core.dotted(node.func) or ""
            if fn.split(".")[-1] not in ("arm", "plan_from_spec"):
                continue
            if not node.args:
                continue
            arg = node.args[0]
            keys: list[str] = []
            if isinstance(arg, ast.Dict):
                keys = [core.literal_str(k) for k in arg.keys
                        if k is not None]
                keys = [k for k in keys if k]
            elif (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                try:
                    doc = json.loads(arg.value)
                    if isinstance(doc, dict):
                        keys = list(doc)
                except json.JSONDecodeError:
                    pass
            for k in keys:
                out.append((pf.rel, node.lineno, k))
    return out


def _documented_sites(corpus) -> list:
    """[(rel, lineno, site)] from RELIABILITY.md: site-shaped backtick
    spans inside the fault-injection section, and JSON object keys in
    fenced code blocks anywhere in the doc."""
    found = corpus.doc_named("RELIABILITY.md")
    if found is None:
        return []
    rel, text = found
    out = []
    in_section = False
    in_fence = False
    for lineno, line in enumerate(text.splitlines(), start=1):
        if line.startswith("```"):
            in_fence = not in_fence
            continue
        if in_fence:
            for m in re.finditer(r'"([a-z0-9_.]+)"\s*:\s*\{', line):
                if _SITE_SPAN_RE.match(m.group(1)):
                    out.append((rel, lineno, m.group(1)))
            continue
        if line.startswith("## "):
            in_section = _DOC_SECTION in line.lower()
            continue
        if not in_section:
            continue
        for span in re.findall(r"`([^`]+)`", line):
            if _SITE_SPAN_RE.match(span):
                out.append((rel, lineno, span))
    return out


class FaultsRule:
    name = "faults"

    def __init__(self, registry_suffix: str = "faultinject.py"):
        self.registry_suffix = registry_suffix

    def run(self, corpus: "core.Corpus") -> list:
        findings: list = []
        reg_pf = corpus.find_py(self.registry_suffix)
        if reg_pf is None:
            return findings  # fixture corpus without the subsystem
        sites = declared_sites(reg_pf)
        if sites is None:
            findings.append(core.Finding(
                rule=self.name, code="faults.no-registry",
                path=reg_pf.rel, line=1,
                message=("no module-level SITES dict literal — the "
                         "canonical declared-site registry is missing"),
                key="faults::registry",
            ))
            return findings
        fired = _fired_sites(corpus, reg_pf.rel)
        for rel, lineno, site in fired:
            if site is None:
                findings.append(core.Finding(
                    rule=self.name, code="faults.non-literal-site",
                    path=rel, line=lineno,
                    message=("fault seam site name is not a string "
                             "literal; the declared-site contract cannot "
                             "see it"),
                    key=f"{rel}::faultseam",
                ))
            elif core.WILDCARD not in site and site not in sites:
                findings.append(core.Finding(
                    rule=self.name, code="faults.unknown-site",
                    path=rel, line=lineno,
                    message=(f"fault site {site!r} is fired here but not "
                             "declared in faultinject.SITES — bench "
                             "--chaos could never arm it by its real "
                             "name"),
                    key=f"site::{site}",
                ))
        for rel, lineno, site in _armed_sites(corpus, reg_pf.rel):
            if site not in sites:
                findings.append(core.Finding(
                    rule=self.name, code="faults.unknown-site",
                    path=rel, line=lineno,
                    message=(f"fault plan arms site {site!r}, which is "
                             "not declared in faultinject.SITES — the "
                             "plan would silently never fire"),
                    key=f"site::{site}",
                ))
        documented = _documented_sites(corpus)
        for rel, lineno, site in documented:
            if site not in sites:
                findings.append(core.Finding(
                    rule=self.name, code="faults.doc-unknown-site",
                    path=rel, line=lineno,
                    message=(f"RELIABILITY.md documents fault site "
                             f"{site!r}, which is not declared in "
                             "faultinject.SITES"),
                    key=f"site::{site}",
                ))
        fired_names = {s for _, _, s in fired if s}
        doc_names = {s for _, _, s in documented}
        for site, lineno in sorted(sites.items()):
            if site not in fired_names:
                findings.append(core.Finding(
                    rule=self.name, code="faults.never-fired",
                    path=reg_pf.rel, line=lineno,
                    message=(f"declared fault site {site!r} has no "
                             "check()/corrupt() seam anywhere in the "
                             "lint scope — a site nothing calls never "
                             "injects"),
                    key=f"site::{site}",
                ))
            if doc_names and site not in doc_names:
                findings.append(core.Finding(
                    rule=self.name, code="faults.undocumented-site",
                    path=reg_pf.rel, line=lineno,
                    message=(f"declared fault site {site!r} is absent "
                             "from RELIABILITY.md's fault-injection "
                             "section"),
                    key=f"site::{site}",
                ))
        return findings
