"""graftlint rule ``config``: the config-knob and alert-grammar
contract (ISSUE 9).

Half one — dead/undocumented knobs: every dataclass field reachable
from the root config class in configs.py must be

  * READ somewhere outside configs.py (an ``x.<field>`` attribute load
    or a literal ``getattr(x, "<field>")``) — a knob nothing consumes
    is a lie in the CLI surface; and
  * NAMED in README.md or docs/*.md — a knob an operator cannot
    discover is configuration by code-reading.

The consumer check is name-based (vulture-style): a field is "alive"
if ANY attribute read in scope uses its name. That is deliberately
conservative — cross-section name collisions can mask a dead knob, but
the check never cries wolf on a live one.

Half two — alert/watch rule strings: every literal rule string in
code, docs, and the config defaults must parse COMPLETELY under
``obs/alerts.py``'s grammar (the real parser is imported — one
grammar, zero drift), with the context rules applied: strings bound to
``watch_rules`` (the lifecycle WATCH probe is stateless) may use
neither ``rate()`` (needs snapshot history) nor ``for N`` (latching
semantics the probe would silently drop). Doc spans are pre-filtered
to comparison-shaped backtick spans so prose never false-positives.
"""

from __future__ import annotations

import ast
import re

from jama16_retina_tpu.analysis import core

# Field-name contexts that carry alert-grammar rule strings, and the
# grammar context each implies.
RULE_FIELDS = {"alert_rules": "alert", "watch_rules": "watch"}

# A doc backtick span that is meant to be a rule: metric-ish token,
# comparison operator, numeric threshold.
_DOC_RULE_RE = re.compile(
    r"^(?:rate\()?[A-Za-z_][A-Za-z0-9_.]*\)?\s*(?:>=|<=|==|!=|>|<)\s*"
    r"[-+]?[0-9.]"
)

_WORD_RE = re.compile(r"[A-Za-z0-9_]+")

ROOT_CLASSES = ("ExperimentConfig", "Config")


def _dataclass_fields(tree: ast.AST) -> "dict[str, list]":
    """{class_name: [(field, annotation_src, default_node, lineno)]}
    for every @dataclass in the module."""
    out: dict[str, list] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        deco = [core.dotted(d.func) if isinstance(d, ast.Call)
                else core.dotted(d) for d in node.decorator_list]
        if not any(d and d.split(".")[-1] == "dataclass" for d in deco):
            continue
        fields = []
        for stmt in node.body:
            if (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)):
                fields.append((
                    stmt.target.id, ast.unparse(stmt.annotation),
                    stmt.value, stmt.lineno,
                ))
        out[node.name] = fields
    return out


def _reachable(classes: "dict[str, list]") -> "list[tuple[str, tuple]]":
    """[(class_name, field_tuple)] for every field of every dataclass
    reachable from the root class through field annotations."""
    root = next((r for r in ROOT_CLASSES if r in classes), None)
    if root is None:
        return []
    seen, queue, out = {root}, [root], []
    while queue:
        cls = queue.pop(0)
        for f in classes[cls]:
            out.append((cls, f))
            for name in _WORD_RE.findall(f[1]):
                if name in classes and name not in seen:
                    seen.add(name)
                    queue.append(name)
    return out


def _attribute_reads(corpus: "core.Corpus", skip_rel: str) -> set:
    """Every attribute name read (plus literal getattr names) anywhere
    in scope outside the configs module."""
    reads: set[str] = set()
    for pf in corpus.py:
        if pf.rel == skip_rel:
            continue
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Attribute) and isinstance(
                    node.ctx, ast.Load):
                reads.add(node.attr)
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "getattr" and len(node.args) >= 2):
                lit = core.literal_str(node.args[1])
                if lit:
                    reads.add(lit)
    return reads


def _doc_words(corpus: "core.Corpus") -> set:
    words: set[str] = set()
    for text in corpus.docs.values():
        words.update(_WORD_RE.findall(text))
    return words


def check_rule_string(text: str, context: str) -> "str | None":
    """None = fine; else the violation message. ``context`` is
    "alert" (full grammar) or "watch" (stateless probe: no rate(),
    no for-latching)."""
    from jama16_retina_tpu.obs import alerts as alerts_lib

    try:
        rule = alerts_lib.parse_rule(text)
    except ValueError as e:
        return str(e)
    if context == "watch":
        if rule.metric.startswith("rate("):
            return ("rate() needs snapshot history; the stateless "
                    "watch_rules probe has none (rejected at controller "
                    "construction)")
        if rule.for_seconds:
            return ("'for N' latches over successive evaluations; the "
                    "stateless watch_rules probe would turn it into "
                    "fire-on-first-sample")
    return None


def _tuple_strs(node) -> list:
    """Literal strings inside a tuple/list expression node."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return []
    out = []
    for elt in node.elts:
        if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
            out.append(elt.value)
    return out


class ConfigRule:
    name = "config"

    def __init__(self, configs_suffix: str = "configs.py"):
        self.configs_suffix = configs_suffix

    def run(self, corpus: "core.Corpus") -> list:
        findings: list = []
        cfg_pf = corpus.find_py(self.configs_suffix)
        if cfg_pf is not None:
            findings.extend(self._check_knobs(corpus, cfg_pf))
            findings.extend(self._check_config_rule_strings(cfg_pf))
        findings.extend(self._check_code_rule_strings(corpus, cfg_pf))
        findings.extend(self._check_doc_rule_strings(corpus))
        return findings

    def _check_knobs(self, corpus, cfg_pf) -> list:
        findings: list = []
        classes = _dataclass_fields(cfg_pf.tree)
        reads = _attribute_reads(corpus, cfg_pf.rel)
        doc_words = _doc_words(corpus)
        for cls, (field, _ann, _default, lineno) in _reachable(classes):
            if field not in reads:
                findings.append(core.Finding(
                    rule=self.name, code="config.dead-knob",
                    path=cfg_pf.rel, line=lineno,
                    message=(f"{cls}.{field} is never read outside "
                             f"{cfg_pf.rel} — a knob nothing consumes; "
                             "wire it or delete it"),
                    key=f"knob::{cls}.{field}",
                ))
            if corpus.docs and field not in doc_words:
                findings.append(core.Finding(
                    rule=self.name, code="config.undocumented-knob",
                    path=cfg_pf.rel, line=lineno,
                    message=(f"{cls}.{field} is named nowhere in "
                             "README.md or docs/ — operators cannot "
                             "discover it"),
                    key=f"knob::{cls}.{field}",
                ))
        return findings

    def _check_config_rule_strings(self, cfg_pf) -> list:
        """Defaults of alert_rules/watch_rules fields in configs."""
        findings: list = []
        for node in ast.walk(cfg_pf.tree):
            if (isinstance(node, ast.AnnAssign)
                    and isinstance(node.target, ast.Name)
                    and node.target.id in RULE_FIELDS
                    and node.value is not None):
                ctx = RULE_FIELDS[node.target.id]
                for text in _tuple_strs(node.value):
                    findings.extend(self._rule_finding(
                        cfg_pf.rel, node.lineno, text, ctx
                    ))
        return findings

    def _check_code_rule_strings(self, corpus, cfg_pf) -> list:
        """Keyword args named alert_rules/watch_rules and literal
        parse_rule(...) arguments anywhere in scope."""
        findings: list = []
        for pf in corpus.py:
            if cfg_pf is not None and pf.rel == cfg_pf.rel:
                continue
            for node in ast.walk(pf.tree):
                if not isinstance(node, ast.Call):
                    continue
                for kw in node.keywords:
                    if kw.arg in RULE_FIELDS:
                        ctx = RULE_FIELDS[kw.arg]
                        for text in _tuple_strs(kw.value):
                            findings.extend(self._rule_finding(
                                pf.rel, node.lineno, text, ctx
                            ))
                fn = core.dotted(node.func) or ""
                if fn.split(".")[-1] == "parse_rule" and node.args:
                    text = core.literal_str(node.args[0])
                    if text is not None and core.WILDCARD not in text:
                        findings.extend(self._rule_finding(
                            pf.rel, node.lineno, text, "alert"
                        ))
        return findings

    def _check_doc_rule_strings(self, corpus) -> list:
        findings: list = []
        for rel, text in sorted(corpus.docs.items()):
            for lineno, line in enumerate(text.splitlines(), start=1):
                for span in re.findall(r"`([^`]+)`", line):
                    if not _DOC_RULE_RE.match(span):
                        continue
                    ctx = "watch" if "watch_rules" in line else "alert"
                    findings.extend(self._rule_finding(
                        rel, lineno, span, ctx
                    ))
        return findings

    def _rule_finding(self, rel, lineno, text, ctx) -> list:
        why = check_rule_string(text, ctx)
        if why is None:
            return []
        code = ("config.watch-context" if ctx == "watch"
                and "probe" in why else "config.alert-grammar")
        return [core.Finding(
            rule=self.name, code=code, path=rel, line=lineno,
            message=f"rule string {text!r}: {why}",
            key=f"{rel}::rule::{text}",
        )]
