"""graftlint rule ``pytest-marks``: test-marker hygiene (ISSUE 9
satellite).

Every ``@pytest.mark.<name>`` used under tests/ must be registered in
pytest.ini's ``markers`` section. pytest only warns on unknown marks —
which means a typo'd tier marker (``@pytest.mark.quik``) silently
drops a test from every ``-m`` selection, the exact failure mode the
curated quick tier cannot afford. Built-in marks (parametrize, skipif,
…) are allowlisted.
"""

from __future__ import annotations

import ast
import configparser

from jama16_retina_tpu.analysis import core

BUILTIN_MARKS = frozenset({
    "parametrize", "skip", "skipif", "xfail", "usefixtures",
    "filterwarnings",
})


def registered_marks(pytest_ini: str) -> "set | None":
    """Marker names from pytest.ini's [pytest] markers value; None when
    the file has no markers section to check against."""
    cp = configparser.ConfigParser()
    try:
        cp.read_string(pytest_ini)
    except configparser.Error:
        return None
    for section in ("pytest", "tool:pytest"):
        if cp.has_option(section, "markers"):
            names = set()
            for line in cp.get(section, "markers").splitlines():
                line = line.strip()
                if line:
                    names.add(line.split(":")[0].split("(")[0].strip())
            return names
    return None


class PytestMarksRule:
    name = "pytest-marks"

    def run(self, corpus: "core.Corpus") -> list:
        if corpus.pytest_ini is None or not corpus.tests:
            return []
        registered = registered_marks(corpus.pytest_ini)
        if registered is None:
            return [core.Finding(
                rule=self.name, code="pytest-marks.no-markers-section",
                path="pytest.ini", line=0,
                message=("pytest.ini has no [pytest] markers section; "
                         "marks cannot be validated"),
                key="pytest::markers-section",
            )]
        findings: list = []
        seen: set[str] = set()
        for pf in corpus.tests:
            for node in ast.walk(pf.tree):
                if not (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Attribute)
                        and node.value.attr == "mark"
                        and isinstance(node.value.value, ast.Name)
                        and node.value.value.id == "pytest"):
                    continue
                mark = node.attr
                if mark in BUILTIN_MARKS or mark in registered:
                    continue
                if mark in seen:
                    continue
                seen.add(mark)
                findings.append(core.Finding(
                    rule=self.name, code="pytest-marks.unregistered-mark",
                    path=pf.rel, line=node.lineno,
                    message=(f"@pytest.mark.{mark} is not registered in "
                             "pytest.ini [pytest] markers — pytest only "
                             "warns, and a typo'd tier mark silently "
                             "drops tests from -m selections"),
                    key=f"mark::{mark}",
                ))
        return findings
