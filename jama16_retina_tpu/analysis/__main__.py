"""graftlint CLI: ``python -m jama16_retina_tpu.analysis [flags]``.

Exit codes: 0 clean, 1 findings, 2 internal error (the contract
scripts/ci_checks.sh and tests/test_analysis.py pin).
"""

from __future__ import annotations

import argparse
import os
import sys
import traceback

from jama16_retina_tpu import analysis
from jama16_retina_tpu.analysis import core, report


def _detect_root(start: str) -> str:
    """Walk up from ``start`` to the directory containing the package
    (running from a subdir of the repo should still lint the repo)."""
    d = os.path.abspath(start)
    while True:
        if os.path.isdir(os.path.join(d, "jama16_retina_tpu")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return os.path.abspath(start)
        d = parent


def main(argv: "list | None" = None) -> int:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description=(analysis.__doc__ or "").splitlines()[0],
    )
    p.add_argument("--root", default="",
                   help="repo root (default: auto-detect from cwd)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    p.add_argument("--rules", default="",
                   help="comma-separated rule subset (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print rule names and exit 0")
    p.add_argument("--suppressions", default="",
                   help=f"suppression file (default: "
                        f"<root>/{core.SUPPRESSIONS_BASENAME})")
    p.add_argument("--baseline", default="",
                   help="accepted-findings file to subtract")
    p.add_argument("--write-baseline", default="",
                   help="write current findings as a baseline file, "
                        "then exit 0")
    args = p.parse_args(argv)

    rules = analysis.default_rules()
    if args.list_rules:
        for r in rules:
            print(r.name)
        return 0
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - {r.name for r in rules}
        if unknown:
            print(f"graftlint: unknown rule(s): {sorted(unknown)} "
                  f"(have: {[r.name for r in rules]})", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in wanted]
    try:
        root = args.root or _detect_root(os.getcwd())
        corpus = core.Corpus(root)
        if not corpus.py:
            # An empty corpus would make every rule vacuously pass — a
            # mis-pointed --root must be loud, never a silent clean.
            print(f"graftlint: no Python files found under {corpus.root} "
                  f"(expected a {corpus.package}/ package); wrong --root?",
                  file=sys.stderr)
            return 2
        baseline = (core.load_baseline(args.baseline)
                    if args.baseline else None)
        findings = core.run_rules(
            corpus, rules,
            suppressions_path=(args.suppressions or None),
            baseline=baseline,
        )
        if args.write_baseline:
            core.write_baseline(args.write_baseline, findings)
            print(f"graftlint: wrote {len(findings)} accepted finding(s) "
                  f"to {args.write_baseline}")
            return 0
        out = (report.render_json(findings, rules, corpus.root)
               if args.as_json else report.render_text(findings, rules))
        print(out)
        return 1 if findings else 0
    except Exception:  # noqa: BLE001 - the exit-2 contract
        traceback.print_exc()
        return 2


if __name__ == "__main__":
    sys.exit(main())
