"""graftlint — an AST-driven contract checker for this repo's
conventions (ISSUE 9).

Five hard rules over the package + scripts + entry scripts, each with
file:line findings and stable suppression keys:

  * ``metrics``  — registry-metric contract: literal ``layer.noun``
    names, help text, no kind/help conflicts, and a round-trip against
    the docs/OBSERVABILITY.md + docs/RELIABILITY.md glossary tables;
  * ``config``   — every config knob has a consumer and a doc mention;
    every literal alert/watch rule string parses under obs/alerts.py's
    grammar with the watch-context restrictions applied;
  * ``faults``   — every fault site fired, armed, or documented
    resolves to ``obs/faultinject.SITES`` (and every declared site is
    fired and documented);
  * ``artifacts`` — durable writes (``os.replace``, ``json.dump``,
    ``np.save``/``pickle.dump`` to disk) happen only through
    ``integrity/artifact.py``'s sealed atomic writer (ISSUE 13);
  * ``locks``    — lock-guarded attributes of threaded classes are
    never written bare;
  * ``purity``   — declared-deterministic scopes never call clocks or
    entropy sources directly (injected-clock parameters excepted);

plus the ``pytest-marks`` hygiene rule over tests/.

Run: ``python -m jama16_retina_tpu.analysis`` or
``python scripts/graftlint.py`` (``--json`` for machines; exit 0
clean / 1 findings / 2 internal error). Suppressions live in
``.graftlint.json`` at the repo root, one justification each.
"""

from __future__ import annotations

from jama16_retina_tpu.analysis.core import (  # noqa: F401
    Corpus,
    Finding,
    run_rules,
)
from jama16_retina_tpu.analysis.rule_artifacts import (  # noqa: F401
    ArtifactsRule,
)
from jama16_retina_tpu.analysis.rule_config import ConfigRule  # noqa: F401
from jama16_retina_tpu.analysis.rule_faults import FaultsRule  # noqa: F401
from jama16_retina_tpu.analysis.rule_locks import LocksRule  # noqa: F401
from jama16_retina_tpu.analysis.rule_metrics import MetricsRule  # noqa: F401
from jama16_retina_tpu.analysis.rule_purity import PurityRule  # noqa: F401
from jama16_retina_tpu.analysis.rule_pytest import (  # noqa: F401
    PytestMarksRule,
)


def default_rules() -> list:
    """The full rule set, in the order findings group best."""
    return [
        MetricsRule(),
        ConfigRule(),
        FaultsRule(),
        ArtifactsRule(),
        LocksRule(),
        PurityRule(),
        PytestMarksRule(),
    ]
