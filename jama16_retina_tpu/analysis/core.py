"""graftlint core: the corpus model, Finding shape, and runner.

ISSUE 9 tentpole — the contract checker for the conventions eight PRs
of growth now rest on. The stack's correctness invariants are mostly
*social* contracts: every registry metric carries help text and a
glossary row, every config knob has a consumer and a doc line, alert
rules parse, fault sites exist before ``bench --chaos`` fires them,
threaded classes keep their lock discipline, declared-deterministic
code stays pure. None of those are visible to the type checker or the
test suite until they break in production. graftlint makes each one a
machine-checked lint rule over the repo's own ASTs and docs — the
"machine-checkable dataflow contracts" operability lever the TF paper
credits (PAPERS.md), applied to a research codebase.

Design constraints:

  * ONE PARSE. Every rule reads the same ``Corpus`` — files are read
    and ``ast.parse``d exactly once, docs are read once — so the full
    repo lints in well under the 10 s budget the bench guard pins.
  * STABLE KEYS, NOT LINE NUMBERS. Every Finding carries a ``key``
    derived from names (file::Class.method.attr, metric::<name>, …),
    so suppressions and baselines survive unrelated edits.
  * SUPPRESSION IS LOUD. Each suppression entry in ``.graftlint.json``
    must carry a non-empty ``reason``; entries that no longer match
    anything are themselves findings — the suppression file can only
    shrink toward honesty, never silently rot.
  * EXIT CODES ARE THE API. 0 clean / 1 findings / 2 internal error —
    scripts/ci_checks.sh and test_lint_repo_clean consume nothing
    else (the ``--json`` reporter exists for humans and dashboards).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os


@dataclasses.dataclass(frozen=True)
class Finding:
    """One contract violation, pointing at a file:line.

    ``rule`` is the coarse rule name (the enable/disable unit);
    ``code`` the specific check (``metrics.help-missing``); ``key`` the
    stable suppression/baseline identity (no line numbers).
    """

    rule: str
    code: str
    path: str
    line: int
    message: str
    key: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.code}] {self.message}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


# The sentinel a dynamic fragment of a metric name canonicalizes to
# (f-string interpolations). Display form is "{*}" — the NUL char keeps
# canonical names unambiguous (no legal metric name contains it).
WILDCARD = "\x00"


def display_name(canonical: str) -> str:
    """Human/suppression form of a canonical (wildcarded) name."""
    return canonical.replace(WILDCARD, "{*}")


def literal_str(node) -> "str | None":
    """Resolve an AST expression to a string: plain constants verbatim,
    f-strings with every interpolated fragment collapsed to WILDCARD.
    None = not statically resolvable (a Name, a .format() call, …)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append(WILDCARD)
        return "".join(parts)
    return None


def dotted(node) -> "str | None":
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _annotate_scopes(tree: ast.AST) -> None:
    """Stamp every node with ``_graft_scope`` — the enclosing
    ``Class.method`` / function qualname / ``<module>`` — the stable
    half of every per-site suppression key."""

    def visit(node, scope):
        for child in ast.iter_child_nodes(node):
            child_scope = scope
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                child_scope = (
                    f"{scope}.{child.name}" if scope != "<module>"
                    else child.name
                )
            child._graft_scope = child_scope  # noqa: SLF001
            visit(child, child_scope)

    tree._graft_scope = "<module>"  # noqa: SLF001
    visit(tree, "<module>")


def scope_of(node) -> str:
    return getattr(node, "_graft_scope", "<module>")


class PyFile:
    """One parsed source file of the corpus."""

    def __init__(self, root: str, rel: str):
        self.rel = rel
        self.path = os.path.join(root, rel)
        with open(self.path, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=rel)
        _annotate_scopes(self.tree)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


# The top-level entry scripts the lint walk covers beside the package
# and scripts/ (the ISSUE 9 scope list).
TOP_LEVEL_FILES = ("bench.py", "train.py", "predict.py", "evaluate.py")


class Corpus:
    """Everything one lint run reads, loaded once and shared by every
    rule: the package + scripts + entry-point ASTs (``py``), the doc
    texts (``docs``: README.md + docs/*.md), the test ASTs (``tests``,
    used only by the pytest-marks rule), and pytest.ini."""

    def __init__(self, root: str, package: str = "jama16_retina_tpu",
                 scripts_dir: str = "scripts",
                 top_level: tuple = TOP_LEVEL_FILES,
                 tests_dir: str = "tests"):
        self.root = os.path.abspath(root)
        self.package = package
        self.py: list[PyFile] = []
        self.tests: list[PyFile] = []
        self.parse_errors: list[Finding] = []
        rels: list[str] = []
        pkg_dir = os.path.join(self.root, package)
        for base, dirs, files in os.walk(pkg_dir):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for f in sorted(files):
                if f.endswith(".py"):
                    rels.append(os.path.relpath(os.path.join(base, f),
                                                self.root))
        sdir = os.path.join(self.root, scripts_dir)
        if os.path.isdir(sdir):
            for f in sorted(os.listdir(sdir)):
                if f.endswith(".py"):
                    rels.append(os.path.join(scripts_dir, f))
        for f in top_level:
            if os.path.exists(os.path.join(self.root, f)):
                rels.append(f)
        for rel in rels:
            self._load(rel, self.py)
        tdir = os.path.join(self.root, tests_dir)
        if os.path.isdir(tdir):
            for f in sorted(os.listdir(tdir)):
                if f.endswith(".py"):
                    self._load(os.path.join(tests_dir, f), self.tests)
        self.docs: dict[str, str] = {}
        readme = os.path.join(self.root, "README.md")
        if os.path.exists(readme):
            self.docs["README.md"] = _read(readme)
        ddir = os.path.join(self.root, "docs")
        if os.path.isdir(ddir):
            for f in sorted(os.listdir(ddir)):
                if f.endswith(".md"):
                    self.docs[os.path.join("docs", f)] = _read(
                        os.path.join(ddir, f)
                    )
        ini = os.path.join(self.root, "pytest.ini")
        self.pytest_ini = _read(ini) if os.path.exists(ini) else None

    def _load(self, rel: str, into: list) -> None:
        try:
            into.append(PyFile(self.root, rel))
        except SyntaxError as e:
            self.parse_errors.append(Finding(
                rule="core", code="core.parse-error", path=rel,
                line=int(e.lineno or 0),
                message=f"cannot parse: {e.msg}", key=f"{rel}::parse",
            ))

    def find_py(self, suffix: str) -> "PyFile | None":
        """The scanned file whose repo-relative path ends with
        ``suffix`` (rules locate configs.py / faultinject.py this way,
        so fixture mini-repos can use any layout)."""
        for pf in self.py:
            if pf.rel.endswith(suffix):
                return pf
        return None

    def doc_named(self, basename: str) -> "tuple[str, str] | None":
        """(rel, text) of the doc with this basename, if present."""
        for rel, text in self.docs.items():
            if os.path.basename(rel) == basename:
                return rel, text
        return None


def _read(path: str) -> str:
    with open(path, encoding="utf-8") as f:
        return f.read()


# --- Suppressions ---------------------------------------------------------

SUPPRESSIONS_BASENAME = ".graftlint.json"


@dataclasses.dataclass(frozen=True)
class Suppression:
    code: str
    key: str
    reason: str

    def matches(self, f: Finding) -> bool:
        if self.key != f.key:
            return False
        return (self.code == f.code or self.code == f.rule
                or f.code.startswith(self.code + "."))


def load_suppressions(path: str) -> tuple[list[Suppression], list[Finding]]:
    """Parse the suppression file; malformed entries (and entries with
    no justification) come back as findings — a suppression that
    cannot say WHY it exists does not suppress anything."""
    sups: list[Suppression] = []
    findings: list[Finding] = []
    if not os.path.exists(path):
        return sups, findings
    rel = os.path.basename(path)
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        findings.append(Finding(
            rule="core", code="core.suppressions-unreadable", path=rel,
            line=0, message=f"cannot read suppression file: {e}",
            key="suppressions::file",
        ))
        return sups, findings
    for i, entry in enumerate(doc.get("suppressions", ())):
        code = str(entry.get("code", "")).strip()
        key = str(entry.get("key", "")).strip()
        reason = str(entry.get("reason", "")).strip()
        if not code or not key:
            findings.append(Finding(
                rule="core", code="core.suppression-malformed", path=rel,
                line=0,
                message=f"suppression #{i} needs both 'code' and 'key'",
                key=f"suppressions::entry{i}",
            ))
            continue
        if not reason:
            findings.append(Finding(
                rule="core", code="core.suppression-no-reason", path=rel,
                line=0,
                message=(f"suppression ({code!r}, {key!r}) carries no "
                         "justification; every suppression must say why"),
                key=f"suppressions::{code}::{key}",
            ))
            continue
        sups.append(Suppression(code=code, key=key, reason=reason))
    return sups, findings


def apply_suppressions(
    findings: list, sups: list, enabled_rules: "set | None" = None
) -> tuple[list, list]:
    """(kept findings, findings for suppressions that matched nothing).
    An unused suppression is reported so the file tracks reality —
    but only when the rule it suppresses actually ran (a --rules
    subset must not misreport the whole-set suppression file)."""
    kept: list[Finding] = []
    used = [False] * len(sups)
    for f in findings:
        hit = False
        for i, s in enumerate(sups):
            if s.matches(f):
                used[i] = True
                hit = True
        if not hit:
            kept.append(f)
    unused = []
    for i, s in enumerate(sups):
        if used[i]:
            continue
        if enabled_rules is not None \
                and s.code.split(".")[0] not in enabled_rules:
            continue
        unused.append(Finding(
            rule="core", code="core.suppression-unused",
            path=SUPPRESSIONS_BASENAME, line=0,
            message=(f"suppression ({s.code!r}, {s.key!r}) matched no "
                     "finding; delete it"),
            key=f"suppressions::unused::{s.code}::{s.key}",
        ))
    return kept, unused


# --- Baseline -------------------------------------------------------------

def load_baseline(path: str) -> set:
    """Accepted (code, key) pairs from a --write-baseline file."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    return {(e["code"], e["key"]) for e in doc.get("accepted", ())}


def write_baseline(path: str, findings: list) -> None:
    from jama16_retina_tpu.integrity import artifact as artifact_lib

    artifact_lib.write_json(
        path,
        {"accepted": [{"code": x.code, "key": x.key} for x in findings]},
        sort_keys=True, trailing_newline=True,
    )


# --- Runner ---------------------------------------------------------------

def run_rules(corpus: Corpus, rules, suppressions_path: "str | None" = None,
              baseline: "set | None" = None) -> list:
    """All enabled rules over one corpus; suppressions and baseline
    applied. Returns findings sorted by (path, line, code)."""
    findings: list[Finding] = list(corpus.parse_errors)
    for rule in rules:
        findings.extend(rule.run(corpus))
    if suppressions_path is None:
        suppressions_path = os.path.join(corpus.root, SUPPRESSIONS_BASENAME)
    sups, sup_findings = load_suppressions(suppressions_path)
    enabled = {r.name for r in rules} | {"core"}
    findings, unused = apply_suppressions(findings, sups, enabled)
    findings.extend(sup_findings)
    findings.extend(unused)
    if baseline:
        findings = [f for f in findings if (f.code, f.key) not in baseline]
    return sorted(findings, key=lambda f: (f.path, f.line, f.code, f.key))
