"""graftlint rule ``purity``: declared-deterministic code must stay
pure (ISSUE 9).

The repo leans on determinism pins — bit-identical autotune decisions,
journal resume idempotency, exact retry schedules — and determinism
only holds if purity is *enforced*, not assumed (the portable-
deterministic-pipelines paper in PAPERS.md makes the same point for
CNN inference). Scopes declared deterministic must not call wall
clocks or entropy sources directly: ``time.time``/``monotonic``/
``perf_counter``/``sleep``, ``random.*``, ``numpy.random.*``,
``os.urandom``, ``uuid.*``, ``datetime.now`` and friends.

The injected-clock escape is structural, not an allowlist: a call
through a parameter (``self._now()``, ``sleep(delay)`` where ``sleep``
is an argument defaulting to ``time.sleep``) never resolves to a
banned dotted name — referencing ``time.time`` as a default value is
fine, *calling* it inside the scope is not. That is exactly the
"inject the clock at the seam" pattern the journal and retry modules
use.

Declared scopes come from the rule's target list (module paths or
``module::function``) plus any function whose ``def`` line carries a
``# graftlint: deterministic`` pragma.
"""

from __future__ import annotations

import ast

from jama16_retina_tpu.analysis import core

BANNED = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.sleep",
    "os.urandom", "uuid.uuid1", "uuid.uuid4",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

BANNED_PREFIXES = ("random.", "numpy.random.", "secrets.")

# The declared-deterministic scopes of THIS repo (ISSUE 9): the
# autotuner's decision policy, the lifecycle journal, and the retry
# schedule. Fixture tests pass their own targets.
DEFAULT_TARGETS = (
    "jama16_retina_tpu/data/autotune.py::decide",
    "jama16_retina_tpu/data/autotune.py::staged_cap",
    "jama16_retina_tpu/ingest/fleettune.py::merge_windows",
    "jama16_retina_tpu/lifecycle/journal.py",
    "jama16_retina_tpu/utils/retry.py",
)

PRAGMA = "graftlint: deterministic"


def _aliases(tree: ast.AST) -> dict:
    """{local name: dotted origin} from the module's imports."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def _resolve_call(node: ast.Call, aliases: dict) -> "str | None":
    chain = core.dotted(node.func)
    if chain is None:
        return None
    root, _, rest = chain.partition(".")
    if root in aliases:
        origin = aliases[root]
        return f"{origin}.{rest}" if rest else origin
    return chain


def _banned(full: str) -> bool:
    if full in BANNED:
        return True
    return any(full == p[:-1] or full.startswith(p)
               for p in BANNED_PREFIXES)


class PurityRule:
    name = "purity"

    def __init__(self, targets: tuple = DEFAULT_TARGETS):
        self.targets = tuple(targets)

    def run(self, corpus: "core.Corpus") -> list:
        findings: list = []
        module_targets = set()
        func_targets: dict[str, set] = {}
        for t in self.targets:
            path, sep, func = t.partition("::")
            if sep:
                func_targets.setdefault(path, set()).add(func)
            else:
                module_targets.add(path)
        for pf in corpus.py:
            scopes: list[tuple[str, ast.AST]] = []
            if any(pf.rel.endswith(m) for m in module_targets):
                scopes.append((f"{pf.rel}::<module>", pf.tree))
            wanted = set()
            for path, funcs in func_targets.items():
                if pf.rel.endswith(path):
                    wanted |= funcs
            for node in ast.walk(pf.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                pragma = PRAGMA in pf.line_text(node.lineno)
                if node.name in wanted or pragma:
                    scopes.append((f"{pf.rel}::{node.name}", node))
            if not scopes:
                continue
            aliases = _aliases(pf.tree)
            seen: set[int] = set()
            for scope_name, scope_node in scopes:
                for node in ast.walk(scope_node):
                    if not isinstance(node, ast.Call) or id(node) in seen:
                        continue
                    seen.add(id(node))
                    full = _resolve_call(node, aliases)
                    if full is None or not _banned(full):
                        continue
                    findings.append(core.Finding(
                        rule=self.name, code="purity.impure-call",
                        path=pf.rel, line=node.lineno,
                        message=(f"{scope_name.split('::')[-1]} is "
                                 f"declared deterministic but calls "
                                 f"{full}(); inject the clock/entropy "
                                 "source as a parameter instead"),
                        key=f"{scope_name}::{full}",
                    ))
        return findings
