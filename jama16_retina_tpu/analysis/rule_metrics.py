"""graftlint rule ``metrics``: the registry-metric contract (ISSUE 9).

Every ``registry.counter/gauge/histogram(name, ...)`` call site in the
lint scope must:

  * pass a statically resolvable name (a string literal or an f-string
    — interpolated fragments become wildcards that match the glossary's
    ``{placeholder}`` patterns);
  * match the ``layer.noun[.sub]`` grammar: >= 2 dot-separated
    lowercase ``[a-z0-9_]`` segments, first segment alphabetic-led;
  * (per NAME, because the registry is get-or-create and most metrics
    have one registration site plus read-only access sites) carry a
    non-empty ``help=`` string at at least one site;
  * never reuse a name with a conflicting kind or a conflicting
    non-empty help text;
  * round-trip against the metric glossary TABLES in
    docs/OBSERVABILITY.md + docs/RELIABILITY.md: an undocumented code
    metric and a documented-but-nonexistent glossary row are both
    findings, so the docs can never drift from the code.

Glossary table convention (what the docs satellite installs): any
markdown table in those two docs whose header row contains "Metric"
and "Kind"; each row's first cell is a backtick-quoted name pattern,
second cell the kind. Patterns may use ``{placeholder}`` / ``<ph>``
for dynamic fragments and ``{a,b,c}`` for literal alternation.
"""

from __future__ import annotations

import ast
import re

from jama16_retina_tpu.analysis import core

KINDS = ("counter", "gauge", "histogram")

_SEGMENT_RE = re.compile(r"^[a-z0-9_\x00]+$")
_FIRST_SEGMENT_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# What a glossary {placeholder} may stand for: any run of name chars,
# dots included (a fault-site placeholder like io.retries.{site}
# expands to dotted site names).
_PLACEHOLDER_RE = r"[A-Za-z0-9_.\x00]+"

_GLOSSARY_DOCS = ("OBSERVABILITY.md", "RELIABILITY.md")


def name_grammar_ok(canonical: str) -> bool:
    segments = canonical.split(".")
    if len(segments) < 2:
        return False
    if not _FIRST_SEGMENT_RE.match(segments[0].replace(core.WILDCARD, "x")):
        return False
    return all(_SEGMENT_RE.match(s) for s in segments)


def pattern_regex(pattern: str) -> "re.Pattern":
    """A glossary name pattern -> regex over canonical code names.
    ``{a,b,c}`` alternations additionally accept a code-side wildcard
    (an f-string fragment can only be checked to the pattern level)."""
    out = []
    for tok in re.split(r"(\{[^}]*\}|<[^>]*>)", pattern):
        if not tok:
            continue
        if tok[0] in "{<":
            inner = tok[1:-1]
            if "," in inner and tok[0] == "{":
                alts = [re.escape(a.strip()) for a in inner.split(",")]
                out.append("(?:" + "|".join(alts + [core.WILDCARD]) + ")")
            else:
                out.append(_PLACEHOLDER_RE)
        else:
            out.append(re.escape(tok))
    return re.compile("".join(out) + r"\Z")


def parse_glossaries(corpus: "core.Corpus") -> "tuple[list, bool]":
    """((rel, line, pattern, kind) rows, any_glossary_doc_present)."""
    entries = []
    present = False
    for basename in _GLOSSARY_DOCS:
        found = corpus.doc_named(basename)
        if found is None:
            continue
        present = True
        rel, text = found
        in_table = False
        for lineno, line in enumerate(text.splitlines(), start=1):
            stripped = line.strip()
            if not (stripped.startswith("|") and stripped.endswith("|")):
                in_table = False
                continue
            cells = [c.strip() for c in stripped.strip("|").split("|")]
            if len(cells) < 2:
                in_table = False
                continue
            low = [c.lower() for c in cells]
            if "metric" in low[0] and "kind" in low[1]:
                in_table = True
                continue
            if set(cells[0]) <= {"-", ":", " "}:
                continue
            if not in_table:
                continue
            m = re.search(r"`([^`]+)`", cells[0])
            k = re.search(r"counter|gauge|histogram", cells[1].lower())
            if m and k:
                entries.append((rel, lineno, m.group(1), k.group(0)))
    return entries, present


class _Site:
    __slots__ = ("pf", "node", "kind", "canonical", "help")

    def __init__(self, pf, node, kind, canonical, help_):
        self.pf = pf
        self.node = node
        self.kind = kind
        self.canonical = canonical
        self.help = help_  # str literal | "<dynamic>" | None


# help= passed as a non-literal expression (e.g. a dict lookup): treat
# as present — the contract is "help exists", not "help is static".
_DYNAMIC = "<dynamic>"


def _registry_receiver(node: ast.Call) -> bool:
    """Is the receiver of this .counter/.gauge/.histogram call a
    registry? Pins the rule to registry-like names (``reg``,
    ``registry``, ``self._registry``, …) and ``default_registry()``
    calls, so ordinary numeric code (``np.histogram(...)``) never
    false-positives. A registry bound to an unconventional local name
    is missed — the conservative direction for a lint."""
    recv = node.func.value
    if isinstance(recv, ast.Call):
        fn = core.dotted(recv.func) or ""
        return fn.split(".")[-1] == "default_registry"
    chain = core.dotted(recv)
    if chain is None:
        return False
    tail = chain.split(".")[-1].lstrip("_")
    return tail in ("reg", "registry")


class MetricsRule:
    name = "metrics"

    def run(self, corpus: "core.Corpus") -> list:
        findings: list = []
        sites: list[_Site] = []
        for pf in corpus.py:
            for node in ast.walk(pf.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in KINDS
                        and _registry_receiver(node)):
                    continue
                help_ = None
                for kw in node.keywords:
                    if kw.arg == "help":
                        v = core.literal_str(kw.value)
                        help_ = v if v is not None else _DYNAMIC
                name_node = node.args[0] if node.args else None
                canonical = (core.literal_str(name_node)
                             if name_node is not None else None)
                if canonical is None:
                    findings.append(core.Finding(
                        rule=self.name, code="metrics.non-literal-name",
                        path=pf.rel, line=node.lineno,
                        message=(f".{node.func.attr}() name is not a "
                                 "resolvable literal; metric names must be "
                                 "static so the glossary round-trip can "
                                 "see them"),
                        key=f"{pf.rel}::{core.scope_of(node)}",
                    ))
                    continue
                sites.append(_Site(pf, node, node.func.attr, canonical,
                                   help_))
                if not name_grammar_ok(canonical):
                    findings.append(core.Finding(
                        rule=self.name, code="metrics.name-grammar",
                        path=pf.rel, line=node.lineno,
                        message=(f"metric name "
                                 f"{core.display_name(canonical)!r} does "
                                 "not match the layer.noun[.sub] grammar "
                                 "(>= 2 lowercase [a-z0-9_] dotted "
                                 "segments)"),
                        key=f"metric::{core.display_name(canonical)}",
                    ))
        by_name: dict[str, list[_Site]] = {}
        for s in sites:
            by_name.setdefault(s.canonical, []).append(s)
        for canonical, group in sorted(by_name.items()):
            disp = core.display_name(canonical)
            first = group[0]
            kinds = sorted({s.kind for s in group})
            if len(kinds) > 1:
                where = ", ".join(sorted(
                    f"{s.pf.rel}:{s.node.lineno} ({s.kind})" for s in group
                ))
                findings.append(core.Finding(
                    rule=self.name, code="metrics.kind-conflict",
                    path=first.pf.rel, line=first.node.lineno,
                    message=(f"metric {disp!r} is registered with "
                             f"conflicting kinds: {where}"),
                    key=f"metric::{disp}",
                ))
            helps = sorted({
                s.help for s in group
                if s.help not in (None, "", _DYNAMIC)
            })
            if len(helps) > 1:
                findings.append(core.Finding(
                    rule=self.name, code="metrics.help-conflict",
                    path=first.pf.rel, line=first.node.lineno,
                    message=(f"metric {disp!r} carries {len(helps)} "
                             "different help texts; one metric, one "
                             "meaning"),
                    key=f"metric::{disp}",
                ))
            has_help = any(
                s.help == _DYNAMIC or (s.help is not None and s.help.strip())
                for s in group
            )
            if not has_help:
                findings.append(core.Finding(
                    rule=self.name, code="metrics.help-missing",
                    path=first.pf.rel, line=first.node.lineno,
                    message=(f"metric {disp!r} has no non-empty help= at "
                             "any registration site; exporters render "
                             "help as the # HELP line operators read"),
                    key=f"metric::{disp}",
                ))
        entries, glossary_present = parse_glossaries(corpus)
        if not glossary_present:
            return findings  # fixture corpus without glossary docs
        if sites and not entries:
            findings.append(core.Finding(
                rule=self.name, code="metrics.no-glossary",
                path=_GLOSSARY_DOCS[0], line=0,
                message=("no metric glossary table found (a table whose "
                         "header has Metric|Kind columns) — the metric "
                         "round-trip has nothing to check against"),
                key="glossary::missing",
            ))
            return findings
        compiled = [
            (rel, lineno, pat, kind, pattern_regex(pat))
            for rel, lineno, pat, kind in entries
        ]
        for canonical, group in sorted(by_name.items()):
            disp = core.display_name(canonical)
            kinds = {s.kind for s in group}
            hit = any(
                kind in kinds and rx.match(canonical)
                for _, _, _, kind, rx in compiled
            )
            if not hit:
                first = group[0]
                findings.append(core.Finding(
                    rule=self.name, code="metrics.undocumented",
                    path=first.pf.rel, line=first.node.lineno,
                    message=(f"metric {disp!r} ({'/'.join(sorted(kinds))}) "
                             "has no glossary row in "
                             f"{' or '.join(_GLOSSARY_DOCS)}"),
                    key=f"metric::{disp}",
                ))
        for rel, lineno, pat, kind, rx in compiled:
            hit = any(
                kind in {s.kind for s in group} and rx.match(canonical)
                for canonical, group in by_name.items()
            )
            if not hit:
                findings.append(core.Finding(
                    rule=self.name, code="metrics.doc-orphan",
                    path=rel, line=lineno,
                    message=(f"glossary row {pat!r} ({kind}) matches no "
                             "metric registered anywhere in the lint "
                             "scope — stale docs or a typo'd pattern"),
                    key=f"glossary::{pat}",
                ))
        return findings
