"""End-to-end training/eval drivers (reference train.py/evaluate.py bodies).

``fit`` is the reference's session loop re-shaped for TPU (SURVEY.md
§3.1): one jit dispatch per step over a data-parallel mesh, periodic
validation AUC, early stopping on best val AUC with orbax best-checkpoint
retention, JSONL metrics. ``fit_ensemble`` repeats it for k
independently-seeded members (reference R11); ``evaluate_checkpoints``
restores member checkpoints, averages probabilities, and emits the
reference's report shape (AUC + operating points; SURVEY.md §3.2).
"""

from __future__ import annotations

import os
import time

import jax
import numpy as np
from absl import logging as absl_logging

from jama16_retina_tpu import models, train_lib
from jama16_retina_tpu.configs import ExperimentConfig
from jama16_retina_tpu.data import augment as augment_lib
from jama16_retina_tpu.data import pipeline
from jama16_retina_tpu.eval import metrics
from jama16_retina_tpu.obs import alerts as obs_alerts
from jama16_retina_tpu.obs import device as obs_device
from jama16_retina_tpu.obs import export as obs_export
from jama16_retina_tpu.obs import faultinject
from jama16_retina_tpu.obs import flightrec as obs_flightrec
from jama16_retina_tpu.obs import registry as obs_registry
from jama16_retina_tpu.obs import trace as obs_trace
from jama16_retina_tpu.obs.spans import StallClock
from jama16_retina_tpu.parallel import mesh as mesh_lib
from jama16_retina_tpu.utils import checkpoint as ckpt_lib
from jama16_retina_tpu.utils import physics
from jama16_retina_tpu.utils.logging import RunLog


def _obs_begin_run(cfg: ExperimentConfig):
    """Run-scope the process-wide registry: apply THIS run's enabled
    flag and zero every metric in place, BEFORE the data pipelines are
    built (their construction-time metrics — the tiered resident-tier
    decode counts, the worker-count gauge — belong to this run).
    Sequential ensemble members each fit() in one process; without the
    reset, member m's telemetry snapshots would carry members 0..m-1's
    cumulative counters and histogram quantiles. The process tracer
    gets the same run-scoping (ISSUE 4): knobs applied, rings cleared —
    a blackbox dump for member m must not replay member m-1's tail."""
    reg = obs_registry.default_registry()
    reg.enabled = cfg.obs.enabled
    reg.reset()
    obs_trace.default_tracer().configure(
        enabled=cfg.obs.enabled and cfg.obs.trace_enabled,
        buffer_events=cfg.obs.trace_buffer_events,
    )
    # Deterministic fault plan (ISSUE 6; obs/faultinject.py): env var
    # wins, then obs.fault_plan; both empty leaves a test-armed plan.
    faultinject.arm_from_env_or_config(cfg.obs.fault_plan)
    return reg


def _telemetry_for(cfg: ExperimentConfig, log: RunLog, workdir: str,
                   flight=None):
    """(registry, StallClock, Snapshotter|None) for one train loop.

    One copy of the wiring rule all three loops share (the registry was
    already run-scoped by _obs_begin_run before the pipelines went up):
    the StallClock feeds trainer.* histograms only when enabled, and
    the Snapshotter reuses the run's own RunLog so
    `telemetry`/`heartbeat` records land in the same JSONL (and its
    per-process mirrors) as everything else.

    SLO/quality alerting (obs/alerts.py; ISSUE 5) rides the same flush
    cadence: when the config implies rules (obs.quality enabled or
    user alert_rules), the Snapshotter carries an AlertManager wired to
    this run's FlightRecorder, so a firing rule writes `alert` records
    into the run JSONL and trips a quality_drift/slo_breach blackbox
    dump (one per reason per run)."""
    reg = obs_registry.default_registry()
    stalls = StallClock(reg if cfg.obs.enabled else None)
    snap = None
    if cfg.obs.enabled:
        alerts = None
        # Reliability rules (ISSUE 6: data-quarantine burn rate) ride
        # the same manager as the quality rules; rules over metrics a
        # train run never publishes stay inactive.
        rules = (obs_alerts.quality_rules(cfg.obs.quality)
                 + obs_alerts.reliability_rules(cfg))
        if rules:
            alerts = obs_alerts.AlertManager(
                rules, registry=reg, flight=flight
            )
        # Fleet segment bus (ISSUE 15): a trainer with obs.fleet_dir
        # set publishes its snapshots/heartbeat/trace rings into the
        # shared fleet dir under the "trainer" role; bus_for returns
        # None (one branch per flush) when the plane is off.
        from jama16_retina_tpu.obs import fleet as obs_fleet

        snap = obs_export.Snapshotter(
            reg, workdir, runlog=log, every_s=cfg.obs.flush_every_s,
            alerts=alerts, fleet=obs_fleet.bus_for(cfg, "trainer",
                                                   registry=reg),
            # Device-utilization plane (ISSUE 19): HBM/MFU/compile
            # gauges sampled on the same flush cadence; None when
            # obs.device_enabled is off (one branch per flush).
            device=obs_device.monitor_for(cfg, registry=reg),
        )
        if cfg.obs.http_port > 0:
            snap.serve_http(cfg.obs.http_port)
    return reg, stalls, snap


def _flight_for(cfg: ExperimentConfig, workdir: str,
                profiler: "_ProfilerWindow | None" = None):
    """The run's FlightRecorder (obs/flightrec.py), or None when obs is
    off. One wiring rule for all three loops: dumps carry THIS run's
    config, record into the run-scoped default registry/tracer, and the
    anomaly-triggered profiler capture routes through the run's
    _ProfilerWindow (flax loops; fit_tf has no jax profiler to arm)."""
    if not cfg.obs.enabled:
        return None
    import dataclasses

    slow = cfg.obs.slow_step_factor
    return obs_flightrec.FlightRecorder(
        workdir,
        config=dataclasses.asdict(cfg),
        registry=obs_registry.default_registry(),
        tracer=obs_trace.default_tracer(),
        blackbox_events=cfg.obs.blackbox_events,
        slow_step_factor=(slow if slow > 0 else float("inf")),
        profile_hook=(profiler.arm if profiler is not None else None),
        blackbox_keep=cfg.obs.blackbox_keep,
        diagnosis=cfg.obs.diagnosis_enabled,
        diagnosis_top_k=cfg.obs.diagnosis_top_k,
    )


def _emit_quality_profile(
    cfg: ExperimentConfig, data_dir: str, predict_fn, log: RunLog,
) -> None:
    """End-of-fit reference-profile artifact (obs/quality.py; ISSUE 5):
    one more val prediction pass with the loop's own scorer, reduced to
    the versioned drift profile (score histogram, input-stat histograms,
    base rate, operating thresholds) the online monitor loads. All
    THREE fit loops wire this (sequential, member-parallel, tf) — the
    knob must not silently no-op on a backend. ``predict_fn() ->
    (grades, probs)`` with probs already ensemble-averaged where
    members exist ([n] binary or [n, C] multiclass). Captures the FINAL
    train state; the canonical profile for a served checkpoint is
    ``evaluate.py --profile_out`` on that checkpoint (same builder,
    restored best state)."""
    from jama16_retina_tpu.obs import quality as quality_lib

    path = cfg.obs.quality.profile_out
    # The prediction pass runs on EVERY process (sharded eval steps
    # carry collectives; a process-0-only call would deadlock a
    # multi-host run) ...
    grades, probs = predict_fn()
    # ... but the artifact itself is host-local: one writer, no
    # last-writer-wins race on a shared-FS profile_out path, and one
    # input-stat pass (split_input_stats already reads the full split
    # in its forced single-process view).
    if jax.process_index() != 0:
        return
    bin_labels = (grades >= 2).astype(np.float64)
    scores = (
        np.asarray(probs, np.float64) if cfg.model.head == "binary"
        else np.asarray(
            metrics.referable_probs_from_multiclass(probs), np.float64
        )
    )
    # Operating thresholds need both classes on val; a degenerate split
    # (smoke fixtures) still gets a profile, just without thresholds.
    thresholds: list = []
    if 0.0 < bin_labels.mean() < 1.0:
        thresholds = [
            metrics.sensitivity_at_specificity(bin_labels, scores, s).as_dict()
            for s in cfg.eval.operating_specificities
        ]
    stats = quality_lib.split_input_stats(
        data_dir, "val", cfg.eval.batch_size, cfg.model.image_size
    )
    profile = quality_lib.build_profile(
        scores, labels=bin_labels, stat_values=stats,
        thresholds=thresholds, bins=cfg.obs.quality.score_bins,
        meta={"config": cfg.name, "split": "val",
              "source": "trainer_end_of_fit"},
    )
    quality_lib.save_profile(path, profile)
    log.write("quality_profile", path=path,
              n_examples=profile["n_examples"])


def _binary_eval_labels(grades: np.ndarray, head: str) -> np.ndarray:
    """evaluation_report expects binary labels for the binary head and raw
    grades for the 5-class head."""
    return (grades >= 2).astype(np.float64) if head == "binary" else grades


def _predict_over_split(
    cfg: ExperimentConfig, data_dir: str, split: str, batch_probs_fn
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Shared eval loop for every backend: iterate eval_batches, compute
    per-batch probs via ``batch_probs_fn(batch) -> [B]-or-[B,C] array``,
    trim padding rows (the mask contract of make_eval_step), concatenate.
    Returns (grades, probs, names) — names are the per-record ids from
    the TFRecords (bytes; feed --save_probs exports).

    ``eval.sharded`` swaps in the decode-sharded stream (each process
    decodes 1/P of the records; metadata comes pre-aligned to the
    assembled permutation, so nothing downstream changes)."""
    batches_fn = (
        pipeline.eval_batches_sharded if cfg.eval.sharded
        else pipeline.eval_batches
    )
    grades_all, probs_all, names_all = [], [], []
    for batch in batches_fn(
        data_dir, split, cfg.eval.batch_size, cfg.model.image_size
    ):
        probs = batch_probs_fn(batch)
        keep = batch["mask"] > 0
        grades_all.append(batch["grade"][keep])
        probs_all.append(probs[keep])
        names_all.append(batch["name"][keep])
    return (
        np.concatenate(grades_all),
        np.concatenate(probs_all),
        np.concatenate(names_all),
    )


def predict_split(
    cfg: ExperimentConfig,
    model,
    state: train_lib.TrainState,
    data_dir: str,
    split: str,
    mesh=None,
    eval_step=None,
    cache: list | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Run the test pipeline (no augmentation) -> (grades, probs, names).

    Pass a prebuilt ``eval_step`` when calling repeatedly (every val
    interval / every ensemble member) — a fresh ``make_eval_step`` closure
    would defeat the jit cache and recompile the backbone each time.

    ``cache``: pass one list across repeated evals of a split to keep
    its batches device-resident between them; the first call fills it,
    later calls skip the host re-parse and re-upload. Same IDEA as
    _predict_split_members' cache but a different tuple layout ((dev,
    grades, names, keep) here; 3-tuples and [k, B]-probs indexing
    there) — the lists are not interchangeable.
    """
    if eval_step is None:
        eval_step = train_lib.make_eval_step(cfg, model, mesh=mesh)

    if cache:
        grades_all, probs_all, names_all = [], [], []
        for dev_batch, kept_grades, kept_names, keep in cache:
            probs = np.asarray(jax.device_get(eval_step(state, dev_batch)))
            grades_all.append(kept_grades)
            probs_all.append(probs[keep])
            names_all.append(kept_names)
        return (
            np.concatenate(grades_all),
            np.concatenate(probs_all),
            np.concatenate(names_all),
        )

    def batch_probs(batch):
        # Only the image rows go to device — 'grade'/'mask' are global
        # host metadata (multi-host: 'image' is the per-process block,
        # see pipeline.eval_batches), and eval_step reads only 'image'.
        if mesh is not None:
            dev_batch = mesh_lib.shard_batch({"image": batch["image"]}, mesh)
        else:
            dev_batch = jax.device_put({"image": batch["image"]})
        if cache is not None:
            keep = batch["mask"] > 0
            cache.append(
                (dev_batch, batch["grade"][keep], batch["name"][keep], keep)
            )
        return np.asarray(jax.device_get(eval_step(state, dev_batch)))

    return _predict_over_split(cfg, data_dir, split, batch_probs)


def predict_split_tf(
    cfg: ExperimentConfig, keras_model, data_dir: str, split: str
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """predict_split's TF-backend twin: same pipeline.eval_batches
    stream, forward pass on host TF instead of the jit eval step. The
    (grades, probs) contract is identical, so everything downstream —
    ensemble averaging, evaluation_report — is untouched (BASELINE.json:5).
    """
    from jama16_retina_tpu.models import tf_backend

    return _predict_over_split(
        cfg, data_dir, split,
        lambda batch: tf_backend.predict_probs(
            keras_model, batch["image"], cfg.model.head, tta=cfg.eval.tta
        ),
    )


class _GrainStateTee:
    """Snapshot the grain iterator's state after every produced batch.

    device_prefetch pulls the iterator AHEAD of the train step by its
    queue depth, so ``it.get_state()`` at checkpoint time describes a
    future position; resume needs the state as of the checkpointed step.
    The tee records state per batch ordinal (a bounded ring: prefetch
    depth is small) so the trainer can persist exactly the state an
    uninterrupted run had after step s's batch."""

    def __init__(self, it, start_ordinal: int, keep: int = 16):
        self._it = it
        self._n = start_ordinal
        # Ring depth must exceed the prefetch lead or the checkpoint
        # step's state is evicted before persistence reads it.
        self._keep = max(16, keep)
        self._states: dict[int, bytes] = {}

    def __iter__(self):
        return self

    def __next__(self):
        batch = next(self._it)
        self._n += 1
        self._states[self._n] = self._it.get_state()
        self._states.pop(self._n - self._keep, None)
        return batch

    def state_after(self, ordinal: int) -> bytes | None:
        return self._states.get(ordinal)


def _grain_state_path(workdir: str, step: int) -> str:
    """Per-PROCESS state file (same convention as RunLog's .p{N}
    mirrors): each process's grain iterator holds its own shard
    positions, and a shared filename would let the last writer clobber
    every other process's resume point."""
    import jax

    idx = jax.process_index()
    name = f"{step}.json" if idx == 0 else f"{step}.p{idx}.json"
    return os.path.join(workdir, "grain_state", name)


def _prune_grain_state(workdir: str, kept_steps: set,
                       protect_above: "int | None" = None) -> None:
    """Drop this PROCESS's grain_state files for steps whose checkpoints
    are gone (ADVICE r3: without this the directory grows unboundedly
    over long worker-mode runs, and states for steps purged by the
    torn-save rollback would outlive their checkpoints).

    ``protect_above``: steps above it are NEVER pruned even when absent
    from ``kept_steps`` — the async-save race guard (a still-finalizing
    orbax save is not listed by all_steps() yet; deleting its grain
    state would make the freshly saved checkpoint unresumable). Pass
    None only when newer-than-kept states are exactly the thing being
    purged (the torn-save rollback)."""
    import jax

    d = os.path.join(workdir, "grain_state")
    if not os.path.isdir(d):
        return
    idx = jax.process_index()
    suffix = ".json" if idx == 0 else f".p{idx}.json"
    for name in os.listdir(d):
        if not name.endswith(suffix):
            continue
        # p0's bare ".json" suffix also matches other processes' files
        # ("12.p1.json" → stem "12.p1"); int() rejects those.
        try:
            s = int(name[: -len(suffix)])
        except ValueError:
            continue
        if s in kept_steps or (protect_above is not None
                               and s > protect_above):
            continue
        try:
            os.remove(os.path.join(d, name))
        except OSError:
            pass


def _persist_grain_state(tee: "_GrainStateTee | None", workdir: str,
                         step: int, kept_steps: "set | None" = None) -> None:
    """Write the worker-mode grain position for ``step`` next to its
    checkpoint (tiny JSON files), then prune states whose checkpoints
    retention has dropped (``kept_steps`` = the Checkpointer's live
    steps; ``step`` itself is always kept — an async save may not be
    listed yet)."""
    if tee is None:
        return
    state = tee.state_after(step)
    if state is None:
        # Legitimate only at a resumed run's first eval (no new batch
        # consumed yet); any other miss means the ring was outrun.
        if step > tee._n - tee._keep:
            return
        absl_logging.warning(
            "grain state for step %d was evicted from the tee ring "
            "(produced up to %d, keep=%d) — this checkpoint will not be "
            "worker-mode resumable", step, tee._n, tee._keep,
        )
        return
    os.makedirs(os.path.join(workdir, "grain_state"), exist_ok=True)
    with open(_grain_state_path(workdir, step), "wb") as f:
        f.write(state)
    if kept_steps is not None:
        kept = set(kept_steps)
        # Only prune BELOW the newest finalized step: anything newer
        # may be an async save that all_steps() does not list yet.
        _prune_grain_state(
            workdir, kept | {step},
            protect_above=max(kept) if kept else -1,
        )


def _load_grain_state(cfg: ExperimentConfig, workdir: str,
                      start_step: int) -> bytes | None:
    """Persisted worker-mode grain position for a resume, when one
    applies. Missing file → None; grain_pipeline then raises its
    documented NotImplementedError for worker-mode skip_batches."""
    if (cfg.data.loader != "grain" or cfg.data.grain_workers <= 0
            or start_step == 0):
        return None
    path = _grain_state_path(workdir, start_step)
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        return f.read()


def _train_stream(
    cfg: ExperimentConfig, data_dir: str, seed: int, skip_batches: int,
    mesh=None, full_batches: bool = False, grain_state: bytes | None = None,
    knobs=None,
):
    """Dispatch on data.loader (SURVEY.md N4): every loader yields the
    same {'image','grade'} batches and honors skip_batches, so the train
    loops never see which one is underneath. 'hbm' yields DEVICE-resident
    batches (the whole split uploaded once — docs/PERF.md §H2D); the
    others yield host arrays for device_prefetch to move.

    ``full_batches``: every process reads the FULL global batch stream
    instead of its 1/P slice — the member-parallel driver's contract
    (its ('member','data') layout needs all rows on every host; see
    pipeline.device_prefetch full_local).

    ``knobs`` (data/autotune.Knobs; data.autotune=true): the live
    decode-worker/stage-depth control surface for the loaders that
    expose it (tiered, rawshard). tfdata/grain tune at the
    device_prefetch layer only (their engines own their internal
    parallelism), and the hbm loader has no steady-state host work to
    tune — both ignore it here."""
    proc_kw = (
        {"process_index": 0, "process_count": 1} if full_batches else {}
    )
    if cfg.data.loader == "hbm":
        from jama16_retina_tpu.data import hbm_pipeline

        return hbm_pipeline.train_batches(
            data_dir, "train", cfg.data, cfg.model.image_size, seed=seed,
            skip_batches=skip_batches, mesh=mesh,
        )
    if cfg.data.loader == "tiered":
        from jama16_retina_tpu.data import tiered_pipeline

        # Device-born batches like 'hbm' (device_prefetch passes them
        # through untouched); partial HBM residency + parallel host
        # decode for the remainder, so the full_batches contract is
        # moot the same way it is for 'hbm' (one global stream).
        return tiered_pipeline.train_batches(
            data_dir, "train", cfg.data, cfg.model.image_size, seed=seed,
            skip_batches=skip_batches, mesh=mesh, knobs=knobs,
        )
    if cfg.data.loader == "rawshard":
        from jama16_retina_tpu.data import rawshard

        # The tiered machinery over ahead-of-time transcoded shards
        # (scripts/transcode_shards.py): bit-identical batches, decode
        # replaced by an mmap row copy (data/rawshard.py).
        return rawshard.train_batches(
            data_dir, "train", cfg.data, cfg.model.image_size, seed=seed,
            skip_batches=skip_batches, mesh=mesh, knobs=knobs,
        )
    if cfg.data.loader == "served":
        from jama16_retina_tpu.data import served

        # Disaggregated ingest (ISSUE 17): batches arrive over a
        # shared-memory ring from a scripts/ingest_server.py process
        # that owns the decode plane for every local consumer. Host
        # batches, same plan as 'tiered' — bit-identical stream.
        return served.train_batches(
            cfg, seed=seed, skip_batches=skip_batches, mesh=mesh,
        )
    if cfg.data.loader == "grain":
        from jama16_retina_tpu.data import grain_pipeline

        return grain_pipeline.train_batches(
            data_dir, "train", cfg.data, cfg.model.image_size, seed=seed,
            skip_batches=skip_batches,
            worker_count=cfg.data.grain_workers,
            initial_state=grain_state, **proc_kw,
        )
    if cfg.data.loader != "tfdata":
        raise ValueError(
            f"unknown data.loader {cfg.data.loader!r} "
            "(want tfdata|grain|hbm|tiered|rawshard|served)"
        )
    return pipeline.train_batches(
        data_dir, "train", cfg.data, cfg.model.image_size, seed=seed,
        skip_batches=skip_batches, **proc_kw,
    )


def _autotune_for(cfg: ExperimentConfig, mesh=None):
    """(knobs, tuner) when data.autotune is on, else (None, None).
    Built AFTER _obs_begin_run (the tuner's gauges/counters belong to
    this run) and BEFORE the pipelines (the loaders capture the knobs
    at construction)."""
    if not cfg.data.autotune:
        return None, None
    from jama16_retina_tpu.data import autotune as autotune_lib

    return autotune_lib.for_config(cfg, mesh=mesh)


def _best_tracking_update(
    aucs, best_auc, best_step, since_best, step: int, min_delta: float
):
    """The best/min_delta/patience bookkeeping rule, vectorized over any
    number of models — THE one copy of the early-stopping rule, shared by
    the scalar drivers (_eval_and_track, via 0-d arrays) and the member-
    parallel driver (length-k vectors), so they cannot desynchronize."""
    improved = np.asarray(aucs) > np.asarray(best_auc) + min_delta
    return (
        np.where(improved, aucs, best_auc),
        np.where(improved, step, best_step),
        np.where(improved, 0, np.asarray(since_best) + 1),
    )


def _check_ema_compat(ckpt, cfg: ExperimentConfig, where: str, step=None):
    """Resume must continue the SAME optimization — an EMA-presence
    mismatch means the config changed under the run; fail loudly rather
    than silently drop/invent the shadow mid-training. (None = metadata
    unreadable: skip the guard rather than misdiagnose.)"""
    has_ema = ckpt.saved_with_ema(step)
    if has_ema is not None and has_ema != (cfg.train.ema_decay > 0):
        raise ValueError(
            f"checkpoint in {where} was trained with ema "
            f"{'on' if has_ema else 'off'} but this run sets "
            f"train.ema_decay={cfg.train.ema_decay} — resume with a "
            "matching config"
        )


def _reconstruct_best_tracking(
    workdir: str, start_step: int, cfg: ExperimentConfig, ckpts: list
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Best/early-stop tracking as of ``start_step``, for resume.

    Primary source: replay the run's own eval history (metrics.jsonl)
    through _best_tracking_update — the SAME min_delta/patience rule the
    live loop applies, so a resumed run stops exactly when an
    uninterrupted one would (the best manager's raw argmax is NOT
    equivalent: sub-min_delta improvements enter its top-k without
    resetting patience). Replays the FIRST eval record per step at
    step <= start_step in file order, which chains across repeated
    interruptions: under sparse saves (train.save_every_evals) a crash
    after an unsaved eval makes the resumed run re-run and re-log that
    eval, so duplicates at one step are legitimate — and deterministic
    replay makes them identical, so first-per-step keeps the patience
    count exact (counting both would double-increment since_best).
    Fallback when no JSONL survives: the best manager's retained peak,
    with patience derived from the eval cadence."""
    from jama16_retina_tpu.utils.logging import read_jsonl

    k = len(ckpts)
    best_auc = np.full((k,), -np.inf)
    best_step = np.zeros((k,), np.int64)
    since_best = np.zeros((k,), np.int64)
    path = os.path.join(workdir, "metrics.jsonl")
    evals = []
    if os.path.exists(path):
        for r in read_jsonl(path):
            if r.get("kind") != "eval" or r.get("step", 0) > start_step:
                continue
            if "val_auc_per_member" in r and len(r["val_auc_per_member"]) == k:
                evals.append((r["step"], r["val_auc_per_member"]))
            elif "val_auc" in r and k == 1:
                evals.append((r["step"], [r["val_auc"]]))
    # One replay per STEP: under sparse saves (train.save_every_evals) a
    # crash after an unsaved eval makes the resumed run re-run and
    # re-log that eval, so the file legitimately holds duplicate records
    # at one step. Deterministic replay makes the duplicates identical;
    # counting them twice would double-increment since_best and fire
    # early stopping before the configured patience.
    kept: dict[int, list] = {}
    for s, a in evals:
        if s not in kept:
            kept[s] = a
        elif not np.allclose(kept[s], a, atol=1e-9, equal_nan=True):
            # equal_nan: a NaN val_auc (degenerate single-class val
            # split) replays deterministically too — NaN != NaN must not
            # flag the run's own re-logged evals on every resume.
            # Deterministic replay should make re-logged evals identical;
            # disagreement means the workdir mixed nondeterministic eval
            # passes (e.g. the TF backend) and the replayed best/patience
            # state may differ from the state actually restored.
            absl_logging.warning(
                "metrics.jsonl holds disagreeing duplicate eval records "
                "at step %d (%s vs %s); replaying the first — best/"
                "patience reconstruction may not match the restored state",
                s, kept[s], a,
            )
    evals = list(kept.items())
    if evals:
        for step, aucs in evals:
            best_auc, best_step, since_best = _best_tracking_update(
                aucs, best_auc, best_step, since_best, step,
                cfg.train.min_delta,
            )
        return best_auc, best_step, since_best
    for m, ckpt in enumerate(ckpts):
        info = ckpt.best_info()
        if info is not None:
            best_step[m], best_auc[m] = info
            since_best[m] = max(
                0, (start_step - info[0]) // cfg.train.eval_every
            )
    return best_auc, best_step, since_best


class _ProfilerWindow:
    """The jax.profiler capture window, shared by the single-model and
    member-parallel train loops. Two ways to open it:

      * the fixed --profile_steps window (SURVEY.md §5.1), planned at
        construction exactly as before (skip the compile+warmup steps
        when the run is long enough, clamp inside short runs, warn when
        no window fits) — behavior unchanged (parity pinned by
        tests/test_trace.py);
      * ``arm(n)`` (ISSUE 4): a TRIGGER-DRIVEN short capture starting
        at the next step boundary — the flight recorder's profile hook
        on NaN/slow-step anomalies (once per run; the rate limit lives
        in the FlightRecorder, and ``arm`` additionally refuses while a
        capture is open so an anomaly inside the fixed window cannot
        double-start the profiler).

    Never leaks an open trace (the next fit() in an ensemble would
    crash on start_trace)."""

    def __init__(self, cfg: ExperimentConfig, log: RunLog, workdir: str,
                 start_step: int):
        self._dir = os.path.join(workdir, "profile")
        self._steps = cfg.train.profile_steps
        self._log = log
        self._start, self._stop = -1, -1
        self._tracing = False
        self._fixed_done = False
        self._arm = 0
        self._n_capture = 0
        self._trigger: "str | None" = None
        if self._steps > 0:
            remaining = cfg.train.steps - start_step
            if remaining < self._steps:
                log.write("profile_skipped", reason=(
                    f"only {remaining} steps remain, profile_steps="
                    f"{self._steps} does not fit"))
            else:
                self._start = min(
                    start_step + 10, cfg.train.steps - self._steps
                )
                self._stop = self._start + self._steps

    def arm(self, steps: int = 5) -> bool:
        """Request a trigger-driven capture of ``steps`` steps starting
        at the next step boundary. Refused (False) while a capture is
        open or another request is pending."""
        if self._tracing or self._arm > 0:
            return False
        self._arm = max(1, int(steps))
        return True

    def before_step(self, step_i: int) -> None:
        # The fixed window normally opens exactly at _start; if an
        # anomaly capture is still open then (>= not ==), it opens at
        # the first free step boundary after — the user asked for this
        # window with --profile_steps, an anomaly must not silently
        # cancel it (a deferred window running past train.steps is
        # closed by finalize() with steps="truncated").
        if (self._start >= 0 and step_i >= self._start
                and not self._fixed_done and not self._tracing):
            self._fixed_done = True
            jax.profiler.start_trace(self._dir)
            self._tracing = True
            self._stop = step_i + self._steps
            self._n_capture = self._steps
            self._trigger = None
        elif self._arm > 0 and not self._tracing:
            n, self._arm = self._arm, 0
            jax.profiler.start_trace(self._dir)
            self._tracing = True
            self._stop = step_i + n
            self._n_capture = n
            self._trigger = "anomaly"

    def after_step(self, step_i: int, state) -> None:
        if self._tracing and step_i + 1 >= self._stop:
            jax.block_until_ready(state)
            jax.profiler.stop_trace()
            self._tracing = False
            extra = {"trigger": self._trigger} if self._trigger else {}
            self._log.write("profile", dir=self._dir,
                            steps=self._n_capture, **extra)

    def finalize(self) -> None:
        if self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False
            self._log.write("profile", dir=self._dir, steps="truncated")


class _ThroughputClock:
    """Train-loop throughput bookkeeping, shared by all three loops (the
    _ProfilerWindow pattern).

    Two rates per log window:
      * ``images_per_sec_window`` — the window rate. Window clocks reset
        after the first (compiling) step and after every eval pause, so
        no window folds a jit compile or an eval/checkpoint block in.
        Named ``_window`` (not plain ``images_per_sec``) so downstream
        tooling cannot mistake a single dispatch-clocked window for a
        fenced measurement (ADVICE r3).
      * ``images_per_sec_avg`` — cumulative images over accumulated
        TRAIN wall time only (compile excluded via the first-step reset;
        eval/checkpoint/persist excluded via pause()/resume()). The
        async dispatch bursts that can make individual windows overshoot
        average out here without paying any per-window device sync.

    Both rates pass the same FLOP-physics guard bench.py applies to
    every published number (utils/physics.rate_ceiling, fed by the AOT
    step's cost_analysis): a rate implying more FLOP/s than the chip's
    peak is published as None, never as a number (VERDICT r3 weak #5).
    """

    def __init__(self, batch_size: int, max_rate: "float | None" = None):
        now = time.time()
        self._batch = batch_size
        self._max_rate = max_rate
        self._first_done = False
        self._t_window = now
        self._imgs_window = 0
        self._t_resume = now
        self._train_time = 0.0
        self._imgs_avg = 0

    def set_ceiling(self, max_rate: "float | None") -> None:
        """Install the physics ceiling (global img/s) once the step
        program's FLOPs are known — i.e. right after the AOT compile."""
        self._max_rate = max_rate

    def _guard(self, rate: float) -> "float | None":
        if self._max_rate is not None and rate > self._max_rate:
            return None
        return round(rate, 2)

    def after_step(self) -> None:
        if not self._first_done:
            # The first dispatch compiled the program (~40-80s on the
            # TPU): restart every clock and drop its images.
            self._first_done = True
            now = time.time()
            self._t_window = now
            self._t_resume = now
            return
        self._imgs_window += self._batch
        self._imgs_avg += self._batch

    def pause(self) -> None:
        """Call before an eval/checkpoint block: train time stops."""
        self._train_time += time.time() - self._t_resume

    def resume(self) -> None:
        now = time.time()
        self._t_resume = now
        self._t_window = now
        self._imgs_window = 0

    def fields(self) -> dict:
        """Per-log-window rate fields; resets the window."""
        now = time.time()
        out = {
            "images_per_sec_window": self._guard(
                self._imgs_window / max(now - self._t_window, 1e-9)
            ),
        }
        train_time = self._train_time + (now - self._t_resume)
        if self._imgs_avg > 0:
            out["images_per_sec_avg"] = self._guard(
                self._imgs_avg / max(train_time, 1e-9)
            )
        self._t_window = now
        self._imgs_window = 0
        return out


def _aot_with_ceiling(cfg, mesh, clock, log, start_step, step_fn, *args):
    """First-batch AOT compile shared by both jax train loops: compile
    the step at its first real args (one compile, same as first-dispatch
    jit), write the timed "compile" record — what lets wall-clock
    artifacts like scripts/time_to_auc.py break compile out of
    time-to-target exactly — and install the throughput clock's physics
    ceiling from the program's cost_analysis FLOPs (utils/physics.py).
    Returns the callable for every subsequent step (the original jit on
    AOT fallback). Callers skip this under cfg.train.debug:
    jax_debug_nans' op-by-op NaN localization lives in the jit dispatch
    wrapper, which a Compiled call would bypass."""
    t_c = time.time()
    compiled, step_flops = train_lib.aot_compile_step(step_fn, *args)
    if compiled is not step_fn:
        log.write("compile", step=start_step,
                  sec=round(time.time() - t_c, 3))
    else:
        # AOT fell back to jit dispatch: the measured seconds cover the
        # FAILED attempt, and the real compile happens inside the first
        # dispatch — a sec here would let time-to-target artifacts
        # subtract the wrong thing. Record the fallback, publish no
        # number (the bench's refuse-don't-guess discipline).
        log.write("compile", step=start_step, sec=None, aot_fallback=True)
    # step_flops IS the program-ledger entry's flops (the one
    # cost_analysis parse; train_lib.aot_compile_step registered it):
    # the physics ceiling here and the device plane's MFU gauges read
    # the same number by construction.
    clock.set_ceiling(physics.rate_ceiling(
        step_flops, cfg.data.batch_size,
        int(np.prod(list(mesh.shape.values()))),
    ))
    entry = obs_device.program_ledger().get("train_step")
    if compiled is not step_fn and entry is not None:
        # Count dispatches for the MFU window: one plain-int increment
        # per step (the devicemon overhead pin's hot-path budget).
        inner = compiled

        def counted_step(*a, **kw):
            entry.note_call()
            return inner(*a, **kw)

        return counted_step
    return compiled


def _eval_cache_bytes(cfg: ExperimentConfig, data_dir: str, split: str) -> int:
    """Device bytes an eval cache for this split would actually hold:
    batches are padded to eval.batch_size, so the resident rows are
    ceil(n/B)*B, not n (a 20-image split at batch 8 uploads 24 rows)."""
    n = len(pipeline.read_split_metadata(data_dir, split)[0])
    b = cfg.eval.batch_size
    return -(-n // b) * b * cfg.model.image_size ** 2 * 3


def _eval_cache_for(
    cfg: ExperimentConfig, data_dir: str, split: str,
    reserved_bytes: int = 0,
):
    """A device-resident eval-batch cache (list to share across evals),
    or None when it should not exist: streamed loaders keep the per-eval
    re-read (their budget story never admitted the split into HBM), and
    even under the hbm/tiered loaders the split must clear the same
    budget discipline the loader applies to train data — all caches
    TOGETHER capped at 10% of the HBM budget (``reserved_bytes`` carries
    the footprint of caches already admitted, so a multi-split eval pass
    cannot pin 3x the gate by admitting each split individually), so the
    cache is never the one tenant that never asked (the train split's
    own gate allows up to 60%, and the train state needs the rest)."""
    if cfg.data.loader not in ("hbm", "tiered", "rawshard"):
        return None
    from jama16_retina_tpu.data import hbm_pipeline

    # read_split_metadata's memoized parse pass: the count comes from
    # the same per-(dir, split) cache the eval protocol already fills,
    # so the gate adds no second scan over the records.
    split_bytes = _eval_cache_bytes(cfg, data_dir, split)
    budget = hbm_pipeline.hbm_budget_bytes(
        budget_base_bytes=cfg.data.hbm_budget_bytes
    )
    if reserved_bytes + split_bytes <= 0.1 * budget:
        return []
    absl_logging.warning(
        "%s split (%.1f MB + %.1f MB already cached) exceeds 10%% of the "
        "HBM budget; evals stream from host instead of caching "
        "device-resident",
        split, split_bytes / 1e6, reserved_bytes / 1e6,
    )
    return None


def _save_due(cfg: ExperimentConfig, step: int) -> bool:
    """Is this eval's checkpoint due under train.save_every_evals?

    Phase derives from the step ordinal (step // eval_every), not a
    loop-local counter, so resume keeps the same save cadence. The final
    step is always due (the run must end durable); so is a stopping
    eval (forced inside _eval_and_track / the member-parallel block);
    so is the FIRST eval (ordinal 1) under train.save_first_eval
    (default on; ADVICE r4) — without it a fresh run has no checkpoint
    until ordinal n, and a crash in that window resumes from step 0."""
    if step >= cfg.train.steps:
        return True
    n = max(1, cfg.train.save_every_evals)
    ordinal = step // cfg.train.eval_every
    if cfg.train.save_first_eval and ordinal == 1:
        return True
    return ordinal % n == 0


def _eval_and_track(
    cfg: ExperimentConfig, log: RunLog, ckpt, step: int,
    predict_fn, state_for_save,
    best_auc: float, best_step: int, since_best: int,
    save_due: bool = True,
    save_fn=None,
    curve_gate: "_DtypeCurveGate | None" = None,
) -> tuple[float, int, int, bool, bool]:
    """The per-eval-interval block shared by every backend's train loop:
    val predict -> referable-DR AUC (the 5-class head collapses to
    P(grade>=2); SURVEY.md N11) -> best/min_delta tracking -> early-stop
    decision -> checkpoint. One copy so the backends cannot
    desynchronize on the early-stopping rule or the eval JSONL shape.

    ``state_for_save`` is a ZERO-ARG CALLABLE, invoked only when the
    save actually happens: materializing the state (a full device->host
    fetch on the jax path) is the dominant per-eval cost when saves are
    sparse (train.save_every_evals). ``save_due`` gates the periodic
    save; a stopping eval ALWAYS saves so the run ends durable. The
    eval record is logged BEFORE the save so time-to-target artifacts
    timestamp the moment the AUC was known, not the fetch behind it.
    Returns (..., stop, saved).

    ``save_fn(step, auc)`` (ISSUE 11) overrides the default
    ``ckpt.save(step, state_for_save(), ...)`` — the flax loops route
    saves through it for async/stall-attributed checkpointing.
    ``curve_gate`` is the train.dtype golden-curve parity gate, checked
    AFTER the eval record lands (the refusing trajectory stays visible
    in the JSONL) and BEFORE any save (a drifted state must not become
    a resume point)."""
    grades, probs = predict_fn()
    bin_probs = (
        probs if cfg.model.head == "binary"
        else metrics.referable_probs_from_multiclass(probs)
    )
    auc = metrics.roc_auc((grades >= 2).astype(np.float64), bin_probs)
    b_auc, b_step, since = _best_tracking_update(
        auc, best_auc, best_step, since_best, step, cfg.train.min_delta
    )
    best_auc, best_step, since_best = float(b_auc), int(b_step), int(since)
    # val_auc is logged at FULL precision: it is the replay source for
    # _reconstruct_best_tracking on resume (rounding would leak into the
    # resumed run's best tracking). best_auc is display-only.
    log.write("eval", step=step, val_auc=float(auc),
              best_auc=round(best_auc, 5), since_best=since_best)
    if curve_gate is not None:
        curve_gate.check(step, float(auc))
    stop = since_best >= cfg.train.early_stop_patience
    saved = save_due or stop
    if saved:
        if save_fn is not None:
            save_fn(step, float(auc))
        else:
            ckpt.save(step, state_for_save(), {"val_auc": auc})
    if stop:
        log.write("early_stop", step=step, best_step=best_step)
    return best_auc, best_step, since_best, stop, saved


def _is_preemption(e: BaseException) -> bool:
    """SIGTERM/SIGINT arrive as in-band SystemExit/KeyboardInterrupt
    (the flight recorder's handlers convert them; PR 4) — the shapes
    that mean 'the scheduler wants this host', for which a final
    durable resume point is worth the save."""
    return isinstance(e, (SystemExit, KeyboardInterrupt))


def _preempt_save(log: RunLog, step: int, save_fn,
                  grain_tee, workdir: str) -> None:
    """Preemption-safe shutdown (ISSUE 6): one unconditional latest/
    checkpoint at the last COMPLETED step plus the worker-mode grain
    state, written between the blackbox dump and process exit, so
    ``train.resume=true`` continues exactly where the SIGTERM landed
    instead of replaying from the last eval-time save (potentially
    eval_every-1 steps of lost work per preemption — routine-preemption
    economics, cf. supercomputer-scale training). ``save_fn(step)``
    does the backend-specific save and returns whether it wrote.
    Best-effort by design: a failing emergency save must not mask the
    original signal's exit path."""
    try:
        saved = save_fn(step)
        _persist_grain_state(grain_tee, workdir, step)
        log.write("preempt_save", step=step, saved=bool(saved))
        absl_logging.warning(
            "preemption: saved resume checkpoint at step %d "
            "(train.resume=true continues here)", step,
        )
    except Exception as e:  # noqa: BLE001 - exit path must proceed
        absl_logging.error(
            "preemption save at step %d failed: %s: %s — resume will "
            "fall back to the last eval-time checkpoint",
            step, type(e).__name__, e,
        )


def _state_snapshot(state):
    """On-device copy of the train state — one fast HBM pass, no host
    round-trip — so a background eval/save (train.eval_overlap /
    train.async_save) never reads buffers the next DONATING train step
    is about to consume. ``x + 0`` forces a fresh output buffer (a jit
    identity would alias the input). Costs one transient extra state
    residency, the same class of documented trade as serve's rollback
    retention. Module-level jit: one trace per state structure, cached
    across every boundary of the run."""
    return _SNAPSHOT_JIT(state)


_SNAPSHOT_JIT = jax.jit(lambda s: jax.tree.map(lambda x: x + 0, s))


def _async_knobs_guard(cfg: ExperimentConfig) -> None:
    """train.async_save / train.eval_overlap are single-process
    features: their work runs on background threads, and a multi-host
    state gather is a COLLECTIVE — all hosts must enter it together,
    which unsynchronized per-host threads cannot guarantee. Refuse
    loudly rather than deadlock the pod."""
    if (cfg.train.async_save or cfg.train.eval_overlap) \
            and jax.process_count() > 1:
        raise ValueError(
            "train.async_save/train.eval_overlap run their state "
            "gathers on background threads and cannot participate in "
            "multi-host collectives — unset them on multi-process runs"
        )


class _BgJob:
    """One background eval/save job (train.eval_overlap): runs ``fn`` on
    a daemon thread; ``result()`` joins and re-raises the job's
    exception in the caller — so a DtypeCurveRejected (or any eval
    failure) from the overlapped block still stops the run loudly, at
    the next collect point instead of mid-boundary."""

    def __init__(self, fn):
        import threading

        self._fn = fn
        self._result = None
        self._err: "BaseException | None" = None
        self._t = threading.Thread(
            target=self._run, daemon=True, name="eval-overlap"
        )
        self._t.start()

    def _run(self) -> None:
        try:
            self._result = self._fn()
        except BaseException as e:  # noqa: BLE001 - re-raised in result()
            self._err = e

    def done(self) -> bool:
        return not self._t.is_alive()

    def result(self):
        self._t.join()
        if self._err is not None:
            raise self._err
        return self._result


def _load_curve_ref(path: str, knob: str) -> dict:
    """step -> pinned val AUC from a metrics.jsonl golden curve; the
    loud refusals name the knob that pinned the path."""
    from jama16_retina_tpu.utils.logging import read_jsonl

    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{knob} {path!r} does not exist — pin a reference run's "
            "metrics.jsonl (or unset the knob to run ungated)"
        )
    ref: dict = {}
    for r in read_jsonl(path):
        if r.get("kind") != "eval" or r.get("step") is None:
            continue
        auc = r.get("ensemble_val_auc", r.get("val_auc"))
        if auc is not None and int(r["step"]) not in ref:
            ref[int(r["step"])] = float(auc)
    if not ref:
        raise ValueError(
            f"{knob} {path!r} holds no eval records — point it at the "
            "reference run's metrics.jsonl"
        )
    return ref


class _DtypeCurveGate:
    """The train-side golden-curve parity gate (ISSUE 11; extended by
    ISSUE 14), mirroring serve/quantize's canary gate. Two arms, same
    machinery:

      * DTYPE — a non-fp32 run must track the pinned fp32 eval-AUC
        trajectory (``train.dtype_curve_ref``) within
        ``train.dtype_curve_tol`` at every matching step, or the run is
        REFUSED (train_lib.DtypeCurveRejected);
      * RECIPE — a large-batch recipe run (LAMB / scaled LR) must track
        the pinned baseline-recipe curve (``train.recipe_curve_ref``)
        within ``train.recipe_curve_tol``, or it is REFUSED
        (train_lib.RecipeCurveRejected).

    fp32/baseline runs and ref-less cheap/recipe runs (logged as
    ungated) no-op. Both arms can gate one run — a bf16 LAMB run
    checks both curves at every eval."""

    def __init__(self, cfg: ExperimentConfig):
        # [(step->auc, tol, exc_cls, description)]
        self._arms: list = []
        tc = cfg.train
        if tc.dtype != "fp32":
            if tc.dtype_curve_ref:
                self._arms.append((
                    _load_curve_ref(
                        tc.dtype_curve_ref, "train.dtype_curve_ref"
                    ),
                    tc.dtype_curve_tol,
                    train_lib.DtypeCurveRejected,
                    f"train.dtype={tc.dtype} drifted from the pinned "
                    "fp32 golden curve"
                    " — the cheap numerics mode is refused; retrain in "
                    "fp32 or widen train.dtype_curve_tol deliberately",
                ))
            else:
                absl_logging.warning(
                    "train.dtype=%s runs UNGATED: no "
                    "train.dtype_curve_ref golden curve is pinned — "
                    "eval-AUC parity with fp32 is not being checked",
                    tc.dtype,
                )
        recipe_run = tc.optimizer == "lamb" or tc.lr_scale_ref_batch > 0
        if recipe_run and tc.recipe_curve_ref:
            self._arms.append((
                _load_curve_ref(
                    tc.recipe_curve_ref, "train.recipe_curve_ref"
                ),
                tc.recipe_curve_tol,
                train_lib.RecipeCurveRejected,
                f"the {tc.optimizer} large-batch recipe drifted from "
                "the pinned baseline golden curve"
                " — the recipe is refused; rebaseline or widen "
                "train.recipe_curve_tol deliberately",
            ))
        elif recipe_run:
            absl_logging.warning(
                "large-batch recipe (optimizer=%s, lr_scale_ref_batch="
                "%d) runs UNGATED: no train.recipe_curve_ref golden "
                "curve is pinned — eval-AUC parity with the baseline "
                "recipe is not being checked",
                tc.optimizer, tc.lr_scale_ref_batch,
            )

    def check(self, step: int, auc: float) -> None:
        for ref_map, tol, exc_cls, desc in self._arms:
            ref = ref_map.get(int(step))
            if ref is None:
                continue
            if abs(float(auc) - ref) > tol:
                head, _, tail = desc.partition(" — ")
                raise exc_cls(
                    f"{head} at step {step}: val AUC {float(auc):.5f} "
                    f"vs pinned {ref:.5f} "
                    f"(|Δ|={abs(float(auc) - ref):.5f} > tol={tol}) — "
                    f"{tail}"
                )


def _run_meta_path(workdir: str) -> str:
    return os.path.join(workdir, "run_meta.json")


def _load_or_write_run_meta(
    workdir: str, seed: int, cfg_name: str, resume: bool
) -> int:
    """Persist the data/PRNG seed so --resume reproduces the exact stream
    even if the CLI seed differs (SURVEY.md §5.4: the saved PRNG 'state'
    is just (seed, step) — keys are derived by fold_in(key(seed), step)
    inside the jit step, and the pipeline is a pure function of seed).

    The persisted seed wins ONLY on resume; a fresh run in a reused
    workdir takes the requested seed and rewrites the meta (otherwise a
    deliberately re-seeded rerun would silently duplicate the old run).
    """
    import json

    path = _run_meta_path(workdir)
    if resume and os.path.exists(path):
        with open(path) as f:
            meta = json.load(f)
        if int(meta.get("seed", seed)) != seed:
            absl_logging.warning(
                "resuming with run_meta seed %s (CLI seed %s ignored for "
                "stream continuity)", meta["seed"], seed,
            )
        return int(meta.get("seed", seed))
    os.makedirs(workdir, exist_ok=True)
    from jama16_retina_tpu.integrity import artifact as artifact_lib

    artifact_lib.write_json(path, {"seed": seed, "config": cfg_name},
                            indent=None)
    return seed


def _warm_start_state(cfg: ExperimentConfig, model, state, mesh):
    """Seed a fresh step-0 state with the best checkpoint under
    ``cfg.train.init_from`` (ISSUE 8 warm-start entry): params and
    batch_stats transplant; the optimizer, schedule, and step counter
    stay fresh — a fine-tune is a NEW run that starts from good
    weights, not a resume. When this run carries an EMA shadow, it
    seeds from the donor's shadow (or its params when the donor has
    none) so the first evals don't average against random init.
    restore_for_eval owns the EMA/legacy tree reconciliation; an
    architecture mismatch surfaces as its loud restore error."""
    donor = restore_for_eval(cfg, model, cfg.train.init_from)
    updates = {"params": donor.params, "batch_stats": donor.batch_stats}
    if state.ema_params is not None:
        updates["ema_params"] = (
            donor.ema_params if donor.ema_params is not None
            else donor.params
        )
    return jax.device_put(
        state.replace(**updates), mesh_lib.replicated(mesh)
    )


def _distill_stream(cfg: ExperimentConfig, model, stream, mesh):
    """Wrap the train stream with teacher soft targets (ISSUE 10
    distillation; ``train.distill_from``): every teacher member restores
    ONCE into a device-resident stacked tree (the serving engine's
    restore-once discipline applied to training), each batch's CLEAN
    images score through one stacked forward, and the ensemble-averaged
    soft scores ride the batch under the ``"soft"`` key — the target
    train_lib.loss_fn trains the student against. The teacher sees the
    un-augmented pixels (the scores the live ensemble would serve);
    augmentation still randomizes the student's view in-step, the
    standard noisy-student asymmetry. Single-host streams only (the
    teacher forward places host batches directly)."""
    dirs = ckpt_lib.discover_member_dirs(cfg.train.distill_from)
    teacher = train_lib.stack_states([
        restore_for_eval(cfg, model, d) for d in dirs
    ])
    teacher = jax.device_put(teacher, mesh_lib.replicated(mesh))
    tstep = train_lib.make_serving_step(cfg, model, mesh=mesh)
    absl_logging.info(
        "distilling from %d teacher member(s) under %s",
        len(dirs), cfg.train.distill_from,
    )

    def wrapped():
        for batch in stream:
            member = np.asarray(jax.device_get(
                tstep(teacher, {"image": np.asarray(batch["image"])})
            ))
            soft = np.asarray(
                metrics.ensemble_average(list(member)), np.float32
            )
            yield {**batch, "soft": soft}

    return wrapped()


def fit(
    cfg: ExperimentConfig,
    data_dir: str,
    workdir: str,
    seed: int | None = None,
    mesh=None,
) -> dict:
    """Train one model; returns {'best_auc', 'best_step', 'stopped_early'}."""
    seed = cfg.train.seed if seed is None else seed
    seed = _load_or_write_run_meta(workdir, seed, cfg.name, cfg.train.resume)
    prev_debug_nans = jax.config.jax_debug_nans
    if cfg.train.debug:
        jax.config.update("jax_debug_nans", True)
    mesh = mesh or mesh_lib.make_mesh(
        cfg.parallel.num_devices, axis=cfg.parallel.data_axis
    )
    # Large-batch recipe resolution (ISSUE 14): linear LR scaling tied
    # to the global batch, applied ONCE here so the optimizer/schedule
    # built below see the effective LR (pure function of cfg + mesh —
    # resume re-derives the identical value).
    cfg = train_lib.resolve_large_batch(cfg, mesh)
    log = RunLog(workdir, tensorboard=cfg.train.tensorboard,
                 fresh=not cfg.train.resume)
    log.write("config", name=cfg.name, seed=seed,
              n_devices=int(np.prod(list(mesh.shape.values()))))

    model = models.build(cfg.model)
    state, tx = train_lib.create_state(cfg, model, jax.random.key(seed))
    state = jax.device_put(state, mesh_lib.replicated(mesh))
    # Donation conflicts with jax_debug_nans' op-by-op re-execution (the
    # donated buffers are gone by the time the NaN checker re-runs), so
    # debug mode trades the in-place state update for usable NaN reports.
    train_step = train_lib.make_train_step(
        cfg, model, tx, mesh=mesh, donate=not cfg.train.debug
    )
    eval_step = train_lib.make_eval_step(cfg, model, mesh=mesh)
    # Device-resident val batches between evals under the hbm loader
    # (budget-gated; None = stream every eval as before).
    val_cache = _eval_cache_for(cfg, data_dir, "val")
    ckpt = ckpt_lib.Checkpointer(
        os.path.abspath(workdir), max_to_keep=cfg.train.max_to_keep
    )
    # Raw-speed training (ISSUE 11): async checkpoint worker, eval
    # overlap, and the train.dtype golden-curve parity gate. Overlap
    # implies the async worker: orbax pins all of a manager's saves to
    # ONE thread (its finalize-thread reset is save-thread-affine), and
    # per-eval _BgJob threads would violate that — the single AsyncSaver
    # worker is the one save thread either way.
    _async_knobs_guard(cfg)
    curve_gate = _DtypeCurveGate(cfg)
    overlap = cfg.train.eval_overlap
    saver = (
        ckpt_lib.AsyncSaver()
        if (cfg.train.async_save or overlap) else None
    )
    eval_job: "_BgJob | None" = None

    start_step = 0
    best_auc, best_step, since_best = -np.inf, 0, 0
    if cfg.train.resume and ckpt.latest_step is not None:
        _check_ema_compat(ckpt, cfg, workdir, ckpt.latest_step)
        state = ckpt.restore(ckpt_lib.abstract_like(state), ckpt.latest_step)
        state = jax.device_put(state, mesh_lib.replicated(mesh))
        start_step = int(jax.device_get(state.step))
        # Rebuild best/early-stop tracking as of the interruption —
        # forgetting the pre-interruption peak would both overrun the
        # patience budget and let a worse post-resume step masquerade as
        # "best" in the report.
        b_auc, b_step, since = _reconstruct_best_tracking(
            workdir, start_step, cfg, [ckpt]
        )
        best_auc, best_step, since_best = (
            float(b_auc[0]), int(b_step[0]), int(since[0])
        )
        log.write("resume", step=start_step,
                  best_auc=(round(best_auc, 5) if np.isfinite(best_auc) else None),
                  since_best=since_best)
    elif cfg.train.init_from:
        # Warm start (never when a resume found a checkpoint above: a
        # resumed run continues ITSELF; the donor only seeds step 0).
        state = _warm_start_state(cfg, model, state, mesh)
        log.write("warm_start", init_from=cfg.train.init_from)

    base_key = jax.random.key(seed)
    _obs_begin_run(cfg)  # before the pipelines create their metrics
    # Closed-loop ingest autotuner (data/autotune.py; data.autotune):
    # live content-invariant knobs the loaders poll, adjusted at every
    # log-window boundary below from the same stall attribution the
    # window's train record carries.
    knobs, tuner = _autotune_for(cfg, mesh=mesh)
    # skip_batches=start_step: one batch per completed step, so a resumed
    # stream continues exactly where the interrupted one stopped
    # (pipeline determinism; SURVEY.md §5.4). Augment/dropout keys need
    # no restoring — they are fold_in(base_key, state.step) in-step.
    stream = _train_stream(
        cfg, data_dir, seed, skip_batches=start_step, mesh=mesh,
        grain_state=_load_grain_state(cfg, workdir, start_step),
        knobs=knobs,
    )
    grain_tee = None
    if cfg.data.loader == "grain" and cfg.data.grain_workers > 0:
        # Worker-mode positions have no (seed, step) closed form — tee
        # the stream so each checkpoint can persist its exact state.
        stream = grain_tee = _GrainStateTee(
            stream, start_step, keep=cfg.data.prefetch_batches + 4
        )
    if cfg.train.distill_from:
        # Ensemble distillation (ISSUE 10): teacher soft scores join
        # every batch; the jit step's loss switches to soft targets on
        # the presence of the "soft" key (train_lib.loss_fn). Resume
        # stays exact — the wrapper is a pure per-batch function of the
        # same deterministic stream.
        stream = _distill_stream(cfg, model, stream, mesh)
        log.write("distill", distill_from=cfg.train.distill_from)
    batches = pipeline.device_prefetch(
        stream,
        sharding=mesh_lib.batch_sharding(mesh),
        size=cfg.data.prefetch_batches,
        per_shard=cfg.data.stage_per_shard,
        knobs=knobs,
    )

    profiler = _ProfilerWindow(cfg, log, workdir, start_step)
    flight = _flight_for(cfg, workdir, profiler)
    if flight is not None:
        flight.install_signal_handlers()

    stopped_early = False
    clock = _ThroughputClock(cfg.data.batch_size)
    last_step = start_step
    _, stalls, snap = _telemetry_for(cfg, log, workdir, flight=flight)

    save_stall = [0.0]
    # Preemption latch (review fix): the SIGTERM path must not spend
    # its grace window joining an in-flight overlapped EVAL — it only
    # needs the already-queued SAVES settled. Once set, a still-running
    # _BgJob skips its own save; the emergency latest/-only save then
    # rides the same worker queue behind anything already submitted.
    preempted = {"flag": False}

    def _save_fn(step_now: int, auc: float) -> None:
        """The eval-time save, stall-attributed (the new 'save'
        segment). Sync: the device->host fetch + orbax write block here
        (old behavior, now measured). Async (train.async_save): an
        on-device snapshot + queue put is the whole stall — the fetch
        and write run on the AsyncSaver worker."""
        t0 = time.perf_counter()
        if saver is not None:
            snap_state = _state_snapshot(state)

            def _do(snap_state=snap_state, step_now=step_now, auc=auc):
                ckpt.save(step_now, jax.device_get(snap_state),
                          {"val_auc": auc})
                _persist_grain_state(grain_tee, workdir, step_now,
                                     kept_steps=ckpt.all_steps())

            saver.submit(_do)
        else:
            ckpt.save(step_now, jax.device_get(state), {"val_auc": auc})
            _persist_grain_state(grain_tee, workdir, step_now,
                                 kept_steps=ckpt.all_steps())
        dt = time.perf_counter() - t0
        stalls.add("save", dt)
        save_stall[0] += dt

    def _submit_eval(step_now: int) -> _BgJob:
        """Dispatch the whole eval block (val predict -> AUC -> gate ->
        best tracking -> save) over an on-device snapshot on a
        background thread (train.eval_overlap); training continues
        through what used to be the eval pause."""
        snap_state = _state_snapshot(state)
        ba, bs, sb = best_auc, best_step, since_best

        def _overlap_save(step_now: int, auc: float,
                          snap_state=snap_state) -> None:
            if preempted["flag"]:
                # The emergency latest/ save owns the exit path; a
                # boundary save racing it could leave latest/ on an
                # older step.
                return

            # The job's snapshot IS the save source — never touch the
            # live (donated) state from this thread.
            def _do():
                # Re-checked on the WORKER too: the eval thread can pass
                # the check above just before the latch sets, but the
                # flag is always set before the emergency job enqueues —
                # so by the time a late boundary save reaches the worker
                # it sees the latch and cannot roll latest/ back.
                if preempted["flag"]:
                    return
                ckpt.save(step_now, jax.device_get(snap_state),
                          {"val_auc": auc})
                _persist_grain_state(grain_tee, workdir, step_now,
                                     kept_steps=ckpt.all_steps())

            if saver is not None:
                saver.submit(_do)
            else:
                _do()

        def job():
            return _eval_and_track(
                cfg, log, ckpt, step_now,
                lambda: predict_split(
                    cfg, model, snap_state, data_dir, "val", mesh,
                    eval_step=eval_step, cache=val_cache,
                )[:2],
                lambda: jax.device_get(snap_state),
                ba, bs, sb, save_due=_save_due(cfg, step_now),
                save_fn=_overlap_save, curve_gate=curve_gate,
            )

        return _BgJob(job)

    try:
        for step_i in range(start_step, cfg.train.steps):
            t_step = time.perf_counter()
            # Fault seam (obs/faultinject.py site "trainer.step"): one
            # global read + branch unarmed; chaos plans inject mid-run
            # failure here to drive the preempt/resume path.
            faultinject.check("trainer.step")
            profiler.before_step(step_i)
            # Stall attribution (obs/spans.py): time blocked in next()
            # is INPUT STARVATION — the pipeline-fed gap measured where
            # it bites — and the train_step call is async dispatch
            # pressure; both land in this window's `train` record.
            with stalls.measure("input"):
                batch = next(batches)
            if step_i == start_step and not cfg.train.debug:
                train_step = _aot_with_ceiling(
                    cfg, mesh, clock, log, start_step,
                    train_step, state, batch, base_key,
                )
            with stalls.measure("dispatch"):
                state, m = train_step(state, batch, base_key)
            last_step = step_i + 1
            clock.after_step()
            if snap is not None:
                snap.progress(step_i + 1)
            # Straggler sentinel: dt stops BEFORE profiler.after_step
            # (closing a profiler window block_until_ready-syncs the
            # whole device backlog — a legitimate pause that must not
            # read as a slow step, exactly like the eval block below).
            dt_step = time.perf_counter() - t_step
            profiler.after_step(step_i, state)
            if flight is not None:
                flight.progress(step_i + 1)
                flight.note_step_time(dt_step, step=step_i + 1)

            if (step_i + 1) % cfg.train.log_every == 0:
                loss = float(m["loss"])
                if flight is not None:
                    # Cheap non-finite sentinel on the ALREADY-fetched
                    # loss (no extra device sync).
                    flight.note_loss(loss, step=step_i + 1)
                stall_fields = stalls.fields()
                log.write(
                    "train", step=step_i + 1, loss=loss,
                    **clock.fields(), **stall_fields,
                )
                if tuner is not None:
                    # One tumbling tuner window per log window: the
                    # stall attribution just computed IS the tuner's
                    # starvation signal (observability as control).
                    tuner.observe(
                        stall_fields["window_sec"],
                        stall_fields["input_wait_sec"],
                    )
                if snap is not None:
                    snap.maybe_flush()

            # Overlapped-eval completion poll (train.eval_overlap):
            # collect a finished background eval the step after it
            # lands, so early stopping / a DtypeCurveRejected fires at
            # most one step late instead of at the next boundary.
            if eval_job is not None and eval_job.done():
                best_auc, best_step, since_best, stop, _ = eval_job.result()
                eval_job = None
                if stop:
                    stopped_early = True
                    break

            if (step_i + 1) % cfg.train.eval_every == 0 or step_i + 1 == cfg.train.steps:
                if overlap:
                    if eval_job is not None:
                        # One eval in flight at a time: the previous
                        # boundary's job must land (its best-tracking
                        # chains into this one). Normally long done —
                        # this wait is the only stall overlap keeps.
                        clock.pause()
                        with stalls.measure("pause"):
                            best_auc, best_step, since_best, stop, _ = (
                                eval_job.result()
                            )
                        eval_job = None
                        clock.resume()
                        if stop:
                            stopped_early = True
                            break
                    eval_job = _submit_eval(step_i + 1)
                else:
                    clock.pause()
                    t_pause = time.perf_counter()
                    save_stall[0] = 0.0
                    best_auc, best_step, since_best, stop, saved = _eval_and_track(
                        cfg, log, ckpt, step_i + 1,
                        lambda: predict_split(
                            cfg, model, state, data_dir, "val", mesh,
                            eval_step=eval_step, cache=val_cache,
                        )[:2],
                        lambda: jax.device_get(state),
                        best_auc, best_step, since_best,
                        save_due=_save_due(cfg, step_i + 1),
                        save_fn=_save_fn, curve_gate=curve_gate,
                    )
                    # 'pause' is the eval-only remainder: _save_fn
                    # already attributed its own blocking time to the
                    # disjoint 'save' segment.
                    stalls.add("pause", max(
                        0.0,
                        time.perf_counter() - t_pause - save_stall[0],
                    ))
                    clock.resume()
                    if stop:
                        stopped_early = True
                        break
    except BaseException as e:
        # Flight recorder (obs/flightrec.py): dump the black box for an
        # unhandled exception — including SIGTERM/SIGINT, which the
        # installed handlers convert to in-band exceptions so this dump
        # runs in normal (not async-signal) context — then re-raise.
        if flight is not None:
            flight.record_exception(e)
        if _is_preemption(e) and last_step > start_step:
            # Do NOT join an in-flight overlapped EVAL — its predict
            # pass can cost most of the SIGTERM grace window (review
            # fix). Latch the preempt flag so the job skips its own
            # save, then settle only the already-QUEUED saves; the
            # emergency save rides the same worker queue behind them
            # (one save thread per manager — the orbax affinity rule).
            preempted["flag"] = True
            if saver is not None:
                try:
                    saver.drain()
                except BaseException:  # noqa: BLE001 - exit path
                    pass
            def _save(step):
                # With an AsyncSaver the emergency save rides the SAME
                # worker thread every other save used — orbax pins a
                # manager's saves to one thread (finalize-thread reset
                # is save-thread-affine).
                if saver is not None:
                    out = {"saved": False}

                    def _do():
                        out["saved"] = ckpt.save_latest(
                            step, jax.device_get(state)
                        )

                    saver.submit(_do)
                    saver.drain()
                    ckpt.wait()
                    return out["saved"]
                saved = ckpt.save_latest(step, jax.device_get(state))
                ckpt.wait()  # durable BEFORE the process exits
                return saved

            _preempt_save(log, last_step, _save, grain_tee, workdir)
        raise
    finally:
        # Early stop / short runs / exceptions must not leak an open
        # trace, installed signal handlers, or a flipped global debug
        # flag.
        profiler.finalize()
        if flight is not None:
            flight.uninstall_signal_handlers()
        if cfg.train.debug:
            jax.config.update("jax_debug_nans", prev_debug_nans)

    # Collect the tail (ISSUE 11): an overlapped final eval and any
    # queued async saves must land before the checkpointer closes —
    # their exceptions (incl. DtypeCurveRejected) surface here.
    if eval_job is not None:
        best_auc, best_step, since_best, stop, _ = eval_job.result()
        eval_job = None
        if stop:
            stopped_early = True
    if saver is not None:
        saver.close()
    ckpt.wait()
    ckpt.close()
    if cfg.obs.quality.profile_out:
        _emit_quality_profile(
            cfg, data_dir,
            lambda: predict_split(
                cfg, model, state, data_dir, "val", mesh,
                eval_step=eval_step, cache=val_cache,
            )[:2],
            log,
        )
    if snap is not None:
        snap.close()  # final telemetry/heartbeat flush; log still open
    log.close()
    return {
        # None (not -inf) when no eval ever ran — e.g. --resume with the
        # restored step already at train.steps. json.dumps would otherwise
        # emit -Infinity, which is not valid JSON.
        "best_auc": float(best_auc) if np.isfinite(best_auc) else None,
        "best_step": int(best_step),
        "stopped_early": stopped_early,
    }


def fit_ensemble(
    cfg: ExperimentConfig, data_dir: str, workdir: str,
    backend: str = "flax",
) -> list[dict]:
    """Train k independently-seeded members (reference R11, BASELINE.json:10),
    each in its own member_NN checkpoint dir.

    ``train.ensemble_parallel=true`` routes to the member-parallel form
    (one stacked XLA program, train_lib.make_ensemble_train_step) —
    same seeds, same checkpoint layout, k× fewer dispatches."""
    if cfg.train.ensemble_parallel:
        if backend != "flax":
            raise ValueError(
                "ensemble_parallel is a flax-path feature (the stacked "
                "member axis is a jax.vmap/GSPMD construct); use the "
                "sequential driver for --device=tf"
            )
        n_dev = cfg.parallel.num_devices or len(jax.devices())
        if n_dev < 2 and not cfg.train.ensemble_parallel_force:
            # Measured-speedup gate: single-chip the stacked step runs
            # BELOW the sequential member rate (bench
            # ensemble4_parallel_speedup 0.85 in r05 — weight/optimizer
            # HBM traffic scales with members while batch does not), so
            # the stacked path on a 1-device mesh ships a known
            # slowdown. The wins it exists for — member-axis mesh
            # topology, k× fewer dispatches amortized across chips —
            # need >= 2 devices.
            absl_logging.warning(
                "train.ensemble_parallel disabled: 1-device mesh and the "
                "stacked step measures SLOWER than sequential members "
                "there (bench ensemble4_parallel_speedup < 1.0); "
                "training the %d members sequentially instead. Set "
                "train.ensemble_parallel_force=true to override.",
                cfg.train.ensemble_size,
            )
        else:
            return fit_ensemble_parallel(cfg, data_dir, workdir)
    fit_fn = fit_tf if backend == "tf" else fit
    results = []
    for member in range(cfg.train.ensemble_size):
        mdir = ckpt_lib.member_dir(workdir, member)
        res = fit_fn(cfg, data_dir, mdir, seed=cfg.train.seed + member)
        results.append({"member": member, "workdir": mdir, **res})
    return results


def _predict_split_members(
    cfg: ExperimentConfig, state, data_dir: str, split: str,
    mesh, eval_step, cache: list | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """predict_split for a STACKED ensemble state: one vmapped forward
    scores all k members per batch -> (grades [n], probs [k, n(, C)]).

    Every process reads the FULL eval stream and full-local placement
    slices each device's shard — the ('member','data') layout's data
    columns interleave across processes, so neither the 1-D process-major
    block contract of eval_batches' local rows nor eval.sharded's decode
    sharding applies here (the flag is ignored, loudly).

    ``cache``: pass the same list across repeated evals of one split to
    keep its batches DEVICE-resident between them (the hbm-loader
    residency philosophy applied to eval): the first call fills it with
    (dev_batch, kept_grades, keep) tuples, later calls skip the host
    re-parse and re-upload entirely (the val split re-upload is ~2-3 s
    per eval on this environment's link — docs/PERF.md §Eval)."""
    if cache:
        grades_all, probs_all = [], []
        for dev_batch, kept_grades, keep in cache:
            probs = np.asarray(jax.device_get(eval_step(state, dev_batch)))
            grades_all.append(kept_grades)
            probs_all.append(probs[:, keep])
        return np.concatenate(grades_all), np.concatenate(probs_all, axis=1)
    if cfg.eval.sharded and jax.process_count() > 1:
        absl_logging.warning(
            "eval.sharded has no effect on the member-parallel driver's "
            "evals: its ('member','data') layout has no per-process "
            "contiguous row block — every host decodes the full eval set"
        )
    grades_all, probs_all = [], []
    for batch in pipeline.eval_batches(
        data_dir, split, cfg.eval.batch_size, cfg.model.image_size,
        process_index=0, process_count=1,
    ):
        if mesh is not None:
            dev_batch = mesh_lib.place_full_local(
                {"image": batch["image"]}, mesh_lib.batch_sharding(mesh)
            )
        else:
            dev_batch = jax.device_put({"image": batch["image"]})
        probs = np.asarray(jax.device_get(eval_step(state, dev_batch)))
        keep = batch["mask"] > 0
        grades_all.append(batch["grade"][keep])
        probs_all.append(probs[:, keep])
        if cache is not None:
            cache.append((dev_batch, batch["grade"][keep], keep))
    return np.concatenate(grades_all), np.concatenate(probs_all, axis=1)


def fit_ensemble_parallel(
    cfg: ExperimentConfig, data_dir: str, workdir: str
) -> list[dict]:
    """Member-parallel ensemble training: all k members advance in ONE
    jit dispatch per step over a ('member', 'data') mesh.

    The TPU-first redesign of the reference's k sequential runs (R11):
    members are independent replicas, so the stacked member dim shards
    across chips with zero cross-member collectives (single-chip it is
    ~parity with sequential — see the ensemble_parallel note in
    configs.py and bench's ensemble4_parallel_speedup; the win is mesh
    topology on pods plus k× fewer dispatches). Member m keeps the
    sequential driver's seed
    (train.seed + m) for init/augment/dropout; all members share the
    train.seed batch stream (documented delta — see configs.py).
    Checkpoints land in the same member_NN/{best,latest} layout, best-by-
    val-AUC per member, so evaluate.py/predict.py ensemble discovery is
    oblivious to how the members were trained. Early stopping fires when
    EVERY member has exhausted its patience; each member's best
    checkpoint is whatever its own val-AUC peak was. ``--resume``
    restores every member's latest checkpoint (this driver keeps them in
    lock-step) and continues the exact stream via skip_batches, same as
    fit(); after a save torn by a mid-eval crash it falls back to the
    newest step ALL members can still restore.

    Multi-host: works. Each process reads the FULL batch stream (the
    ('member','data') device layout interleaves data columns across
    processes, so there is no per-process row block — see
    mesh_lib.place_full_local), state/keys are created INSIDE jit with
    member-axis out-shardings, and checkpoint/metric gathers reshard to
    replicated first (an ICI all-gather) so device_get is host-legal.
    """
    k = cfg.train.ensemble_size
    if cfg.train.init_from:
        raise ValueError(
            "train.init_from warm-starts ONE member from ONE checkpoint "
            "dir; the member-parallel driver would seed every stacked "
            "member identically (diversity collapse). Fine-tune members "
            "through sequential fit() calls — the lifecycle controller's "
            "RETRAIN phase does exactly that"
        )
    mesh = mesh_lib.make_ensemble_mesh(
        k, cfg.parallel.num_devices,
        member_axis_size=cfg.parallel.member_axis_size,
        data_axis=cfg.parallel.data_axis,
    )
    cfg = train_lib.resolve_large_batch(cfg, mesh)
    prev_debug_nans = jax.config.jax_debug_nans
    if cfg.train.debug:
        jax.config.update("jax_debug_nans", True)
    # The persisted member-0 seed is the base seed on resume (stream
    # continuity — same rule as fit()); member m's meta then pins base+m.
    seed = _load_or_write_run_meta(
        ckpt_lib.member_dir(workdir, 0), cfg.train.seed, cfg.name,
        cfg.train.resume,
    )
    for m in range(1, k):
        persisted = _load_or_write_run_meta(
            ckpt_lib.member_dir(workdir, m), seed + m, cfg.name,
            cfg.train.resume,
        )
        if persisted != seed + m:
            # The helper's "CLI seed ignored" warning promises stream
            # continuity, but this driver derives member streams from
            # base+m regardless — a mismatched persisted seed means the
            # workdir belongs to a different ensemble run; silently
            # changing member m's PRNG stream would corrupt it.
            raise ValueError(
                f"member {m} run_meta pins seed {persisted}, but this "
                f"ensemble derives member seeds from base {seed} "
                f"(expected {seed + m}) — the workdir belongs to a "
                "differently-seeded ensemble; resume with the original "
                "base seed or use a fresh workdir"
            )
    # Marker distinguishing this driver's workdirs from the sequential
    # driver's (identical member_NN layout otherwise). The torn-save
    # rollback below DELETES checkpoints; it must never fire on a
    # half-finished sequential-ensemble workdir, whose members are
    # legitimately at different steps.
    marker = os.path.join(workdir, ".member_parallel")
    # Read BEFORE writing: a resume of a sequential workdir must not
    # first stamp it as member-parallel and then trust the stamp.
    was_member_parallel = os.path.exists(marker)
    os.makedirs(workdir, exist_ok=True)
    with open(marker, "w") as f:
        f.write("workdir written by trainer.fit_ensemble_parallel\n")
    log = RunLog(workdir, tensorboard=cfg.train.tensorboard,
                 fresh=not cfg.train.resume)
    log.write(
        "config", name=cfg.name, seed=seed, ensemble_parallel=True,
        n_members=k, mesh_shape=dict(mesh.shape),
    )

    # manual_data wants axis_name='data' BN (explicit moment pmeans);
    # harmless otherwise: axis_name only engages at train=True inside
    # the manual region, so init/eval/checkpoint trees are identical.
    manual_data = cfg.train.ensemble_manual_data and mesh.size > 1
    model = models.build(
        cfg.model, axis_name="data" if manual_data else None
    )
    # State and keys are built INSIDE jit with member-axis out-shardings
    # (multi-host legal: no host-side stacked copy to place).
    state, tx = train_lib.create_ensemble_state(
        cfg, model, [seed + m for m in range(k)], mesh=mesh
    )
    train_step = train_lib.make_ensemble_train_step(
        cfg, model, tx, mesh=mesh, donate=not cfg.train.debug,
        manual_data=manual_data,
    )
    eval_step = train_lib.make_ensemble_eval_step(cfg, model, mesh=mesh)
    # Under the hbm loader the val split stays device-resident between
    # evals too (same residency philosophy; the cache is filled on the
    # first eval, budget-gated by _eval_cache_for).
    val_cache = _eval_cache_for(cfg, data_dir, "val")
    # Checkpoint/host gathers: on multi-host, reshard member-sharded ->
    # replicated first (an all-gather riding ICI) — device_get is only
    # legal for fully-addressable arrays there. Single-process the state
    # is already fully addressable and the k-fold replicated copy would
    # be a pure HBM spike (k=10 Inception states are GBs), so skip it.
    if jax.process_count() > 1:
        gather_state = jax.jit(
            lambda s: s, out_shardings=mesh_lib.replicated(mesh)
        )
    else:
        def gather_state(s):
            return s
    base_keys = train_lib.stack_member_keys(
        [seed + m for m in range(k)], mesh=mesh
    )
    ckpts = [
        ckpt_lib.Checkpointer(
            os.path.abspath(ckpt_lib.member_dir(workdir, m)),
            max_to_keep=cfg.train.max_to_keep,
        )
        for m in range(k)
    ]
    # Raw-speed training (ISSUE 11): async checkpoint worker, eval
    # overlap, and the train.dtype golden-curve parity gate (checked on
    # the ENSEMBLE val AUC — the quantity this driver optimizes for).
    # Overlap implies the async worker (one save thread per manager —
    # the orbax finalize-thread affinity rule; see fit()).
    _async_knobs_guard(cfg)
    curve_gate = _DtypeCurveGate(cfg)
    overlap = cfg.train.eval_overlap
    saver = (
        ckpt_lib.AsyncSaver()
        if (cfg.train.async_save or overlap) else None
    )
    eval_job: "_BgJob | None" = None

    start_step = 0
    best_auc = np.full((k,), -np.inf)
    best_step = np.zeros((k,), np.int64)
    since_best = np.zeros((k,), np.int64)
    if cfg.train.resume:
        latest = [c.latest_step for c in ckpts]
        if any(s is not None for s in latest):
            # This driver checkpoints every member in lock-step at each
            # save-due eval (train.save_every_evals; skipped evals save
            # no member), so an intact member-parallel workdir has all
            # members at ONE step. Differing steps mean either a
            # sequential-run workdir OR a save torn by a crash between
            # the per-member save() calls — recover by rolling every
            # member back to the newest step they ALL still have
            # (best/ retention often keeps it).
            if None in latest or len(set(latest)) != 1:
                if not was_member_parallel:
                    # Members at different steps in a workdir this
                    # driver never stamped = a half-finished SEQUENTIAL
                    # ensemble; rolling back would delete its perfectly
                    # valid newer checkpoints.
                    raise ValueError(
                        f"member checkpoints are at different steps "
                        f"{latest} and this is not a member-parallel "
                        "workdir — resume the sequential ensemble with "
                        "train.ensemble_parallel=false. (If this workdir "
                        "was in fact written by a member-parallel run "
                        "OLDER than the .member_parallel marker, create "
                        "that marker file in the workdir to enable the "
                        "torn-save rollback instead.)"
                    )
                common = set.intersection(
                    *[c.all_steps() for c in ckpts]
                ) if ckpts else set()
                if not common:
                    raise ValueError(
                        f"member checkpoints are at different steps "
                        f"{latest} and share no restorable step — a "
                        "save was torn by a crash and retention has "
                        "dropped the last common step; the workdir "
                        "needs manual surgery (or restart fresh)"
                    )
                step0 = max(common)
                absl_logging.warning(
                    "member latest checkpoints disagree (%s) — likely a "
                    "save torn by a crash; rolling back to the newest "
                    "common step %d", latest, step0,
                )
                # Purge the abandoned timeline: stale newer checkpoints
                # would collide with the re-run's saves at the same
                # steps and hijack a later resume.
                for c in ckpts:
                    c.delete_newer_than(step0)
                # The rolled-back steps' grain states are part of that
                # abandoned timeline too (ADVICE r3).
                _prune_grain_state(
                    workdir, {s for s in set.union(
                        *[c.all_steps() for c in ckpts]) if s <= step0},
                )
            else:
                step0 = latest[0]
            for m, c in enumerate(ckpts):
                _check_ema_compat(
                    c, cfg, ckpt_lib.member_dir(workdir, m), step0
                )
            # Shape-only skeleton per member (leaf[1:] strips the member
            # dim) — no device->host transfer of the fresh stacked state.
            member_abstract = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(np.shape(x)[1:], x.dtype),
                state,
            )
            members = [c.restore(member_abstract, step0) for c in ckpts]
            host_state = jax.tree.map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]), *members
            )
            state = mesh_lib.place_full_local(
                host_state, mesh_lib.member_sharding(mesh)
            )
            start_step = int(step0)
            # Same eval-history replay fit() does on resume — exact
            # min_delta/patience semantics, per member.
            best_auc, best_step, since_best = _reconstruct_best_tracking(
                workdir, start_step, cfg, ckpts
            )
            log.write(
                "resume", step=start_step,
                best_auc_per_member=[
                    (round(float(a), 5) if np.isfinite(a) else None)
                    for a in best_auc
                ],
            )

    _obs_begin_run(cfg)  # before the pipelines create their metrics
    knobs, tuner = _autotune_for(cfg, mesh=mesh)
    stream = _train_stream(
        cfg, data_dir, seed, skip_batches=start_step, mesh=mesh,
        full_batches=True,
        grain_state=_load_grain_state(cfg, workdir, start_step),
        knobs=knobs,
    )
    grain_tee = None
    if cfg.data.loader == "grain" and cfg.data.grain_workers > 0:
        # Same worker-mode persistence contract as fit() — states land
        # in <workdir>/grain_state/ (per process; members share the one
        # full stream so there is one state per process, not per member).
        stream = grain_tee = _GrainStateTee(
            stream, start_step, keep=cfg.data.prefetch_batches + 4
        )
    batches = pipeline.device_prefetch(
        stream,
        sharding=mesh_lib.batch_sharding(mesh),
        size=cfg.data.prefetch_batches,
        full_local=True,
        knobs=knobs,
    )

    profiler = _ProfilerWindow(cfg, log, workdir, start_step)
    flight = _flight_for(cfg, workdir, profiler)
    if flight is not None:
        flight.install_signal_handlers()
    stopped_early = False
    clock = _ThroughputClock(cfg.data.batch_size)
    last_step = start_step
    _, stalls, snap = _telemetry_for(cfg, log, workdir, flight=flight)

    save_stall = [0.0]
    # Preemption latch — same contract as fit(): a still-running
    # overlapped eval skips its save once the exit path owns latest/.
    preempted = {"flag": False}

    def _eval_members(step_now, snap_state, ba, bs, sb,
                      stable: bool, attribute: bool):
        """One full member-parallel eval block: predict -> per-member
        AUCs -> dtype-curve gate -> best tracking -> lock-step save.
        Runs inline (``attribute=True`` stall-attributes the save to
        the 'save' segment) or as an overlapped _BgJob over an
        on-device snapshot (``stable=True``: the snapshot is already
        safe against the next step's donation)."""
        grades, probs = _predict_split_members(
            cfg, snap_state, data_dir, "val", mesh, eval_step,
            cache=val_cache,
        )
        bin_labels = (grades >= 2).astype(np.float64)
        member_probs = [
            p if cfg.model.head == "binary"
            else metrics.referable_probs_from_multiclass(p)
            for p in probs
        ]
        aucs = np.array([
            metrics.roc_auc(bin_labels, p) for p in member_probs
        ])
        ens_auc = metrics.roc_auc(
            bin_labels, metrics.ensemble_average(member_probs)
        )
        ba, bs, sb = _best_tracking_update(
            aucs, ba, bs, sb, step_now, cfg.train.min_delta
        )
        # Full precision on val_auc_per_member — the resume replay
        # source (same note as _eval_and_track). Logged BEFORE the
        # checkpoint fetch so time-to-target artifacts timestamp when
        # the AUC was known.
        log.write(
            "eval", step=step_now,
            val_auc_per_member=[float(a) for a in aucs],
            ensemble_val_auc=round(float(ens_auc), 5),
            best_auc_per_member=[round(float(a), 5) for a in ba],
        )
        curve_gate.check(step_now, float(ens_auc))
        stopping = bool(np.all(sb >= cfg.train.early_stop_patience))
        if (_save_due(cfg, step_now) or stopping) and not preempted["flag"]:
            # The dominant per-eval cost when saves are due: the
            # stacked state is k full train states (1.56 GB at k=4
            # flagship scale) fetched device->host — sync, that fetch
            # blocks here (train.save_every_evals spaces these out,
            # docs/PERF.md §Eval); under train.async_save only an
            # on-device snapshot + queue put does.
            t0 = time.perf_counter()
            src = snap_state if stable else (
                _state_snapshot(snap_state) if saver is not None
                else snap_state
            )

            def _do(src=src, step_now=step_now, aucs=aucs):
                # Worker-side latch re-check (same race note as fit()'s
                # _overlap_save): a late boundary save must never land
                # behind the emergency save and roll latest/ back.
                if preempted["flag"]:
                    return
                host_state = jax.device_get(gather_state(src))
                for m in range(k):
                    ckpts[m].save(
                        step_now,
                        train_lib.unstack_member(host_state, m),
                        {"val_auc": float(aucs[m])},
                    )
                _persist_grain_state(
                    grain_tee, workdir, step_now,
                    kept_steps=set.union(*[c.all_steps() for c in ckpts]),
                )

            if saver is not None:
                saver.submit(_do)
            else:
                _do()
            if attribute:
                dt = time.perf_counter() - t0
                stalls.add("save", dt)
                save_stall[0] += dt
        if stopping:
            log.write("early_stop", step=step_now,
                      best_step=[int(s) for s in bs])
        return ba, bs, sb, stopping

    try:
        for step_i in range(start_step, cfg.train.steps):
            t_step = time.perf_counter()
            faultinject.check("trainer.step")
            profiler.before_step(step_i)
            with stalls.measure("input"):
                batch = next(batches)
            if step_i == start_step and not cfg.train.debug:
                # Images/call in the ceiling is the DATASET batch (all k
                # members consume the same stream) while flops/call
                # covers all k members — the true stacked-program bound.
                train_step = _aot_with_ceiling(
                    cfg, mesh, clock, log, start_step,
                    train_step, state, batch, base_keys,
                )
            with stalls.measure("dispatch"):
                state, m_out = train_step(state, batch, base_keys)
            last_step = step_i + 1
            clock.after_step()
            if snap is not None:
                snap.progress(step_i + 1)
            # dt stops BEFORE profiler.after_step: a closing profiler
            # window's block_until_ready sync must not read as a slow
            # step (same exclusion as fit()).
            dt_step = time.perf_counter() - t_step
            profiler.after_step(step_i, state)
            if flight is not None:
                flight.progress(step_i + 1)
                flight.note_step_time(dt_step, step=step_i + 1)

            if (step_i + 1) % cfg.train.log_every == 0:
                losses = np.asarray(jax.device_get(m_out["loss"]))
                if flight is not None:
                    # ANY member's non-finite loss trips the sentinel
                    # (the members are independent; one diverging must
                    # not hide in the mean).
                    flight.note_loss(losses, step=step_i + 1)
                stall_fields = stalls.fields()
                log.write(
                    "train", step=step_i + 1,
                    loss=round(float(losses.mean()), 6),
                    loss_per_member=[round(float(x), 6) for x in losses],
                    **clock.fields(), **stall_fields,
                )
                if tuner is not None:
                    tuner.observe(
                        stall_fields["window_sec"],
                        stall_fields["input_wait_sec"],
                    )
                if snap is not None:
                    snap.maybe_flush()

            # Overlapped-eval completion poll (same contract as fit()).
            if eval_job is not None and eval_job.done():
                best_auc, best_step, since_best, stopping = eval_job.result()
                eval_job = None
                if stopping:
                    stopped_early = True
                    break

            if (step_i + 1) % cfg.train.eval_every == 0 or step_i + 1 == cfg.train.steps:
                if overlap:
                    if eval_job is not None:
                        clock.pause()
                        with stalls.measure("pause"):
                            best_auc, best_step, since_best, stopping = (
                                eval_job.result()
                            )
                        eval_job = None
                        clock.resume()
                        if stopping:
                            stopped_early = True
                            break
                    snap_state = _state_snapshot(state)
                    eval_job = _BgJob(
                        lambda step_now=step_i + 1, snap_state=snap_state,
                        ba=best_auc, bs=best_step, sb=since_best:
                        _eval_members(step_now, snap_state, ba, bs, sb,
                                      stable=True, attribute=False)
                    )
                else:
                    clock.pause()
                    t_pause = time.perf_counter()
                    save_stall[0] = 0.0
                    best_auc, best_step, since_best, stopping = _eval_members(
                        step_i + 1, state, best_auc, best_step, since_best,
                        stable=False, attribute=True,
                    )
                    stalls.add("pause", max(
                        0.0,
                        time.perf_counter() - t_pause - save_stall[0],
                    ))
                    clock.resume()
                    if stopping:
                        stopped_early = True
                        break
    except BaseException as e:
        if flight is not None:
            flight.record_exception(e)
        if _is_preemption(e) and last_step > start_step:
            # Latch-then-drain, never join the in-flight eval (same
            # grace-window rationale as fit()'s preempt path).
            preempted["flag"] = True
            if saver is not None:
                try:
                    saver.drain()
                except BaseException:  # noqa: BLE001 - exit path
                    pass
            def _save(step):
                # Every member in lock-step, same as the eval-time save
                # — a preempted member-parallel run must stay a valid
                # member-parallel workdir (all latests at ONE step).
                def _do():
                    host_state = jax.device_get(gather_state(state))
                    wrote = False
                    for m in range(k):
                        wrote = ckpts[m].save_latest(
                            step, train_lib.unstack_member(host_state, m)
                        ) or wrote
                    return wrote

                if saver is not None:
                    # Same one-save-thread rule as fit()'s preempt path.
                    out = {"saved": False}
                    saver.submit(lambda: out.__setitem__("saved", _do()))
                    saver.drain()
                    for c in ckpts:
                        c.wait()
                    return out["saved"]
                wrote = _do()
                for c in ckpts:
                    c.wait()
                return wrote

            _preempt_save(log, last_step, _save, grain_tee, workdir)
        raise
    finally:
        profiler.finalize()
        if flight is not None:
            flight.uninstall_signal_handlers()
        if cfg.train.debug:
            jax.config.update("jax_debug_nans", prev_debug_nans)

    # Tail collection (ISSUE 11), mirroring fit(): the overlapped final
    # eval and queued async saves land before the checkpointers close.
    if eval_job is not None:
        best_auc, best_step, since_best, stopping = eval_job.result()
        eval_job = None
        if stopping:
            stopped_early = True
    if saver is not None:
        saver.close()
    for c in ckpts:
        c.wait()
        c.close()
    if cfg.obs.quality.profile_out:
        def _ensemble_predict():
            grades, probs = _predict_split_members(
                cfg, state, data_dir, "val", mesh, eval_step,
                cache=val_cache,
            )
            # Same reduction evaluate_checkpoints applies: float64 mean
            # over members BEFORE any multiclass->referable collapse.
            return grades, metrics.ensemble_average(list(probs))

        _emit_quality_profile(cfg, data_dir, _ensemble_predict, log)
    if snap is not None:
        snap.close()
    log.close()
    return [
        {
            "member": m,
            "workdir": ckpt_lib.member_dir(workdir, m),
            "best_auc": float(best_auc[m]) if np.isfinite(best_auc[m]) else None,
            "best_step": int(best_step[m]),
            "stopped_early": stopped_early,
        }
        for m in range(k)
    ]


def _keras_schedule(tc):
    """train_lib.make_schedule's keras LearningRateSchedule twin (same
    three shapes, same clamp rule for infeasible warmups) so fit_tf
    trains under the SAME LR curve as the flax path."""
    import tensorflow as tf

    if tc.lr_schedule == "constant":
        return tc.learning_rate
    if tc.lr_schedule == "cosine":
        return tf.keras.optimizers.schedules.CosineDecay(
            tc.learning_rate, tc.steps
        )
    if tc.lr_schedule == "warmup_cosine":
        warmup = max(1, min(tc.warmup_steps, tc.steps - 1))
        if warmup != tc.warmup_steps:
            absl_logging.warning(
                "warmup_steps=%d does not fit in steps=%d; clamped to %d",
                tc.warmup_steps, tc.steps, warmup,
            )
        return tf.keras.optimizers.schedules.CosineDecay(
            0.0, tc.steps - warmup,
            warmup_target=tc.learning_rate, warmup_steps=warmup,
        )
    raise ValueError(f"unknown lr_schedule {tc.lr_schedule!r}")


def fit_tf(
    cfg: ExperimentConfig, data_dir: str, workdir: str, seed: int | None = None
) -> dict:
    """The legacy-backend training loop: ``train.py --device=tf``.

    Completes the ``--device={tf,tpu}`` gate (SURVEY.md §5.6) on the
    train side: a keras InceptionV3 trained on host TF, fed by the SAME
    pipeline.train_batches stream, logged in the SAME JSONL shape, early-
    stopped on the SAME val-AUC rule — and its best checkpoints written
    through the keras->flax transplant into the SAME orbax format, so a
    TF-trained model is evaluable by either backend.

    Honest deltas from the TPU path — now only the structural ones:
      * keras InceptionV3 has no auxiliary head, so the flax objective's
        ``aux_weight`` loss term is absent here;
      * optax moments are not representable in keras — a --resume of a
        tf-trained checkpoint restarts them (the LR-schedule POSITION
        does resume: optimizer.iterations is set to the restored step);
      * weight decay is masked by variable NAME (beta/bias excluded)
        rather than by rank — equivalent for these architectures.
    Closed in round 3 (VERDICT r2 #6): augmentation is the full numpy
    twin of the TPU path (augment.augment_batch_np — flips, dihedral
    transpose, brightness/contrast, YIQ saturation/hue, same ranges),
    and make_schedule's constant/cosine/warmup_cosine all map onto
    keras LearningRateSchedules (_keras_schedule).
    """
    import tensorflow as tf

    from jama16_retina_tpu.models import tf_backend, transplant

    if cfg.train.ema_decay > 0:
        raise ValueError(
            "train.ema_decay is a flax-path feature; the legacy tf "
            "backend has no EMA shadow (see TrainConfig.ema_decay)"
        )
    if cfg.train.init_from:
        raise ValueError(
            "train.init_from warm-starts from an orbax (flax) "
            "checkpoint; the legacy tf backend cannot load one — "
            "fine-tune on the flax path"
        )
    if cfg.data.loader in ("hbm", "tiered", "rawshard", "served"):
        raise ValueError(
            f"data.loader={cfg.data.loader!r} is wired into the flax "
            "train loops (device-resident batches, or the ingest "
            "service's shared-memory ring); the legacy tf backend has "
            "no wiring — use the tfdata or grain loader with --device=tf"
        )
    if cfg.data.autotune:
        raise ValueError(
            "data.autotune is wired into the flax train loops (the "
            "tuner reads their stall attribution at log boundaries); "
            "the legacy tf backend has no wiring — unset data.autotune "
            "with --device=tf"
        )
    if cfg.data.loader == "grain" and cfg.data.grain_workers > 0:
        raise ValueError(
            "data.grain_workers>0 is unsupported on the legacy tf "
            "backend: worker-mode resume needs the grain-state "
            "persistence wired into the flax drivers — a long tf run "
            "would train fine but never be resumable. Use "
            "grain_workers=0 (or the flax path) with --device=tf"
        )
    # Raw-speed knobs (ISSUE 11) are flax-path features; house style is
    # to refuse loudly rather than silently train without them.
    if cfg.train.dtype != "fp32":
        raise ValueError(
            f"train.dtype={cfg.train.dtype!r} is a flax-path feature "
            "(bf16 master-weight mixed precision lives in the jit train "
            "step); the legacy tf backend trains fp32 only"
        )
    if cfg.train.use_pallas_fused:
        raise ValueError(
            "train.use_pallas_fused is a flax-path feature (Mosaic "
            "kernels inside the jit step); unset it with --device=tf"
        )
    if cfg.train.accum_steps > 1:
        raise ValueError(
            "train.accum_steps>1 is implemented inside the flax jit "
            "step; the legacy tf backend has no accumulation wiring — "
            "a silently un-accumulated run would train a different "
            "recipe. Unset it with --device=tf"
        )
    if cfg.train.async_save or cfg.train.eval_overlap:
        raise ValueError(
            "train.async_save/train.eval_overlap are wired into the "
            "flax train loops (snapshot + background worker); the "
            "legacy tf backend saves synchronously — unset them with "
            "--device=tf"
        )
    if (cfg.train.optimizer == "lamb" or cfg.train.lr_scale_ref_batch > 0
            or cfg.train.recipe_curve_ref):
        raise ValueError(
            "the large-batch recipe (train.optimizer=lamb / "
            "train.lr_scale_ref_batch / train.recipe_curve_ref) is a "
            "flax-path feature (ISSUE 14): keras has no LAMB twin and "
            "the golden-curve gate lives in the flax eval block — "
            "unset them with --device=tf"
        )
    seed = cfg.train.seed if seed is None else seed
    seed = _load_or_write_run_meta(workdir, seed, cfg.name, cfg.train.resume)
    tf.keras.utils.set_random_seed(seed)
    log = RunLog(workdir, tensorboard=cfg.train.tensorboard,
                 fresh=not cfg.train.resume)
    log.write("config", name=cfg.name, seed=seed, backend="tf")

    keras_model = models.build(cfg.model, backend="tf")
    tc = cfg.train
    # Mirror train_lib.make_optimizer: the same LR schedule (keras
    # LearningRateSchedule twin of make_schedule), decoupled weight
    # decay, global-norm clipping, and the slim-era RMSprop eps=1.0.
    lr = _keras_schedule(tc)
    clip = tc.gradient_clip_norm if tc.gradient_clip_norm > 0 else None
    # keras AdamW requires a float weight_decay (None crashes); the base-
    # optimizer kwarg on SGD/RMSprop wants None to mean "disabled".
    wd_or_none = tc.weight_decay if tc.weight_decay else None
    if tc.optimizer == "adamw":
        opt = tf.keras.optimizers.AdamW(
            lr, weight_decay=float(tc.weight_decay),
            global_clipnorm=clip,
        )
    elif tc.optimizer == "sgdm":
        opt = tf.keras.optimizers.SGD(
            lr, momentum=tc.momentum, nesterov=True,
            weight_decay=wd_or_none, global_clipnorm=clip,
        )
    elif tc.optimizer == "rmsprop":
        opt = tf.keras.optimizers.RMSprop(
            lr, rho=0.9, momentum=tc.momentum, epsilon=1.0,
            weight_decay=wd_or_none, global_clipnorm=clip,
        )
    else:
        raise ValueError(f"unknown optimizer {tc.optimizer!r}")
    if tc.weight_decay:
        # train_lib._decay_mask decays rank>=2 kernels only; the keras
        # analogue is excluding BN betas and dense biases by name.
        opt.exclude_from_weight_decay(var_names=["beta", "bias"])
    if cfg.model.head == "binary":
        loss = tf.keras.losses.BinaryCrossentropy(
            from_logits=True, label_smoothing=tc.label_smoothing
        )
    else:
        # Sparse CE has no label_smoothing in keras; one-hot targets keep
        # the objective aligned with train_lib._head_loss.
        loss = tf.keras.losses.CategoricalCrossentropy(
            from_logits=True, label_smoothing=tc.label_smoothing
        )
    keras_model.compile(optimizer=opt, loss=loss)

    # Flax twin: the orbax tree the transplant fills per save. Built on
    # whatever jax platform is active (train.py pins CPU under --device=tf).
    model = models.build(cfg.model)
    state0, _ = train_lib.create_state(cfg, model, jax.random.key(seed))
    state0 = jax.device_get(state0)
    ckpt = ckpt_lib.Checkpointer(
        os.path.abspath(workdir), max_to_keep=cfg.train.max_to_keep
    )

    start_step = 0
    if cfg.train.resume and ckpt.latest_step is not None:
        if ckpt.saved_with_ema(ckpt.latest_step):
            raise ValueError(
                f"checkpoint in {workdir} carries an EMA shadow; the tf "
                "backend cannot continue that training (ema is flax-only)"
            )
        restored = ckpt.restore(
            ckpt_lib.abstract_like(state0), ckpt.latest_step
        )
        tf_backend.load_flax_state(
            keras_model, restored.params, restored.batch_stats
        )
        start_step = int(np.asarray(restored.step))
        # Resume the LR-schedule POSITION (keras schedules read
        # optimizer.iterations). Moments still restart — the documented
        # structural delta.
        keras_model.optimizer.iterations.assign(start_step)
        log.write("resume", step=start_step)

    _obs_begin_run(cfg)  # before the pipeline creates its metrics
    batches = _train_stream(cfg, data_dir, seed, skip_batches=start_step)
    best_auc, best_step, since_best = -np.inf, start_step, 0
    stopped_early = False
    clock = _ThroughputClock(cfg.data.batch_size)
    # No jax profiler on this backend: the flight recorder's anomaly
    # dumps still fire, with no capture hook to arm.
    flight = _flight_for(cfg, workdir, profiler=None)
    _, stalls, snap = _telemetry_for(cfg, log, workdir, flight=flight)
    if flight is not None:
        flight.install_signal_handlers()
    try:
        for step_i in range(start_step, tc.steps):
            t_step = time.perf_counter()
            # Host augmentation counts as INPUT here: on this backend the
            # data prep runs on host CPU ahead of the (synchronous) keras
            # step, so it starves the step exactly like decode does.
            with stalls.measure("input"):
                batch = next(batches)
                # Per-step generator keyed on (seed, step): a resumed run
                # draws the same augmentations an uninterrupted one would
                # (the numpy analogue of fit's fold_in(base_key, step);
                # SURVEY.md §5.4). augment_batch_np is the full numpy twin
                # of the TPU path (includes normalize; a no-op pass-through
                # when augment=false).
                x = augment_lib.augment_batch_np(
                    np.random.default_rng((seed, step_i)), batch["image"],
                    cfg.data,
                )
            if cfg.model.head == "binary":
                y = (batch["grade"] >= 2).astype(np.float32)[:, None]
            else:
                y = np.eye(cfg.model.num_classes, dtype=np.float32)[
                    batch["grade"].astype(np.int64)
                ]
            with stalls.measure("dispatch"):
                step_loss = float(keras_model.train_on_batch(x, y))
            clock.after_step()
            if snap is not None:
                snap.progress(step_i + 1)
            if flight is not None:
                flight.progress(step_i + 1)
                flight.note_step_time(
                    time.perf_counter() - t_step, step=step_i + 1
                )

            if (step_i + 1) % tc.log_every == 0:
                if flight is not None:
                    # train_on_batch already returned a host float; the
                    # sentinel costs one isfinite.
                    flight.note_loss(step_loss, step=step_i + 1)
                log.write("train", step=step_i + 1, loss=step_loss,
                          **clock.fields(), **stalls.fields())
                if snap is not None:
                    snap.maybe_flush()

            if (step_i + 1) % tc.eval_every == 0 or step_i + 1 == tc.steps:
                clock.pause()
                t_pause = time.perf_counter()
                def _tf_state_for_save(step_now=step_i + 1):
                    params, batch_stats = transplant.transplant_from_keras(
                        keras_model, state0.params, state0.batch_stats
                    )
                    return state0.replace(
                        step=np.asarray(step_now, np.int32),
                        params=params, batch_stats=batch_stats,
                    )

                best_auc, best_step, since_best, stop, _ = _eval_and_track(
                    cfg, log, ckpt, step_i + 1,
                    lambda: predict_split_tf(cfg, keras_model, data_dir, "val")[:2],
                    _tf_state_for_save,
                    best_auc, best_step, since_best,
                    save_due=_save_due(cfg, step_i + 1),
                )
                stalls.add("pause", time.perf_counter() - t_pause)
                clock.resume()
                if stop:
                    stopped_early = True
                    break
    except BaseException as e:
        if flight is not None:
            flight.record_exception(e)
        raise
    finally:
        if flight is not None:
            flight.uninstall_signal_handlers()

    ckpt.wait()
    ckpt.close()
    if cfg.obs.quality.profile_out:
        _emit_quality_profile(
            cfg, data_dir,
            lambda: predict_split_tf(cfg, keras_model, data_dir, "val")[:2],
            log,
        )
    if snap is not None:
        snap.close()
    log.close()
    return {
        "best_auc": float(best_auc) if np.isfinite(best_auc) else None,
        "best_step": int(best_step),
        "stopped_early": stopped_early,
    }


def restore_for_eval(
    cfg: ExperimentConfig, model, ckpt_dir: str, mesh=None
) -> train_lib.TrainState:
    """Restore a member's best checkpoint (reference evaluate.py restore).

    Checkpointer.restore reconciles the abstract tree with whether the
    CHECKPOINT carries an EMA shadow (orbax tree metadata), not the eval
    config — so a model trained with --set train.ema_decay=0.999 (or a
    pre-EMA legacy checkpoint) evaluates correctly under any preset.
    """
    state, _ = train_lib.create_state(cfg, model, jax.random.key(0))
    ckpt = ckpt_lib.Checkpointer(os.path.abspath(ckpt_dir))
    restored = ckpt.restore(ckpt_lib.abstract_like(jax.device_get(state)))
    ckpt.close()
    if mesh is not None:
        restored = jax.device_put(restored, mesh_lib.replicated(mesh))
    return restored


def evaluate_checkpoints(
    cfg: ExperimentConfig,
    data_dir: str,
    ckpt_dirs: list[str],
    split: str = "test",
    mesh=None,
    backend: str = "flax",
    threshold_split: str | None = None,
    threshold_data_dir: str | None = None,
    bootstrap: int = 0,
    save_probs: str | None = None,
    calibrate: bool = False,
    profile_out: str | None = None,
) -> dict:
    """Single- or multi-checkpoint (ensemble-averaged) evaluation
    (SURVEY.md §3.2; BASELINE.json:10 'averaged logits').

    ``backend="tf"`` routes the forward pass through the keras legacy-
    graph stand-in (models/tf_backend.py) — same checkpoints, same
    pipeline, same metrics layer, per the north-star plugin boundary.

    ``threshold_split`` (e.g. "val") additionally runs the paper's
    operating-point protocol: thresholds chosen at the fixed
    specificities on that split, applied unchanged to ``split``
    (metrics.transferred_operating_points). ``threshold_data_dir``
    points the tuning split at ANOTHER dataset — the actual JAMA/
    replication protocol is thresholds tuned on the EyePACS val set and
    applied to Messidor-2, which lives in a different TFRecord dir.
    ``bootstrap`` > 0 adds 95% CIs to AUC and to the sensitivities of
    both the self-tuned and the transferred operating points.
    ``calibrate`` fits a temperature on the tuning split (requires
    ``threshold_split``) and reports calibrated Brier/ECE on the eval
    split — AUC and ROC thresholds are rank-invariant under temperature,
    so only the calibration metrics change.
    ``profile_out`` writes the versioned quality-observability reference
    profile (obs/quality.py; ISSUE 5) for THIS checkpoint set on THIS
    split: the ensemble score histogram, per-channel input-statistic
    histograms, base rate, and the report's operating thresholds — the
    artifact ``obs.quality.profile_path`` points serving at. Emit it on
    the split the thresholds were chosen on (normally val).
    """
    if not ckpt_dirs:
        raise ValueError("need at least one checkpoint dir")
    if calibrate and not threshold_split:
        raise ValueError(
            "calibrate=True needs threshold_split: temperature must be "
            "fit on a tuning split, never on the split being reported"
        )
    tune_dir = threshold_data_dir or data_dir
    # realpath: './tfr', 'tfr/' and a symlink to tfr are the same eval
    # set — spelling differences must not bypass the self-tuning guard.
    if threshold_split == split and (
        os.path.realpath(tune_dir) == os.path.realpath(data_dir)
    ):
        raise ValueError(
            f"threshold_split={split!r} on the same data dir is the eval "
            "set itself — self-tuned thresholds are exactly the bias this "
            "protocol avoids (the plain operating_points rows already "
            "report them)"
        )
    mesh = mesh or mesh_lib.make_mesh(
        cfg.parallel.num_devices, axis=cfg.parallel.data_axis
    )
    model = models.build(cfg.model)  # flax: checkpoint tree structure
    if backend == "tf":
        from jama16_retina_tpu.models import tf_backend

        keras_model = models.build(cfg.model, backend="tf")
        eval_step = None
    else:
        eval_step = train_lib.make_eval_step(cfg, model, mesh=mesh)

    # One device-resident cache per (dir, split) prediction pass, shared
    # across members: k checkpoints would otherwise re-parse and
    # re-upload the same eval batches k times (budget-gated; {} entries
    # stay None for streamed loaders or oversized splits). The caches
    # live simultaneously, so admission is gated on their JOINT
    # footprint (cached_bytes), not per split (ADVICE r4).
    eval_caches: dict[tuple, list | None] = {}
    cached_bytes = 0

    def member_predict(state, from_dir, eval_split):
        nonlocal cached_bytes
        if backend == "tf":
            return predict_split_tf(cfg, keras_model, from_dir, eval_split)
        cache_key = (from_dir, eval_split)
        if cache_key not in eval_caches:
            cache = _eval_cache_for(
                cfg, from_dir, eval_split, reserved_bytes=cached_bytes
            )
            if cache is not None:
                cached_bytes += _eval_cache_bytes(cfg, from_dir, eval_split)
            eval_caches[cache_key] = cache
        return predict_split(
            cfg, model, state, from_dir, eval_split, mesh,
            eval_step=eval_step, cache=eval_caches[cache_key],
        )

    # (key, data_dir, split) prediction passes; tune pass only if asked.
    passes = [("eval", data_dir, split)]
    if threshold_split:
        passes.append(("tune", tune_dir, threshold_split))
    prob_lists: dict[str, list] = {k: [] for k, _, _ in passes}
    grades_by: dict[str, np.ndarray] = {}
    eval_names = None  # identical across members (grade check pins this)
    for d in ckpt_dirs:
        state = restore_for_eval(cfg, model, d, mesh)
        if backend == "tf":
            # Same preference as the jit eval step: the EMA shadow is
            # the model of record when it was trained with one.
            tf_backend.load_flax_state(
                keras_model, train_lib.eval_params(state), state.batch_stats
            )
        for key, from_dir, s in passes:
            g, p, nm = member_predict(state, from_dir, s)
            if key in grades_by and not np.array_equal(g, grades_by[key]):
                raise RuntimeError("checkpoints saw different eval sets")
            grades_by[key] = g
            if key == "eval":
                eval_names = nm
            prob_lists[key].append(p)

    probs = metrics.ensemble_average(prob_lists["eval"])
    labels = _binary_eval_labels(grades_by["eval"], cfg.model.head)
    report = metrics.evaluation_report(
        labels,
        probs,
        cfg.eval.operating_specificities,
        bootstrap_samples=bootstrap,
    )
    if threshold_split:
        to_binary = (
            (lambda p: p) if cfg.model.head == "binary"
            else metrics.referable_probs_from_multiclass
        )
        tune_bin = (grades_by["tune"] >= 2).astype(np.float64)
        tune_p = to_binary(metrics.ensemble_average(prob_lists["tune"]))
        eval_bin = (grades_by["eval"] >= 2).astype(np.float64)
        eval_p = to_binary(probs)
        report["operating_points_transferred"] = (
            metrics.transferred_operating_points(
                tune_bin, tune_p, eval_bin, eval_p,
                cfg.eval.operating_specificities,
                bootstrap_samples=bootstrap,
            )
        )
        report["threshold_split"] = threshold_split
        if threshold_data_dir:
            report["threshold_data_dir"] = threshold_data_dir
        if calibrate:
            temp = metrics.fit_temperature(tune_bin, tune_p)
            cal = metrics.apply_temperature(eval_p, temp)
            report["calibration"] = {
                "temperature": round(temp, 4),
                "brier": metrics.brier_score(eval_bin, cal),
                "ece": metrics.expected_calibration_error(eval_bin, cal),
            }
    if save_probs:
        # Join the preprocessing gradability score per image (QUALITY.md
        # step 4: do misses correlate with low-quality captures?). -1
        # marks records written without a score (legacy/synthetic).
        from jama16_retina_tpu.data import tfrecord as tfrecord_lib

        quality_by_name = tfrecord_lib.read_quality_by_name(
            tfrecord_lib.list_split(data_dir, split)
        )
        _write_probs_csv(
            save_probs, eval_names, grades_by["eval"], probs,
            cfg.model.head, quality_by_name,
        )
        report["probs_file"] = save_probs
    if profile_out:
        from jama16_retina_tpu.obs import quality as quality_lib

        eval_bin = (grades_by["eval"] >= 2).astype(np.float64)
        scores = (
            np.asarray(probs, np.float64) if cfg.model.head == "binary"
            else np.asarray(
                metrics.referable_probs_from_multiclass(probs), np.float64
            )
        )
        stats = quality_lib.split_input_stats(
            data_dir, split, cfg.eval.batch_size, cfg.model.image_size
        )
        profile = quality_lib.build_profile(
            scores, labels=eval_bin, stat_values=stats,
            thresholds=[
                {"target_specificity": row["target_specificity"],
                 "threshold": row["threshold"]}
                for row in report["operating_points"]
            ],
            bins=cfg.obs.quality.score_bins,
            meta={"config": cfg.name, "split": split,
                  "n_models": len(ckpt_dirs), "source": "evaluate"},
        )
        quality_lib.save_profile(profile_out, profile)
        report["profile_out"] = profile_out
    report["split"] = split
    report["n_models"] = len(ckpt_dirs)
    return report


def _write_probs_csv(
    path: str, names: np.ndarray, grades: np.ndarray, probs: np.ndarray,
    head: str, quality_by_name: "dict[bytes, float] | None" = None,
) -> None:
    """Per-image ensemble-averaged probabilities as CSV — the raw
    material for error analysis / external recalibration that the final
    report's aggregates can't provide. One row per eval example; the
    ``quality`` column carries the preprocessing gradability score
    (-1 when the record predates it)."""
    import csv

    def qual(nm) -> str:
        if quality_by_name is None:
            return "-1"
        return f"{quality_by_name.get(nm, -1.0):.4f}"

    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        if head == "binary":
            w.writerow(["name", "grade", "quality", "prob_referable"])
            for nm, g, p in zip(names, grades, probs):
                w.writerow([nm.decode(), int(g), qual(nm), f"{float(p):.6f}"])
        else:
            n_cls = probs.shape[-1]
            w.writerow(
                ["name", "grade", "quality", "prob_referable"]
                + [f"prob_grade_{c}" for c in range(n_cls)]
            )
            referable = metrics.referable_probs_from_multiclass(probs)
            for nm, g, p, r in zip(names, grades, probs, referable):
                w.writerow(
                    [nm.decode(), int(g), qual(nm), f"{float(r):.6f}"]
                    + [f"{float(x):.6f}" for x in p]
                )
