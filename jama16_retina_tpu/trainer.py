"""End-to-end training/eval drivers (reference train.py/evaluate.py bodies).

``fit`` is the reference's session loop re-shaped for TPU (SURVEY.md
§3.1): one jit dispatch per step over a data-parallel mesh, periodic
validation AUC, early stopping on best val AUC with orbax best-checkpoint
retention, JSONL metrics. ``fit_ensemble`` repeats it for k
independently-seeded members (reference R11); ``evaluate_checkpoints``
restores member checkpoints, averages probabilities, and emits the
reference's report shape (AUC + operating points; SURVEY.md §3.2).
"""

from __future__ import annotations

import os
import time

import jax
import numpy as np
from absl import logging as absl_logging

from jama16_retina_tpu import models, train_lib
from jama16_retina_tpu.configs import ExperimentConfig
from jama16_retina_tpu.data import pipeline
from jama16_retina_tpu.eval import metrics
from jama16_retina_tpu.parallel import mesh as mesh_lib
from jama16_retina_tpu.utils import checkpoint as ckpt_lib
from jama16_retina_tpu.utils.logging import RunLog


def _binary_eval_labels(grades: np.ndarray, head: str) -> np.ndarray:
    """evaluation_report expects binary labels for the binary head and raw
    grades for the 5-class head."""
    return (grades >= 2).astype(np.float64) if head == "binary" else grades


def predict_split(
    cfg: ExperimentConfig,
    model,
    state: train_lib.TrainState,
    data_dir: str,
    split: str,
    mesh=None,
    eval_step=None,
) -> tuple[np.ndarray, np.ndarray]:
    """Run the test pipeline (no augmentation) -> (grades, probs) on host.

    Pass a prebuilt ``eval_step`` when calling repeatedly (every val
    interval / every ensemble member) — a fresh ``make_eval_step`` closure
    would defeat the jit cache and recompile the backbone each time.
    """
    if eval_step is None:
        eval_step = train_lib.make_eval_step(cfg, model, mesh=mesh)
    grades_all, probs_all = [], []
    for batch in pipeline.eval_batches(
        data_dir, split, cfg.eval.batch_size, cfg.model.image_size
    ):
        # Only the image rows go to device — 'grade'/'mask' are global
        # host metadata (multi-host: 'image' is the per-process block,
        # see pipeline.eval_batches), and eval_step reads only 'image'.
        if mesh is not None:
            dev_batch = mesh_lib.shard_batch({"image": batch["image"]}, mesh)
        else:
            dev_batch = jax.device_put({"image": batch["image"]})
        probs = np.asarray(jax.device_get(eval_step(state, dev_batch)))
        keep = batch["mask"] > 0
        grades_all.append(batch["grade"][keep])
        probs_all.append(probs[keep])
    return np.concatenate(grades_all), np.concatenate(probs_all)


def _run_meta_path(workdir: str) -> str:
    return os.path.join(workdir, "run_meta.json")


def _load_or_write_run_meta(
    workdir: str, seed: int, cfg_name: str, resume: bool
) -> int:
    """Persist the data/PRNG seed so --resume reproduces the exact stream
    even if the CLI seed differs (SURVEY.md §5.4: the saved PRNG 'state'
    is just (seed, step) — keys are derived by fold_in(key(seed), step)
    inside the jit step, and the pipeline is a pure function of seed).

    The persisted seed wins ONLY on resume; a fresh run in a reused
    workdir takes the requested seed and rewrites the meta (otherwise a
    deliberately re-seeded rerun would silently duplicate the old run).
    """
    import json

    path = _run_meta_path(workdir)
    if resume and os.path.exists(path):
        with open(path) as f:
            meta = json.load(f)
        if int(meta.get("seed", seed)) != seed:
            absl_logging.warning(
                "resuming with run_meta seed %s (CLI seed %s ignored for "
                "stream continuity)", meta["seed"], seed,
            )
        return int(meta.get("seed", seed))
    os.makedirs(workdir, exist_ok=True)
    with open(path, "w") as f:
        json.dump({"seed": seed, "config": cfg_name}, f)
    return seed


def fit(
    cfg: ExperimentConfig,
    data_dir: str,
    workdir: str,
    seed: int | None = None,
    mesh=None,
) -> dict:
    """Train one model; returns {'best_auc', 'best_step', 'stopped_early'}."""
    seed = cfg.train.seed if seed is None else seed
    seed = _load_or_write_run_meta(workdir, seed, cfg.name, cfg.train.resume)
    prev_debug_nans = jax.config.jax_debug_nans
    if cfg.train.debug:
        jax.config.update("jax_debug_nans", True)
    mesh = mesh or mesh_lib.make_mesh(cfg.parallel.num_devices)
    log = RunLog(workdir)
    log.write("config", name=cfg.name, seed=seed,
              n_devices=int(np.prod(list(mesh.shape.values()))))

    model = models.build(cfg.model)
    state, tx = train_lib.create_state(cfg, model, jax.random.key(seed))
    state = jax.device_put(state, mesh_lib.replicated(mesh))
    # Donation conflicts with jax_debug_nans' op-by-op re-execution (the
    # donated buffers are gone by the time the NaN checker re-runs), so
    # debug mode trades the in-place state update for usable NaN reports.
    train_step = train_lib.make_train_step(
        cfg, model, tx, mesh=mesh, donate=not cfg.train.debug
    )
    eval_step = train_lib.make_eval_step(cfg, model, mesh=mesh)
    ckpt = ckpt_lib.Checkpointer(
        os.path.abspath(workdir), max_to_keep=cfg.train.max_to_keep
    )

    start_step = 0
    if cfg.train.resume and ckpt.latest_step is not None:
        state = ckpt.restore(ckpt_lib.abstract_like(state), ckpt.latest_step)
        state = jax.device_put(state, mesh_lib.replicated(mesh))
        start_step = int(jax.device_get(state.step))
        log.write("resume", step=start_step)

    base_key = jax.random.key(seed)
    # skip_batches=start_step: one batch per completed step, so a resumed
    # stream continues exactly where the interrupted one stopped
    # (pipeline determinism; SURVEY.md §5.4). Augment/dropout keys need
    # no restoring — they are fold_in(base_key, state.step) in-step.
    batches = pipeline.device_prefetch(
        pipeline.train_batches(
            data_dir, "train", cfg.data, cfg.model.image_size, seed=seed,
            skip_batches=start_step,
        ),
        sharding=mesh_lib.batch_sharding(mesh),
        size=cfg.data.prefetch_batches,
    )

    # Profiler window (SURVEY.md §5.1): skip the compile+warmup steps when
    # the run is long enough, clamp the window inside short runs, and warn
    # when no window fits at all.
    profile_start, profile_stop = -1, -1
    if cfg.train.profile_steps > 0:
        remaining = cfg.train.steps - start_step
        if remaining < cfg.train.profile_steps:
            log.write("profile_skipped", reason=(
                f"only {remaining} steps remain, profile_steps="
                f"{cfg.train.profile_steps} does not fit"))
        else:
            profile_start = min(
                start_step + 10, cfg.train.steps - cfg.train.profile_steps
            )
            profile_stop = profile_start + cfg.train.profile_steps
    tracing = False

    best_auc, best_step, since_best = -np.inf, start_step, 0
    stopped_early = False
    t_log, imgs_since = time.time(), 0
    try:
        for step_i in range(start_step, cfg.train.steps):
            if step_i == profile_start:
                jax.profiler.start_trace(os.path.join(workdir, "profile"))
                tracing = True
            state, m = train_step(state, next(batches), base_key)
            if tracing and step_i + 1 >= profile_stop:
                jax.block_until_ready(state)
                jax.profiler.stop_trace()
                tracing = False
                log.write("profile", dir=os.path.join(workdir, "profile"),
                          steps=cfg.train.profile_steps)
            imgs_since += cfg.data.batch_size

            if (step_i + 1) % cfg.train.log_every == 0:
                dt = time.time() - t_log
                log.write(
                    "train", step=step_i + 1, loss=float(m["loss"]),
                    images_per_sec=round(imgs_since / max(dt, 1e-9), 2),
                )
                t_log, imgs_since = time.time(), 0

            if (step_i + 1) % cfg.train.eval_every == 0 or step_i + 1 == cfg.train.steps:
                grades, probs = predict_split(
                    cfg, model, state, data_dir, "val", mesh, eval_step=eval_step
                )
                # Early stopping always tracks *referable-DR* AUC; the
                # 5-class head collapses to P(grade>=2) here (SURVEY.md N11).
                bin_probs = (
                    probs if cfg.model.head == "binary"
                    else metrics.referable_probs_from_multiclass(probs)
                )
                auc = metrics.roc_auc((grades >= 2).astype(np.float64), bin_probs)
                ckpt.save(step_i + 1, jax.device_get(state), {"val_auc": auc})
                if auc > best_auc + cfg.train.min_delta:
                    best_auc, best_step, since_best = auc, step_i + 1, 0
                else:
                    since_best += 1
                log.write("eval", step=step_i + 1, val_auc=round(auc, 5),
                          best_auc=round(best_auc, 5), since_best=since_best)
                if since_best >= cfg.train.early_stop_patience:
                    stopped_early = True
                    log.write("early_stop", step=step_i + 1, best_step=best_step)
                    break
    finally:
        # Early stop / short runs / exceptions must not leak an open trace
        # (the next fit() in an ensemble would crash on start_trace) or a
        # flipped global debug flag.
        if tracing:
            jax.profiler.stop_trace()
            log.write("profile", dir=os.path.join(workdir, "profile"),
                      steps="truncated")
        if cfg.train.debug:
            jax.config.update("jax_debug_nans", prev_debug_nans)

    ckpt.wait()
    ckpt.close()
    log.close()
    return {
        # None (not -inf) when no eval ever ran — e.g. --resume with the
        # restored step already at train.steps. json.dumps would otherwise
        # emit -Infinity, which is not valid JSON.
        "best_auc": float(best_auc) if np.isfinite(best_auc) else None,
        "best_step": int(best_step),
        "stopped_early": stopped_early,
    }


def fit_ensemble(
    cfg: ExperimentConfig, data_dir: str, workdir: str
) -> list[dict]:
    """Train k independently-seeded members (reference R11, BASELINE.json:10),
    each in its own member_NN checkpoint dir."""
    results = []
    for member in range(cfg.train.ensemble_size):
        mdir = ckpt_lib.member_dir(workdir, member)
        res = fit(cfg, data_dir, mdir, seed=cfg.train.seed + member)
        results.append({"member": member, "workdir": mdir, **res})
    return results


def restore_for_eval(
    cfg: ExperimentConfig, model, ckpt_dir: str, mesh=None
) -> train_lib.TrainState:
    """Restore a member's best checkpoint (reference evaluate.py restore)."""
    state, _ = train_lib.create_state(cfg, model, jax.random.key(0))
    ckpt = ckpt_lib.Checkpointer(os.path.abspath(ckpt_dir))
    restored = ckpt.restore(ckpt_lib.abstract_like(jax.device_get(state)))
    ckpt.close()
    if mesh is not None:
        restored = jax.device_put(restored, mesh_lib.replicated(mesh))
    return restored


def evaluate_checkpoints(
    cfg: ExperimentConfig,
    data_dir: str,
    ckpt_dirs: list[str],
    split: str = "test",
    mesh=None,
) -> dict:
    """Single- or multi-checkpoint (ensemble-averaged) evaluation
    (SURVEY.md §3.2; BASELINE.json:10 'averaged logits')."""
    if not ckpt_dirs:
        raise ValueError("need at least one checkpoint dir")
    mesh = mesh or mesh_lib.make_mesh(cfg.parallel.num_devices)
    model = models.build(cfg.model)
    eval_step = train_lib.make_eval_step(cfg, model, mesh=mesh)
    prob_list, grades = [], None
    for d in ckpt_dirs:
        state = restore_for_eval(cfg, model, d, mesh)
        g, p = predict_split(
            cfg, model, state, data_dir, split, mesh, eval_step=eval_step
        )
        if grades is not None and not np.array_equal(g, grades):
            raise RuntimeError("checkpoints saw different eval sets")
        grades = g
        prob_list.append(p)
    probs = metrics.ensemble_average(prob_list)
    report = metrics.evaluation_report(
        _binary_eval_labels(grades, cfg.model.head),
        probs,
        cfg.eval.operating_specificities,
    )
    report["split"] = split
    report["n_models"] = len(ckpt_dirs)
    return report
