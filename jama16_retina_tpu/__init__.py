"""jama16_retina_tpu — a TPU-native (JAX/XLA/pjit/pallas) training and
evaluation framework with the capabilities of the JAMA-2016 diabetic
retinopathy replication (`MasatoAkiyama/jama16-retina-replication`).

The reference repo's capability surface (see /root/repo/SURVEY.md and
BASELINE.json `north_star`) is: offline fundus preprocessing of Kaggle
EyePACS and Messidor-2 into sharded TFRecords; `train.py`/`evaluate.py`
entry points with a `--device` backend gate; an Inception-v3 builder
(TF-Slim in the reference → Flax here) with binary referable-DR and
5-class ICDR heads; data-parallel training with gradient allreduce and
cross-replica BatchNorm over ICI; early stopping on validation AUC with
best-checkpoint saving; 10-model averaged-logit ensembles; and a
backend-agnostic evaluation layer reporting ROC-AUC and
sensitivity-at-fixed-specificity operating points.

This package is a ground-up TPU-first redesign, not a port: all hot-loop
compute is a single XLA program per step (jit/shard_map over a
`jax.sharding.Mesh`), collectives ride ICI via `lax.psum`/`pmean`, and
optional pallas kernels cover fused elementwise hot spots.
"""

__version__ = "0.1.0"
