"""Chip FLOP-physics bounds, shared by bench.py and the train loops.

Round 2's bench published rates implying 8-25x the chip's peak FLOP/s
(the axon tunnel can complete host-visible sync primitives before the
device work actually ran); round 3 added the fence + physics-guard
discipline to bench.py. This module is that discipline's single home so
the train loops' own throughput telemetry (trainer._ThroughputClock) is
held to the same standard as the bench: a rate whose implied FLOP/s
exceeds the chip's peak is a measurement bug by definition, and nothing
in this repo publishes it (VERDICT r3 weak #5).
"""

from __future__ import annotations

# Per-chip peak dense bf16 FLOP/s by device-kind substring (public Cloud
# TPU specs). Guards can only ever REJECT with this table: unknown kinds
# (including the fake CPU devices tests run on) get a deliberately
# generous default, so a guard refuses the impossible, never the merely
# fast.
PEAK_BF16_TFLOPS = (
    ("v5 lite", 197.0), ("v5e", 197.0), ("v5p", 459.0),
    ("v6", 918.0), ("trillium", 918.0),
    ("v4", 275.0), ("v3", 123.0), ("v2", 46.0),
)
DEFAULT_PEAK_TFLOPS = 2000.0


def peak_flops(log=None) -> float:
    """Peak dense bf16 FLOP/s of one local device (chip peak)."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for sub, tflops in PEAK_BF16_TFLOPS:
        if sub in kind:
            return tflops * 1e12
    if log is not None:
        log(f"unknown device kind {kind!r}: physics guard using generous "
            f"{DEFAULT_PEAK_TFLOPS:.0f} TFLOP/s default")
    return DEFAULT_PEAK_TFLOPS * 1e12


def flops_from_cost_analysis(compiled) -> "float | None":
    """Total FLOPs of a compiled XLA program per cost_analysis, or None
    when unavailable. THE parser for cost_analysis' version-dependent
    return shape (dict vs one-element list of dicts) — shared by
    bench.py and train_lib.aot_compile_step so the bench's physics
    guard and the train loops' throughput ceiling cannot diverge when
    the API shifts again."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0)) if ca else 0.0
    except Exception:  # pragma: no cover - environment-dependent
        return None
    return flops if flops > 0 else None


def rate_ceiling(flops_per_call: "float | None", images_per_call: int,
                 n_dev: int = 1) -> "float | None":
    """Max physically possible GLOBAL images/sec for a step program that
    costs ``flops_per_call`` FLOPs and advances ``images_per_call``
    images over ``n_dev`` devices; None when FLOPs are unknown (no
    guard, matching bench._physics_guard's contract).

    ``flops_per_call`` is read as the TOTAL program cost. XLA's
    cost_analysis on a GSPMD module is ambiguous between total and
    per-device FLOPs; treating it as total can only make this ceiling
    up to n_dev x too GENEROUS, which keeps the guard sound (it may
    fail to reject, it can never wrongly reject).
    """
    if not flops_per_call or flops_per_call <= 0:
        return None
    return peak_flops() * n_dev * images_per_call / flops_per_call
