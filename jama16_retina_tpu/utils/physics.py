"""Chip FLOP-physics bounds, shared by bench.py and the train loops.

Round 2's bench published rates implying 8-25x the chip's peak FLOP/s
(the axon tunnel can complete host-visible sync primitives before the
device work actually ran); round 3 added the fence + physics-guard
discipline to bench.py. This module is that discipline's single home so
the train loops' own throughput telemetry (trainer._ThroughputClock) is
held to the same standard as the bench: a rate whose implied FLOP/s
exceeds the chip's peak is a measurement bug by definition, and nothing
in this repo publishes it (VERDICT r3 weak #5).
"""

from __future__ import annotations

# Per-chip peak dense bf16 FLOP/s by device-kind substring (public Cloud
# TPU specs). Guards can only ever REJECT with this table: unknown kinds
# (including the fake CPU devices tests run on) get a deliberately
# generous default, so a guard refuses the impossible, never the merely
# fast.
PEAK_BF16_TFLOPS = (
    ("v5 lite", 197.0), ("v5e", 197.0), ("v5p", 459.0),
    ("v6", 918.0), ("trillium", 918.0),
    ("v4", 275.0), ("v3", 123.0), ("v2", 46.0),
)
DEFAULT_PEAK_TFLOPS = 2000.0

# Per-chip peak HBM bandwidth (GB/s) by the same device-kind substrings
# (public Cloud TPU specs). Feeds the device plane's roofline ridge
# point and achieved-bandwidth fractions (obs/device.py); same
# guard-direction discipline — unknown kinds get a generous default so
# bandwidth fractions read low, never impossibly high.
PEAK_HBM_GBPS = (
    ("v5 lite", 819.0), ("v5e", 819.0), ("v5p", 2765.0),
    ("v6", 1640.0), ("trillium", 1640.0),
    ("v4", 1228.0), ("v3", 900.0), ("v2", 700.0),
)
DEFAULT_PEAK_HBM_GBPS = 5000.0


def peak_flops(log=None) -> float:
    """Peak dense bf16 FLOP/s of one local device (chip peak)."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for sub, tflops in PEAK_BF16_TFLOPS:
        if sub in kind:
            return tflops * 1e12
    if log is not None:
        log(f"unknown device kind {kind!r}: physics guard using generous "
            f"{DEFAULT_PEAK_TFLOPS:.0f} TFLOP/s default")
    return DEFAULT_PEAK_TFLOPS * 1e12


def peak_hbm_bytes_per_sec(log=None) -> float:
    """Peak HBM bandwidth of one local device in bytes/s."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for sub, gbps in PEAK_HBM_GBPS:
        if sub in kind:
            return gbps * 1e9
    if log is not None:
        log(f"unknown device kind {kind!r}: using generous "
            f"{DEFAULT_PEAK_HBM_GBPS:.0f} GB/s HBM default")
    return DEFAULT_PEAK_HBM_GBPS * 1e9


def program_costs(compiled) -> "tuple[float | None, float | None]":
    """(flops, bytes_accessed) of a compiled XLA program per
    cost_analysis; either is None when unavailable. THE parser for
    cost_analysis' version-dependent return shape (dict vs one-element
    list of dicts) — shared by bench.py, train_lib.aot_compile_step,
    and the obs/device.py program ledger so the bench's physics guard,
    the train loops' throughput ceiling, and the MFU/roofline gauges
    cannot diverge when the API shifts again."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = ca or {}
        flops = float(ca.get("flops", 0.0))
        nbytes = float(ca.get("bytes accessed", 0.0))
    except Exception:  # pragma: no cover - environment-dependent
        return None, None
    return (flops if flops > 0 else None,
            nbytes if nbytes > 0 else None)


def flops_from_cost_analysis(compiled) -> "float | None":
    """Total FLOPs of a compiled XLA program, or None when unavailable
    (thin view over :func:`program_costs`, kept for its callers)."""
    return program_costs(compiled)[0]


def rate_ceiling(flops_per_call: "float | None", images_per_call: int,
                 n_dev: int = 1) -> "float | None":
    """Max physically possible GLOBAL images/sec for a step program that
    costs ``flops_per_call`` FLOPs and advances ``images_per_call``
    images over ``n_dev`` devices; None when FLOPs are unknown (no
    guard, matching bench._physics_guard's contract).

    ``flops_per_call`` is read as the TOTAL program cost. XLA's
    cost_analysis on a GSPMD module is ambiguous between total and
    per-device FLOPs; treating it as total can only make this ceiling
    up to n_dev x too GENEROUS, which keeps the guard sound (it may
    fail to reject, it can never wrongly reject).
    """
    if not flops_per_call or flops_per_call <= 0:
        return None
    return peak_flops() * n_dev * images_per_call / flops_per_call
