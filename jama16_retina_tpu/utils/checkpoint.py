"""Checkpoint management on orbax (SURVEY.md N12/§5.4, reference R9).

Reference behavior to match: save best-so-far by validation AUC, one
directory per ensemble member, restore-for-eval (``tf.train.Saver``
semantics). Orbax gives the TPU-native version: async-capable, sharded-
array aware, with ``best_fn`` retention driven by the metrics we pass at
save time. State saved = params + BN stats + optimizer state + step
(the full ``train_lib.TrainState``).
"""

from __future__ import annotations

import os

import jax
import numpy as np
import orbax.checkpoint as ocp
from absl import logging as absl_logging

from jama16_retina_tpu.train_lib import TrainState

BEST_METRIC = "val_auc"


class CheckpointError(RuntimeError):
    """Actionable restore failure (ISSUE 6 satellite): names WHICH
    checkpoint (directory + step) failed and WHY, instead of the deep
    orbax/pytree traceback a truncated or corrupted checkpoint dir
    otherwise surfaces as. Raised by ``Checkpointer.restore`` for both
    ``trainer.restore_for_eval`` and ``ServingEngine`` construction;
    the original exception rides as ``__cause__``."""


def member_dir(checkpoint_dir: str, member: int) -> str:
    """One directory per ensemble member (reference R9/R11 layout)."""
    return os.path.join(checkpoint_dir, f"member_{member:02d}")


def discover_member_dirs(root: str) -> list[str]:
    """Ensemble discovery for the CLIs (evaluate.py/predict.py): the
    member_NN subdirs written by member_dir, else the root itself as a
    single model. Lives here so the layout convention has one home."""
    import glob

    members = sorted(glob.glob(os.path.join(root, "member_*")))
    return members or [root]


class Checkpointer:
    """Best-by-val-AUC retention PLUS an unconditional latest checkpoint.

    Orbax's ``best_fn`` retention deletes a just-saved step at save time
    when it is not among the top ``max_to_keep`` by metric — so a single
    best-retention manager silently rolls ``--resume`` back to an old
    best step after a val-AUC plateau. Two managers fix that: ``best/``
    keeps the top-k by val AUC (the reference's save-best Saver
    semantics, R9), ``latest/`` keeps exactly the newest step for resume.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import zlib

        self._directory = directory
        self._max_to_keep = max_to_keep
        # Distinct barrier_sync_key_prefix per manager AND per directory:
        # on multi-host runs the managers finalize async saves through
        # named orbax barriers, and identical prefixes collide ("Barrier
        # ... is already ongoing"), deadlocking the coordination service
        # at the next save. Per-directory disambiguation matters for the
        # member-parallel driver, which keeps k member Checkpointers
        # alive simultaneously. crc32, not hash(): PYTHONHASHSEED
        # randomizes hash() per process, and the prefix must agree
        # across all hosts.
        tag = zlib.crc32(os.path.abspath(directory).encode()) & 0xFFFFFFFF
        self._best = ocp.CheckpointManager(
            os.path.join(directory, "best"),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                best_fn=lambda m: float(m[BEST_METRIC]),
                best_mode="max",
                create=True,
                multiprocessing_options=ocp.options.MultiprocessingOptions(
                    barrier_sync_key_prefix=f"best{tag:08x}"
                ),
            ),
        )
        self._latest = ocp.CheckpointManager(
            os.path.join(directory, "latest"),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=1,
                create=True,
                multiprocessing_options=ocp.options.MultiprocessingOptions(
                    barrier_sync_key_prefix=f"latest{tag:08x}"
                ),
            ),
        )
        # In-memory view of the best-manager's kept metrics: the
        # enters-top-k decision gates a COLLECTIVE save, so on multi-host
        # every process must reach the identical verdict — re-reading
        # just-written (possibly still-finalizing) metrics from disk is
        # a race across processes. Seeded from disk once at construction
        # (all saves are finished then), updated in-memory per save.
        # save() appends optimistically BEFORE the async save commits; a
        # failed save would leave a phantom entry suppressing future
        # best/ saves, so wait() reconciles against disk (every call
        # site waits before closing).
        self._rebuild_best_kept()

    def _rebuild_best_kept(self) -> None:
        self._best_kept = []
        for s in self._best.all_steps():
            m = self._best.metrics(s)
            if m is not None:
                self._best_kept.append(float(m[BEST_METRIC]))
        self._best_kept = sorted(self._best_kept)[-self._max_to_keep:]

    def save(self, step: int, state: TrainState, metrics: dict) -> None:
        """``latest/`` is written every time; ``best/`` only when this step
        would actually enter the top-k by metric — otherwise orbax would
        serialize the full state just to delete it during retention,
        doubling checkpoint IO on every non-improving eval."""
        from jama16_retina_tpu.obs import faultinject

        # Fault seam (ISSUE 11; obs/faultinject.py "ckpt.save"): one
        # global read + branch unarmed. Latency plans here widen the
        # in-flight-save window so the kill -9 drill in tests/
        # test_faults.py can land inside it deterministically.
        faultinject.check("ckpt.save")
        # orbax refuses a new save while the previous one's async
        # finalize is still running (CheckpointManager asserts
        # _finalize_thread is None) — settle it first. Normally
        # instant; only a save cadence outpacing finalization (e.g.
        # back-to-back AsyncSaver jobs) ever waits here.
        self._best.wait_until_finished()
        self._latest.wait_until_finished()
        # Numpy SCALARS (np.int32 etc., e.g. a stacked state's step
        # counter after unstack_member's x[m] indexing) are rejected by
        # older orbax StandardSave ("Unsupported type"); 0-d ndarrays
        # are accepted by every version, and restore is unchanged.
        state = jax.tree.map(
            lambda x: np.asarray(x) if isinstance(x, np.generic) else x,
            state,
        )
        metric = float(metrics[BEST_METRIC])
        if self._enters_best(metric):
            self._best.save(
                step,
                args=ocp.args.StandardSave(state),
                metrics={k: float(v) for k, v in metrics.items()},
            )
            self._best_kept = sorted(self._best_kept + [metric])
            self._best_kept = self._best_kept[-self._max_to_keep:]
        self._latest.save(step, args=ocp.args.StandardSave(state))

    def save_latest(self, step: int, state: TrainState) -> bool:
        """Unconditional ``latest/``-only save — the preemption path
        (ISSUE 6): a SIGTERM mid-run has no fresh val metric, and a
        placeholder metric would poison ``best/`` retention, so only
        the resume point is written. Returns False (no-op) when the
        step is already saved — a preemption landing exactly on an
        eval-step save must not collide with orbax's
        StepAlreadyExistsError."""
        from jama16_retina_tpu.obs import faultinject

        faultinject.check("ckpt.save")
        # Same previous-save settling rule as save() — the preemption
        # path may land while an eval-time async save is finalizing.
        self._latest.wait_until_finished()
        if step in self._latest.all_steps():
            return False
        state = jax.tree.map(
            lambda x: np.asarray(x) if isinstance(x, np.generic) else x,
            state,
        )
        self._latest.save(step, args=ocp.args.StandardSave(state))
        return True

    def _enters_best(self, metric: float) -> bool:
        # Decided from the in-memory view (see __init__) — deterministic
        # across processes because the metric sequence is.
        if len(self._best_kept) < self._max_to_keep:
            return True
        return metric > self._best_kept[0]

    def wait(self) -> None:
        self._best.wait_until_finished()
        self._latest.wait_until_finished()
        # All async saves settled: drop any phantom _best_kept entry
        # whose save failed to commit (see __init__).
        self._rebuild_best_kept()

    def _pick(self, step: int | None):
        """Resolve (manager, step) the way restore() selects them."""
        if step is not None:
            mngr = self._best if step in self._best.all_steps() else self._latest
            return mngr, step
        if self.best_step is not None:
            return self._best, self.best_step
        if self.latest_step is not None:
            return self._latest, self.latest_step
        raise FileNotFoundError(f"no checkpoints in {self._best.directory}")

    def _tree_keys(self, mngr, step: int) -> list[str] | None:
        """Stringified tree keys of the saved state, from the step's
        on-disk metadata (manager.item_metadata() returns None on freshly
        opened managers — handlers register only after a save/restore).
        This reads orbax's internal _METADATA layout; if a future orbax
        moves it, return None and callers fall back to the config-derived
        abstract tree (pre-adaptive behavior) instead of breaking every
        restore."""
        import json

        try:
            meta_path = os.path.join(
                str(mngr.directory), str(step), "default", "_METADATA"
            )
            with open(meta_path) as f:
                return list(json.load(f)["tree_metadata"])
        except (OSError, KeyError, ValueError) as e:
            absl_logging.warning(
                "could not read checkpoint tree metadata (%s: %s); "
                "restoring with the config-derived tree", type(e).__name__, e,
            )
            return None

    def saved_with_ema(self, step: int | None = None) -> bool | None:
        """Whether the checkpoint (default: the one restore() would pick)
        carries an EMA shadow — read from the saved tree metadata, NOT
        from any config, so eval can adapt to what the training run
        actually wrote (train.ema_decay is a train-time choice the eval
        config cannot be trusted to repeat). Returns None when the
        metadata is unreadable (unknown ≠ 'no shadow': resume guards must
        not misdiagnose an EMA run as ema-off)."""
        keys = self._tree_keys(*self._pick(step))
        if keys is None:
            return None
        return any(k.startswith("('ema_params', ") for k in keys)

    @property
    def best_step(self) -> int | None:
        return self._best.best_step()

    def best_info(self) -> tuple[int, float] | None:
        """(step, val_auc) of the retained best checkpoint, from the
        best-manager's on-disk metrics — lets a resumed run reconstruct
        its best/early-stop tracking instead of forgetting the
        pre-interruption peak."""
        s = self._best.best_step()
        if s is None:
            return None
        m = self._best.metrics(s)
        if m is None:
            return None
        return int(s), float(m[BEST_METRIC])

    @property
    def latest_step(self) -> int | None:
        return self._latest.latest_step()

    def all_steps(self) -> set[int]:
        """Every step restorable from either manager — the member-parallel
        driver's torn-save recovery searches these for the newest step
        ALL members still have."""
        return set(self._best.all_steps()) | set(self._latest.all_steps())

    def delete_newer_than(self, step: int) -> None:
        """Purge checkpoints newer than ``step`` from both managers.

        The member-parallel torn-save rollback re-trains from an older
        common step; a member's abandoned-timeline checkpoint left in
        place would (a) collide with the re-run's save at the same step
        (orbax raises StepAlreadyExistsError) and (b) win max_to_keep's
        lowest-step-first retention, so a second crash would resume from
        the abandoned state."""
        purged = False
        for mngr in (self._best, self._latest):
            for s in sorted(mngr.all_steps()):
                if s > step:
                    mngr.delete(s)
                    purged = True
        if purged:
            # Deleted steps' metrics must not suppress future best/ saves.
            self._rebuild_best_kept()

    def _do_restore(self, mngr, step: int, abstract):
        """One orbax restore through the reliability seams (ISSUE 6):
        the ``ckpt.restore`` fault point, bounded-backoff retry on
        transient I/O, and — for everything else (truncated arrays,
        missing members, mangled metadata) — a CheckpointError naming
        the directory and step, because 'which checkpoint broke' is the
        first question the operator runbook asks and a 40-frame pytree
        traceback does not answer it."""
        from jama16_retina_tpu.obs import faultinject
        from jama16_retina_tpu.utils import retry as retry_lib

        def _once():
            faultinject.check("ckpt.restore")
            return mngr.restore(step, args=ocp.args.StandardRestore(abstract))

        try:
            return retry_lib.retry_call(
                _once, attempts=3, site="ckpt.restore"
            )
        except OSError as e:
            raise CheckpointError(
                f"checkpoint restore failed with transient I/O errors "
                f"after retries: step {step} under {self._directory!r} "
                f"({type(e).__name__}: {e})"
            ) from e
        except Exception as e:
            raise CheckpointError(
                f"checkpoint at step {step} under {self._directory!r} "
                f"is unreadable ({type(e).__name__}: {e}) — the "
                "directory is likely truncated/corrupted (torn copy, "
                "partial delete); restore another step (available: "
                f"{sorted(self.all_steps())}) or re-save the member"
            ) from e

    def restore(self, abstract_state: TrainState, step: int | None = None
                ) -> TrainState:
        """Restore ``step`` if given (from whichever manager has it),
        else the best step, else the latest.

        The abstract tree is reconciled with the CHECKPOINT's saved
        structure around the optional ``ema_params`` field, so any
        checkpoint restores under any config:
          * shadow saved  -> abstract gets a params-shaped shadow slot;
          * ``None`` saved -> abstract's shadow slot cleared;
          * field absent (pre-EMA legacy checkpoint) -> restore the four
            original fields as a dict and rebuild the TrainState —
            orbax treats present-as-None vs absent as a structure
            mismatch, so the field cannot simply be nulled.
        """
        mngr, step = self._pick(step)
        keys = self._tree_keys(mngr, step)
        abstract = abstract_state
        if keys is not None:
            if any(k.startswith("('ema_params', ") for k in keys):
                if abstract.ema_params is None:
                    abstract = abstract.replace(
                        ema_params=jax.tree.map(lambda x: x, abstract.params)
                    )
            elif "('ema_params',)" in keys:
                abstract = abstract.replace(ema_params=None)
            else:  # legacy: saved before the field existed
                fields = ("step", "params", "batch_stats", "opt_state")
                restored = self._do_restore(
                    mngr, step,
                    {f: getattr(abstract, f) for f in fields},
                )
                return TrainState(**restored, ema_params=None)
        return self._do_restore(mngr, step, abstract)

    def close(self) -> None:
        self._best.close()
        self._latest.close()


def abstract_like(state: TrainState) -> TrainState:
    """Shape/dtype skeleton for StandardRestore."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), state
    )


class AsyncSaver:
    """Background checkpoint writer (``train.async_save``; ISSUE 11).

    One worker thread executes submitted save jobs strictly in
    submission order, so the step loop's stall at a save boundary
    shrinks to an on-device state snapshot plus a queue put — the
    device->host fetch (the ~48 s dominant cost at k=4 flagship scale
    on this environment, docs/PERF.md §Eval) and the orbax write both
    run off-loop. A job is a zero-arg callable; the trainer closes the
    snapshot, the Checkpointer, and the grain-state persist into it.

    Failure contract: a job's exception is LATCHED and re-raised at the
    next ``submit()`` or ``drain()`` — a failed checkpoint write stops
    the run loudly, one boundary late, instead of being swallowed by a
    daemon thread. ``drain()`` blocks until every submitted job
    finished; the SIGTERM preemption path calls it BEFORE
    ``save_latest`` so the emergency save can never interleave with an
    in-flight async save on the same orbax managers. kill -9 mid-job
    leaves at most an uncommitted orbax tmp step, which ``all_steps()``
    never lists — resume falls back to the last committed step (pinned
    in tests/test_faults.py)."""

    def __init__(self):
        import queue
        import threading

        self._q: "queue.Queue" = queue.Queue()
        self._err: "BaseException | None" = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="ckpt-async-saver"
        )
        self._thread.start()

    def _run(self) -> None:
        while True:
            job = self._q.get()
            try:
                if job is None:
                    return
                job()
            except BaseException as e:  # noqa: BLE001 - latched, re-raised
                self._err = e
            finally:
                self._q.task_done()

    def _raise_pending(self) -> None:
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    def submit(self, job) -> None:
        """Enqueue one save job (runs after every previously submitted
        job). Re-raises a prior job's latched failure first."""
        if self._closed:
            raise RuntimeError("AsyncSaver is closed")
        self._raise_pending()
        self._q.put(job)

    def drain(self) -> None:
        """Block until every submitted job has finished; re-raise any
        latched failure."""
        self._q.join()
        self._raise_pending()

    def close(self) -> None:
        """Drain, stop the worker, and surface any latched failure."""
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._thread.join()
        self._raise_pending()
