"""Checkpoint management on orbax (SURVEY.md N12/§5.4, reference R9).

Reference behavior to match: save best-so-far by validation AUC, one
directory per ensemble member, restore-for-eval (``tf.train.Saver``
semantics). Orbax gives the TPU-native version: async-capable, sharded-
array aware, with ``best_fn`` retention driven by the metrics we pass at
save time. State saved = params + BN stats + optimizer state + step
(the full ``train_lib.TrainState``).
"""

from __future__ import annotations

import os

import jax
import numpy as np
import orbax.checkpoint as ocp

from jama16_retina_tpu.train_lib import TrainState

BEST_METRIC = "val_auc"


def member_dir(checkpoint_dir: str, member: int) -> str:
    """One directory per ensemble member (reference R9/R11 layout)."""
    return os.path.join(checkpoint_dir, f"member_{member:02d}")


def discover_member_dirs(root: str) -> list[str]:
    """Ensemble discovery for the CLIs (evaluate.py/predict.py): the
    member_NN subdirs written by member_dir, else the root itself as a
    single model. Lives here so the layout convention has one home."""
    import glob

    members = sorted(glob.glob(os.path.join(root, "member_*")))
    return members or [root]


class Checkpointer:
    """Best-by-val-AUC retention PLUS an unconditional latest checkpoint.

    Orbax's ``best_fn`` retention deletes a just-saved step at save time
    when it is not among the top ``max_to_keep`` by metric — so a single
    best-retention manager silently rolls ``--resume`` back to an old
    best step after a val-AUC plateau. Two managers fix that: ``best/``
    keeps the top-k by val AUC (the reference's save-best Saver
    semantics, R9), ``latest/`` keeps exactly the newest step for resume.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        self._max_to_keep = max_to_keep
        self._best = ocp.CheckpointManager(
            os.path.join(directory, "best"),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                best_fn=lambda m: float(m[BEST_METRIC]),
                best_mode="max",
                create=True,
            ),
        )
        self._latest = ocp.CheckpointManager(
            os.path.join(directory, "latest"),
            options=ocp.CheckpointManagerOptions(max_to_keep=1, create=True),
        )

    def save(self, step: int, state: TrainState, metrics: dict) -> None:
        """``latest/`` is written every time; ``best/`` only when this step
        would actually enter the top-k by metric — otherwise orbax would
        serialize the full state just to delete it during retention,
        doubling checkpoint IO on every non-improving eval."""
        if self._enters_best(float(metrics[BEST_METRIC])):
            self._best.save(
                step,
                args=ocp.args.StandardSave(state),
                metrics={k: float(v) for k, v in metrics.items()},
            )
        self._latest.save(step, args=ocp.args.StandardSave(state))

    def _enters_best(self, metric: float) -> bool:
        steps = self._best.all_steps()
        if len(steps) < self._max_to_keep:
            return True
        kept = []
        for s in steps:
            m = self._best.metrics(s)
            if m is None:  # metricless step (shouldn't happen): displaceable
                return True
            kept.append(float(m[BEST_METRIC]))
        return metric > min(kept)

    def wait(self) -> None:
        self._best.wait_until_finished()
        self._latest.wait_until_finished()

    def saved_with_ema(self, step: int | None = None) -> bool:
        """Whether the checkpoint (default: the one restore() would pick)
        carries an EMA shadow — read from orbax's saved tree metadata,
        NOT from any config, so eval can adapt its abstract tree to what
        the training run actually wrote (train.ema_decay is a train-time
        choice the eval config cannot be trusted to repeat)."""
        import json

        if step is None:
            step = self.best_step if self.best_step is not None else self.latest_step
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self._best.directory}")
        mngr = self._best if step in self._best.all_steps() else self._latest
        # manager.item_metadata() returns None on a freshly opened manager
        # (handlers register only after a save/restore call), so read the
        # step's tree metadata from disk: leaf keys nested under
        # ('ema_params', ...) exist iff a shadow was saved — an ema-less
        # state stores the single placeholder key ('ema_params',).
        meta_path = os.path.join(
            str(mngr.directory), str(step), "default", "_METADATA"
        )
        with open(meta_path) as f:
            tree = json.load(f)["tree_metadata"]
        return any(k.startswith("('ema_params', ") for k in tree)

    @property
    def best_step(self) -> int | None:
        return self._best.best_step()

    @property
    def latest_step(self) -> int | None:
        return self._latest.latest_step()

    def restore(self, abstract_state: TrainState, step: int | None = None
                ) -> TrainState:
        """Restore ``step`` if given (from whichever manager has it),
        else the best step, else the latest."""
        if step is not None:
            mngr = self._best if step in self._best.all_steps() else self._latest
            return mngr.restore(step, args=ocp.args.StandardRestore(abstract_state))
        if self.best_step is not None:
            return self._best.restore(
                self.best_step, args=ocp.args.StandardRestore(abstract_state)
            )
        if self.latest_step is not None:
            return self._latest.restore(
                self.latest_step, args=ocp.args.StandardRestore(abstract_state)
            )
        raise FileNotFoundError(f"no checkpoints in {self._best.directory}")

    def close(self) -> None:
        self._best.close()
        self._latest.close()


def abstract_like(state: TrainState) -> TrainState:
    """Shape/dtype skeleton for StandardRestore."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), state
    )
