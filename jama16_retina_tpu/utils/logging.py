"""Run metrics logging (SURVEY.md §5.5).

Reference: console prints + periodic val AUC. Build: absl console logs
plus one JSONL file per run — a line per event (train step stats, eval
reports) — identical shape for every backend/config so runs diff cleanly.
"""

from __future__ import annotations

import json
import os
import time
from typing import IO

from absl import logging as absl_logging


class RunLog:
    def __init__(self, workdir: str, name: str = "metrics.jsonl"):
        os.makedirs(workdir, exist_ok=True)
        self.path = os.path.join(workdir, name)
        self._fh: IO = open(self.path, "a")

    def write(self, kind: str, **fields) -> dict:
        rec = {"kind": kind, "t": round(time.time(), 3), **fields}
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        absl_logging.info("%s %s", kind, {k: v for k, v in fields.items()})
        return rec

    def close(self) -> None:
        self._fh.close()


def read_jsonl(path: str) -> list[dict]:
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]
