"""Run metrics logging (SURVEY.md §5.5).

Reference: console prints + periodic val AUC. Build: absl console logs
plus one JSONL file per run — a line per event (train step stats, eval
reports) — identical shape for every backend/config so runs diff cleanly.
Optional TensorBoard scalars (``tensorboard=True``) mirror the numeric
fields of train/eval records into ``<workdir>/tb`` for users of the
reference's TF-era tooling; the JSONL stays the system of record.
"""

from __future__ import annotations

import json
import numbers
import os
import time
from typing import IO

from absl import logging as absl_logging


class RunLog:
    def __init__(self, workdir: str, name: str = "metrics.jsonl",
                 tensorboard: bool = False):
        os.makedirs(workdir, exist_ok=True)
        self.path = os.path.join(workdir, name)
        self._fh: IO = open(self.path, "a")
        self._tb = None
        if tensorboard:
            import tensorflow as tf

            self._tb = tf.summary.create_file_writer(
                os.path.join(workdir, "tb")
            )

    def write(self, kind: str, **fields) -> dict:
        rec = {"kind": kind, "t": round(time.time(), 3), **fields}
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        absl_logging.info("%s %s", kind, {k: v for k, v in fields.items()})
        if self._tb is not None and "step" in fields:
            import tensorflow as tf

            with self._tb.as_default():
                for k, v in fields.items():
                    if k != "step" and isinstance(v, numbers.Real):
                        tf.summary.scalar(
                            f"{kind}/{k}", float(v), step=int(fields["step"])
                        )
            self._tb.flush()
        return rec

    def close(self) -> None:
        self._fh.close()
        if self._tb is not None:
            self._tb.close()


def read_jsonl(path: str) -> list[dict]:
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]
