"""Run metrics logging (SURVEY.md §5.5).

Reference: console prints + periodic val AUC. Build: absl console logs
plus one JSONL file per run — a line per event (train step stats, eval
reports) — identical shape for every backend/config so runs diff cleanly.
Optional TensorBoard scalars (``tensorboard=True``) mirror the numeric
fields of train/eval records into ``<workdir>/tb`` for users of the
reference's TF-era tooling; the JSONL stays the system of record.

Multi-host: every process runs the same loop over the same global state,
so process 0 owns ``metrics.jsonl`` (the system of record — concurrent
appends from P processes would tear/duplicate it) and every other
process mirrors its records to ``metrics.p{N}.jsonl``. The per-process
mirrors are the heartbeat files of SURVEY.md §5.3: a wedged host is
visible as a stale ``metrics.p{N}.jsonl`` mtime even while process 0
keeps advancing toward the blocked collective.
"""

from __future__ import annotations

import json
import numbers
import os
import threading
import time
from typing import IO

from absl import logging as absl_logging


class RunLog:
    def __init__(self, workdir: str, name: str = "metrics.jsonl",
                 tensorboard: bool = False, fresh: bool = False):
        """``fresh``: a NON-resume run reusing a workdir rotates the
        existing JSONL to ``<name>.prev`` (clobbering an older .prev)
        instead of appending — the file is the resume-replay source for
        best/early-stop tracking, and inherited eval records from a
        previous run would poison a later resume of THIS run with a
        best_auc it never achieved."""
        os.makedirs(workdir, exist_ok=True)
        self._workdir = workdir
        self._name = name
        self._fresh = fresh
        self._want_tb = tensorboard
        # The file paths depend on jax.process_index(), which would
        # force-initialize a jax backend from a mere constructor — defer
        # until the first write (by which point the trainer has long
        # since initialized jax deliberately).
        self.path = os.path.join(workdir, name)
        self._fh: IO | None = None
        self._tb = None
        self._opened = False
        # Serializes open+write+flush: the serve path's batcher worker
        # and telemetry snapshotter write from background threads
        # concurrently with the main loop, and interleaved write()/
        # flush() pairs on one file handle can TEAR a JSONL line —
        # which read_jsonl's torn-line skip would then silently drop
        # on resume replay (ISSUE 3 satellite).
        self._write_lock = threading.Lock()

    def _ensure_open(self) -> None:
        if self._opened:
            return
        self._opened = True
        import jax

        idx = jax.process_index()
        if idx != 0:
            stem, ext = os.path.splitext(self._name)
            self.path = os.path.join(self._workdir, f"{stem}.p{idx}{ext}")
        if self._fresh and os.path.exists(self.path):
            from jama16_retina_tpu.integrity import artifact as artifact_lib

            artifact_lib.rename(self.path, self.path + ".prev")
        self._fh = open(self.path, "a")
        if self._want_tb and idx == 0:
            import tensorflow as tf

            self._tb = tf.summary.create_file_writer(
                os.path.join(self._workdir, "tb")
            )

    def write(self, kind: str, **fields) -> dict:
        rec = {"kind": kind, "t": round(time.time(), 3), **fields}
        line = json.dumps(rec) + "\n"
        with self._write_lock:
            self._ensure_open()
            self._fh.write(line)
            self._fh.flush()
        absl_logging.info("%s %s", kind, {k: v for k, v in fields.items()})
        # TB mirrors step-indexed scalar series only: heartbeats are
        # liveness records (their step may legitimately be None when no
        # loop body ran, and epoch-time payloads are not curves).
        if (self._tb is not None and fields.get("step") is not None
                and kind != "heartbeat"):
            import tensorflow as tf

            with self._tb.as_default():
                for k, v in fields.items():
                    if k != "step" and isinstance(v, numbers.Real):
                        tf.summary.scalar(
                            f"{kind}/{k}", float(v), step=int(fields["step"])
                        )
            self._tb.flush()
        return rec

    def close(self) -> None:
        with self._write_lock:
            if self._fh is not None:
                self._fh.close()
        if self._tb is not None:
            self._tb.close()


def read_jsonl(path: str) -> list[dict]:
    """Parse a JSONL file, SKIPPING malformed lines (warned, not raised):
    a run killed mid-flush leaves a torn final line, and the resume path
    replays this file — a preempted run must stay resumable."""
    records = []
    with open(path) as fh:
        for i, line in enumerate(fh):
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                absl_logging.warning(
                    "%s:%d: skipping malformed JSONL line (torn write?)",
                    path, i + 1,
                )
    return records
