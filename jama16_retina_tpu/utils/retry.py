"""Bounded exponential-backoff retry for transient I/O (ISSUE 6).

One retry policy for the whole data/checkpoint plane instead of ad-hoc
loops: TFRecord reads (data/grain_pipeline.TFRecordIndex), orbax
checkpoint restore (utils/checkpoint.Checkpointer), and predict.py's
per-image file reads all route transient failures through
``retry_call``. Design constraints:

  * BOUNDED. ``attempts`` is a hard cap — a permanently broken path
    must surface the ORIGINAL exception (raised from the last attempt,
    with the attempt count in the log), never spin forever. Retry is
    for transience, not for masking rot; the quarantine layer
    (data.quarantined counters) owns persistent badness.
  * CHEAP WHEN QUIET. The first attempt pays one try/except frame and
    nothing else — no clock reads, no telemetry — so retry wrappers are
    safe on hot paths (a TFRecordIndex.read happens per training
    image).
  * OBSERVABLE WHEN LOUD. Every retried-then-attempted call increments
    ``io.retries`` (and ``io.retries.{site}`` when a site name is
    given) in the process registry, so a link that flaps surfaces in
    telemetry/.prom/obs_report long before it hard-fails a run.
  * DETERMINISTIC IN TESTS. The backoff sleeps through an injectable
    ``sleep`` callable and the delays are a pure function of
    (base_delay, attempt) — no jitter — so tests/test_faults.py can
    pin the exact schedule with a recording fake.
"""

from __future__ import annotations

import time
from typing import Callable, Iterable

from absl import logging as absl_logging

from jama16_retina_tpu.obs import registry as obs_registry

# The exception classes retry_call treats as transient by default:
# filesystem/network hiccups. ValueError & friends (corrupt payloads)
# are NOT here — a malformed record does not get better on retry; it
# gets quarantined (data/grain_pipeline.py) or raised.
DEFAULT_TRANSIENT: tuple = (OSError, IOError)


def backoff_delays(attempts: int, base_delay: float,
                   max_delay: float) -> Iterable[float]:
    """The sleep schedule between attempts: base * 2^k, capped.
    Pure function of its arguments (no jitter) — the determinism the
    fault-injection tests pin."""
    d = base_delay
    for _ in range(max(0, attempts - 1)):
        yield min(d, max_delay)
        d *= 2.0


def retry_call(
    fn: Callable,
    *args,
    attempts: int = 3,
    base_delay: float = 0.05,
    max_delay: float = 2.0,
    retry_on: tuple = DEFAULT_TRANSIENT,
    site: str = "",
    sleep: Callable[[float], None] = time.sleep,
    registry: "obs_registry.Registry | None" = None,
    **kwargs,
):
    """``fn(*args, **kwargs)`` with up to ``attempts`` tries.

    Exceptions in ``retry_on`` trigger a backoff-and-retry; anything
    else propagates immediately (corrupt data must not burn the retry
    budget meant for transient I/O). The LAST attempt's exception is
    re-raised unchanged, so callers' except clauses keep matching the
    original type. ``site`` names the call site in the retry counters
    (``io.retries.{site}``) and the warning log.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    delays = backoff_delays(attempts, base_delay, max_delay)
    for attempt in range(1, attempts + 1):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            if attempt == attempts:
                absl_logging.warning(
                    "retry budget exhausted%s after %d attempts: %s: %s",
                    f" at {site}" if site else "", attempts,
                    type(e).__name__, e,
                )
                raise
            reg = (registry if registry is not None
                   else obs_registry.default_registry())
            reg.counter(
                "io.retries",
                help="transient I/O failures that were retried "
                     "(utils/retry.py), all sites",
            ).inc()
            if site:
                reg.counter(
                    f"io.retries.{site}",
                    help="transient I/O failures retried at this one "
                         "call site",
                ).inc()
            delay = next(delays)
            absl_logging.warning(
                "transient %s%s (attempt %d/%d), retrying in %.3fs: %s",
                type(e).__name__, f" at {site}" if site else "",
                attempt, attempts, delay, e,
            )
            if delay > 0:
                sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
