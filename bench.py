#!/usr/bin/env python
"""Benchmark: flagship train-step throughput on the local chip.

Prints exactly ONE JSON line:
  {"metric": "train_images_per_sec_per_chip", "value": N,
   "unit": "images/sec/chip", "vs_baseline": R}

Workload = the production config of record (BASELINE.json:7): Inception-v3,
binary head, 299x299, global batch 32, aux head on, bf16 compute — the
full train step (on-device augment + fwd/bwd + optax update) as compiled
by train_lib.make_train_step, fed device-resident uint8 batches.

``vs_baseline``: the reference never published throughput (BASELINE.md),
so the denominator is derived from the driver-set target "train wall-clock
< 1 hour on a v3-8 slice" (BASELINE.json:5): the replication protocol
passes ~15 epochs x ~57k EyePACS images ≈ 860k images through the model;
doing that in 3600 s on 8 chips needs ≈ 30 images/sec/chip. So
vs_baseline = value / 30, i.e. >1.0 means this chip alone beats the
per-chip rate the 1-hour target requires.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_IMAGES_PER_SEC_PER_CHIP = 30.0  # see module docstring
WARMUP_STEPS = 3
TIMED_STEPS = 20


def main() -> None:
    import jax

    from jama16_retina_tpu import models, train_lib
    from jama16_retina_tpu.configs import get_config
    from jama16_retina_tpu.parallel import mesh as mesh_lib

    cfg = get_config("eyepacs_binary")
    batch_size = cfg.data.batch_size
    size = cfg.model.image_size

    mesh = mesh_lib.make_mesh()  # all local devices (1 chip under axon)
    n_dev = mesh.devices.size
    print(f"bench: {n_dev} device(s), batch {batch_size}, {size}px",
          file=sys.stderr)

    model = models.build(cfg.model)
    state, tx = train_lib.create_state(cfg, model, jax.random.key(0))
    state = jax.device_put(state, mesh_lib.replicated(mesh))
    step = train_lib.make_train_step(cfg, model, tx, mesh=mesh)

    rng = np.random.default_rng(0)
    batch = mesh_lib.shard_batch(
        {
            "image": rng.integers(0, 256, (batch_size, size, size, 3), np.uint8),
            "grade": rng.integers(0, 5, (batch_size,), np.int32),
        },
        mesh,
    )
    key = jax.random.key(1)

    t0 = time.time()
    for _ in range(WARMUP_STEPS):
        state, m = step(state, batch, key)
    jax.block_until_ready(state)
    print(f"bench: warmup+compile {time.time() - t0:.1f}s", file=sys.stderr)

    t0 = time.time()
    for _ in range(TIMED_STEPS):
        state, m = step(state, batch, key)
    jax.block_until_ready(state)
    dt = time.time() - t0

    images_per_sec = TIMED_STEPS * batch_size / dt
    per_chip = images_per_sec / n_dev
    print(f"bench: {TIMED_STEPS} steps in {dt:.2f}s, loss={float(m['loss']):.4f}",
          file=sys.stderr)
    print(json.dumps({
        "metric": "train_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMAGES_PER_SEC_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    main()
