#!/usr/bin/env python
"""Benchmark: flagship train-step throughput on the local chip.

Prints exactly ONE JSON line; the headline metric is the device-fed
train-step rate:
  {"metric": "train_images_per_sec_per_chip", "value": N,
   "unit": "images/sec/chip", "vs_baseline": R, ...extras}

Extra keys quantify the rest of the system (VERDICT.md round-1 #3):
  device_only        — same as value: jit train step fed device-resident
                       uint8 batches (cycled over several distinct
                       batches, not one reused batch).
  pipeline_fed       — train step fed by the real tf.data pipeline
                       (TFRecord -> parse -> batch -> device_prefetch),
                       raw-encoded records. The end-to-end number.
  host_decode_jpeg   — images/sec the 1-vCPU host sustains decoding
                       JPEG TFRecords at 299px (no device work).
  host_parse_raw     — same for pre-decoded raw records (the shipped
                       mitigation: decode paid once offline).
  host_grain_raw     — the grain loader (data/grain_pipeline.py) on the
                       same raw records: random-access index + protobuf
                       parse, no tf.data runtime.
  augment_jnp / augment_pallas — the augmentation stage alone, jnp
                       composition vs the fused pallas kernel
                       (ops/pallas_augment.py), compiled on this chip.
  device_only_b128   — the same train step at per-chip batch 128. The
                       config of record pins the GLOBAL batch at 32
                       (4/chip on a v3-8), and at 32/chip the step is
                       HBM-bound on stem activations (docs/PERF.md); this
                       number shows the amortized rate the chip reaches
                       when batch is not pinned by the experiment.
  eval_images_per_sec — the jit eval step (forward-only, eval batch) on
                       this chip: the per-model cost of the k-model
                       ensemble evaluation protocol (BASELINE.json:10).
  ensemble4_member_images_per_sec / ensemble4_parallel_speedup —
                       the member-parallel ensemble step (4 stacked
                       members, train_lib.make_ensemble_train_step) in
                       member-images/sec/chip, and its ratio to the
                       sequential member rate (device_only). Single-chip
                       this sits near 1.0 (weight/optimizer HBM traffic
                       scales with members); the capability's payoff is
                       pod topology — see configs.py ensemble_parallel.

Workload = the production config of record (BASELINE.json:7): Inception-v3,
binary head, 299x299, global batch 32, aux head on, bf16 compute — the
full train step (on-device augment + fwd/bwd + optax update) as compiled
by train_lib.make_train_step.

``--use_pallas`` routes the train step's color augmentation through the
fused pallas kernel (cfg.data.use_pallas=True) so the compiled-kernel
path is exercised inside the production program.

``vs_baseline``: the reference never published throughput (BASELINE.md),
so the denominator is derived from the driver-set target "train wall-clock
< 1 hour on a v3-8 slice" (BASELINE.json:5): the replication protocol
passes ~15 epochs x ~57k EyePACS images ~= 860k images through the model;
doing that in 3600 s on 8 chips needs ~= 30 images/sec/chip. So
vs_baseline = value / 30, i.e. >1.0 means this chip alone beats the
per-chip rate the 1-hour target requires.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time
from typing import Any

import numpy as np

BASELINE_IMAGES_PER_SEC_PER_CHIP = 30.0  # see module docstring
WARMUP_STEPS = 3
# 50 timed steps ≈ 1.4s on-device: run-to-run variance of the headline
# number was ~±4% at 20 steps (BENCH history 1086..1172 img/s).
TIMED_STEPS = 50
N_DISTINCT_BATCHES = 4
# Synthetic TFRecord fixture for the host/pipeline measurements. Cached
# across runs (rendering 299px fundus images costs ~0.1 s each on this
# host; the bench must not pay that every invocation).
BENCH_DATA_DIR = os.environ.get("BENCH_DATA_DIR", "/tmp/retina_bench_data")
BENCH_N_IMAGES = 256


def _log(msg: str) -> None:
    print(f"bench: {msg}", file=sys.stderr)


def _ensure_bench_data(image_size: int) -> dict:
    """Write (once) two synthetic splits: jpeg- and raw-encoded."""
    from jama16_retina_tpu.data import tfrecord

    dirs = {}
    for enc in ("jpeg", "raw"):
        d = os.path.join(BENCH_DATA_DIR, f"{image_size}_{enc}")
        marker = os.path.join(d, ".complete")
        if not os.path.exists(marker):
            _log(f"writing {BENCH_N_IMAGES} synthetic {enc} records -> {d}")
            tfrecord.write_synthetic_split(
                d, "train", BENCH_N_IMAGES, image_size=image_size,
                num_shards=4, seed=0, encoding=enc,
            )
            with open(marker, "w") as f:
                f.write("ok")
        dirs[enc] = d
    return dirs


def _host_rate(data_dir: str, cfg, image_size: int, n_batches: int = 30,
               loader: str = "tfdata") -> float:
    """Images/sec of the host loader alone (parse/decode+batch, no TPU)."""
    if loader == "grain":
        from jama16_retina_tpu.data import grain_pipeline as mod
    else:
        from jama16_retina_tpu.data import pipeline as mod
    it = mod.train_batches(data_dir, "train", cfg.data, image_size, seed=0)
    for _ in range(3):  # warm threads/autotune
        next(it)
    t0 = time.time()
    for _ in range(n_batches):
        next(it)
    dt = time.time() - t0
    # Tear down promptly: a leaked tf.data iterator keeps its autotune/
    # reader threads alive and steals CPU from the next measurement
    # (observed: the grain rate halves when measured after tf.data
    # without this).
    if hasattr(it, "close"):
        it.close()
    del it
    import gc

    gc.collect()
    return n_batches * cfg.data.batch_size / dt


def _timed_steps(step, state, batch_iter, key, n_steps: int, batch_size: int,
                 n_dev: int, warmup: int = WARMUP_STEPS) -> tuple[float, Any]:
    """Shared timing discipline for every train-step measurement: warm up
    (compile included), block, time ``n_steps``, block; returns
    (images/sec/chip, final state). ``batch_iter`` is any callable
    ``i -> batch`` (cycled list or pipeline iterator)."""
    import jax

    for i in range(warmup):
        state, _ = step(state, batch_iter(i), key)
    jax.block_until_ready(state)
    t0 = time.time()
    for i in range(n_steps):
        state, m = step(state, batch_iter(i), key)
    jax.block_until_ready(state)
    rate = n_steps * batch_size / (time.time() - t0) / n_dev
    return rate, state


def _augment_rate(images_u8, data_cfg, use_pallas: bool, n: int = 30) -> float:
    """Images/sec of the augmentation stage alone, compiled on this chip."""
    import jax

    cfg = dataclasses.replace(data_cfg, use_pallas=use_pallas)
    from jama16_retina_tpu.data import augment

    fn = jax.jit(lambda k, im: augment.augment_batch(k, im, cfg))
    key = jax.random.key(0)
    out = fn(key, images_u8)
    jax.block_until_ready(out)
    t0 = time.time()
    for i in range(n):
        out = fn(jax.random.fold_in(key, i), images_u8)
    jax.block_until_ready(out)
    return n * images_u8.shape[0] / (time.time() - t0)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--use_pallas", action="store_true",
        help="force the fused pallas color-jitter kernel on (it is already "
             "the eyepacs_binary preset default; see --no_pallas)",
    )
    parser.add_argument(
        "--no_pallas", action="store_true",
        help="force the jnp augmentation composition instead of the kernel",
    )
    parser.add_argument(
        "--skip_host", action="store_true",
        help="device-only measurements (skip TFRecord fixture + host rates)",
    )
    parser.add_argument(
        "--skip_b128", action="store_true",
        help="skip the batch-128 scaling datapoint (saves its ~40s compile "
             "for quick checks)",
    )
    parser.add_argument(
        "--skip_ensemble", action="store_true",
        help="skip the 4-member stacked-ensemble datapoint (saves its "
             "compile for quick checks)",
    )
    args = parser.parse_args()

    import jax

    from jama16_retina_tpu import models, train_lib
    from jama16_retina_tpu.configs import get_config
    from jama16_retina_tpu.data import pipeline
    from jama16_retina_tpu.parallel import mesh as mesh_lib

    cfg = get_config("eyepacs_binary")
    if args.use_pallas or args.no_pallas:
        cfg = cfg.replace(data=dataclasses.replace(
            cfg.data, use_pallas=not args.no_pallas))
    batch_size = cfg.data.batch_size
    size = cfg.model.image_size

    mesh = mesh_lib.make_mesh()  # all local devices (1 chip under axon)
    n_dev = mesh.devices.size
    _log(f"{n_dev} device(s), batch {batch_size}, {size}px, "
         f"use_pallas={cfg.data.use_pallas}")

    model = models.build(cfg.model)
    state, tx = train_lib.create_state(cfg, model, jax.random.key(0))
    state = jax.device_put(state, mesh_lib.replicated(mesh))
    step = train_lib.make_train_step(cfg, model, tx, mesh=mesh)

    rng = np.random.default_rng(0)
    batches = [
        mesh_lib.shard_batch(
            {
                "image": rng.integers(0, 256, (batch_size, size, size, 3), np.uint8),
                "grade": rng.integers(0, 5, (batch_size,), np.int32),
            },
            mesh,
        )
        for _ in range(N_DISTINCT_BATCHES)
    ]
    key = jax.random.key(1)

    t0 = time.time()
    device_only, state = _timed_steps(
        step, state, lambda i: batches[i % N_DISTINCT_BATCHES], key,
        TIMED_STEPS, batch_size, n_dev,
    )
    _log(f"device_only: {TIMED_STEPS} steps in {time.time() - t0:.1f}s "
         f"incl. warmup+compile ({device_only:.1f} img/s/chip)")

    extras: dict = {"use_pallas": cfg.data.use_pallas}

    # Augmentation stage alone: jnp vs fused pallas kernel on this chip.
    aug_imgs = jax.device_put(batches[0]["image"])
    try:
        extras["augment_jnp"] = round(_augment_rate(aug_imgs, cfg.data, False), 1)
        extras["augment_pallas"] = round(_augment_rate(aug_imgs, cfg.data, True), 1)
        _log(f"augment-only: jnp {extras['augment_jnp']} img/s, "
             f"pallas {extras['augment_pallas']} img/s")
    except Exception as e:  # pragma: no cover - bench must still emit JSON
        _log(f"augment microbench failed: {type(e).__name__}: {e}")

    if not args.skip_host:
        dirs = _ensure_bench_data(size)
        extras["host_decode_jpeg"] = round(_host_rate(dirs["jpeg"], cfg, size), 1)
        extras["host_parse_raw"] = round(_host_rate(dirs["raw"], cfg, size), 1)
        _log(f"host feed: jpeg-decode {extras['host_decode_jpeg']} img/s, "
             f"raw-parse {extras['host_parse_raw']} img/s")
        try:
            extras["host_grain_raw"] = round(
                _host_rate(dirs["raw"], cfg, size, loader="grain"), 1
            )
            _log(f"host feed (grain loader, raw): "
                 f"{extras['host_grain_raw']} img/s")
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"grain host bench failed: {type(e).__name__}: {e}")

        # End-to-end: the real pipeline (raw records) feeding the train
        # step through device_prefetch — what a training run actually gets.
        it = pipeline.device_prefetch(
            pipeline.train_batches(dirs["raw"], "train", cfg.data, size, seed=0),
            sharding=mesh_lib.batch_sharding(mesh),
            size=cfg.data.prefetch_batches,
        )
        rate, state = _timed_steps(
            step, state, lambda i: next(it), key, TIMED_STEPS, batch_size,
            n_dev, warmup=3,
        )
        extras["pipeline_fed"] = round(rate, 2)
        _log(f"pipeline_fed: {extras['pipeline_fed']} img/s/chip")

    # Eval-side rate: the forward-only jit eval step at the eval batch
    # size — multiply by k models x test-set size for the ensemble
    # evaluation cost (ten-model protocol, BASELINE.json:10).
    try:
        eval_step = train_lib.make_eval_step(cfg, model, mesh=mesh)
        eval_bs = cfg.eval.batch_size
        eval_batch = mesh_lib.shard_batch(
            {"image": rng.integers(0, 256, (eval_bs, size, size, 3), np.uint8)},
            mesh,
        )
        probs = eval_step(state, eval_batch)
        jax.block_until_ready(probs)
        n_eval = 30
        t0 = time.time()
        for _ in range(n_eval):
            probs = eval_step(state, eval_batch)
        jax.block_until_ready(probs)
        extras["eval_images_per_sec"] = round(
            n_eval * eval_bs / (time.time() - t0) / n_dev, 2
        )
        _log(f"eval step: {extras['eval_images_per_sec']} img/s/chip "
             f"(batch {eval_bs}, forward-only)")
    except Exception as e:  # pragma: no cover - bench must emit JSON
        _log(f"eval bench failed: {type(e).__name__}: {e}")

    # Batch-scaling datapoint: per-chip batch 128 (see docstring). Placed
    # AFTER every section that reads `state`: the donating step consumes
    # its buffers, and a mid-section failure here must not poison a
    # later measurement. A second compile (~40s); the measurement ~2s.
    if not args.skip_b128:
        try:
            big = 128 * n_dev
            big_batches = [
                mesh_lib.shard_batch(
                    {
                        "image": rng.integers(
                            0, 256, (big, size, size, 3), np.uint8
                        ),
                        "grade": rng.integers(0, 5, (big,), np.int32),
                    },
                    mesh,
                )
                for _ in range(2)
            ]
            rate, state = _timed_steps(
                step, state, lambda i: big_batches[i % 2], key, 20, big, n_dev
            )
            extras["device_only_b128"] = round(rate, 2)
            _log(f"device_only @ batch 128/chip: "
                 f"{extras['device_only_b128']} img/s/chip")
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"batch-128 bench failed: {type(e).__name__}: {e}")

    # Member-parallel ensemble training (train_lib.make_ensemble_train_step):
    # 4 stacked members, one program, same batch-32 workload. The
    # speedup column is what the stacked form buys over 4 sequential
    # member-steps — the reference's k-sequential ensemble protocol is
    # the denominator of the <1h wall-clock target (BASELINE.json:5,10).
    if not args.skip_ensemble:
        try:
            k = 4
            ens_state, ens_tx = train_lib.create_ensemble_state(
                cfg, model, list(range(k))
            )
            ens_state = jax.device_put(ens_state, mesh_lib.replicated(mesh))
            ens_step = train_lib.make_ensemble_train_step(
                cfg, model, ens_tx, mesh=None
            )
            ens_keys = train_lib.stack_member_keys(list(range(k)))
            rate, _ = _timed_steps(
                lambda st, b, key: ens_step(st, b, ens_keys),
                ens_state, lambda i: batches[i % N_DISTINCT_BATCHES], key,
                20, k * batch_size, n_dev,
            )
            extras["ensemble4_member_images_per_sec"] = round(rate, 2)
            extras["ensemble4_parallel_speedup"] = round(rate / device_only, 2)
            _log(f"ensemble k=4 stacked step: {rate:.1f} member-img/s/chip "
                 f"({extras['ensemble4_parallel_speedup']}x the sequential "
                 "member rate)")
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"ensemble bench failed: {type(e).__name__}: {e}")

    extras["device_only"] = round(device_only, 2)
    print(json.dumps({
        "metric": "train_images_per_sec_per_chip",
        "value": round(device_only, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(device_only / BASELINE_IMAGES_PER_SEC_PER_CHIP, 3),
        **extras,
    }))


if __name__ == "__main__":
    main()
