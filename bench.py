#!/usr/bin/env python
"""Benchmark: flagship train-step throughput on the local chip.

Prints exactly ONE JSON line; the headline metric is the device-fed
train-step rate:
  {"metric": "train_images_per_sec_per_chip", "value": N,
   "unit": "images/sec/chip", "vs_baseline": R, ...extras}

Extra keys quantify the rest of the system (VERDICT.md round-1 #3):
  device_only        — same as value: jit train step fed device-resident
                       uint8 batches (cycled over several distinct
                       batches, not one reused batch).
  pipeline_fed       — train step fed by the real tf.data pipeline
                       (TFRecord -> parse -> batch -> device_prefetch),
                       raw-encoded records. The end-to-end number.
  host_decode_jpeg   — images/sec the 1-vCPU host sustains decoding
                       JPEG TFRecords at 299px (no device work).
  host_parse_raw     — same for pre-decoded raw records (the shipped
                       mitigation: decode paid once offline).
  host_grain_raw     — the grain loader (data/grain_pipeline.py) on the
                       same raw records: random-access index + protobuf
                       parse, no tf.data runtime.
  augment_jnp / augment_pallas — the augmentation stage alone, jnp
                       composition vs the fused pallas kernel
                       (ops/pallas_augment.py), compiled on this chip.
  device_only_b128   — the same train step at per-chip batch 128. The
                       config of record pins the GLOBAL batch at 32
                       (4/chip on a v3-8), and at 32/chip the step is
                       HBM-bound on stem activations (docs/PERF.md); this
                       number shows the amortized rate the chip reaches
                       when batch is not pinned by the experiment.
  eval_images_per_sec — the jit eval step (forward-only, eval batch) on
                       this chip: the per-model cost of the k-model
                       ensemble evaluation protocol (BASELINE.json:10).
  pipeline_fed_tiered — the tiered loader (data/tiered_pipeline.py) at
                       a pinned 7/8-resident budget: most rows served
                       from the HBM spill cache, the rest decoded by the
                       parallel host stage and staged per shard. The
                       ramp datapoint between pipeline_fed (0% resident)
                       and pipeline_fed_hbm (100%). Companion keys:
                       tiered_load_sec, tiered_resident_fraction, and
                       tiered_zero_budget_fallback_ok (budget-0 batches
                       verified bit-identical to an independent
                       host-decoded reference of the streamed tier).
  ensemble4_member_images_per_sec / ensemble4_parallel_speedup —
                       the member-parallel ensemble step (4 stacked
                       members, train_lib.make_ensemble_train_step) in
                       member-images/sec/chip, and its ratio to the
                       sequential member rate (device_only). Single-chip
                       this sits near 1.0 (weight/optimizer HBM traffic
                       scales with members); the capability's payoff is
                       pod topology — see configs.py ensemble_parallel.
                       A measured ratio < 1.0 is never published as a
                       speedup ON A 1-DEVICE MESH: the key is withheld
                       and the value lands in ensemble4_parallel_gated
                       with a logged reason (trainer.fit_ensemble
                       auto-falls back to the sequential driver there
                       to match). On >= 4-device meshes the real ratio
                       publishes ungated (ISSUE 14: member-sharded
                       stacking is the production path at that width).
  train_mesh_d{N}_images_per_sec / serve_mesh_d{N}_images_per_sec /
  train_mesh_d{N}_vs_d1
                     — mesh-scaling rows (ISSUE 14): the pjit+LAMB
                       train step and the ASSEMBLED serving engine
                       measured across simulated device counts via
                       scripts/dryrun_multichip.py (fresh fake-device
                       subprocess per count, single-threaded per
                       device). --skip_mesh skips.
  time_to_auc_sec_lamb / time_to_auc_lamb_speedup
                     — the LAMB large-batch recipe (2x global batch,
                       linear-scaled LR) vs the adamw reference run,
                       same seed/target (ISSUE 14 acceptance row).
  serve_*            — the serving engine (serve/engine.py):
                       serve_images_per_sec (k=1 saturated engine
                       throughput at the eval batch; self-fencing —
                       every call returns host probs),
                       serve_ensemble4_images_per_sec (images through
                       the k=4 stacked ensemble/sec) vs
                       serve_sequential_members_images_per_sec (the
                       pre-engine predict.py path: k sequential
                       host-fetched member dispatches at batch 8) with
                       their ratio serve_ensemble4_vs_sequential, and
                       offered-load latency serve_p50_ms_cN /
                       serve_p99_ms_cN + serve_offered_images_per_sec_cN
                       at N concurrent closed-loop submitters through
                       the micro-batcher. Every serve_* img/s rate
                       rides the same physics guard (FLOPs from the
                       compiled serving program).
  device_only_telemetry / telemetry_overhead_pct / telemetry_overhead_ok
                     — the device_only window re-run with the trainer's
                       per-step telemetry ops live (obs/ registry +
                       StallClock; ISSUE 3): the hot-path cost of
                       runtime telemetry, PINNED within 2% of the
                       uninstrumented headline (_telemetry_overhead_guard;
                       also bounded per-op in tests/test_bench_guard.py).
  device_only_tracing / tracing_overhead_pct / tracing_overhead_ok
                     — the same window once more with the EVENT TRACER
                       on as well (obs/trace.py; ISSUE 4): the span/
                       StallClock call sites now additionally append
                       ring-buffer trace events. Same ≤2% pin against
                       the uninstrumented headline — the contract that
                       lets obs.trace_enabled default on.
  pipeline_fed_rawshard / host_rawshard — the ahead-of-time transcoded
                       raw-shard loader (data/rawshard.py; ISSUE 7):
                       the JPEG split transcoded once into mmap-able
                       array shards (rawshard_transcode_sec, paid
                       offline), then streamed end-to-end into the
                       train step. host_rawshard is the shard decoder's
                       host-only feed rate (the steady state rides mmap
                       row memcpys instead of JPEG decode; its ratio to
                       host_parse_raw lands in rawshard_vs_raw_parse)
                       and rawshard_bit_identical_ok pins the stream
                       equal, post-decode, to the streamed tier over
                       the source records.
  pipeline_fed_autotuned — the tiered loader at the same pinned 7/8
                       budget, started from PESSIMAL knobs (1 decode
                       worker, depth-1 staging/prefetch) with the
                       closed-loop ingest autotuner live
                       (data/autotune.py; data.autotune): tumbling
                       windows of input-wait attribution drive online
                       knob climbs, the timed window measures the
                       CONVERGED state, and autotune_final_knobs /
                       autotune_adjustments record where the tuner
                       landed and how many moves it took — the
                       trajectory captures WHY feed moved.
  device_only_autotune / autotune_overhead_pct / autotune_overhead_ok
                     — the same window with the ingest autotuner's
                       steady-state costs live (a per-batch knob poll +
                       a converged tuner window observation every 10
                       steps): the ≤2% pin that makes data.autotune
                       safe to leave on (shared _overhead_guard).
  device_only_quality / quality_overhead_pct / quality_overhead_ok
                     — the same window with the model-quality drift
                       monitor (obs/quality.py; ISSUE 5) observing one
                       host batch of images+scores per step (score
                       binning, per-image input statistics, windowed
                       PSI). Same ≤2% pin (_quality_overhead_guard):
                       the contract that makes obs.quality safe to
                       enable on a serving fleet. Disabled is one
                       branch, strictly cheaper.
  device_only_lifecycle / lifecycle_overhead_pct / lifecycle_overhead_ok
                     — the same window with the lifecycle layer's
                       steady-state costs live (ISSUE 8): one unarmed
                       lifecycle fault-site check + the idle-shadow
                       branch per step, plus an AlertManager carrying
                       an on_fire callback evaluated every 10 steps
                       (the flush-cadence wiring, far denser than any
                       real flush). Same ≤2% pin — the contract that
                       lets the self-healing controller attach to a
                       production serving/train process for free while
                       idle.

Workload = the production config of record (BASELINE.json:7): Inception-v3,
binary head, 299x299, global batch 32, aux head on, bf16 compute — the
full train step (on-device augment + fwd/bwd + optax update) as compiled
by train_lib.make_train_step.

``--use_pallas`` routes the train step's color augmentation through the
fused pallas kernel (cfg.data.use_pallas=True) so the compiled-kernel
path is exercised inside the production program.

``vs_baseline``: the reference never published throughput (BASELINE.md),
so the denominator is derived from the driver-set target "train wall-clock
< 1 hour on a v3-8 slice" (BASELINE.json:5): the replication protocol
passes ~15 epochs x ~57k EyePACS images ~= 860k images through the model;
doing that in 3600 s on 8 chips needs ~= 30 images/sec/chip. So
vs_baseline = value / 30, i.e. >1.0 means this chip alone beats the
per-chip rate the 1-hour target requires.

Timing discipline (round 3, VERDICT r2 #1): every timed section ends with
a HOST-FETCHED scalar fence (`_fence`) — a device->host copy of a reduce
of the final output — instead of ``jax.block_until_ready``. The round-2
driver artifact showed block_until_ready-based windows can report
physically impossible rates on the axon tunnel (BENCH_r02's eval/b128/
ensemble rows were 8-25x above what the committed trace and v5e peak
allow); a host fetch of a value data-dependent on every timed step cannot
complete early. Train-style sections chain naturally (state_{i+1} depends
on state_i, so one fence on the final state covers all steps); forward-only
sections chain an on-device scalar accumulator through each iteration.
The fence's own cost is measured on already-complete data and subtracted.

On top of that, a PHYSICS GUARD computes each section's FLOPs/image from
the compiled program's cost analysis and REFUSES to publish any rate that
implies more FLOP/s than the chip's peak (`physics_peak_tflops` in the
output; 197 TFLOP/s bf16 for this v5e-class chip). A refused key is
logged and omitted — the bench can no longer silently emit garbage.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time
from typing import Any

import numpy as np

BASELINE_IMAGES_PER_SEC_PER_CHIP = 30.0  # see module docstring
WARMUP_STEPS = 3
# 50 timed steps ≈ 1.4s on-device: run-to-run variance of the headline
# number was ~±4% at 20 steps (BENCH history 1086..1172 img/s).
TIMED_STEPS = 50
N_DISTINCT_BATCHES = 4
# Synthetic TFRecord fixture for the host/pipeline measurements. Cached
# across runs (rendering 299px fundus images costs ~0.1 s each on this
# host; the bench must not pay that every invocation).
BENCH_DATA_DIR = os.environ.get("BENCH_DATA_DIR", "/tmp/retina_bench_data")
BENCH_N_IMAGES = 256


def _log(msg: str) -> None:
    print(f"bench: {msg}", file=sys.stderr)


def _peak_flops() -> float:
    # Peak-FLOPs table + lookup live in utils/physics.py so the train
    # loops' throughput telemetry is held to the same physics standard
    # as this bench (trainer._ThroughputClock).
    from jama16_retina_tpu.utils import physics

    return physics.peak_flops(log=_log)


def _fence(tree) -> float:
    """Host-visible completion fence: reduce the LARGEST leaf of ``tree``
    to a scalar ON DEVICE and fetch it. The fetch is data-dependent on
    that leaf's producing computation, so unlike block_until_ready it
    cannot return before the work actually ran (BENCH_r02 showed
    block_until_ready-based windows emitting impossible rates on the
    axon tunnel). Largest leaf, not leaves[0]: TrainState's first leaf
    is the step COUNTER, whose value chain (step+1 per iteration) never
    touches the heavy compute — a runtime retiring output buffers
    independently could service that fetch early. The largest leaf is a
    parameter/image tensor, squarely downstream of the matmuls."""
    import jax
    import jax.numpy as jnp

    leaf = max(jax.tree_util.tree_leaves(tree), key=lambda x: x.size)
    return float(jax.device_get(jnp.sum(leaf.astype(jnp.float32))))


def _fence_cost(tree) -> float:
    """Seconds one ``_fence`` costs on already-complete data — the fixed
    dispatch + D2H overhead to subtract from fenced timing windows."""
    t0 = time.time()
    _fence(tree)
    return time.time() - t0


def _flops_of(fn, *args) -> "float | None":
    """Total FLOPs of one call of jitted ``fn`` at these args, from the
    compiled program's cost analysis (AOT lower+compile; the persistent
    compilation cache set up in main() makes this share work with the
    dispatch-path compile instead of doubling it). The cost_analysis
    parsing itself is shared with the train loops' throughput ceiling
    (utils/physics.flops_from_cost_analysis)."""
    from jama16_retina_tpu.utils import physics

    try:
        compiled = fn.lower(*args).compile()
    except Exception as e:  # pragma: no cover - bench must still emit JSON
        _log(f"cost analysis unavailable: {type(e).__name__}: {e}")
        return None
    return physics.flops_from_cost_analysis(compiled)


def build_train_fixture(cfg, mesh, batch_size: int):
    """(step, state, batches, key) for a device-only train measurement —
    THE fixture both this bench's device_only/b128 sections and
    scripts/stem_experiments.py time, so variant rows stay comparable
    to the headline by construction, not by copy-paste."""
    import jax
    import numpy as np

    from jama16_retina_tpu import models, train_lib
    from jama16_retina_tpu.parallel import mesh as mesh_lib

    size = cfg.model.image_size
    model = models.build(cfg.model)
    state, tx = train_lib.create_state(cfg, model, jax.random.key(0))
    state = jax.device_put(state, mesh_lib.replicated(mesh))
    step = train_lib.make_train_step(cfg, model, tx, mesh=mesh)
    rng = np.random.default_rng(0)
    batches = [
        mesh_lib.shard_batch(
            {
                "image": rng.integers(
                    0, 256, (batch_size, size, size, 3), np.uint8),
                "grade": rng.integers(0, 5, (batch_size,), np.int32),
            },
            mesh,
        )
        for _ in range(N_DISTINCT_BATCHES)
    ]
    return step, state, batches, jax.random.key(1)


def _publish(extras: dict, key: str, rate: float,
             flops_per_image: "float | None", peak: float,
             suffix: str = "") -> "float | None":
    """Guard-then-publish, shared by every measured section: refuse
    physically impossible rates, else round into ``extras`` and log.
    Returns the published rate (None when refused)."""
    rate = _physics_guard(key, rate, flops_per_image, peak)
    if rate is None:
        return None
    extras[key] = round(rate, 2)
    _log(f"{key}: {extras[key]} img/s/chip{suffix}")
    return rate


def _physics_guard(name: str, rate: float, flops_per_image: "float | None",
                   peak: float) -> "float | None":
    """``rate`` (img/s/chip) if physically possible, else None (refuse).

    A rate whose implied FLOP/s exceeds the chip's peak is a measurement
    bug by definition — publish nothing rather than garbage (VERDICT r2
    #1: BENCH_r02 emitted eval/b128/ensemble rates 8-25x beyond peak).
    """
    if flops_per_image is None:
        return rate
    implied = rate * flops_per_image
    if implied > peak:
        _log(f"PHYSICS VIOLATION: {name}={rate:.1f} img/s/chip implies "
             f"{implied / 1e12:.0f} TFLOP/s > chip peak {peak / 1e12:.0f} "
             f"TFLOP/s; key refused")
        return None
    return rate


def tiered_resident_bytes(n_images: int, image_size: int) -> int:
    """The pinned partial-residency budget the tiered section measures
    at: 7/8 of the synthetic split resident, 1/8 streamed. Chosen so the
    steady-state H2D shrinks ~8x vs the fully streamed row — enough to
    clear the >= 3x acceptance bar on a tunnel-limited host while still
    exercising a REAL mixed-tier batch every step."""
    from jama16_retina_tpu.data import hbm_pipeline

    return hbm_pipeline.row_bytes(image_size) * (n_images * 7 // 8)


def tiered_residency_plan(n_images: int, image_size: int,
                          batch_size: int = 32) -> float:
    """Fraction of the split the tiered section's budget actually pins
    (plan_residency rounds the per-batch quota down), for the log line
    and the bench-guard test."""
    from jama16_retina_tpu.data import hbm_pipeline, tiered_pipeline

    capacity = hbm_pipeline.resident_row_capacity(
        image_size, budget_bytes=tiered_resident_bytes(n_images, image_size)
    )
    _, _, n_res = tiered_pipeline.plan_residency(
        n_images, batch_size, capacity
    )
    return n_res / n_images


def _gate_ensemble_speedup(extras: dict, rate: float,
                           device_only: float, n_dev: int = 1,
                           member_sharded: bool = False) -> None:
    """Publish ensemble4_parallel_speedup ONLY when the stacked path is
    actually a speedup; a measured slowdown is auto-disabled with a
    logged reason and recorded under ..._gated instead (mirroring
    trainer.fit_ensemble's single-device fallback), so the report can
    never again ship a <1.0 'speedup' as if it were the production
    path. The gating reason ALSO lands in the JSON record
    (``ensemble4_parallel_gated_reason``; ISSUE 7): a trajectory file
    must explain a withheld key by itself, not via a stderr log that
    rotated away.

    UN-GATED on >= 4-device meshes (ISSUE 14) ONLY when the measured
    step was genuinely ``member_sharded``: member-sharded stacking is
    the PRODUCTION path there — the member axis amortizes exactly what
    a single chip cannot — so the real ratio publishes whatever it
    measures (a <1.0 value on a wide mesh would be a genuine
    regression the trajectory must show, not hide) and the 1-device
    gated-reason row never appears. Device count alone is NOT enough
    (ISSUE 17 regression): bench's in-process ensemble step runs
    replicated (``mesh=None``), so on a fake-device CPU host that
    shows 8 "devices" the old ``n_dev >= 4`` rule published a 0.85
    slowdown ungated. The caller must assert the sharding, not the
    width."""
    # Gate on the UNROUNDED ratio: a 0.996 slowdown must not round up
    # to a published "1.0 speedup". Round only for display.
    speedup = rate / device_only
    if member_sharded and n_dev >= 4:
        extras["ensemble4_parallel_speedup"] = round(speedup, 2)
        _log(
            f"ensemble4 stacked step on a {n_dev}-device mesh: "
            f"{speedup:.3f}x the sequential member rate (published "
            "ungated — member-sharded stacking is the production path "
            "at this width)"
        )
        return
    if speedup >= 1.0:
        extras["ensemble4_parallel_speedup"] = round(speedup, 2)
        return
    extras["ensemble4_parallel_gated"] = round(speedup, 2)
    extras["ensemble4_parallel_gated_reason"] = (
        f"stacked k=4 step measured {speedup:.3f}x the sequential member "
        f"rate on this {n_dev}-device mesh: weight/optimizer HBM traffic "
        "scales with members while the batch does not, so single-chip "
        "stacking amortizes nothing; the capability pays off on member-"
        "sharded pod slices (configs.py train.ensemble_parallel). "
        "trainer.fit_ensemble auto-falls back to the sequential driver "
        "on 1-device meshes for the same reason."
    )
    _log(
        f"ensemble4 stacked step is SLOWER than sequential members on "
        f"this chip ({speedup:.3f}x < 1.0: weight/optimizer HBM traffic "
        f"scales with members) — speedup key gated; "
        f"trainer.fit_ensemble auto-falls back to the sequential driver "
        f"on 1-device meshes for the same reason"
    )


def _instrumented_step(step, registry, tracer=None):
    """Wrap a train step with the SAME per-step telemetry ops the
    trainer's hot loop pays (obs/spans.StallClock segment timing into
    registry histograms + a step counter): what the telemetry-overhead
    pin actually measures. ``tracer`` (obs/trace.Tracer) additionally
    routes each StallClock segment into the event timeline — the
    tracing-overhead pin's workload. Returns (wrapped_step,
    wrap_batch_iter)."""
    from jama16_retina_tpu.obs.spans import StallClock

    stalls = StallClock(registry, tracer=tracer)
    c_steps = registry.counter(
        "bench.steps",
        help="train steps executed by bench.py's instrumented "
             "overhead-pin workload",
    )

    def wrapped(state, batch, key):
        with stalls.measure("dispatch"):
            out = step(state, batch, key)
        c_steps.inc()
        return out

    def wrap_batch_iter(batch_iter):
        def get(i):
            with stalls.measure("input"):
                return batch_iter(i)
        return get

    return wrapped, wrap_batch_iter


def _overhead_guard(extras: dict, key: str, rate_on: float,
                    rate_off: float, max_overhead: float = 0.02) -> bool:
    """The obs overhead pin (ISSUE 3 telemetry, ISSUE 4 tracing):
    device_only with the instrumentation enabled must stay within
    ``max_overhead`` (2%) of disabled. Publishes the measured overhead
    either way under ``{key}_overhead_pct``; a violation is flagged
    loudly in ``{key}_overhead_ok`` (and the log) instead of silently
    shipping a slowed hot path. Negative overhead (instrumented run
    timed FASTER — tunnel noise) clamps to 0 for the published
    percentage."""
    overhead = 1.0 - rate_on / rate_off
    extras[f"{key}_overhead_pct"] = round(max(0.0, overhead) * 100, 2)
    ok = overhead <= max_overhead
    extras[f"{key}_overhead_ok"] = ok
    if not ok:
        _log(
            f"{key.upper()} OVERHEAD VIOLATION: instrumented device_only "
            f"{rate_on:.1f} img/s/chip is {overhead * 100:.1f}% below "
            f"uninstrumented {rate_off:.1f} (pin: <= "
            f"{max_overhead * 100:.0f}%) — the obs hot path regressed"
        )
    else:
        _log(
            f"{key} overhead: {extras[f'{key}_overhead_pct']}% "
            f"(pin <= {max_overhead * 100:.0f}%)"
        )
    return ok


def _telemetry_overhead_guard(extras: dict, rate_on: float,
                              rate_off: float,
                              max_overhead: float = 0.02) -> bool:
    return _overhead_guard(extras, "telemetry", rate_on, rate_off,
                           max_overhead)


def _tracing_overhead_guard(extras: dict, rate_on: float,
                            rate_off: float,
                            max_overhead: float = 0.02) -> bool:
    return _overhead_guard(extras, "tracing", rate_on, rate_off,
                           max_overhead)


def _quality_overhead_guard(extras: dict, rate_on: float,
                            rate_off: float,
                            max_overhead: float = 0.02) -> bool:
    """ISSUE 5's pin: the drift monitor's per-batch observe (score
    binning + per-image input statistics + windowed PSI publication)
    enabled must stay within 2% of device_only — the contract that
    makes obs.quality safe to enable on a production serving fleet.
    The disabled path is strictly cheaper (one branch)."""
    return _overhead_guard(extras, "quality", rate_on, rate_off,
                           max_overhead)


def _autotune_overhead_guard(extras: dict, rate_on: float,
                             rate_off: float,
                             max_overhead: float = 0.02) -> bool:
    """ISSUE 7's pin, same shared math: device_only with the ingest
    autotuner's steady-state hot-path costs live — the per-batch knob
    poll the loaders pay plus a converged tuner's window observation
    at the log cadence — must stay within 2% of the uninstrumented
    headline. The contract that makes data.autotune safe to leave on
    for a production run (the tuner's decide() is O(1) math per
    WINDOW, never per step)."""
    return _overhead_guard(extras, "autotune", rate_on, rate_off,
                           max_overhead)


def _devicemon_overhead_guard(extras: dict, rate_on: float,
                              rate_off: float,
                              max_overhead: float = 0.02) -> bool:
    """ISSUE 19's pin, same shared math: device_only with the device-
    utilization plane's steady-state hot-path costs live — one program-
    ledger call count per step (the counted-step closure the trainer
    wraps around the compiled step) plus a full DeviceMonitor.sample()
    every 10 steps (memory_stats walk + gauge publishes, at a far
    denser cadence than any real telemetry flush) — must stay within
    2% of the uninstrumented headline. The contract that lets
    obs.device_enabled default on."""
    return _overhead_guard(extras, "devicemon", rate_on, rate_off,
                           max_overhead)


def _lifecycle_overhead_guard(extras: dict, rate_on: float,
                              rate_off: float,
                              max_overhead: float = 0.02) -> bool:
    """ISSUE 8's pin, same shared math: device_only with the lifecycle
    layer IDLE — an unarmed lifecycle fault site plus the engine's
    idle-shadow branch per step, plus an on_fire-carrying AlertManager
    evaluated at a 10-step cadence — must stay within 2% of the
    uninstrumented headline. The contract that lets the self-healing
    controller ride a production process permanently: a closed loop
    that taxes the hot path while nothing is wrong would never be
    left enabled."""
    return _overhead_guard(extras, "lifecycle", rate_on, rate_off,
                           max_overhead)


def _audit_overhead_guard(extras: dict, rate_on: float,
                          rate_off: float,
                          max_overhead: float = 0.02) -> bool:
    """ISSUE 20's pin, same shared math: device_only with the audit
    ledger LIVE — one record() per step (the sampling decision +
    bounded put_nowait the serve path pays) while the daemon writer
    thread concurrently digests rows and seals real segments every 25
    records — must stay within 2% of the uninstrumented headline. The
    writer's CPU contention is deliberately inside the measurement:
    the contract is that full-rate provenance auditing rides a
    production serving process, not just that the enqueue is cheap."""
    return _overhead_guard(extras, "audit", rate_on, rate_off,
                           max_overhead)


def _robustness_overhead_guard(extras: dict, rate_on: float,
                               rate_off: float,
                               max_overhead: float = 0.02) -> bool:
    """ISSUE 6's pin, same shared math: device_only with the
    reliability seams live but DISABLED — an unarmed fault point
    (obs/faultinject.check: one global read + branch) plus a
    shedding-disabled admission check per step — must stay within 2%
    of the uninstrumented headline. This is the contract that lets the
    fault seams and admission control ship always-compiled-in instead
    of behind an ifdef-style build flag."""
    return _overhead_guard(extras, "robustness", rate_on, rate_off,
                           max_overhead)


def _cheappath_overhead_guard(extras: dict, rate_on: float,
                              rate_off: float,
                              max_overhead: float = 0.02) -> bool:
    """ISSUE 10's pin, same shared math: device_only plus the per-batch
    bookkeeping the cheap-path layer adds OFF-DEVICE — the cascade's
    escalation-band mask + row counters and the compile-cache's
    per-bucket executable-table lookup — must stay within 2% of the
    uninstrumented headline. The contract that lets the cascade/cache
    wrappers sit on every request instead of behind a build flag."""
    return _overhead_guard(extras, "cheappath", rate_on, rate_off,
                           max_overhead)


def _router_overhead_guard(extras: dict, rate_on: float,
                           rate_off: float,
                           max_overhead: float = 0.02) -> bool:
    """ISSUE 12's pin, same shared math: the SAME workload routed
    through a 1-replica Router (submit -> tick re-binning -> replica
    worker -> future reassembly) must stay within 2% of calling the
    replica directly — the front door's bookkeeping must never tax the
    serving hot path it fronts."""
    return _overhead_guard(extras, "router", rate_on, rate_off,
                           max_overhead)


def _interactive_overhead_guard(extras: dict, rate_on: float,
                                rate_off: float,
                                max_overhead: float = 0.02) -> bool:
    """ISSUE 16's pin, same shared math: the batch path with the
    interactive machinery compiled in but DISABLED — the cascade's
    speculative branch off, the router's fusion-aware tick bookkeeping
    and submit wake-up scan running over single-tenant queues — must
    stay within 2% of dispatching the same serial cascade directly.
    The contract that lets speculation/fusion ship always-present
    behind config knobs (policy v2 opts deployments in) instead of a
    build flag."""
    return _overhead_guard(extras, "interactive", rate_on, rate_off,
                           max_overhead)


def _integrity_overhead_guard(extras: dict, rate_on: float,
                              rate_off: float,
                              max_overhead: float = 0.02) -> bool:
    """ISSUE 13's pin, same shared math: device_only with the sealed-
    artifact layer's hot-path residue — the unarmed ``integrity.write``
    seam branch charged per step (conservative: real steps only pay it
    when a durable write happens) plus a FULL sealed-JSON publish
    (serialize + sha256 + tmp + fsync + rename) every 25 steps, a far
    denser durable-write cadence than any real checkpoint/telemetry
    interval. The contract the tentpole claims: checksum cost rides
    writes, never the train/serve hot loop."""
    return _overhead_guard(extras, "integrity", rate_on, rate_off,
                           max_overhead)


def _fleet_overhead_guard(extras: dict, rate_on: float,
                          rate_off: float,
                          max_overhead: float = 0.02) -> bool:
    """ISSUE 15's pin, same shared math: device_only with the fleet
    plane's hot-path residue — the DISABLED segment bus is one
    ``is not None`` branch per flush check (the production default:
    obs.fleet_dir empty), plus a real sealed segment publish every 25
    steps (serialize + sha256 + atomic rename + prune), a far denser
    publish cadence than any real obs.flush_every_s. The contract that
    lets every process of a deployment join the fleet dir without
    taxing its own hot loop."""
    return _overhead_guard(extras, "fleet", rate_on, rate_off,
                           max_overhead)


def _diagnosis_overhead_guard(extras: dict, rate_on: float,
                              rate_off: float,
                              max_overhead: float = 0.02) -> bool:
    """ISSUE 18's pin, same shared math: device_only with the causal-
    diagnosis plane's hot-path residue — per-step provenance stamping
    (build the compact record + one small memcpy into a mapped
    provenance region, exactly what the ingest server pays per served
    batch) plus the DISABLED analyzer branch (the critical-path
    analyzer is pure and runs only inside FlightRecorder dumps; steady
    state pays one ``if``) — must stay within 2% of the uninstrumented
    headline. The contract that lets ingest.provenance default on for
    production deployments."""
    return _overhead_guard(extras, "diagnosis", rate_on, rate_off,
                           max_overhead)


def _router_bench(extras: dict) -> None:
    """Router scaling rows (ISSUE 12): the dispatch pipeline measured
    OFF-DEVICE over stub replicas with a fixed simulated per-row
    service time (time.sleep releases the GIL, so replica overlap is
    real concurrency — the same role the fake infer plays in the chaos
    smoke). Published as ``router_k{1,2,4}_images_per_sec`` plus the
    ``router_k4_vs_k1`` scaling ratio (acceptance: >= 2.5x on 4
    replicas), ``router_vs_single_engine`` (routed k=1 vs calling the
    same replica directly), and the shared <=2% ``_overhead_guard``
    pin. These are router-dispatch rates, not model rates — no model
    FLOPs run, so the physics guard deliberately does not apply (its
    FLOPs numerator does not exist for a sleep)."""
    import dataclasses as _dc
    import threading

    from jama16_retina_tpu.configs import get_config
    from jama16_retina_tpu.obs.registry import Registry
    from jama16_retina_tpu.serve.router import Router

    ROWS = 64           # rows per request == the bin/bucket size
    PER_ROW_S = 50e-6   # simulated device time per row
    FIXED_S = 1e-3      # simulated per-dispatch fixed cost
    WORKERS = 8         # closed-loop submitters
    PER_WORKER = 25     # requests each

    class _StubReplica:
        def __init__(self, rid):
            self.generation = rid

        def probs(self, rows):
            time.sleep(FIXED_S + PER_ROW_S * rows.shape[0])
            return rows.reshape(rows.shape[0], -1).sum(axis=1)

    rows = np.zeros((ROWS, 2, 2, 3), np.uint8)
    total_rows = WORKERS * PER_WORKER * ROWS

    # The direct baseline: the same total rows through ONE replica,
    # dispatch after dispatch — exactly what the router's single
    # replica worker does, minus the router.
    stub = _StubReplica(0)
    t0 = time.perf_counter()
    for _ in range(WORKERS * PER_WORKER):
        stub.probs(rows)
    rate_direct = total_rows / (time.perf_counter() - t0)

    cfg = get_config("smoke")
    cfg = cfg.replace(serve=_dc.replace(
        cfg.serve, max_batch=ROWS, bucket_sizes=(ROWS,),
        max_wait_ms=1.0, router_tick_ms=1.0,
    ))

    def routed_rate(k: int) -> float:
        router = Router(
            cfg, engines=[_StubReplica(r) for r in range(k)],
            registry=Registry(),
        )
        errs: list = []

        def run(w):
            try:
                for _ in range(PER_WORKER):
                    router.submit(rows).result()
            except Exception as e:  # noqa: BLE001 - re-raised below
                errs.append(e)

        threads = [
            threading.Thread(target=run, args=(w,))
            for w in range(WORKERS)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        router.close()
        if errs:
            raise errs[0]
        return total_rows / dt

    rates = {}
    for k in (1, 2, 4):
        rates[k] = routed_rate(k)
        extras[f"router_k{k}_images_per_sec"] = round(rates[k], 1)
        _log(f"router k={k}: {rates[k]:.0f} img/s (stub replicas, "
             f"{WORKERS} submitters)")
    extras["router_k4_vs_k1"] = round(rates[4] / rates[1], 2)
    extras["router_vs_single_engine"] = round(rates[1] / rate_direct, 3)
    _router_overhead_guard(extras, rates[1], rate_direct)
    _log(f"router scaling: k4/k1 = {extras['router_k4_vs_k1']}x, "
         f"routed/direct = {extras['router_vs_single_engine']}")


def _interactive_bench(extras: dict) -> None:
    """Interactive latency rows (ISSUE 16): single-row closed-loop
    requests (c=1 — one outstanding request, the fixed offered load an
    interactive client presents) through Router + CascadeEngine over
    stub engines with FIXED simulated service times — off-device like
    ``_router_bench``, so the rows measure the dispatch machinery, not
    the model. Every row escalates (the worst case for the cascade).

      serve_interactive_p99_ms         — p99 with the interactive path
                                         on: speculative escalation
                                         (student and ensemble dispatch
                                         concurrently; the escalated
                                         row pays max, not sum) plus
                                         the submit wake-up;
      serve_interactive_serial_p99_ms  — the SAME workload with
                                         serve.cascade_speculative off
                                         (student-then-ensemble);
      serve_interactive_speedup        — serial p99 / speculative p99;
                                         acceptance >= 1.5x, flagged in
                                         interactive_latency_ok.

    The router runs a deliberately COARSE 50 ms tick: the p99 landing
    at service-time scale (not tick scale) is the submit wake-up
    working — the old tick/4 poll floored a lone request's queue wait
    at ~12.5 ms regardless of its deadline.

    The shared <=2% ``_interactive_overhead_guard`` pin rides along:
    64-row batch requests through Router + serial cascade with every
    ISSUE 16 knob at its default vs the same serial cascade dispatched
    directly."""
    import dataclasses as _dc

    from jama16_retina_tpu.configs import get_config
    from jama16_retina_tpu.obs.registry import Registry
    from jama16_retina_tpu.serve.cascade import CascadeEngine
    from jama16_retina_tpu.serve.router import Router

    T_STUDENT = 8e-3   # simulated per-dispatch student service time
    T_ENSEMBLE = 8e-3  # simulated per-dispatch ensemble service time
    N_REQ = 60

    class _Stub:
        """kind='student' pins every score inside the escalation band
        (all rows escalate); kind='ensemble' returns the row sums."""

        def __init__(self, kind, fixed_s, per_row_s=0.0):
            self.kind = kind
            self.fixed_s = fixed_s
            self.per_row_s = per_row_s
            self.generation = 0

        def probs(self, rows):
            time.sleep(self.fixed_s + self.per_row_s * rows.shape[0])
            if self.kind == "student":
                return np.full(rows.shape[0], 0.5)
            return rows.reshape(rows.shape[0], -1).astype(
                np.float64).sum(axis=1)

    base = get_config("smoke")
    one = np.zeros((1, 2, 2, 3), np.uint8)

    def run(speculative: bool):
        reg = Registry()
        ccfg = base.replace(serve=_dc.replace(
            base.serve, max_batch=4, bucket_sizes=(1, 4),
            max_wait_ms=2.0, router_tick_ms=50.0,
            cascade_thresholds=(0.5,), cascade_band=0.6,
            cascade_speculative=speculative,
        ))
        casc = CascadeEngine(
            ccfg, _Stub("student", T_STUDENT),
            _Stub("ensemble", T_ENSEMBLE), registry=reg,
        )
        router = Router(ccfg, engines=[casc], registry=reg)
        try:
            lats, _ = _offered_load(
                lambda r: router.submit(r, priority="interactive"),
                1, N_REQ, lambda w, i: one,
            )
        finally:
            router.close()
            casc.close()
        return _latency_summary(lats), reg

    spec, reg_spec = run(True)
    serial, _ = run(False)
    extras["serve_interactive_p99_ms"] = spec["p99_ms"]
    extras["serve_interactive_serial_p99_ms"] = serial["p99_ms"]
    speedup = serial["p99_ms"] / spec["p99_ms"]
    extras["serve_interactive_speedup"] = round(speedup, 2)
    extras["interactive_latency_ok"] = speedup >= 1.5
    counts = reg_spec.snapshot()["counters"]
    extras["serve_interactive_speculated_rows"] = int(
        counts.get("serve.cascade.speculated", 0)
    )
    if not extras["interactive_latency_ok"]:
        _log(
            f"INTERACTIVE LATENCY VIOLATION: speculative p99 "
            f"{spec['p99_ms']} ms is only "
            f"{speedup:.2f}x better than serial "
            f"{serial['p99_ms']} ms (acceptance >= 1.5x)"
        )
    else:
        _log(
            f"interactive c=1 p99: speculative {spec['p99_ms']} ms vs "
            f"serial {serial['p99_ms']} ms ({speedup:.2f}x, 50 ms tick "
            "— submit wake-up bounds queue wait)"
        )

    # Disabled-machinery overhead pin: 64-row batch requests, serial
    # cascade, every ISSUE 16 knob at its default — routed vs direct.
    ROWS, FIXED_S, PER_ROW_S = 64, 1e-3, 50e-6
    WORKERS, PER_WORKER = 8, 12
    rows = np.zeros((ROWS, 2, 2, 3), np.uint8)
    ocfg = base.replace(serve=_dc.replace(
        base.serve, max_batch=ROWS, bucket_sizes=(ROWS,),
        max_wait_ms=1.0, router_tick_ms=1.0,
        cascade_thresholds=(0.5,), cascade_band=0.6,
    ))
    total_rows = WORKERS * PER_WORKER * ROWS
    casc_direct = CascadeEngine(
        ocfg, _Stub("student", FIXED_S, PER_ROW_S),
        _Stub("ensemble", FIXED_S, PER_ROW_S), registry=Registry(),
    )
    t0 = time.perf_counter()
    for _ in range(WORKERS * PER_WORKER):
        casc_direct.probs(rows)
    rate_direct = total_rows / (time.perf_counter() - t0)
    casc_direct.close()

    reg_r = Registry()
    casc_routed = CascadeEngine(
        ocfg, _Stub("student", FIXED_S, PER_ROW_S),
        _Stub("ensemble", FIXED_S, PER_ROW_S), registry=reg_r,
    )
    router = Router(ocfg, engines=[casc_routed], registry=reg_r)
    try:
        _, window = _offered_load(
            router.submit, WORKERS, PER_WORKER, lambda w, i: rows
        )
    finally:
        router.close()
        casc_routed.close()
    rate_routed = total_rows / window
    _interactive_overhead_guard(extras, rate_routed, rate_direct)


def _chaos_smoke(extras: dict) -> None:
    """``--chaos``: deterministically drive every recovery path the
    reliability layer claims, off-device (tiny batcher + fake infer +
    poison-record fixture), and publish the counters — a bench-level
    proof that an ARMED FaultPlan injects and each layer recovers,
    without waiting for production to break. Publishes chaos_ok plus
    the per-site injection ledger; any recovery failing publishes
    chaos_ok=false loudly (the bench still emits JSON)."""
    import tempfile

    from jama16_retina_tpu.data import tfrecord as tfrecord_lib
    from jama16_retina_tpu.data.grain_pipeline import (
        ParallelDecoder,
        TFRecordIndex,
    )
    from jama16_retina_tpu.obs import faultinject
    from jama16_retina_tpu.obs.registry import Registry
    from jama16_retina_tpu.serve.batcher import (
        DeadlineExceeded,
        MicroBatcher,
        Overloaded,
    )

    ok = True
    reg = Registry()
    spec_counts: dict = {}
    plan = faultinject.plan_from_spec({
        # Poison record: corrupt the 3rd TFRecord payload read.
        "tfrecord.read": {"kind": "corrupt", "on_calls": [3]},
        # One failed engine dispatch: the batcher's window-error drill.
        "engine.dispatch": {"kind": "error", "on_calls": [2],
                           "error": "RuntimeError",
                           "message": "chaos dispatch"},
        # Lifecycle sites (ISSUE 8): one transient RETRAIN failure (the
        # journal must hold position and the re-drive must resume), a
        # GATE failure (must fail CLOSED -> terminal ROLLBACK with the
        # journal intact), and one transient swap failure in the
        # second, healthy cycle.
        "lifecycle.retrain": {"kind": "error", "on_calls": [1],
                              "error": "RuntimeError",
                              "message": "chaos retrain"},
        "lifecycle.gate": {"kind": "error", "on_calls": [1],
                           "error": "RuntimeError",
                           "message": "chaos gate"},
        "lifecycle.swap": {"kind": "error", "on_calls": [1],
                           "error": "RuntimeError",
                           "message": "chaos swap"},
        # Compile cache (ISSUE 10): the first entry load fails — must
        # degrade to a counted recompile, never surface to a request.
        "serve.compile_cache.load": {"kind": "error", "on_calls": [1],
                                     "error": "OSError",
                                     "message": "chaos cache load"},
        # Front-door router (ISSUE 12): the 4th bin dispatch kills its
        # replica mid-storm — bins retry on siblings, zero drops.
        "serve.router.dispatch": {"kind": "error", "on_calls": [4],
                                  "error": "RuntimeError",
                                  "message": "chaos replica death"},
    })
    prev = faultinject.arm(plan)
    try:
        # 1) Data plane: a corrupt payload is quarantined + substituted,
        #    the decode epoch survives.
        with tempfile.TemporaryDirectory() as d:
            tfrecord_lib.write_synthetic_split(
                d, "train", 8, image_size=32, num_shards=1, seed=0
            )
            index = TFRecordIndex(tfrecord_lib.list_split(d, "train"))
            dec = ParallelDecoder(index, 32, workers=1, registry=reg)
            batch = dec.decode_batch(range(8))
            ok &= batch["image"].shape == (8, 32, 32, 3)
            ok &= reg.counter("data.quarantined").value >= 1
            dec.close()

        # 2) Serve plane: an injected dispatch-style failure fails only
        #    its window; the worker survives; deadline + shed reject
        #    typed. (A fake infer stands in for the engine — the seam
        #    fires via check() exactly as the engine calls it.)
        def infer(rows):
            faultinject.check("engine.dispatch")
            return rows.reshape(rows.shape[0], -1).sum(axis=1)

        b = MicroBatcher(infer, max_batch=4, max_wait_ms=1.0,
                         registry=reg, shed_queue_depth=1000)
        f1 = b.submit(np.ones((1, 4)))
        f1.result(timeout=30)
        f2 = b.submit(np.ones((1, 4)))  # 2nd dispatch: injected error
        try:
            f2.result(timeout=30)
            ok = False
        except RuntimeError:
            pass
        f3 = b.submit(np.ones((1, 4)))  # worker survived
        f3.result(timeout=30)
        f4 = b.submit(np.ones((1, 4)), deadline_ms=1e-6)
        try:
            f4.result(timeout=30)
            deadline_ok = False
        except DeadlineExceeded:
            deadline_ok = True
        except Exception:
            deadline_ok = False
        ok &= deadline_ok
        b.close()
        shed = MicroBatcher(infer, max_batch=4, autostart=False,
                            registry=reg, shed_queue_depth=1)
        shed.submit(np.ones((1, 4)))
        try:
            shed.submit(np.ones((1, 4)))
            ok = False
        except Overloaded:
            pass
        shed.close()
        ok &= reg.counter("serve.batcher.window_errors").value >= 1
        ok &= reg.counter("serve.shed.deadline").value >= 1
        ok &= reg.counter("serve.shed.queue_depth").value >= 1

        # 2b) Compile cache (ISSUE 10): the injected first load fails
        #     and must degrade to a counted miss (the recompile path),
        #     the second load hits, and a directory built for another
        #     fingerprint is refused loudly, never served.
        import jax
        import jax.numpy as jnp

        from jama16_retina_tpu.serve.compilecache import (
            CompileCache,
            CompileCacheStale,
        )

        with tempfile.TemporaryDirectory() as cd:
            cache = CompileCache(cd, {"probe": 1}, registry=reg)
            probe = jax.jit(lambda x: x + 1).lower(
                jnp.zeros((2,), jnp.float32)
            ).compile()
            saved = cache.save("probe", probe)
            ok &= cache.load("probe") is None  # injected: degrade
            ok &= reg.counter("serve.compile_cache.misses").value >= 1
            if saved:  # backends without executable serialization skip
                ok &= cache.load("probe") is not None  # real deserialize
                ok &= reg.counter("serve.compile_cache.hits").value >= 1
            try:
                CompileCache(cd, {"probe": 2}, registry=reg)
                ok = False  # stale fingerprint must refuse
            except CompileCacheStale:
                pass

        # 2c) Front-door router (ISSUE 12): a replica dies mid-storm
        #     (injected at serve.router.dispatch) — its bins retry on
        #     siblings with typed accounting; ZERO dropped requests,
        #     and every response stays attributable to the
        #     (replica, generation) that served it.
        import dataclasses as _dc
        import threading as _threading

        from jama16_retina_tpu.configs import get_config as _gc
        from jama16_retina_tpu.serve.router import Router

        class _ChaosReplica:
            def __init__(self, rid):
                self.generation = rid

            def probs(self, rows):
                time.sleep(5e-4)
                return rows.reshape(rows.shape[0], -1).astype(
                    np.float64).sum(axis=1)

        rcfg = _gc("smoke")
        rcfg = rcfg.replace(serve=_dc.replace(
            rcfg.serve, max_batch=8, bucket_sizes=(8,), max_wait_ms=1.0,
        ))
        router = Router(
            rcfg, engines=[_ChaosReplica(r) for r in range(4)],
            registry=reg,
        )
        futs: list = []
        futs_lock = _threading.Lock()

        def _storm(w):
            rng = np.random.default_rng(w)
            for i in range(10):
                r_rows = rng.integers(0, 256, (8, 2, 2, 3), np.uint8)
                f = router.submit(
                    r_rows,
                    priority="interactive" if i % 2 else "batch",
                )
                with futs_lock:
                    futs.append((r_rows, f))

        storm_threads = [
            _threading.Thread(target=_storm, args=(w,)) for w in range(4)
        ]
        for t in storm_threads:
            t.start()
        for t in storm_threads:
            t.join()
        drops = 0
        for r_rows, f in futs:
            try:
                out = f.result(timeout=60)
            except Exception:  # noqa: BLE001 - counted as a drop
                drops += 1
                continue
            ref = r_rows.reshape(8, -1).astype(np.float64).sum(axis=1)
            ok &= bool(np.array_equal(out, ref))
            segs = getattr(f, "segments", None)
            ok &= bool(segs) and all(
                "replica" in s and "generation" in s for s in segs
            )
        router.close()
        ok &= drops == 0
        ok &= reg.counter("serve.router.retried_bins").value >= 1
        ok &= reg.counter("serve.router.replica_failures").value >= 1
        extras["chaos_router_zero_drops"] = drops == 0

        # 2d) Speculative cascade (ISSUE 16): a replica dies while
        #     speculation is in flight. Two speculative-cascade
        #     replicas (stub student pinned inside the band, so every
        #     row speculates AND escalates); a dedicated one-shot plan
        #     kills the 3rd dispatch of THIS storm — the bin retries on
        #     the sibling, zero drops, and every answer is still the
        #     ensemble's (the speculated work of the dead dispatch is
        #     discarded, never half-applied).
        from jama16_retina_tpu.serve.cascade import CascadeEngine

        class _SpecStub:
            def __init__(self, kind):
                self.kind = kind
                self.generation = 3

            def probs(self, rows):
                time.sleep(3e-4)
                if self.kind == "student":
                    return np.full(rows.shape[0], 0.5)
                return rows.reshape(rows.shape[0], -1).astype(
                    np.float64).sum(axis=1)

        scfg = _gc("smoke")
        scfg = scfg.replace(serve=_dc.replace(
            scfg.serve, max_batch=4, bucket_sizes=(4,), max_wait_ms=1.0,
            cascade_thresholds=(0.5,), cascade_band=0.6,
            cascade_speculative=True,
        ))
        cascs = [
            CascadeEngine(scfg, _SpecStub("student"), _SpecStub("ens"),
                          registry=reg)
            for _ in range(2)
        ]
        plan_spec = faultinject.plan_from_spec({
            "serve.router.dispatch": {
                "kind": "error", "on_calls": [3],
                "error": "RuntimeError",
                "message": "chaos replica death mid-speculation",
            },
        })
        faultinject.arm(plan_spec)
        try:
            router2 = Router(scfg, engines=list(cascs), registry=reg)
            futs2: list = []
            rng2 = np.random.default_rng(16)
            for _ in range(12):
                s_rows = rng2.integers(0, 256, (4, 2, 2, 3), np.uint8)
                futs2.append((s_rows, router2.submit(
                    s_rows, priority="interactive")))
            drops2 = 0
            for s_rows, f in futs2:
                try:
                    out = f.result(timeout=60)
                except Exception:  # noqa: BLE001 - counted as a drop
                    drops2 += 1
                    continue
                ref = s_rows.reshape(4, -1).astype(np.float64).sum(axis=1)
                ok &= bool(np.array_equal(out, ref))
            router2.close()
            for c in cascs:
                c.close()
            spec_counts = plan_spec.counts()
        finally:
            faultinject.arm(plan)  # restore the main plan for 3)
        ok &= drops2 == 0
        ok &= spec_counts["serve.router.dispatch"]["fires"] >= 1
        ok &= reg.counter("serve.cascade.speculated").value >= 1
        extras["chaos_speculation_zero_drops"] = drops2 == 0

        # 3) Lifecycle plane (ISSUE 8): the journaled state machine
        #    driven through all three injected fault sites, off-device
        #    (seam-injected retrain/gates, a duck-typed engine for the
        #    swap/rollback steps).
        from jama16_retina_tpu.configs import get_config, override
        from jama16_retina_tpu.lifecycle import (
            Journal,
            LifecycleController,
        )

        lcfg = override(get_config("smoke"), [
            "lifecycle.enabled=true", "lifecycle.watch_probes=1",
            "lifecycle.watch_interval_s=0", "lifecycle.shadow_wait_s=0",
            "lifecycle.shadow_requests=1",
        ])

        class _FakeEngine:
            """Duck-typed swap surface: the drill proves the
            CONTROLLER's crash/fault discipline; the real engine's
            swap/rollback is pinned on-model in tests/test_faults.py
            and tests/test_lifecycle.py."""

            def __init__(self, registry):
                self.registry = registry
                self.quality = None
                self._gen = type("G", (), {"member_dirs": ["live"]})()
                self._report = {"requests": 1, "rows": 1, "errors": 0,
                                "max_abs_dev": 0.0, "mean_abs_dev": 0.0}

            def prepare_candidate(self, member_dirs=None, state=None,
                                  warm=False):
                return object()

            def begin_shadow(self, candidate=None, fraction=0.25,
                             **kw):
                return {"fraction": fraction, "every": 1}

            def shadow_report(self):
                return dict(self._report)

            def end_shadow(self, promote=False):
                out = dict(self._report)
                if promote:
                    out["reload"] = {"generation": 1, "n_members": 1}
                return out

            def reload(self, member_dirs=None, state=None):
                return {"generation": 1, "n_members": 1}

            def rollback(self):
                return {"generation": 2, "restored_from": 0,
                        "n_members": 1}

        with tempfile.TemporaryDirectory() as wd:
            # Cycle 1: retrain fault (transient, resumed) then gate
            # fault -> fail closed -> terminal ROLLBACK, journal whole.
            ctl = LifecycleController(
                lcfg, wd, registry=reg,
                retrain_fn=lambda c, root: ["cand"],
                live_member_dirs=["live"], sleep=lambda s: None,
            )
            ctl.trigger(reason="chaos_drift")
            try:
                ctl.run()
                ok = False  # the injected retrain fault must surface
            except RuntimeError:
                pass
            ok &= ctl.state == "DRIFT_DETECTED"  # journal unadvanced
            ok &= ctl.run() == "ROLLBACK"        # resume -> gate fails closed
            j = Journal(os.path.join(wd, "lifecycle"))
            ok &= j.state == "ROLLBACK" and not j.cycle_open()
            gate = j.find("GATE")
            ok &= gate is not None and gate["passed"] is False
            # Cycle 2: healthy candidate through the fake swap surface;
            # the injected swap fault is transient — resume promotes,
            # watch stays healthy, terminal COMMIT + live pointer.
            from jama16_retina_tpu.lifecycle.controller import (
                GateVerdict,
            )

            ctl2 = LifecycleController(
                lcfg, wd, engine=_FakeEngine(reg), registry=reg,
                retrain_fn=lambda c, root: ["cand2"],
                gate_fns=[lambda c, g: GateVerdict("fake", True)],
                live_member_dirs=["live"], sleep=lambda s: None,
            )
            ctl2.trigger(reason="chaos_drift_2")
            try:
                ctl2.run()
                ok = False  # the injected swap fault must surface
            except RuntimeError:
                pass
            ok &= ctl2.state == "GATE"  # journal held at the gate pass
            ok &= ctl2.run() == "COMMIT"
            j2 = Journal(os.path.join(wd, "lifecycle"))
            ok &= j2.read_live() == ["cand2"]
    except Exception as e:  # pragma: no cover - bench must emit JSON
        _log(f"chaos smoke failed: {type(e).__name__}: {e}")
        ok = False
    finally:
        faultinject.arm(prev)
    extras["chaos_ok"] = bool(ok)
    extras["chaos_injections"] = {
        site: c["fires"] for site, c in plan.counts().items()
    }
    for site, c in spec_counts.items():
        extras["chaos_injections"][site] = (
            extras["chaos_injections"].get(site, 0) + c["fires"]
        )
    _log(f"chaos smoke: ok={ok}, injections={extras['chaos_injections']}")


def _chaos_integrity(extras: dict) -> None:
    """``--chaos`` disaster drill, durable-state half (ISSUE 13):
    seed a REAL serving-ready workdir (checkpoint + live.json + closed
    journal cycle + policy + profile + sealed canary + transcoded
    rawshard split), corrupt every sealed artifact class with a
    mid-file bit flip, and prove the whole chain: each loader refuses
    typed (ArtifactCorrupt) or degrades counted, ``graftfsck`` detects
    every corpse (exit 1, naming the files), ``--repair`` + the named
    rebuild commands return the workdir to serving-ready (fsck exit 0,
    a real ServingEngine restores off live.json, live.json intact) —
    and kill -9 INSIDE the sealed writer (held open at the
    integrity.write.commit seam) leaves no readable torn artifact.
    Publishes ``chaos_integrity_ok`` + per-phase booleans."""
    import signal
    import subprocess
    import sys as _sys
    import tempfile

    import jax

    from jama16_retina_tpu import models, train_lib
    from jama16_retina_tpu.configs import get_config, override
    from jama16_retina_tpu.data import rawshard as rawshard_lib
    from jama16_retina_tpu.data import tfrecord as tfrecord_lib
    from jama16_retina_tpu.integrity import artifact as artifact_lib
    from jama16_retina_tpu.integrity import fsck as fsck_lib
    from jama16_retina_tpu.lifecycle.journal import Journal
    from jama16_retina_tpu.obs import quality as quality_lib
    from jama16_retina_tpu.obs.registry import default_registry
    from jama16_retina_tpu.serve import policy as policy_lib
    from jama16_retina_tpu.serve.engine import ServingEngine
    from jama16_retina_tpu.utils import checkpoint as ckpt_lib

    ok = True
    size = 32
    # The loaders count corruption on the process default registry
    # (that is the alert rule's input) — the drill must read the SAME
    # counter, strictly increased, or a counting regression would pass.
    reg = default_registry()

    def bitflip(path: str, marker: "bytes | None" = None) -> None:
        """Flip one bit. For JSON artifacts a ``marker`` inside a
        string VALUE is targeted, so the file stays parseable and the
        drill deterministically exercises the checksum (not the
        parser); binaries flip mid-file."""
        with open(path, "rb") as f:
            blob = bytearray(f.read())
        i = blob.find(marker) if marker else len(blob) // 2
        assert i >= 0, f"marker {marker!r} not in {path}"
        blob[i] ^= 0x01
        with open(path, "wb") as f:
            f.write(bytes(blob))

    def expect_corrupt(fn) -> bool:
        try:
            fn()
        except artifact_lib.ArtifactCorrupt:
            return True
        except ValueError:
            # A flipped byte can break JSON syntax instead of content;
            # the loud unparseable refusal is equally typed.
            return True
        return False

    with tempfile.TemporaryDirectory() as wd:
        # --- seed the serving-ready workdir --------------------------
        cfg = override(get_config("smoke"), [
            f"model.image_size={size}", "serve.max_batch=4",
            "serve.bucket_sizes=4",
        ])
        model = models.build(cfg.model)
        member = os.path.join(wd, "member_00")
        m_state, _ = train_lib.create_state(cfg, model, jax.random.key(0))
        ck = ckpt_lib.Checkpointer(member)
        ck.save(1, jax.device_get(m_state), {"val_auc": 0.5})
        ck.wait()
        ck.close()
        j = Journal(os.path.join(wd, "lifecycle"))
        j.write_live([member])
        j.append("DRIFT_DETECTED", cycle=0, reason="drill")
        j.append("ROLLBACK", cycle=0, cause="drill")  # closed cycle
        pol = policy_lib.derive_policy(
            [{"bucket": 4, "concurrency": 1, "images_per_sec": 100.0,
              "p50_ms": 2.0, "p99_ms": 5.0}],
            {"arch": "drill"},
        )
        ppath = os.path.join(wd, "serve_policy.json")
        policy_lib.save_policy(ppath, pol)
        rng = np.random.default_rng(7)
        prpath = os.path.join(wd, "profile.json")
        quality_lib.save_profile(prpath, quality_lib.build_profile(
            rng.random(256), thresholds=[{"threshold": 0.5}],
        ))
        cimgs = rng.integers(0, 256, (2, size, size, 3), np.uint8)
        cpath = quality_lib.save_canary(
            os.path.join(wd, "canary.npz"), cimgs, scores=rng.random(2)
        )
        src = os.path.join(wd, "data")
        tfrecord_lib.write_synthetic_split(
            src, "train", 8, image_size=size, num_shards=1, seed=0
        )
        rawshard_lib.transcode_split(src, "train", image_size=size,
                                     shard_records=4, workers=1)
        shard_dir = rawshard_lib.default_shard_dir(src, size)
        baseline = fsck_lib.fsck_workdir(wd)
        extras["chaos_integrity_baseline_clean"] = baseline.clean
        ok &= baseline.clean

        # --- corrupt every class; typed refusal / counted degrade ----
        # Baseline BEFORE the refusal section: every in-process typed
        # refusal below must strictly grow the default registry's
        # integrity.corrupt (the alert rule's input).
        corrupt_before = reg.counter("integrity.corrupt").value
        bitflip(ppath, marker=b"drill")
        try:
            policy_lib.load_policy(ppath)
            policy_refused = False
        except (artifact_lib.ArtifactCorrupt, policy_lib.PolicyStale):
            policy_refused = True
        ok &= policy_refused
        bitflip(prpath, marker=b"threshold")
        ok &= expect_corrupt(lambda: quality_lib.load_profile(prpath))
        bitflip(cpath)
        ok &= expect_corrupt(lambda: quality_lib.load_canary_file(cpath))
        jpath = os.path.join(wd, "lifecycle", "journal.json")
        bitflip(jpath, marker=b"drill")
        ok &= expect_corrupt(
            lambda: Journal(os.path.join(wd, "lifecycle"))
        )
        shard = sorted(
            p for p in os.listdir(shard_dir)
            if p.endswith(".images.npy")
        )[0]
        bitflip(os.path.join(shard_dir, shard))

        # --- graftfsck detects every corpse (exit 1, names files) ----
        script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "scripts", "graftfsck.py")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r1 = subprocess.run(
            [_sys.executable, script, wd, "--json"],
            capture_output=True, text=True, env=env, timeout=300,
        )
        ok &= r1.returncode == 1
        try:
            rep1 = json.loads(r1.stdout)
            named = {f["path"] for f in rep1["findings"]}
        except Exception:  # noqa: BLE001
            named = set()
            ok = False
        for must in (ppath, prpath, cpath, jpath,
                     os.path.join(shard_dir, shard)):
            ok &= any(must in p for p in named)
        extras["chaos_integrity_detected"] = len(named)

        # --- repair + named rebuilds -> serving-ready ----------------
        r2 = subprocess.run(
            [_sys.executable, script, wd, "--repair"],
            capture_output=True, text=True, env=env, timeout=300,
        )
        # Rebuild the derivable pieces exactly as the findings direct:
        # resume the transcode (trimmed shards), re-derive the policy,
        # re-emit the profile, re-pin the canary.
        rawshard_lib.transcode_split(src, "train", image_size=size,
                                     shard_records=4, workers=1)
        policy_lib.save_policy(ppath, pol)
        quality_lib.save_profile(prpath, quality_lib.build_profile(
            rng.random(256), thresholds=[{"threshold": 0.5}],
        ))
        quality_lib.save_canary(cpath, cimgs, scores=rng.random(2))
        r3 = subprocess.run(
            [_sys.executable, script, wd],
            capture_output=True, text=True, env=env, timeout=300,
        )
        ok &= r3.returncode == 0
        extras["chaos_integrity_repaired_clean"] = r3.returncode == 0
        live = Journal(os.path.join(wd, "lifecycle")).read_live()
        ok &= live == [member]  # live.json intact through it all
        try:
            engine = ServingEngine(cfg, live, model=model)
            probe = rng.integers(0, 256, (2, size, size, 3), np.uint8)
            ok &= engine.probs(probe).shape[0] == 2
        except Exception as e:  # noqa: BLE001
            _log(f"chaos integrity: engine restore failed: {e}")
            ok = False
        counted = reg.counter("integrity.corrupt").value > corrupt_before
        extras["chaos_integrity_corrupt_counted"] = counted
        ok &= counted

        # --- kill -9 inside the sealed writer ------------------------
        # The child appends a journal entry with the commit seam held
        # open (latency plan at integrity.write.commit); SIGKILL lands
        # mid-write. No torn artifact may ever be readable: the journal
        # still loads (old content) and only an inert .tmp remains.
        kdir = os.path.join(wd, "kill9")
        Journal(kdir).append("DRIFT_DETECTED", cycle=0, reason="pre")
        child_src = (
            "import os, sys\n"
            "sys.path.insert(0, %r)\n"
            "from jama16_retina_tpu.obs import faultinject\n"
            "faultinject.arm_from_env_or_config()\n"
            "from jama16_retina_tpu.lifecycle.journal import Journal\n"
            "Journal(%r).append('RETRAIN', cycle=0, note='torn')\n"
            % (os.path.dirname(os.path.abspath(__file__)), kdir)
        )
        kenv = dict(
            env,
            JAMA16_FAULTS=json.dumps({
                "integrity.write.commit": {
                    "kind": "latency", "on_calls": [1], "delay_s": 60.0,
                },
            }),
        )
        child = subprocess.Popen([_sys.executable, "-c", child_src],
                                 env=kenv)
        deadline = time.time() + 60
        tmp_seen = False
        while time.time() < deadline:
            if any(".tmp." in n for n in os.listdir(kdir)):
                tmp_seen = True
                break
            if child.poll() is not None:
                break
            time.sleep(0.02)
        child.send_signal(signal.SIGKILL)
        child.wait(timeout=30)
        ok &= tmp_seen
        j_after = Journal(kdir)  # must load cleanly: the OLD content
        ok &= j_after.state == "DRIFT_DETECTED"
        extras["chaos_integrity_kill9_ok"] = bool(
            tmp_seen and j_after.state == "DRIFT_DETECTED"
        )

    extras["chaos_integrity_ok"] = bool(ok)
    _log(f"chaos integrity drill: ok={ok}")


def _chaos_ingest(extras: dict) -> None:
    """``--chaos`` ingest drill (ISSUE 17): both ingest fault sites
    fired deterministically against a REAL in-process server. An armed
    ``ingest.attach`` refuses the attach with a typed error frame (the
    consumer raises; nothing half-attached survives server-side). An
    armed ``ingest.ring.write`` then kills a live consumer's pump
    mid-epoch — the drill proves the recovery contract end to end: the
    reattach resumes from the lease journal strictly inside the dropped
    stream (no restart-from-0), the resumed stream stays bit-identical
    to the independent host-decoded reference, and the decode ledger
    grows by EXACTLY the run-ahead arithmetic (zero re-decode, counted:
    a per-consumer decode replay would at least double the delta).

    Publishes ``chaos_ingest_ok`` + per-phase booleans and merges both
    sites into the ``chaos_injections`` ledger."""
    import shutil
    import tempfile

    from jama16_retina_tpu.configs import DataConfig, get_config, override
    from jama16_retina_tpu.data import tfrecord as tfrecord_lib
    from jama16_retina_tpu.data import tiered_pipeline
    from jama16_retina_tpu.data.served import ServedStream
    from jama16_retina_tpu.ingest.server import IngestServer
    from jama16_retina_tpu.obs import faultinject
    from jama16_retina_tpu.obs.registry import Registry

    ok = True
    reg = Registry()
    plan = faultinject.plan_from_spec({
        "ingest.attach": {
            "kind": "error", "on_calls": [1], "error": "RuntimeError",
            "message": "chaos drill: attach refused",
        },
        # The 12th slot write lands mid-epoch-2 of the 6-step fixture
        # stream (run-ahead included): steps 0..10 are announced, step
        # 11 is decoded, then the write faults and the pump dies.
        "ingest.ring.write": {
            "kind": "error", "on_calls": [12], "error": "RuntimeError",
            "message": "chaos drill: ring write failed",
        },
    })
    prev = faultinject.arm(plan)
    root = tempfile.mkdtemp(prefix="jama16-chaos-ingest-")
    server = None
    try:
        data_dir = os.path.join(root, "data")
        tfrecord_lib.write_synthetic_split(
            data_dir, "train", 48, image_size=32, num_shards=2, seed=0,
        )
        cfg = override(get_config("smoke"), [
            "model.image_size=32",
            "data.batch_size=8",
            f"ingest.socket_path={os.path.join(root, 'ingest.sock')}",
        ])
        server = IngestServer(data_dir, cfg, registry=reg)
        server.start()
        kw = dict(split="train", seed=9, batch_size=8, image_size=32,
                  capacity_rows=24)

        # Site 1: the armed attach must come back as a TYPED refusal
        # (error frame -> RuntimeError), not a hang or a half-attach.
        refused = False
        try:
            ServedStream(cfg.ingest.socket_path, "chaos-consumer",
                         start_step=None, **kw)
        except RuntimeError:
            refused = True
        ok &= refused
        extras["chaos_ingest_attach_refused"] = bool(refused)

        # Site 2: attach for real (call 2 passes), stream until the
        # armed ring write drops the connection mid-epoch.
        refs_it = tiered_pipeline.host_reference_batches(
            data_dir, "train", DataConfig(batch_size=8), 32, seed=9,
            capacity_rows=24,
        )
        refs = [next(refs_it) for _ in range(14)]
        s1 = ServedStream(cfg.ingest.socket_path, "chaos-consumer",
                          start_step=None, **kw)
        ok &= s1.start_step == 0
        consumed = 0
        dropped = False
        try:
            for i in range(14):
                got = next(s1)
                ok &= np.array_equal(got["image"], refs[i]["image"])
                ok &= np.array_equal(got["grade"], refs[i]["grade"])
                consumed += 1
        except (ConnectionError, TimeoutError):
            dropped = True
        ok &= dropped and 0 < consumed < 14
        extras["chaos_ingest_dropped_mid_epoch"] = bool(
            dropped and 0 < consumed < 14
        )
        decode_before = reg.counter("ingest.decode.batches").value

        # Recovery: reattach at start_step=None -> the lease journal
        # position. It must land INSIDE the dropped stream (the server
        # may not have processed the final in-flight credits, so <=
        # consumed; 0 would mean the lease never advanced).
        s2 = ServedStream(cfg.ingest.socket_path, "chaos-consumer",
                          start_step=None, **kw)
        resume = s2.start_step
        ok &= 0 < resume <= consumed
        for i in range(resume, 14):
            got = next(s2)
            ok &= np.array_equal(got["image"], refs[i]["image"])
            ok &= np.array_equal(got["grade"], refs[i]["grade"])
        s2.close()
        # The server processes s2's trailing credits (and their refill
        # decodes) asynchronously after the detach — settle the ledger
        # before asserting on it.
        decode_c = reg.counter("ingest.decode.batches")
        last, quiet = decode_c.value, 0
        for _ in range(100):
            time.sleep(0.05)
            cur = decode_c.value
            quiet = quiet + 1 if cur == last else 0
            last = cur
            if quiet >= 4:
                break
        decode_delta = decode_c.value - decode_before
        # Zero-re-decode ledger arithmetic: before the drop the server
        # decoded steps 0..11 (the faulted write's batch included), so
        # its decoded-batch cache holds steps 4..11. The resumed pump
        # re-serves the overlap (resume..11) from that cache — cache
        # HITS, not decodes — and only steps >= 12 decode. s2 reads
        # through step 13 and its pump runs at most ``target`` ahead,
        # so the settled delta must land in [2, target + 2] (the upper
        # edge depends on where the consumer's close lands relative to
        # the run-ahead refills). Any decode replay of the overlap
        # would push the delta past the run-ahead bound.
        target = max(1, min(
            cfg.ingest.ring_slots,
            tiered_pipeline.resolve_stage_depth(cfg.data),
        ))
        no_redecode = 2 <= decode_delta <= target + 2
        cache_hits = reg.counter("ingest.cache.hits").value
        ok &= no_redecode and cache_hits >= 1
        ok &= reg.counter("ingest.lease.resumes").value >= 1
        extras["chaos_ingest_resume_step"] = int(resume)
        extras["chaos_ingest_decode_delta"] = int(decode_delta)
        extras["chaos_ingest_no_redecode"] = bool(
            no_redecode and cache_hits >= 1
        )
        _log(
            f"chaos ingest drill: attach refused, pump killed at step "
            f"{consumed}, resumed at {resume} bit-identical, decode "
            f"ledger +{int(decode_delta)} (run-ahead only; cache hits "
            f"{int(cache_hits)})"
        )
    except Exception as e:  # pragma: no cover - bench must emit JSON
        _log(f"chaos ingest drill failed: {type(e).__name__}: {e}")
        ok = False
    finally:
        faultinject.arm(prev)
        if server is not None:
            server.close()
        shutil.rmtree(root, ignore_errors=True)

    counts = {site: c["fires"] for site, c in plan.counts().items()}
    extras.setdefault("chaos_injections", {}).update(counts)
    ok &= counts.get("ingest.attach", 0) >= 1
    ok &= counts.get("ingest.ring.write", 0) >= 1
    extras["chaos_ingest_ok"] = bool(ok)
    _log(f"chaos ingest drill: ok={ok}")


def _chaos_diagnose(extras: dict) -> None:
    """``--chaos`` diagnosis drill (ISSUE 18): three INJECTED
    bottlenecks, each diagnosed by the critical-path analyzer into the
    MATCHING typed verdict — the proof that the verdicts mean what
    they claim.

    * A throttled decode plane (latency plan on ``ingest.decode``,
      ample ring run-ahead, back-to-back consumer) must diagnose
      ``decode_bound``: the consumer's waits are real decode wall.
    * The SAME decode throttle behind a 1-slot ring and a bursty
      consumer must diagnose ``credit_starved``: with no run-ahead
      credit, the post-burst fetch stalls on work the server could
      have hidden — the server's genuine credit starvation (stamped in
      provenance) absorbs the wait before decode gets any.
    * A device-only loop (dispatch wall dominating a small input wait;
      sleeps stand in for the device exactly like the router bench's
      stub replicas) must diagnose ``device_bound``.

    Publishes ``diagnose_ok`` + per-phase booleans and merges the
    ``ingest.decode`` fires into the ``chaos_injections`` ledger."""
    import shutil
    import tempfile

    from jama16_retina_tpu.configs import get_config, override
    from jama16_retina_tpu.data import tfrecord as tfrecord_lib
    from jama16_retina_tpu.data.served import ServedStream
    from jama16_retina_tpu.obs import criticalpath, faultinject
    from jama16_retina_tpu.obs import trace as trace_lib
    from jama16_retina_tpu.obs.registry import Registry

    DELAY_S = 0.02

    def served_phase(label, overrides, consume):
        """One injected bottleneck against a REAL server + consumer:
        arm the decode throttle, stream under an enabled tracer,
        return (DiagnosisVerdict, injected fire count)."""
        from jama16_retina_tpu.ingest.server import IngestServer

        plan = faultinject.plan_from_spec({
            "ingest.decode": {"kind": "latency", "every": 1,
                              "delay_s": DELAY_S},
        })
        prev_plan = faultinject.arm(plan)
        prev_tr = trace_lib.set_default_tracer(
            trace_lib.Tracer(enabled=True))
        root = tempfile.mkdtemp(prefix=f"jama16-chaos-diag-{label}-")
        server = None
        stream = None
        try:
            data_dir = os.path.join(root, "data")
            tfrecord_lib.write_synthetic_split(
                data_dir, "train", 48, image_size=32, num_shards=2,
                seed=0,
            )
            cfg = override(get_config("smoke"), [
                "model.image_size=32",
                "data.batch_size=8",
                f"ingest.socket_path={os.path.join(root, 'ingest.sock')}",
            ] + overrides)
            server = IngestServer(data_dir, cfg, registry=Registry())
            server.start()
            stream = ServedStream(
                cfg.ingest.socket_path, f"diag-{label}",
                start_step=None, split="train", seed=9, batch_size=8,
                image_size=32, capacity_rows=24,
            )
            consume(stream)
            verdict = criticalpath.diagnose(
                trace_lib.default_tracer().events())
        finally:
            if stream is not None:
                stream.close()
            if server is not None:
                server.close()
            trace_lib.set_default_tracer(prev_tr)
            faultinject.arm(prev_plan)
            shutil.rmtree(root, ignore_errors=True)
        return verdict, plan.counts()["ingest.decode"]["fires"]

    ok = True
    fires_total = 0
    try:
        def back_to_back(stream):
            for _ in range(12):
                next(stream)

        v1, fires = served_phase("decode", [], back_to_back)
        fires_total += fires
        d1 = v1.verdict == "decode_bound" and fires >= 1
        extras["chaos_diagnose_decode_bound"] = bool(d1)
        ok &= d1
        _log(f"chaos diagnose decode phase: {v1.verdict} "
             f"(confidence {v1.confidence})")

        def bursty(stream):
            # Burst-then-idle: the 1-slot ring cannot bank run-ahead
            # during the idle half, so the busy half's fetch stalls.
            for i in range(12):
                next(stream)
                if i % 2 == 0:
                    time.sleep(0.05)

        v2, fires = served_phase("starve", ["ingest.ring_slots=1"],
                                 bursty)
        fires_total += fires
        d2 = v2.verdict == "credit_starved" and fires >= 1
        extras["chaos_diagnose_credit_starved"] = bool(d2)
        ok &= d2
        _log(f"chaos diagnose starve phase: {v2.verdict} "
             f"(confidence {v2.confidence})")

        prev_tr = trace_lib.set_default_tracer(
            trace_lib.Tracer(enabled=True))
        try:
            tr = trace_lib.default_tracer()
            for _ in range(6):
                t0 = time.perf_counter()
                time.sleep(0.001)
                t1 = time.perf_counter()
                tr.complete("trainer.input", t0, t1, {})
                time.sleep(0.012)
                t2 = time.perf_counter()
                tr.complete("trainer.dispatch", t1, t2, {})
            v3 = criticalpath.diagnose(tr.events())
        finally:
            trace_lib.set_default_tracer(prev_tr)
        d3 = v3.verdict == "device_bound"
        extras["chaos_diagnose_device_bound"] = bool(d3)
        ok &= d3
        _log(f"chaos diagnose device phase: {v3.verdict} "
             f"(confidence {v3.confidence})")
    except Exception as e:  # pragma: no cover - bench must emit JSON
        _log(f"chaos diagnose drill failed: {type(e).__name__}: {e}")
        ok = False

    extras.setdefault("chaos_injections", {})["ingest.decode"] = (
        int(fires_total))
    extras["diagnose_ok"] = bool(ok)
    _log(f"chaos diagnose drill: ok={ok}")


def _chaos_device(extras: dict) -> None:
    """``--chaos`` device-utilization drill (ISSUE 19): two INJECTED
    device pathologies, each landing in the MATCHING typed verdict or
    alert — the proof the device plane's refinement means what it
    claims.

    * A dispatch-dominant trace window paired with a LOW-MFU compute-
      class device summary must refine ``device_bound`` into
      ``device_underutilized`` (the device is the wall but mostly
      idle — launch overhead / tiny batches, not compute saturation);
      the SAME window with a memory-class summary must refine into
      ``device_membw_bound``.
    * A DeviceMonitor sampling a fake device at 95% HBM occupancy
      must publish headroom below the 10% alert line, and the
      reliability rule set must latch ``hbm_pressure`` after the
      for-60s window (driven with injected clocks — deterministic).

    Publishes ``device_ok`` + per-phase booleans."""
    from jama16_retina_tpu.configs import get_config
    from jama16_retina_tpu.obs import alerts as obs_alerts
    from jama16_retina_tpu.obs import criticalpath
    from jama16_retina_tpu.obs import device as device_lib
    from jama16_retina_tpu.obs import trace as trace_lib
    from jama16_retina_tpu.obs.registry import Registry

    ok = True
    try:
        # Dispatch-dominant window: the device is the critical path.
        tr = trace_lib.Tracer(enabled=True)
        for _ in range(6):
            t0 = time.perf_counter()
            time.sleep(0.001)
            t1 = time.perf_counter()
            tr.complete("trainer.input", t0, t1, {})
            time.sleep(0.012)
            t2 = time.perf_counter()
            tr.complete("trainer.dispatch", t1, t2, {})
        events = tr.events()

        # Low MFU + compute class: device-bound but mostly idle. The
        # 3% MFU stays under SATURATED_MFU at any local device count.
        v_low = criticalpath.diagnose(events, device={
            "mfu": 0.03, "dominant_class": "compute",
        })
        d1 = v_low.verdict == "device_underutilized"
        extras["chaos_device_underutilized"] = bool(d1)
        ok &= d1
        _log(f"chaos device low-MFU phase: {v_low.verdict}")

        # Memory class: bandwidth is the wall regardless of MFU.
        v_mem = criticalpath.diagnose(events, device={
            "mfu": 0.6, "dominant_class": "memory",
        })
        d2 = v_mem.verdict == "device_membw_bound"
        extras["chaos_device_membw_bound"] = bool(d2)
        ok &= d2
        _log(f"chaos device membw phase: {v_mem.verdict}")

        # HBM-pressure window: fake device at 95% occupancy -> the
        # headroom gauge lands under the alert line, and the for-60s
        # rule latches across two injected-clock evaluations.
        class _PressedDev:
            def memory_stats(self):
                limit = 16 << 30
                return {"bytes_in_use": int(limit * 0.95),
                        "peak_bytes_in_use": int(limit * 0.95),
                        "bytes_limit": limit}

        reg = Registry()
        mon = device_lib.DeviceMonitor(reg, devices=[_PressedDev()],
                                       ledger=device_lib.ProgramLedger())
        mon.sample()
        head = reg.snapshot()["gauges"].get("device.hbm.headroom_frac")
        d3 = head is not None and head < device_lib.HBM_PRESSURE_HEADROOM
        extras["chaos_device_headroom_frac"] = (
            round(head, 4) if head is not None else None)
        ok &= d3

        cfg = get_config("smoke")
        mgr = obs_alerts.AlertManager(
            obs_alerts.reliability_rules(cfg), registry=reg,
        )
        mgr.evaluate(now=1000.0)
        firing = mgr.evaluate(now=1061.0)
        d4 = any(f.get("reason") == "hbm_pressure" for f in firing)
        extras["chaos_device_hbm_pressure_fired"] = bool(d4)
        ok &= d4
        _log(f"chaos device HBM phase: headroom={head}, "
             f"hbm_pressure fired={d4}")
    except Exception as e:  # pragma: no cover - bench must emit JSON
        _log(f"chaos device drill failed: {type(e).__name__}: {e}")
        ok = False

    extras["device_ok"] = bool(ok)
    _log(f"chaos device drill: ok={ok}")


def _latency_summary(latencies_ms) -> dict:
    """p50/p99/mean over one offered-load window's per-request
    latencies. Both percentiles come from the SAME sorted sample, so
    p50 <= p99 holds by construction — asserted anyway (and pinned by
    tests/test_bench_guard.py): a violated invariant means the
    collection is corrupted, and corrupted latencies must no more be
    published than physics-violating rates."""
    lat = np.asarray(sorted(float(x) for x in latencies_ms), np.float64)
    if lat.size == 0:
        raise ValueError("empty latency sample")
    out = {
        "p50_ms": round(float(np.percentile(lat, 50)), 2),
        "p99_ms": round(float(np.percentile(lat, 99)), 2),
        "mean_ms": round(float(lat.mean()), 2),
        "n": int(lat.size),
    }
    assert out["p50_ms"] <= out["p99_ms"], out
    return out


def _offered_load(submit, concurrency: int, requests_per_worker: int,
                  payload) -> tuple[list, float]:
    """Closed-loop offered load against a MicroBatcher-style ``submit``:
    ``concurrency`` submitter threads each fire ``requests_per_worker``
    single-image requests back-to-back (a new request the moment the
    last completes — so offered load scales with concurrency and the
    batcher sees genuinely CONCURRENT submitters, not a pre-staged
    batch). Returns (per-request latencies in ms, window seconds).

    Latency here is end-to-end request latency: submit -> future
    resolved with HOST-side probabilities. The result of every request
    is a host numpy array, so each latency sample is fenced by
    construction — there is no async handle to close a window early
    (the same reason round 3 moved bench timing to host-fetched
    fences)."""
    import threading

    lat: list = [[] for _ in range(concurrency)]
    errs: list = []

    def run(w):
        try:
            for i in range(requests_per_worker):
                t0 = time.perf_counter()
                submit(payload(w, i)).result()
                lat[w].append((time.perf_counter() - t0) * 1e3)
        except Exception as e:  # noqa: BLE001 - re-raised on main thread
            errs.append(e)

    threads = [
        threading.Thread(target=run, args=(w,)) for w in range(concurrency)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    if errs:
        raise errs[0]
    return [x for per in lat for x in per], dt


def _ensure_bench_data(image_size: int) -> dict:
    """Write (once) two synthetic splits: jpeg- and raw-encoded."""
    from jama16_retina_tpu.data import tfrecord

    dirs = {}
    for enc in ("jpeg", "raw"):
        d = os.path.join(BENCH_DATA_DIR, f"{image_size}_{enc}")
        marker = os.path.join(d, ".complete")
        if not os.path.exists(marker):
            _log(f"writing {BENCH_N_IMAGES} synthetic {enc} records -> {d}")
            tfrecord.write_synthetic_split(
                d, "train", BENCH_N_IMAGES, image_size=image_size,
                num_shards=4, seed=0, encoding=enc,
            )
            with open(marker, "w") as f:
                f.write("ok")
        dirs[enc] = d
    return dirs


def _host_rate(data_dir: str, cfg, image_size: int, n_batches: int = 30,
               loader: str = "tfdata") -> float:
    """Images/sec of the host loader alone (parse/decode+batch, no TPU)."""
    if loader == "grain":
        from jama16_retina_tpu.data import grain_pipeline as mod
    else:
        from jama16_retina_tpu.data import pipeline as mod
    it = mod.train_batches(data_dir, "train", cfg.data, image_size, seed=0)
    for _ in range(3):  # warm threads/autotune
        next(it)
    t0 = time.time()
    for _ in range(n_batches):
        next(it)
    dt = time.time() - t0
    # Tear down promptly: a leaked tf.data iterator keeps its autotune/
    # reader threads alive and steals CPU from the next measurement
    # (observed: the grain rate halves when measured after tf.data
    # without this).
    if hasattr(it, "close"):
        it.close()
    del it
    import gc

    gc.collect()
    return n_batches * cfg.data.batch_size / dt


def _timed_steps(step, state, batch_iter, key, n_steps: int, batch_size: int,
                 n_dev: int, warmup: int = WARMUP_STEPS) -> tuple[float, Any]:
    """Shared timing discipline for every train-step measurement: warm up
    (compile included), fence, time ``n_steps``, fence; returns
    (images/sec/chip, final state). ``batch_iter`` is any callable
    ``i -> batch`` (cycled list or pipeline iterator).

    The step chains state through iterations, so the single closing
    ``_fence`` on the final state is data-dependent on EVERY timed step;
    its own fixed cost is measured up front and subtracted. The fence
    cost on the axon tunnel is a noisy ~22-80 ms (drifts hour to hour),
    so one sample could inflate the published rate by several percent —
    take the median of 3 samples instead (ADVICE r3).
    """
    for i in range(warmup):
        state, _ = step(state, batch_iter(i), key)
    _fence(state)  # completes warmup + compiles the fence's reduce
    sync = sorted(_fence_cost(state) for _ in range(3))[1]
    t0 = time.time()
    for i in range(n_steps):
        state, m = step(state, batch_iter(i), key)
    _fence(state)
    dt = max(time.time() - t0 - sync, 1e-9)
    rate = n_steps * batch_size / dt / n_dev
    return rate, state


def _timed_forward(fn, n: int, images_per_call: int, n_dev: int = 1,
                   warmup: int = 2) -> float:
    """Images/sec/chip of forward-only ``fn(i) -> array`` calls whose
    outputs do NOT chain: an on-device scalar accumulator is folded in
    each iteration so the closing host fetch depends on every call."""
    import jax
    import jax.numpy as jnp

    acc_add = jax.jit(lambda a, p: a + jnp.sum(p.astype(jnp.float32)))
    acc = jnp.zeros((), jnp.float32)
    for i in range(warmup):
        acc = acc_add(acc, fn(i))
    _fence(acc)  # completes warmup AND compiles the fence's reduce
    sync = sorted(_fence_cost(acc) for _ in range(3))[1]  # median of 3
    t0 = time.time()
    for i in range(n):
        acc = acc_add(acc, fn(i))
    _fence(acc)
    dt = max(time.time() - t0 - sync, 1e-9)
    return n * images_per_call / dt / n_dev


def _augment_rate(images_u8, data_cfg, use_pallas: bool, n: int = 100) -> float:
    """Images/sec of the augmentation stage alone, compiled on this chip."""
    import jax

    cfg = dataclasses.replace(data_cfg, use_pallas=use_pallas)
    from jama16_retina_tpu.data import augment

    fn = jax.jit(lambda k, im: augment.augment_batch(k, im, cfg))
    key = jax.random.key(0)
    return _timed_forward(
        lambda i: fn(jax.random.fold_in(key, i), images_u8),
        n, images_u8.shape[0],
    )


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--use_pallas", action="store_true",
        help="force the fused pallas color-jitter kernel on (it is already "
             "the eyepacs_binary preset default; see --no_pallas)",
    )
    parser.add_argument(
        "--no_pallas", action="store_true",
        help="force the jnp augmentation composition instead of the kernel",
    )
    parser.add_argument(
        "--skip_host", action="store_true",
        help="device-only measurements (skip TFRecord fixture + host rates)",
    )
    parser.add_argument(
        "--skip_b128", action="store_true",
        help="skip the batch-128 scaling datapoint (saves its ~40s compile "
             "for quick checks)",
    )
    parser.add_argument(
        "--skip_ensemble", action="store_true",
        help="skip the 4-member stacked-ensemble datapoint (saves its "
             "compile for quick checks)",
    )
    parser.add_argument(
        "--skip_serve", action="store_true",
        help="skip the serving-engine section (saturated throughput + "
             "offered-load latency; two serving-step compiles)",
    )
    parser.add_argument(
        "--skip_autotune", action="store_true",
        help="skip the autotuned-ingest section (pipeline_fed_autotuned: "
             "the closed-loop tuner converging from pessimal knobs; its "
             "convergence windows cost ~60 extra train steps)",
    )
    parser.add_argument(
        "--skip_frontier", action="store_true",
        help="skip the serve_frontier latency/throughput sweep "
             "(serve.bucket_sizes x concurrency; one serving compile "
             "per swept bucket)",
    )
    parser.add_argument(
        "--skip_router", action="store_true",
        help="skip the front-door router scaling rows (ISSUE 12: "
             "router_k{1,2,4}_images_per_sec over stub replicas + the "
             "<=2% routed-vs-direct overhead pin; off-device, ~10s)",
    )
    parser.add_argument(
        "--skip_time_to_auc", action="store_true",
        help="skip the time-to-AUC rows (ISSUE 11/14: smoke-scale "
             "fit_ensemble runs — fp32, bf16, and the LAMB large-batch "
             "recipe — through scripts/time_to_auc.py; the accepted "
             "north-star metric lands in the trajectory JSON as "
             "time_to_auc_sec_* / time_to_auc_lamb_speedup)",
    )
    parser.add_argument(
        "--skip_mesh", action="store_true",
        help="skip the mesh-scaling rows (ISSUE 14: "
             "train_mesh_d{1,4}_images_per_sec / serve_mesh_d{N} via "
             "scripts/dryrun_multichip.py — one fresh fake-device "
             "subprocess per count, single-threaded per device; "
             "~2-4 min cold)",
    )
    parser.add_argument(
        "--time_to_auc_target", type=float, default=0.95,
        help="fixed target val AUC for the time_to_auc_sec_* rows",
    )
    parser.add_argument(
        "--chaos", action="store_true",
        help="run the deterministic fault-injection smoke (ISSUE 6): "
             "arm a FaultPlan, drive poison-record quarantine, batcher "
             "window-error recovery, deadline expiry, and load "
             "shedding off-device; publishes chaos_ok + the per-site "
             "injection ledger. Plus the ISSUE 13 durable-state "
             "disaster drill: bit-flip every sealed artifact class, "
             "graftfsck detect + --repair back to serving-ready, and "
             "kill -9 inside the sealed writer (chaos_integrity_*)",
    )
    args = parser.parse_args()

    import jax

    from jama16_retina_tpu import models, train_lib
    from jama16_retina_tpu.configs import get_config
    from jama16_retina_tpu.data import pipeline
    from jama16_retina_tpu.parallel import mesh as mesh_lib

    # Persistent compilation cache: the AOT lower+compile used for cost
    # analysis and the dispatch-path compile then share one compilation
    # instead of paying the ~40-80s train-step compile twice (and repeat
    # bench invocations start warm).
    mesh_lib.enable_persistent_compilation_cache(
        os.environ.get("BENCH_JIT_CACHE", "/tmp/retina_bench_jitcache")
    )
    peak = _peak_flops()

    cfg = get_config("eyepacs_binary")
    if args.use_pallas or args.no_pallas:
        cfg = cfg.replace(data=dataclasses.replace(
            cfg.data, use_pallas=not args.no_pallas))
    batch_size = cfg.data.batch_size
    size = cfg.model.image_size

    mesh = mesh_lib.make_mesh()  # all local devices (1 chip under axon)
    n_dev = mesh.devices.size
    _log(f"{n_dev} device(s), batch {batch_size}, {size}px, "
         f"use_pallas={cfg.data.use_pallas}")

    step, state, batches, key = build_train_fixture(cfg, mesh, batch_size)
    # Later sections (eval step, b128, ensemble) still need the module
    # definition and a pixel source; contents of random eval pixels are
    # timing-irrelevant, so a fresh stream is fine.
    model = models.build(cfg.model)
    rng = np.random.default_rng(7)

    # FLOPs/image of the compiled train step — the physics guard's
    # numerator for every train-style section (per-IMAGE cost is batch-
    # size- and member-count-invariant to within BN/optimizer epsilon, so
    # one analysis covers device_only, pipeline_fed, b128, and the
    # stacked ensemble's member-images).
    train_flops = _flops_of(step, state, batches[0], key)
    flops_per_image = train_flops / batch_size if train_flops else None

    t0 = time.time()
    device_only, state = _timed_steps(
        step, state, lambda i: batches[i % N_DISTINCT_BATCHES], key,
        TIMED_STEPS, batch_size, n_dev,
    )
    _log(f"device_only: {TIMED_STEPS} steps in {time.time() - t0:.1f}s "
         f"incl. warmup+compile ({device_only:.1f} img/s/chip)")
    headline_serialized = False
    guarded = _physics_guard("device_only", device_only, flops_per_image, peak)
    if guarded is None:
        # The headline must still be a trustworthy number: re-measure
        # fully serialized with a fence per step — a strict lower bound
        # on the true rate (sync cost deliberately NOT subtracted; see
        # the log message below).
        headline_serialized = True
        _log("re-measuring headline with per-step fences (strict lower "
             "bound: fully serialized, sync cost NOT subtracted — "
             "subtracting a 50x-amplified single sync sample could "
             "overshoot the true rate)")
        t0 = time.time()
        for i in range(TIMED_STEPS):
            state, _ = step(state, batches[i % N_DISTINCT_BATCHES], key)
            _fence(state)
        dt = max(time.time() - t0, 1e-9)
        device_only = TIMED_STEPS * batch_size / dt / n_dev
        if _physics_guard("device_only", device_only, flops_per_image,
                          peak) is None:
            raise RuntimeError(
                "serialized per-step timing still implies an impossible "
                "rate — the clock or the device is lying; no trustworthy "
                "headline exists on this host"
            )

    extras: dict = {"use_pallas": cfg.data.use_pallas}
    extras["physics_peak_tflops"] = round(peak / 1e12, 1)
    if flops_per_image:
        extras["train_gflops_per_image"] = round(flops_per_image / 1e9, 2)
        # Model FLOPs utilization of the headline (ISSUE 19): the SAME
        # numbers the physics guard already trusts (device_only is
        # img/s/CHIP, peak is per-chip), read as a fraction instead of
        # a ceiling — what the MFU gauge (obs/device.py) reports for a
        # production run of this step.
        extras["train_mfu"] = round(
            device_only * flops_per_image / peak, 4)

    # Telemetry overhead pin (ISSUE 3): the SAME step/batches/window as
    # device_only, with the trainer's per-step telemetry ops live
    # (StallClock segment timing feeding registry histograms + counter
    # incs). Guarded to stay within 2% of the uninstrumented headline —
    # the contract that lets cfg.obs.enabled default on.
    if not headline_serialized:
        try:
            from jama16_retina_tpu.obs.registry import Registry

            telem_step, wrap_iter = _instrumented_step(step, Registry())
            rate_t, state = _timed_steps(
                telem_step, state,
                wrap_iter(lambda i: batches[i % N_DISTINCT_BATCHES]), key,
                TIMED_STEPS, batch_size, n_dev,
            )
            rate_t = _publish(
                extras, "device_only_telemetry", rate_t,
                flops_per_image, peak,
                suffix=" (device_only + trainer-style telemetry ops)",
            )
            if rate_t is not None:
                _telemetry_overhead_guard(extras, rate_t, device_only)
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"telemetry overhead bench failed: {type(e).__name__}: {e}")

    # Tracing overhead pin (ISSUE 4): the same window once more with the
    # event tracer ON as well — the span/StallClock call sites now
    # additionally append per-thread ring-buffer events (obs/trace.py).
    # Same 2% budget against the UNINSTRUMENTED headline — the contract
    # that lets cfg.obs.trace_enabled default on.
    if not headline_serialized:
        try:
            from jama16_retina_tpu.obs.registry import Registry
            from jama16_retina_tpu.obs.trace import Tracer

            tracer = Tracer(enabled=True, buffer_events=4096)
            traced_step, wrap_iter_tr = _instrumented_step(
                step, Registry(), tracer=tracer
            )
            rate_tr, state = _timed_steps(
                traced_step, state,
                wrap_iter_tr(lambda i: batches[i % N_DISTINCT_BATCHES]), key,
                TIMED_STEPS, batch_size, n_dev,
            )
            rate_tr = _publish(
                extras, "device_only_tracing", rate_tr,
                flops_per_image, peak,
                suffix=" (device_only + telemetry + event-trace ops)",
            )
            if rate_tr is not None:
                _tracing_overhead_guard(extras, rate_tr, device_only)
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"tracing overhead bench failed: {type(e).__name__}: {e}")

    # Quality-monitor overhead pin (ISSUE 5): the same device_only
    # window with a QualityMonitor observing one host batch of images +
    # scores per step — the per-batch cost the serving engine pays when
    # obs.quality is enabled (input-stat extraction dominates; PSI math
    # runs only at window boundaries, which this window crosses).
    if not headline_serialized:
        try:
            import dataclasses as _dc

            from jama16_retina_tpu.configs import QualityConfig
            from jama16_retina_tpu.obs import quality as quality_lib
            from jama16_retina_tpu.obs.registry import Registry

            qrng = np.random.default_rng(11)
            qsize = cfg.model.image_size
            qimgs = qrng.integers(
                0, 256, (batch_size, qsize, qsize, 3), np.uint8
            )
            qscores = qrng.random(batch_size)
            profile = quality_lib.build_profile(
                qrng.random(4096),
                stat_values=quality_lib.input_stat_values(qimgs),
                thresholds=[{"threshold": 0.5}],
            )
            monitor = quality_lib.QualityMonitor(
                _dc.replace(QualityConfig(), enabled=True,
                            window_scores=batch_size * 4),
                registry=Registry(), profile=profile,
            )

            def quality_step(s, batch, k):
                out = step(s, batch, k)
                monitor.observe(qimgs, qscores)
                return out

            rate_q, state = _timed_steps(
                quality_step, state,
                lambda i: batches[i % N_DISTINCT_BATCHES], key,
                TIMED_STEPS, batch_size, n_dev,
            )
            rate_q = _publish(
                extras, "device_only_quality", rate_q,
                flops_per_image, peak,
                suffix=" (device_only + quality-monitor observe per batch)",
            )
            if rate_q is not None:
                _quality_overhead_guard(extras, rate_q, device_only)
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"quality overhead bench failed: {type(e).__name__}: {e}")

    # Robustness overhead pin (ISSUE 6): the same device_only window
    # with the reliability seams live but DISABLED — one unarmed fault
    # point per step (obs/faultinject.check: global read + branch) plus
    # the two disabled-shed admission branches the batcher's submit
    # pays when serve.shed_* are 0. Same ≤2% budget, shared guard math
    # — the contract that lets the seams ship always-compiled-in.
    if not headline_serialized:
        try:
            from jama16_retina_tpu.obs import faultinject

            shed_queue_depth = 0  # the production defaults: shedding off
            shed_in_flight = 0
            n_queued = n_in_flight = 0

            def robust_step(s, batch, k):
                faultinject.check("trainer.step")
                if (shed_queue_depth > 0
                        and n_queued >= shed_queue_depth):
                    raise RuntimeError("unreachable: shedding disabled")
                if (shed_in_flight > 0
                        and n_in_flight >= shed_in_flight):
                    raise RuntimeError("unreachable: shedding disabled")
                return step(s, batch, k)

            rate_r, state = _timed_steps(
                robust_step, state,
                lambda i: batches[i % N_DISTINCT_BATCHES], key,
                TIMED_STEPS, batch_size, n_dev,
            )
            rate_r = _publish(
                extras, "device_only_robustness", rate_r,
                flops_per_image, peak,
                suffix=" (device_only + unarmed fault point + "
                       "disabled-shed admission branches)",
            )
            if rate_r is not None:
                _robustness_overhead_guard(extras, rate_r, device_only)
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"robustness overhead bench failed: "
                 f"{type(e).__name__}: {e}")

    # Integrity overhead pin (ISSUE 13): the sealed-artifact layer's
    # whole hot-path residue — one unarmed integrity.write seam branch
    # per step plus a full sealed publish every 25 steps (see
    # _integrity_overhead_guard). Same ≤2% budget, shared guard math.
    if not headline_serialized:
        try:
            import shutil as _shutil
            import tempfile as _tempfile

            from jama16_retina_tpu.integrity import (
                artifact as artifact_lib,
            )
            from jama16_retina_tpu.obs import faultinject

            i_dir = _tempfile.mkdtemp(prefix="bench_integrity_")
            i_path = os.path.join(i_dir, "probe.json")
            i_state = {"n": 0, "writes": 0}

            def integrity_step(s, batch, k):
                faultinject.check("integrity.write")
                out = step(s, batch, k)
                i_state["n"] += 1
                if i_state["n"] >= 25:
                    i_state["writes"] += 1
                    artifact_lib.write_sealed_json(
                        i_path,
                        {"writes": i_state["writes"],
                         "payload": list(range(64))},
                        schema="integrity.probe", version=1,
                    )
                    i_state["n"] = 0
                return out

            rate_i, state = _timed_steps(
                integrity_step, state,
                lambda i: batches[i % N_DISTINCT_BATCHES], key,
                TIMED_STEPS, batch_size, n_dev,
            )
            _shutil.rmtree(i_dir, ignore_errors=True)
            rate_i = _publish(
                extras, "device_only_integrity", rate_i,
                flops_per_image, peak,
                suffix=" (device_only + unarmed integrity.write seam + "
                       "sealed publish every 25 steps)",
            )
            if rate_i is not None:
                _integrity_overhead_guard(extras, rate_i, device_only)
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"integrity overhead bench failed: "
                 f"{type(e).__name__}: {e}")

    # Fleet overhead pin (ISSUE 15): the segment bus's whole hot-path
    # residue — the disabled-bus branch per step (obs.fleet_dir empty,
    # the production default, is one `is not None` check per flush)
    # plus a REAL sealed segment publish every 25 steps (see
    # _fleet_overhead_guard). Same ≤2% budget, shared guard math.
    if not headline_serialized:
        try:
            import shutil as _shutil
            import tempfile as _tempfile

            from jama16_retina_tpu.obs import fleet as fleet_lib
            from jama16_retina_tpu.obs.registry import Registry

            f_dir = _tempfile.mkdtemp(prefix="bench_fleet_")
            f_reg = Registry()
            f_reg.counter(
                "bench.steps",
                help="train steps executed by bench.py's instrumented "
                     "overhead-pin workload",
            ).inc()
            f_bus = fleet_lib.FleetBus(f_dir, "bench", registry=f_reg,
                                       keep_segments=4)
            f_state = {"n": 0, "disabled_bus": None}

            def fleet_step(s, batch, k):
                out = step(s, batch, k)
                # The production default: no bus — one branch.
                if f_state["disabled_bus"] is not None:
                    raise RuntimeError("unreachable: fleet bus off")
                f_state["n"] += 1
                if f_state["n"] >= 25:
                    f_state["n"] = 0
                    f_bus.publish(f_reg.snapshot(),
                                  heartbeat={"step": f_state["n"]})
                return out

            rate_f, state = _timed_steps(
                fleet_step, state,
                lambda i: batches[i % N_DISTINCT_BATCHES], key,
                TIMED_STEPS, batch_size, n_dev,
            )
            _shutil.rmtree(f_dir, ignore_errors=True)
            rate_f = _publish(
                extras, "device_only_fleet", rate_f,
                flops_per_image, peak,
                suffix=" (device_only + disabled-bus branch + sealed "
                       "segment publish every 25 steps)",
            )
            if rate_f is not None:
                _fleet_overhead_guard(extras, rate_f, device_only)
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"fleet overhead bench failed: {type(e).__name__}: {e}")

    # Audit overhead pin (ISSUE 20): the provenance ledger's whole
    # hot-path residue — one record() per step (sampling decision +
    # bounded put_nowait) with the daemon writer digesting rows and
    # sealing REAL segments every 25 records in a tempdir ledger
    # concurrently. Same ≤2% budget, shared guard math — see
    # _audit_overhead_guard.
    if not headline_serialized:
        try:
            import shutil as _shutil
            import tempfile as _tempfile

            from jama16_retina_tpu.obs import audit as _audit_lib
            from jama16_retina_tpu.obs.registry import Registry

            a_dir = _tempfile.mkdtemp(prefix="bench_audit_")
            a_ledger = _audit_lib.AuditLedger(
                a_dir, registry=Registry(), sample=1.0, seal_every=25,
                queue_max=1024, thresholds=(0.5,),
            )
            a_rows = np.zeros((8, size, size, 3), np.uint8)
            a_scores = np.linspace(0.1, 0.9, 8)

            def audit_step(s, batch, k):
                out = step(s, batch, k)
                a_ledger.record(a_rows, a_scores, trace_id="bench",
                                generation=0)
                return out

            rate_a, state = _timed_steps(
                audit_step, state,
                lambda i: batches[i % N_DISTINCT_BATCHES], key,
                TIMED_STEPS, batch_size, n_dev,
            )
            a_ledger.close()
            _shutil.rmtree(a_dir, ignore_errors=True)
            rate_a = _publish(
                extras, "device_only_audit", rate_a,
                flops_per_image, peak,
                suffix=" (device_only + one audit record() per step + "
                       "writer-thread digesting/sealing every 25)",
            )
            if rate_a is not None:
                _audit_overhead_guard(extras, rate_a, device_only)
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"audit overhead bench failed: {type(e).__name__}: {e}")

    # Diagnosis overhead pin (ISSUE 18): the causal-diagnosis plane's
    # whole hot-path residue — per-step provenance stamping (build the
    # compact record + length-prefixed JSON memcpy into a mapped slot
    # region, what the ingest server pays per served batch) plus the
    # DISABLED analyzer branch (the critical-path analyzer is pure and
    # runs only inside FlightRecorder dumps; steady state pays one
    # `if`). Same ≤2% budget, shared guard math — see
    # _diagnosis_overhead_guard.
    if not headline_serialized:
        try:
            from jama16_retina_tpu.ingest import protocol as _protocol
            from jama16_retina_tpu.obs import trace as _trace_lib

            _, d_slot_bytes = _protocol.slot_layout(batch_size, size)
            d_buf = bytearray(d_slot_bytes)
            d_state = {"seq": 0, "analyzer": None}
            d_tr = _trace_lib.default_tracer()

            def diagnosis_step(s, batch, k):
                out = step(s, batch, k)
                d_state["seq"] += 1
                ctx = _trace_lib.new_context()
                _protocol.write_provenance(
                    d_buf, 0, batch_size, size, {
                        "v": _protocol.PROTOCOL_VERSION,
                        "seq": d_state["seq"],
                        "step": d_state["seq"],
                        "decode_s": 0.001,
                        "cache_hit": 0,
                        "credit_wait_s": 0.0,
                        "t_write_unix": 0.0,
                        "trace": ctx.wire(),
                    })
                # The production default: analyzer off-path — the
                # disabled-tracer branch is the whole per-step cost.
                if d_tr.enabled and d_state["analyzer"] is not None:
                    raise RuntimeError("unreachable: analyzer off")
                return out

            rate_d, state = _timed_steps(
                diagnosis_step, state,
                lambda i: batches[i % N_DISTINCT_BATCHES], key,
                TIMED_STEPS, batch_size, n_dev,
            )
            rate_d = _publish(
                extras, "device_only_diagnosis", rate_d,
                flops_per_image, peak,
                suffix=" (device_only + per-step provenance stamp + "
                       "disabled-analyzer branch)",
            )
            if rate_d is not None:
                _diagnosis_overhead_guard(extras, rate_d, device_only)
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"diagnosis overhead bench failed: "
                 f"{type(e).__name__}: {e}")

    # Autotune overhead pin (ISSUE 7): the same device_only window with
    # the steady-state costs a tuned run pays per step — one live knob
    # poll (what the loaders' fill loops do per batch) — plus a
    # CONVERGED tuner observing a window boundary every 10 steps (the
    # trainer's log-cadence wiring, at a far denser cadence than any
    # real log_every). Same ≤2% budget, shared guard math.
    if not headline_serialized:
        try:
            from jama16_retina_tpu.data import autotune as autotune_lib
            from jama16_retina_tpu.data.hbm_pipeline import row_bytes
            from jama16_retina_tpu.obs.registry import Registry

            a_knobs = autotune_lib.Knobs(1, 1, 1)
            a_tuner = autotune_lib.IngestAutotuner(
                a_knobs,
                autotune_lib.Limits(
                    hbm_headroom_bytes=10**9,
                    batch_bytes=batch_size * row_bytes(size),
                ),
                registry=Registry(),
            )
            a_state = {"t0": time.perf_counter(), "n": 0}

            def autotune_step(s, batch, k):
                a_knobs.stage_depth  # the loaders' per-batch poll
                out = step(s, batch, k)
                a_state["n"] += 1
                if a_state["n"] >= 10:
                    now = time.perf_counter()
                    # input_wait 0: the converged steady state (device-
                    # fed batches never starve) — the quiet/dead-band
                    # decision path a production run sits on.
                    a_tuner.observe(now - a_state["t0"], 0.0)
                    a_state["t0"] = now
                    a_state["n"] = 0
                return out

            rate_a, state = _timed_steps(
                autotune_step, state,
                lambda i: batches[i % N_DISTINCT_BATCHES], key,
                TIMED_STEPS, batch_size, n_dev,
            )
            rate_a = _publish(
                extras, "device_only_autotune", rate_a,
                flops_per_image, peak,
                suffix=" (device_only + live knob poll + tuner window "
                       "observe every 10 steps)",
            )
            if rate_a is not None:
                _autotune_overhead_guard(extras, rate_a, device_only)
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"autotune overhead bench failed: {type(e).__name__}: {e}")

    # Device-monitor overhead pin (ISSUE 19): the same device_only
    # window with the device-utilization plane's steady-state costs
    # live — the program ledger's per-step call count (the trainer's
    # counted-step closure) plus a full DeviceMonitor.sample() every
    # 10 steps (far denser than any real telemetry flush). The monitor
    # samples a FAKE device's memory_stats so the pin measures the
    # plane's own bookkeeping, not a backend's stats quirks — the
    # sample path (stats walk, owner ledger sum, gauge publishes,
    # program-delta MFU math) is identical. Same ≤2% budget, shared
    # guard math — the contract that lets obs.device_enabled default
    # on.
    if not headline_serialized:
        try:
            from jama16_retina_tpu.obs import device as device_lib
            from jama16_retina_tpu.obs.registry import Registry

            class _FakeDev:
                def memory_stats(self):
                    return {"bytes_in_use": 6 << 30,
                            "peak_bytes_in_use": 7 << 30,
                            "bytes_limit": 16 << 30}

            dm_ledger = device_lib.ProgramLedger()
            dm_entry = dm_ledger.register(
                "bench_step", flops_per_call=train_flops or 1e9,
                bytes_per_call=1e8,
            )
            dm_mon = device_lib.DeviceMonitor(
                Registry(), devices=[_FakeDev()], ledger=dm_ledger,
                peak_flops_per_s=peak,
            )
            dm_mon.sample()  # baseline tick off the clock
            dm_state = {"n": 0}

            def devicemon_step(s, batch, k):
                dm_entry.note_call()
                out = step(s, batch, k)
                dm_state["n"] += 1
                if dm_state["n"] >= 10:
                    dm_mon.sample()
                    dm_state["n"] = 0
                return out

            rate_dm, state = _timed_steps(
                devicemon_step, state,
                lambda i: batches[i % N_DISTINCT_BATCHES], key,
                TIMED_STEPS, batch_size, n_dev,
            )
            rate_dm = _publish(
                extras, "device_only_devicemon", rate_dm,
                flops_per_image, peak,
                suffix=" (device_only + per-step ledger count + "
                       "monitor sample every 10 steps)",
            )
            if rate_dm is not None:
                _devicemon_overhead_guard(extras, rate_dm, device_only)
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"devicemon overhead bench failed: "
                 f"{type(e).__name__}: {e}")

    # Lifecycle overhead pin (ISSUE 8): the same device_only window
    # with the self-healing layer ATTACHED BUT IDLE — one unarmed
    # lifecycle fault site + the idle-shadow branch per step, plus an
    # AlertManager carrying an on_fire action callback evaluated every
    # 10 steps (the flush-cadence wiring, far denser than any real
    # flush interval). Same ≤2% budget, shared guard math: a closed
    # loop that taxes the hot path while nothing is wrong would never
    # be left enabled in production.
    if not headline_serialized:
        try:
            from jama16_retina_tpu.obs import alerts as obs_alerts
            from jama16_retina_tpu.obs import faultinject
            from jama16_retina_tpu.obs.registry import Registry

            l_reg = Registry()
            l_actions: list = []
            l_mgr = obs_alerts.AlertManager(
                [obs_alerts.AlertRule("quality.canary_ok", "<", 1.0)],
                registry=l_reg, on_fire=l_actions.append,
            )
            idle_shadow = None  # the engine's per-request shadow branch
            l_state = {"n": 0}

            def lifecycle_step(s, batch, k):
                faultinject.check("lifecycle.swap")
                if idle_shadow is not None:
                    raise RuntimeError("unreachable: shadow idle")
                out = step(s, batch, k)
                l_state["n"] += 1
                if l_state["n"] >= 10:
                    l_mgr.evaluate()
                    l_state["n"] = 0
                return out

            rate_l, state = _timed_steps(
                lifecycle_step, state,
                lambda i: batches[i % N_DISTINCT_BATCHES], key,
                TIMED_STEPS, batch_size, n_dev,
            )
            rate_l = _publish(
                extras, "device_only_lifecycle", rate_l,
                flops_per_image, peak,
                suffix=" (device_only + idle lifecycle seams + "
                       "on_fire-carrying alert evaluate every 10 steps)",
            )
            if rate_l is not None:
                _lifecycle_overhead_guard(extras, rate_l, device_only)
            if l_actions:
                _log("lifecycle overhead bench: unexpected on_fire "
                     f"actions {l_actions}")
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"lifecycle overhead bench failed: "
                 f"{type(e).__name__}: {e}")

    # Cheap-path overhead pin (ISSUE 10): the same device_only window
    # plus the per-batch host bookkeeping the cascade + compile-cache
    # layer adds to every request — the escalation-band mask over a
    # batch of scores, the student/escalated row counters, and the
    # per-bucket compiled-executable table lookup the engine's dispatch
    # now performs. Same ≤2% budget, shared guard math.
    if not headline_serialized:
        try:
            from jama16_retina_tpu.obs.registry import Registry

            cp_reg = Registry()
            c_student = cp_reg.counter("serve.cascade.student_rows")
            c_escal = cp_reg.counter("serve.cascade.escalated_rows")
            compiled_table = {batch_size: step}
            cp_thresholds = (0.5,)
            cp_band = 0.05
            cp_scores = np.random.default_rng(13).random(batch_size)

            def cheappath_step(s, batch, k):
                fn = compiled_table.get(batch_size, step)
                out = fn(s, batch, k)
                mask = np.zeros(batch_size, bool)
                for thr in cp_thresholds:
                    mask |= np.abs(cp_scores - thr) <= cp_band
                c_student.inc(batch_size)
                n_esc = int(mask.sum())
                if n_esc:
                    c_escal.inc(n_esc)
                return out

            rate_cp, state = _timed_steps(
                cheappath_step, state,
                lambda i: batches[i % N_DISTINCT_BATCHES], key,
                TIMED_STEPS, batch_size, n_dev,
            )
            rate_cp = _publish(
                extras, "device_only_cheappath", rate_cp,
                flops_per_image, peak,
                suffix=" (device_only + cascade band mask/counters + "
                       "compiled-table lookup per batch)",
            )
            if rate_cp is not None:
                _cheappath_overhead_guard(extras, rate_cp, device_only)
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"cheap-path overhead bench failed: "
                 f"{type(e).__name__}: {e}")

    # Raw-speed train rows (ISSUE 11), mirroring the serve_dtype_*
    # pattern: the SAME device-only window with the train-side precision
    # axis at bf16 (fp32 master weights; train_lib._bf16_params), and —
    # where Mosaic lowers — the fused Pallas step path on top. Each
    # row's physics guard uses its own compiled program's FLOPs; the
    # _vs_fp32 ratios are the dials' measured payoff on this chip.
    if not headline_serialized:
        try:
            bf16_cfg = cfg.replace(train=dataclasses.replace(
                cfg.train, dtype="bf16"))
            step_b, state_b, batches_b, key_b = build_train_fixture(
                bf16_cfg, mesh, batch_size
            )
            flops_b = _flops_of(step_b, state_b, batches_b[0], key_b)
            rate_b, _ = _timed_steps(
                step_b, state_b,
                lambda i: batches_b[i % N_DISTINCT_BATCHES], key_b,
                TIMED_STEPS, batch_size, n_dev,
            )
            rate_b = _publish(
                extras, "train_dtype_bf16_images_per_sec", rate_b,
                flops_b / batch_size if flops_b else None, peak,
                suffix=" (train.dtype=bf16, fp32 master weights)",
            )
            if rate_b is not None:
                extras["train_dtype_bf16_vs_fp32"] = round(
                    rate_b / device_only, 2
                )
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"train dtype bench failed: {type(e).__name__}: {e}")

        # Fused-kernel rows only where Mosaic actually lowers: off-TPU
        # the kernels run in interpret mode — a correctness harness
        # that would bench Python, not the fused path.
        if jax.default_backend() == "tpu":
            try:
                fused_cfg = cfg.replace(train=dataclasses.replace(
                    cfg.train, dtype="bf16", use_pallas_fused=True))
                step_f, state_f, batches_f, key_f = build_train_fixture(
                    fused_cfg, mesh, batch_size
                )
                flops_f = _flops_of(step_f, state_f, batches_f[0], key_f)
                rate_f, _ = _timed_steps(
                    step_f, state_f,
                    lambda i: batches_f[i % N_DISTINCT_BATCHES], key_f,
                    TIMED_STEPS, batch_size, n_dev,
                )
                rate_f = _publish(
                    extras, "train_fused_images_per_sec", rate_f,
                    flops_f / batch_size if flops_f else None, peak,
                    suffix=" (train.dtype=bf16 + train.use_pallas_fused: "
                           "fused normalize+augment and fused adamw)",
                )
                if rate_f is not None:
                    extras["train_fused_vs_fp32"] = round(
                        rate_f / device_only, 2
                    )
            except Exception as e:  # pragma: no cover - bench emits JSON
                _log(f"train fused bench failed: {type(e).__name__}: {e}")
        else:
            _log("train_fused rows skipped: Mosaic needs the TPU "
                 "backend (interpret mode would bench Python)")

    # Checkpoint-save / eval stall rows (ISSUE 11): seconds the step
    # loop BLOCKS at a boundary — sync (the before) vs async/overlapped
    # (the after). Self-fencing: the sync save returns after the orbax
    # write was handed off with the host state materialized, and the
    # overlapped eval's residual stall is the result() join.
    try:
        import shutil
        import tempfile as _tf

        from jama16_retina_tpu import trainer as trainer_lib
        from jama16_retina_tpu.utils import checkpoint as ckpt_lib

        # Two separate Checkpointer dirs: orbax pins a manager's saves
        # to ONE thread (finalize-thread affinity), and these two rows
        # deliberately save from different threads.
        ck_dir = _tf.mkdtemp(prefix="bench_ckpt_stall_")
        ck = ckpt_lib.Checkpointer(ck_dir, max_to_keep=1)
        t0 = time.perf_counter()
        ck.save(1, jax.device_get(state), {"val_auc": 0.5})
        extras["ckpt_save_stall_sync_sec"] = round(
            time.perf_counter() - t0, 3
        )
        ck.wait()
        ck.close()
        ck_dir2 = _tf.mkdtemp(prefix="bench_ckpt_stall_async_")
        ck2 = ckpt_lib.Checkpointer(ck_dir2, max_to_keep=1)
        saver = ckpt_lib.AsyncSaver()
        t0 = time.perf_counter()
        snap_state = trainer_lib._state_snapshot(state)
        saver.submit(lambda: ck2.save(
            1, jax.device_get(snap_state), {"val_auc": 0.5}
        ))
        extras["ckpt_save_stall_sec"] = round(
            time.perf_counter() - t0, 3
        )
        saver.drain()
        saver.close()
        ck2.wait()
        ck2.close()
        shutil.rmtree(ck_dir, ignore_errors=True)
        shutil.rmtree(ck_dir2, ignore_errors=True)
        _log(f"ckpt save stall: sync {extras['ckpt_save_stall_sync_sec']}s "
             f"-> async {extras['ckpt_save_stall_sec']}s")

        # Eval stall: one full val-style forward pass + host AUC,
        # blocking the loop (before) vs overlapped behind train steps
        # with only the tail join left on the loop (after).
        ev_step = train_lib.make_eval_step(cfg, model, mesh=mesh)
        ev_batch = mesh_lib.shard_batch(
            {"image": rng.integers(
                0, 256, (cfg.eval.batch_size, size, size, 3), np.uint8
            )},
            mesh,
        )
        ev_labels = rng.integers(0, 2, (cfg.eval.batch_size,))

        def eval_pass(src):
            from jama16_retina_tpu.eval import metrics as metrics_lib

            for _ in range(5):
                probs = np.asarray(jax.device_get(ev_step(src, ev_batch)))
            if ev_labels.min() != ev_labels.max():
                metrics_lib.roc_auc(ev_labels.astype(np.float64), probs)
            return True

        eval_pass(state)  # compile + warm
        t0 = time.perf_counter()
        eval_pass(state)
        extras["eval_stall_blocking_sec"] = round(
            time.perf_counter() - t0, 3
        )
        snap_state = trainer_lib._state_snapshot(state)
        job = trainer_lib._BgJob(lambda: eval_pass(snap_state))
        stall = 0.0
        for i in range(10):
            state, _ = step(state, batches[i % N_DISTINCT_BATCHES], key)
        t0 = time.perf_counter()
        job.result()
        stall += time.perf_counter() - t0
        _fence(state)
        extras["eval_stall_sec"] = round(stall, 3)
        _log(f"eval stall: blocking {extras['eval_stall_blocking_sec']}s "
             f"-> overlapped residual {extras['eval_stall_sec']}s")
    except Exception as e:  # pragma: no cover - bench must emit JSON
        _log(f"stall rows bench failed: {type(e).__name__}: {e}")

    if args.chaos:
        _chaos_smoke(extras)
        _chaos_integrity(extras)
        _chaos_ingest(extras)
        _chaos_diagnose(extras)
        _chaos_device(extras)
        extras["chaos_ok"] = bool(
            extras.get("chaos_ok") and extras.get("chaos_integrity_ok")
            and extras.get("chaos_ingest_ok")
            and extras.get("diagnose_ok")
            and extras.get("device_ok")
        )

    # Augmentation stage alone: jnp vs fused pallas kernel on this chip.
    aug_imgs = jax.device_put(batches[0]["image"])
    try:
        extras["augment_jnp"] = round(_augment_rate(aug_imgs, cfg.data, False), 1)
        extras["augment_pallas"] = round(_augment_rate(aug_imgs, cfg.data, True), 1)
        _log(f"augment-only: jnp {extras['augment_jnp']} img/s, "
             f"pallas {extras['augment_pallas']} img/s")
    except Exception as e:  # pragma: no cover - bench must still emit JSON
        _log(f"augment microbench failed: {type(e).__name__}: {e}")

    if not args.skip_host:
        dirs = _ensure_bench_data(size)
        extras["host_decode_jpeg"] = round(_host_rate(dirs["jpeg"], cfg, size), 1)
        extras["host_parse_raw"] = round(_host_rate(dirs["raw"], cfg, size), 1)
        _log(f"host feed: jpeg-decode {extras['host_decode_jpeg']} img/s, "
             f"raw-parse {extras['host_parse_raw']} img/s")
        try:
            extras["host_grain_raw"] = round(
                _host_rate(dirs["raw"], cfg, size, loader="grain"), 1
            )
            _log(f"host feed (grain loader, raw): "
                 f"{extras['host_grain_raw']} img/s")
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"grain host bench failed: {type(e).__name__}: {e}")

        # End-to-end: the real pipeline (raw records) feeding the train
        # step through device_prefetch — what a training run actually gets.
        it = pipeline.device_prefetch(
            pipeline.train_batches(dirs["raw"], "train", cfg.data, size, seed=0),
            sharding=mesh_lib.batch_sharding(mesh),
            size=cfg.data.prefetch_batches,
        )
        rate, state = _timed_steps(
            step, state, lambda i: next(it), key, TIMED_STEPS, batch_size,
            n_dev, warmup=3,
        )
        _publish(extras, "pipeline_fed", rate, flops_per_image, peak)

        # HBM-resident loader (data.loader=hbm): whole split uploaded
        # once, per-step on-device gather — zero steady-state H2D, the
        # shipped answer to the axon H2D collapse (docs/PERF.md §H2D).
        try:
            from jama16_retina_tpu.data import hbm_pipeline

            t0 = time.time()
            hbm_it = hbm_pipeline.train_batches(
                dirs["raw"], "train", cfg.data, size, seed=0, mesh=mesh
            )
            _fence(next(hbm_it)["image"])  # decode + upload + first gather
            extras["hbm_load_first_sec"] = round(time.time() - t0, 2)
            # Warm-state-explicit re-measure (ISSUE 11 bench-noise fix):
            # the first-touch number swung 22.18 -> 2.73 s across rounds
            # (BENCH_r03 vs r05) with whatever page-cache/tf-graph state
            # the earlier host sections happened to leave behind. A
            # second construction over the same files is
            # deterministically WARM — that is the trajectory-comparable
            # number, published under the historical hbm_load_sec key;
            # the ambient first-touch stays alongside as
            # hbm_load_first_sec (cold only on a truly cold host).
            del hbm_it  # release the first copy's device residency
            t0 = time.time()
            hbm_it = hbm_pipeline.train_batches(
                dirs["raw"], "train", cfg.data, size, seed=0, mesh=mesh
            )
            _fence(next(hbm_it)["image"])
            extras["hbm_load_sec"] = round(time.time() - t0, 2)
            rate, state = _timed_steps(
                step, state, lambda i: next(hbm_it), key,
                TIMED_STEPS, batch_size, n_dev,
            )
            _publish(
                extras, "pipeline_fed_hbm", rate, flops_per_image, peak,
                suffix=(f" (hbm-resident loader; one-time load "
                        f"{extras['hbm_load_sec']}s)"),
            )
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"hbm pipeline bench failed: {type(e).__name__}: {e}")

        # Tiered loader (data.loader=tiered): partial HBM residency —
        # pin most rows, stream the rest through the parallel host
        # decoder with staged H2D. Measured at a PINNED partial budget
        # (the all-or-nothing hbm row above is the 100% endpoint, the
        # streamed row the 0% endpoint) so the ramp between them is a
        # real datapoint, not an extrapolation. Also asserts the
        # zero-budget fallback is bit-identical to the streamed tier.
        try:
            from jama16_retina_tpu.data import tiered_pipeline

            frac = tiered_residency_plan(BENCH_N_IMAGES, size)
            t_cfg = dataclasses.replace(
                cfg.data,
                tiered_resident_bytes=tiered_resident_bytes(
                    BENCH_N_IMAGES, size
                ),
            )
            t0 = time.time()
            tiered_it = tiered_pipeline.train_batches(
                dirs["raw"], "train", t_cfg, size, seed=0, mesh=mesh
            )
            _fence(next(tiered_it)["image"])  # resident decode + upload
            extras["tiered_load_sec"] = round(time.time() - t0, 2)
            extras["tiered_resident_fraction"] = round(frac, 3)
            rate, state = _timed_steps(
                step, state, lambda i: next(tiered_it), key,
                TIMED_STEPS, batch_size, n_dev,
            )
            _publish(
                extras, "pipeline_fed_tiered", rate, flops_per_image, peak,
                suffix=(f" (tiered loader, {frac:.0%} HBM-resident; "
                        f"one-time load {extras['tiered_load_sec']}s)"),
            )

            # Zero-budget fallback pin: the first batches of a
            # budget-0 tiered stream must be bit-identical to the
            # INDEPENDENT host-decoded reference sequence (plan ->
            # record ids -> direct decode; no staging/combine jit), so
            # the check can actually fail if the streamed tier's device
            # plumbing ever corrupts, reorders, or re-derives batches.
            z_cfg = dataclasses.replace(cfg.data, tiered_resident_bytes=0)
            a_it = tiered_pipeline.train_batches(
                dirs["raw"], "train", z_cfg, size, seed=0, mesh=mesh
            )
            b_it = tiered_pipeline.host_reference_batches(
                dirs["raw"], "train", cfg.data, size, seed=0,
                capacity_rows=0,
            )
            for _ in range(3):
                a, b = next(a_it), next(b_it)
                if not (
                    np.array_equal(np.asarray(a["image"]),
                                   np.asarray(b["image"]))
                    and np.array_equal(np.asarray(a["grade"]),
                                       np.asarray(b["grade"]))
                ):
                    raise RuntimeError(
                        "tiered loader at budget 0 diverged from the "
                        "streamed path — fallback contract broken"
                    )
            extras["tiered_zero_budget_fallback_ok"] = True
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"tiered pipeline bench failed: {type(e).__name__}: {e}")

        # Served loader (data.loader=served; ISSUE 17): the SAME tiered
        # epoch plan, but decode runs on the disaggregated ingest
        # service's decode plane and batches arrive over a
        # shared-memory ring + unix control socket. The bench hosts the
        # server in-process (its serve threads are the real ones) so
        # the protocol frames, slab copies, and credit round-trips are
        # all measured; only the process boundary is elided. Two rows:
        # pipeline_fed_served is the served twin of pipeline_fed_tiered
        # (1 consumer driving the train step; rides the physics guard
        # at the train step's FLOPs/image); pipeline_fed_served_x2 is
        # the decode-once proof — 2 concurrent consumers at the SAME
        # spec pull raw streams, and the service must hold each
        # consumer at (>=) the single-consumer tiered line while the
        # aggregate clears 1.5x single, which is only possible if
        # decode is paid once, not per consumer (the decode/served
        # counter ratio below is the ledger-level receipt). The x2 row
        # publishes with flops_per_image=None: raw stream pulls run no
        # train step, so there is no FLOPs ceiling to hold them to —
        # the guard passes the rate through by contract.
        try:
            import shutil
            import tempfile
            import threading

            from jama16_retina_tpu.data import hbm_pipeline, served
            from jama16_retina_tpu.ingest.server import IngestServer
            from jama16_retina_tpu.obs.registry import Registry

            ing_root = tempfile.mkdtemp(prefix="jama16-bench-ingest-")
            ing_reg = Registry()
            s_cfg = dataclasses.replace(
                cfg,
                data=dataclasses.replace(
                    cfg.data,
                    tiered_resident_bytes=tiered_resident_bytes(
                        BENCH_N_IMAGES, size
                    ),
                ),
                ingest=dataclasses.replace(
                    cfg.ingest,
                    socket_path=os.path.join(ing_root, "ingest.sock"),
                ),
            )
            server = IngestServer(dirs["raw"], s_cfg, registry=ing_reg)
            server.start()
            # Same capacity derivation as the tiered section above —
            # the spec pins it so the server's plan is bit-identical.
            capacity = hbm_pipeline.resident_row_capacity(
                size, n_dev,
                budget_bytes=tiered_resident_bytes(BENCH_N_IMAGES, size),
            )
            try:
                s1 = served.ServedStream(
                    s_cfg.ingest.socket_path, "bench-solo", "train",
                    seed=0, batch_size=batch_size, image_size=size,
                    capacity_rows=capacity,
                )
                it = pipeline.device_prefetch(
                    iter(s1), sharding=mesh_lib.batch_sharding(mesh),
                    size=cfg.data.prefetch_batches,
                )
                rate, state = _timed_steps(
                    step, state, lambda i: next(it), key,
                    TIMED_STEPS, batch_size, n_dev, warmup=3,
                )
                s1.close()
                _publish(
                    extras, "pipeline_fed_served", rate, flops_per_image,
                    peak, suffix=" (ingest service, 1 consumer)",
                )

                # x2: fresh seed so nothing is prepaid by the solo row
                # — the shared decode both consumers ride is the one
                # that happens DURING the timed window.
                d0 = ing_reg.counter("ingest.decode.batches").value
                v0 = ing_reg.counter("ingest.batches_served").value
                barrier = threading.Barrier(2)
                x2_rates = [0.0, 0.0]
                x2_errs: list = []

                def _x2_consume(idx: int) -> None:
                    st = served.ServedStream(
                        s_cfg.ingest.socket_path, f"bench-x2-{idx}",
                        "train", seed=1, batch_size=batch_size,
                        image_size=size, capacity_rows=capacity,
                    )
                    try:
                        next(st)  # attach + first fill outside the clock
                        barrier.wait(timeout=120)
                        t0 = time.perf_counter()
                        for _ in range(TIMED_STEPS):
                            next(st)
                        dt = time.perf_counter() - t0
                        x2_rates[idx] = TIMED_STEPS * batch_size / dt
                    except Exception as e:  # pragma: no cover
                        x2_errs.append(e)
                    finally:
                        st.close()

                x2_threads = [
                    threading.Thread(target=_x2_consume, args=(i,),
                                     daemon=True)
                    for i in range(2)
                ]
                for t in x2_threads:
                    t.start()
                for t in x2_threads:
                    t.join(timeout=300)
                if x2_errs:
                    raise x2_errs[0]
                agg = sum(x2_rates)
                each_min = min(x2_rates)
                decode_delta = ing_reg.counter(
                    "ingest.decode.batches").value - d0
                served_delta = ing_reg.counter(
                    "ingest.batches_served").value - v0
                extras["served_x2_each_min"] = round(each_min, 2)
                tiered_rate = extras.get("pipeline_fed_tiered")
                if tiered_rate:
                    extras["served_x2_each_vs_tiered"] = round(
                        each_min / tiered_rate, 2
                    )
                    extras["served_x2_each_holds_tiered"] = bool(
                        each_min >= tiered_rate
                    )
                solo_rate = extras.get("pipeline_fed_served")
                if solo_rate:
                    extras["served_x2_aggregate_vs_single"] = round(
                        agg / solo_rate, 2
                    )
                    extras["served_x2_decode_once"] = bool(
                        agg > 1.5 * solo_rate
                    )
                # Ledger receipt: 2 consumers at one spec served ~2
                # batches per decode. Re-decoding per consumer would
                # push the ratio to ~1.0; leave generous slack for the
                # run-ahead fill beyond the timed window.
                if served_delta:
                    extras["served_x2_decode_per_served"] = round(
                        decode_delta / served_delta, 3
                    )
                _publish(
                    extras, "pipeline_fed_served_x2", agg, None, peak,
                    suffix=(f" aggregate (2 consumers, each >= "
                            f"{round(each_min, 1)}; decode/served "
                            f"{extras.get('served_x2_decode_per_served')})"),
                )
            finally:
                server.close()
                shutil.rmtree(ing_root, ignore_errors=True)
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"served pipeline bench failed: {type(e).__name__}: {e}")

        # Raw-shard loader (data.loader=rawshard; ISSUE 7): the JPEG
        # split transcoded ONCE into mmap-able raw array shards
        # (scripts/transcode_shards.py), then streamed (zero residency
        # budget — the row this section isolates is the decode stage,
        # not the spill cache). host_rawshard is the host feed rate of
        # the shard decoder alone (the twin of host_parse_raw: what
        # the steady state rides instead of JPEG decode);
        # pipeline_fed_rawshard is the end-to-end train rate. The
        # bit-identity pin re-decodes the SOURCE JPEG records through
        # the streamed tier and compares — the transcode must be an
        # encoding change, never a data change.
        try:
            from jama16_retina_tpu.data import rawshard as rawshard_lib
            from jama16_retina_tpu.data import tiered_pipeline

            t0 = time.time()
            rawshard_lib.transcode_split(
                dirs["jpeg"], "train", image_size=size, shard_records=64
            )
            extras["rawshard_transcode_sec"] = round(time.time() - t0, 2)

            rs = rawshard_lib.RawShardSplit(
                rawshard_lib.default_shard_dir(dirs["jpeg"], size),
                "train", image_size=size, source_dir=dirs["jpeg"],
            )
            dec = rawshard_lib.RawShardDecoder(rs, workers=1)
            id_rng = np.random.default_rng(5)
            order = id_rng.permutation(len(rs))
            ids = [
                order[(i * batch_size + j) % len(rs)]
                for i in range(33) for j in range(batch_size)
            ]
            for j in range(3):  # warm the page cache (the steady state)
                dec.decode_batch(
                    ids[j * batch_size:(j + 1) * batch_size]
                )
            t0 = time.time()
            for i in range(3, 33):
                dec.decode_batch(
                    ids[i * batch_size:(i + 1) * batch_size]
                )
            dt = time.time() - t0
            dec.close()
            extras["host_rawshard"] = round(30 * batch_size / dt, 1)
            if extras.get("host_parse_raw"):
                extras["rawshard_vs_raw_parse"] = round(
                    extras["host_rawshard"] / extras["host_parse_raw"], 2
                )
            _log(f"host feed (rawshard mmap rows): "
                 f"{extras['host_rawshard']} img/s")

            r_cfg = dataclasses.replace(cfg.data, tiered_resident_bytes=0)
            raw_it = rawshard_lib.train_batches(
                dirs["jpeg"], "train", r_cfg, size, seed=0, mesh=mesh
            )
            rate, state = _timed_steps(
                step, state, lambda i: next(raw_it), key,
                TIMED_STEPS, batch_size, n_dev,
            )
            _publish(
                extras, "pipeline_fed_rawshard", rate, flops_per_image,
                peak,
                suffix=(" (AOT-transcoded raw shards, streamed; "
                        f"transcode {extras['rawshard_transcode_sec']}s "
                        "paid once offline)"),
            )
            if extras.get("pipeline_fed") and extras.get(
                    "pipeline_fed_rawshard"):
                # The host-feed ceiling rawshard removed is visible in
                # rawshard_vs_raw_parse; end-to-end it must at least
                # hold the streamed raw-record rate (whatever bottleneck
                # — H2D, device — comes next is shared by both paths).
                extras["rawshard_vs_pipeline_fed"] = round(
                    extras["pipeline_fed_rawshard"]
                    / extras["pipeline_fed"], 2
                )

            # Bit-identity pin (post-decode): the rawshard stream vs
            # the streamed tier decoding the SOURCE JPEG records.
            a_it = rawshard_lib.train_batches(
                dirs["jpeg"], "train", r_cfg, size, seed=0, mesh=mesh
            )
            b_it = tiered_pipeline.streamed_batches(
                dirs["jpeg"], "train", cfg.data, size, seed=0, mesh=mesh
            )
            for _ in range(3):
                a, b = next(a_it), next(b_it)
                if not (
                    np.array_equal(np.asarray(a["image"]),
                                   np.asarray(b["image"]))
                    and np.array_equal(np.asarray(a["grade"]),
                                       np.asarray(b["grade"]))
                ):
                    raise RuntimeError(
                        "rawshard batches diverged from the streamed "
                        "path — the AOT transcode changed the data, "
                        "not just the encoding"
                    )
            extras["rawshard_bit_identical_ok"] = True
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"rawshard bench failed: {type(e).__name__}: {e}")

        # Closed-loop ingest autotuner (data.autotune; ISSUE 7): the
        # tiered loader at the SAME pinned 7/8-resident budget as the
        # tiered row, but started from deliberately PESSIMAL knobs
        # (1 decode worker, stage depth 1, prefetch 1 — the floor a
        # misconfigured deployment would sit at). The tuner observes
        # tumbling windows of the loop's own input-wait attribution
        # and climbs the knobs online; the timed window then measures
        # the CONVERGED steady state, and the JSON records the final
        # knob values + adjustment count so the trajectory captures
        # WHY the feed rate moved, not just that it did.
        if not args.skip_autotune:
            try:
                from jama16_retina_tpu.data import autotune as autotune_lib
                from jama16_retina_tpu.data import tiered_pipeline
                from jama16_retina_tpu.obs.spans import StallClock

                a_data = dataclasses.replace(
                    cfg.data,
                    autotune=True, decode_workers=1, stage_depth=1,
                    prefetch_batches=1,
                    tiered_resident_bytes=tiered_resident_bytes(
                        BENCH_N_IMAGES, size
                    ),
                )
                a_cfg = cfg.replace(data=a_data)
                knobs, tuner = autotune_lib.for_config(a_cfg, mesh=mesh)
                t0 = time.time()
                tuned_it = tiered_pipeline.train_batches(
                    dirs["raw"], "train", a_data, size, seed=0, mesh=mesh,
                    knobs=knobs,
                )
                _fence(next(tuned_it)["image"])
                extras["autotuned_load_sec"] = round(time.time() - t0, 2)

                # Convergence windows: 10 tumbling windows of 6 steps,
                # exactly the trainer's wiring (StallClock input
                # attribution -> tuner.observe at the boundary).
                stalls = StallClock(None)
                for _ in range(10):
                    for i in range(6):
                        with stalls.measure("input"):
                            b = next(tuned_it)
                        state, _ = step(state, b, key)
                    f = stalls.fields()
                    tuner.observe(
                        f["window_sec"], f["input_wait_sec"]
                    )
                rate, state = _timed_steps(
                    step, state, lambda i: next(tuned_it), key,
                    TIMED_STEPS, batch_size, n_dev,
                )
                extras["autotune_final_knobs"] = knobs.as_dict()
                extras["autotune_adjustments"] = int(
                    tuner._c_adjust.value
                )
                _publish(
                    extras, "pipeline_fed_autotuned", rate,
                    flops_per_image, peak,
                    suffix=(" (tiered loader, autotuner converged from "
                            "pessimal knobs in "
                            f"{extras['autotune_adjustments']} "
                            f"adjustments -> {extras['autotune_final_knobs']})"),
                )
            except Exception as e:  # pragma: no cover - bench must emit JSON
                _log(f"autotune bench failed: {type(e).__name__}: {e}")

    # Eval-side rate: the forward-only jit eval step at the eval batch
    # size — multiply by k models x test-set size for the ensemble
    # evaluation cost (ten-model protocol, BASELINE.json:10).
    try:
        eval_step = train_lib.make_eval_step(cfg, model, mesh=mesh)
        eval_bs = cfg.eval.batch_size
        eval_batch = mesh_lib.shard_batch(
            {"image": rng.integers(0, 256, (eval_bs, size, size, 3), np.uint8)},
            mesh,
        )
        eval_flops = _flops_of(eval_step, state, eval_batch)
        # 100 iterations ≈ 1-2s window: the ~22ms fixed sync cost on this
        # tunnel is >2% of a 30-iteration window and was visibly noising
        # the forward-only rates run to run.
        rate = _timed_forward(
            lambda i: eval_step(state, eval_batch), 100, eval_bs, n_dev
        )
        _publish(
            extras, "eval_images_per_sec", rate,
            eval_flops / eval_bs if eval_flops else None, peak,
            suffix=f" (batch {eval_bs}, forward-only)",
        )
    except Exception as e:  # pragma: no cover - bench must emit JSON
        _log(f"eval bench failed: {type(e).__name__}: {e}")

    # The NORTH-STAR hardware shape (VERDICT r4 #3): BASELINE.json:5
    # names global batch 32 on a v3-8 slice — 4 images/chip. Every
    # other row here measures per-chip batch >=32 (this chip's sweet
    # spot), so the pod-slice story was extrapolated; this row measures
    # the actual per-replica shard. Steps are ~ms at batch 4, so take
    # 100 of them; the same physics guard applies. Expect well below
    # b32's rate — the stem is HBM-bound and batch 4 amortizes nothing
    # (docs/PERF.md §Pod translates this number to the v3-8 target).
    # Runs BEFORE b128: the donating step chains `state`, and b128 (the
    # most OOM-prone batch) must not be able to poison this row.
    try:
        b4 = 4 * n_dev
        b4_batches = [
            mesh_lib.shard_batch(
                {
                    "image": rng.integers(
                        0, 256, (b4, size, size, 3), np.uint8
                    ),
                    "grade": rng.integers(0, 5, (b4,), np.int32),
                },
                mesh,
            )
            for _ in range(2)
        ]
        rate, state = _timed_steps(
            step, state, lambda i: b4_batches[i % 2], key, 100, b4, n_dev
        )
        _publish(
            extras, "device_only_b4", rate, flops_per_image, peak,
            suffix=" (batch 4/chip: the v3-8 north-star per-replica shard)",
        )
    except Exception as e:  # pragma: no cover - bench must emit JSON
        _log(f"batch-4 bench failed: {type(e).__name__}: {e}")
        # The donating step may have consumed `state`'s buffers before
        # the failure; rebuild so the b128/ensemble sections below
        # measure from a valid state instead of use-after-donate.
        _, state, _, _ = build_train_fixture(cfg, mesh, batch_size)

    # Batch-scaling datapoint: per-chip batch 128 (see docstring). Placed
    # AFTER every section that reads `state`: the donating step consumes
    # its buffers, and a mid-section failure here must not poison a
    # later measurement. A second compile (~40s); the measurement ~2s.
    if not args.skip_b128:
        try:
            big = 128 * n_dev
            big_batches = [
                mesh_lib.shard_batch(
                    {
                        "image": rng.integers(
                            0, 256, (big, size, size, 3), np.uint8
                        ),
                        "grade": rng.integers(0, 5, (big,), np.int32),
                    },
                    mesh,
                )
                for _ in range(2)
            ]
            rate, state = _timed_steps(
                step, state, lambda i: big_batches[i % 2], key, 20, big, n_dev
            )
            _publish(
                extras, "device_only_b128", rate, flops_per_image, peak,
                suffix=" (batch 128/chip)",
            )
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"batch-128 bench failed: {type(e).__name__}: {e}")

    # Member-parallel ensemble training (train_lib.make_ensemble_train_step):
    # 4 stacked members, one program, same batch-32 workload. The
    # speedup column is what the stacked form buys over 4 sequential
    # member-steps — the reference's k-sequential ensemble protocol is
    # the denominator of the <1h wall-clock target (BASELINE.json:5,10).
    if not args.skip_ensemble:
        try:
            k = 4
            ens_state, ens_tx = train_lib.create_ensemble_state(
                cfg, model, list(range(k))
            )
            ens_state = jax.device_put(ens_state, mesh_lib.replicated(mesh))
            ens_step = train_lib.make_ensemble_train_step(
                cfg, model, ens_tx, mesh=None
            )
            ens_keys = train_lib.stack_member_keys(list(range(k)))
            rate, _ = _timed_steps(
                lambda st, b, key: ens_step(st, b, ens_keys),
                ens_state, lambda i: batches[i % N_DISTINCT_BATCHES], key,
                20, k * batch_size, n_dev,
            )
            rate = _publish(
                extras, "ensemble4_member_images_per_sec", rate,
                flops_per_image, peak,
                suffix=" (member-img/s, k=4 stacked step)",
            )
            if rate is not None and not headline_serialized:
                # Ratio only against a like-measured denominator: a
                # serialized-fallback headline is deliberately
                # pessimistic, and dividing the pipelined ensemble rate
                # by it would overstate the speedup. This step runs
                # replicated (mesh=None), never member-sharded, so the
                # wide-mesh un-gate must not apply however many
                # (possibly fake) devices the host shows.
                _gate_ensemble_speedup(extras, rate, device_only, n_dev,
                                       member_sharded=False)
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"ensemble bench failed: {type(e).__name__}: {e}")

    # Serving engine (serve/engine.py): the inference half of the north
    # star under the same discipline. Throughput sections are
    # self-fencing — every engine.probs() call returns HOST numpy
    # probabilities, so a timing window cannot close before the device
    # work ran — and every published img/s rides the same physics guard
    # as the train rates (FLOPs from the compiled serving program).
    if not args.skip_serve:
        try:
            from jama16_retina_tpu.eval import metrics as metrics_lib
            from jama16_retina_tpu.serve.engine import ServingEngine

            eval_bs = cfg.eval.batch_size
            serve_cfg = cfg.replace(serve=dataclasses.replace(
                cfg.serve, max_batch=eval_bs, bucket_sizes=(eval_bs,),
            ))
            imgs = rng.integers(
                0, 256, (eval_bs, size, size, 3), np.uint8
            )

            # k=1 saturated throughput at the eval batch size — the
            # engine's overhead over the bare eval step (bucket pad,
            # staged H2D, per-call D2H fetch) is exactly what this
            # number exposes; acceptance bar is >= 0.9x
            # eval_images_per_sec at the same batch size.
            st1, _ = train_lib.create_ensemble_state(cfg, model, [0])
            eng1 = ServingEngine(
                serve_cfg, model=model, mesh=mesh, state=st1
            )
            serve_flops = _flops_of(eng1._step, eng1.state, {"image": imgs})
            eng1.probs(imgs)  # compile + warm
            n_calls = 50
            t0 = time.perf_counter()
            for _ in range(n_calls):
                eng1.probs(imgs)
            dt = time.perf_counter() - t0
            rate1 = _publish(
                extras, "serve_images_per_sec",
                n_calls * eval_bs / dt / n_dev,
                serve_flops / eval_bs if serve_flops else None, peak,
                suffix=f" (k=1 engine, batch {eval_bs}, host-fetched "
                       "probs each call)",
            )
            if rate1 is not None and serve_flops:
                # Serving-side MFU at this bucket (ISSUE 19): same
                # rate/FLOPs/peak triple as the guard, as a fraction.
                extras[f"serve_mfu_b{eval_bs}"] = round(
                    rate1 * (serve_flops / eval_bs) / peak, 4)

            # k=4 ensemble serving: images THROUGH the whole ensemble
            # per second (each image costs 4 member passes — the guard
            # uses the stacked program's own FLOPs, which include all
            # members).
            k = 4
            st4, _ = train_lib.create_ensemble_state(
                cfg, model, list(range(k))
            )
            eng4 = ServingEngine(
                serve_cfg, model=model, mesh=mesh, state=st4
            )
            serve4_flops = _flops_of(eng4._step, eng4.state, {"image": imgs})
            flops4_per_image = (
                serve4_flops / eval_bs if serve4_flops else None
            )
            eng4.probs(imgs)
            n_calls = 25
            t0 = time.perf_counter()
            for _ in range(n_calls):
                eng4.probs(imgs)
            dt = time.perf_counter() - t0
            rate4 = _publish(
                extras, "serve_ensemble4_images_per_sec",
                n_calls * eval_bs / dt / n_dev, flops4_per_image, peak,
                suffix=f" (k=4 stacked engine, batch {eval_bs})",
            )

            # The pre-engine predict.py path on the SAME inputs: k
            # sequential member forwards per batch at predict's default
            # --batch_size 8, each host-fetched before the next member
            # dispatches (the acceptance ratio's denominator; restores
            # and per-process compiles are NOT charged to it, so the
            # measured speedup is conservative).
            pb = 8
            seq_eval_step = train_lib.make_eval_step(cfg, model, mesh=mesh)
            members = [
                jax.device_put(
                    train_lib.unstack_member(st4, m),
                    mesh_lib.replicated(mesh),
                )
                for m in range(k)
            ]
            blocks = [imgs[i:i + pb] for i in range(0, eval_bs, pb)]

            def seq_pass():
                prob_list = [
                    np.concatenate([
                        np.asarray(seq_eval_step(stm, {"image": b}))
                        for b in blocks
                    ])
                    for stm in members
                ]
                return metrics_lib.ensemble_average(prob_list)

            seq_pass()  # compile + warm
            reps = 8
            t0 = time.perf_counter()
            for _ in range(reps):
                seq_pass()
            dt = time.perf_counter() - t0
            rate_seq = _publish(
                extras, "serve_sequential_members_images_per_sec",
                reps * eval_bs / dt / n_dev, flops4_per_image, peak,
                suffix=f" (k=4 sequential member dispatches, batch {pb} — "
                       "the pre-engine predict.py path)",
            )
            if rate4 is not None and rate_seq is not None:
                extras["serve_ensemble4_vs_sequential"] = round(
                    rate4 / rate_seq, 2
                )

            # Offered-load latency: closed-loop single-image submitters
            # through the micro-batcher at several concurrency levels.
            # Two buckets (8 and eval_bs) bound the compile count while
            # letting lone requests run a small shape.
            lat_cfg = cfg.replace(serve=dataclasses.replace(
                cfg.serve, max_batch=eval_bs, bucket_sizes=(8, eval_bs),
                max_wait_ms=2.0,
            ))
            eng_l = ServingEngine(
                lat_cfg, model=model, mesh=mesh, state=st4
            )
            eng_l.probs(imgs[:8])
            eng_l.probs(imgs)  # compile both buckets
            one = imgs[:1]
            for conc in (1, 8, 32):
                batcher = eng_l.make_batcher()
                try:
                    lats, window = _offered_load(
                        batcher.submit, conc, 20, lambda w, i: one
                    )
                finally:
                    batcher.close()
                s = _latency_summary(lats)
                extras[f"serve_p50_ms_c{conc}"] = s["p50_ms"]
                extras[f"serve_p99_ms_c{conc}"] = s["p99_ms"]
                _publish(
                    extras, f"serve_offered_images_per_sec_c{conc}",
                    len(lats) / window / n_dev, flops4_per_image, peak,
                    suffix=f" (closed loop, {conc} submitters; p50 "
                           f"{s['p50_ms']} ms / p99 {s['p99_ms']} ms)",
                )
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"serve bench failed: {type(e).__name__}: {e}")

        # Per-dtype serving rows (ISSUE 10): the SAME k=4 stacked
        # workload with the engine's precision axis at bf16 (cast
        # stacked params — half the weight HBM traffic) and int8 (AQT
        # per-channel weight quantization, dequant fused into the one
        # serving program). Each row's physics guard uses its own
        # compiled program's FLOPs; the _vs_fp32 ratio is the dial's
        # measured payoff on this chip.
        try:
            for d in ("bf16", "int8"):
                dcfg = serve_cfg.replace(serve=dataclasses.replace(
                    serve_cfg.serve, dtype=d,
                ))
                eng_d = ServingEngine(
                    dcfg, model=model, mesh=mesh, state=st4
                )
                flops_d = _flops_of(
                    eng_d._step, eng_d.state, {"image": imgs}
                )
                eng_d.probs(imgs)  # compile + warm
                n_calls = 25
                t0 = time.perf_counter()
                for _ in range(n_calls):
                    eng_d.probs(imgs)
                dt = time.perf_counter() - t0
                rate_d = _publish(
                    extras, f"serve_dtype_{d}_images_per_sec",
                    n_calls * eval_bs / dt / n_dev,
                    flops_d / eval_bs if flops_d else None, peak,
                    suffix=f" (k=4 stacked engine, serve.dtype={d})",
                )
                if rate_d is not None and rate4 is not None:
                    extras[f"serve_dtype_{d}_vs_fp32"] = round(
                        rate_d / rate4, 2
                    )
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"serve dtype bench failed: {type(e).__name__}: {e}")

        # Distilled-cascade speedup (ISSUE 10 acceptance): student (k=1)
        # scores everything, only rows inside the escalation band pay
        # the k=4 stacked ensemble. The band is CALIBRATED on the
        # student's own score distribution so ~15% of rows escalate —
        # the <=20% regime the >=2x acceptance bar names (a synthetic
        # stand-in for "most traffic is nowhere near the operating
        # thresholds", which random-init members cannot exhibit
        # naturally).
        try:
            from jama16_retina_tpu.serve.cascade import CascadeEngine

            s_scores = np.asarray(eng1.probs(imgs), np.float64)
            thr = float(np.quantile(s_scores, 0.85))
            band = float(np.quantile(np.abs(s_scores - thr), 0.15))
            casc_cfg = serve_cfg.replace(serve=dataclasses.replace(
                serve_cfg.serve,
                cascade_band=band, cascade_thresholds=(thr,),
            ))
            casc = CascadeEngine(casc_cfg, eng1, eng4)
            casc.probs(imgs)  # warm both halves through the cascade
            c_student = casc._c_student_rows.value
            c_escal = casc._c_escalated_rows.value
            n_calls = 25
            t0 = time.perf_counter()
            for _ in range(n_calls):
                casc.probs(imgs)
            dt = time.perf_counter() - t0
            d_student = casc._c_student_rows.value - c_student
            d_escal = casc._c_escalated_rows.value - c_escal
            frac = d_escal / max(1.0, d_student)
            rate_c = _publish(
                extras, "serve_cascade_images_per_sec",
                n_calls * eval_bs / dt / n_dev,
                serve_flops / eval_bs if serve_flops else None, peak,
                suffix=(f" (distilled cascade, {frac:.0%} of rows "
                        "escalated to the k=4 ensemble)"),
            )
            extras["cascade_escalated_fraction"] = round(frac, 3)
            if rate_c is not None and rate4 is not None:
                extras["cascade_speedup"] = round(rate_c / rate4, 2)
                _log(f"cascade_speedup: {extras['cascade_speedup']}x "
                     f"over the always-stacked k=4 baseline")
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"cascade bench failed: {type(e).__name__}: {e}")

        # Zero cold-start (ISSUE 10): construction -> first served
        # request, cold (empty persistent compile cache: every bucket
        # is one real AOT compile, saved) vs warm (a second engine over
        # the SAME cache: every bucket deserializes). The warm number
        # is what an engine restart / reload-candidate warmup costs
        # with the cache populated. (On repeat bench invocations the
        # cold row may understate a true first-boot compile: jax's own
        # persistent compilation cache — enabled process-wide above —
        # can pre-warm the lower+compile; the hit/miss counters in
        # tests pin the reuse contract exactly.)
        try:
            import shutil
            import tempfile

            cache_dir = os.path.join(
                tempfile.gettempdir(), "retina_bench_serve_cache"
            )
            shutil.rmtree(cache_dir, ignore_errors=True)
            cache_cfg = serve_cfg.replace(serve=dataclasses.replace(
                serve_cfg.serve, compile_cache_dir=cache_dir,
            ))
            t0 = time.perf_counter()
            eng_cold = ServingEngine(
                cache_cfg, model=model, mesh=mesh, state=st4
            )
            eng_cold.probs(imgs)
            cold = time.perf_counter() - t0
            t0 = time.perf_counter()
            eng_warm = ServingEngine(
                cache_cfg, model=model, mesh=mesh, state=st4
            )
            eng_warm.probs(imgs)
            warm = time.perf_counter() - t0
            extras["serve_cold_start_sec"] = round(cold, 2)
            extras["serve_warm_start_sec"] = round(warm, 2)
            extras["serve_warm_start_frac"] = round(warm / cold, 3)
            _log(f"serve cold start {cold:.2f}s -> warm restart "
                 f"{warm:.2f}s ({warm / cold:.1%}) off the persistent "
                 "compile cache")
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"compile-cache bench failed: {type(e).__name__}: {e}")

        # Latency/throughput frontier (ISSUE 10 satellite; "Batch Size
        # Influence on GPU/TPU Performance", PAPERS.md): sweep
        # serve.bucket_sizes x offered concurrency instead of the PR-2
        # spot checks, so bucket policy is chosen from a MEASURED
        # frontier. One serving compile per swept bucket.
        if not args.skip_frontier:
            try:
                frontier = []
                # Small buckets (2, 4) in the default grid (ISSUE 16):
                # the v2 interactive class is derived from measured
                # single-request-scale points, not extrapolated.
                for b in sorted({2, 4, 8, 16, eval_bs}):
                    fcfg = cfg.replace(serve=dataclasses.replace(
                        cfg.serve, max_batch=b, bucket_sizes=(b,),
                        max_wait_ms=2.0,
                    ))
                    eng_f = ServingEngine(
                        fcfg, model=model, mesh=mesh, state=st4
                    )
                    eng_f.probs(imgs[:b])  # compile + warm
                    one = imgs[:1]
                    for conc in (1, 8, 32):
                        batcher = eng_f.make_batcher()
                        try:
                            lats, window = _offered_load(
                                batcher.submit, conc, 20,
                                lambda w, i: one,
                            )
                        finally:
                            batcher.close()
                        s = _latency_summary(lats)
                        rate = len(lats) / window / n_dev
                        guarded = _physics_guard(
                            f"serve_frontier_b{b}_c{conc}", rate,
                            flops4_per_image, peak,
                        )
                        frontier.append({
                            "bucket": int(b),
                            "concurrency": int(conc),
                            "images_per_sec": (
                                round(rate, 2) if guarded is not None
                                else None
                            ),
                            "p50_ms": s["p50_ms"],
                            "p99_ms": s["p99_ms"],
                        })
                        _log(
                            f"frontier b{b} c{conc}: "
                            f"{rate:.1f} img/s, p50 {s['p50_ms']} ms / "
                            f"p99 {s['p99_ms']} ms"
                        )
                extras["serve_frontier"] = frontier
            except Exception as e:  # pragma: no cover - bench emits JSON
                _log(f"serve frontier bench failed: "
                     f"{type(e).__name__}: {e}")

        # Small-batch fusion recovery (ISSUE 16 tentpole a): two
        # tenants each offering batch-4 requests — the device_only_b4
        # regime where a lone small dispatch leaves the chip far under
        # b128 utilization (BENCH_r05: 359.7 vs ~2000 img/s/chip) —
        # routed with serve.router_fusion on, so the tenants' agreeing
        # programs share ONE stacked b8 dispatch (demuxed by offset),
        # vs the SAME offered load unfused (per-tenant b4 bins).
        # Acceptance: fused >= 1.5x the unfused same-run baseline
        # (smallbatch_fusion_ok). Mesh-less engines only — the
        # serve/fusion.py contract — so multi-device runs skip the row.
        try:
            if n_dev == 1:
                import threading as _threading

                from jama16_retina_tpu.obs.registry import (
                    Registry as _Reg,
                )
                from jama16_retina_tpu.serve.router import (
                    Router as _Router,
                )

                SB = 4
                PER_TENANT_WORKERS = 2
                SB_REQS = 25
                st1b, _ = train_lib.create_ensemble_state(
                    cfg, model, [1]
                )

                def _smallbatch_rate(fused: bool):
                    reg = _Reg()
                    fcfg = cfg.replace(serve=dataclasses.replace(
                        cfg.serve, max_batch=2 * SB,
                        bucket_sizes=(SB, 2 * SB), max_wait_ms=3.0,
                        router_fusion=fused,
                    ))
                    eng_a = ServingEngine(
                        fcfg, model=model, mesh=None, state=st1
                    )
                    eng_b = ServingEngine(
                        fcfg, model=model, mesh=None, state=st1b
                    )
                    for e in (eng_a, eng_b):  # compile both buckets
                        e.probs(imgs[:SB])
                        e.probs(imgs[:2 * SB])
                    if fused:
                        # Whether the storm's bins actually mix
                        # tenants is timing-dependent, so the k=2
                        # stacked program may otherwise first compile
                        # INSIDE the timed window (at 299px that
                        # compile dominates it). Warm it directly
                        # with the same raw-uint8 rows submit bins.
                        from jama16_retina_tpu.serve import (
                            fusion as fusion_lib,
                        )

                        class _Part:
                            __slots__ = ("model",)

                            def __init__(self, model):
                                self.model = model

                        fusion_lib.score_mixed(
                            {"a": eng_a, "b": eng_b}, imgs[:2 * SB],
                            [(_Part("a"), 0, SB), (_Part("b"), 0, SB)],
                            2 * SB, cache=None,
                        )
                    router = _Router(
                        fcfg,
                        engines={"a": [eng_a], "b": [eng_b]},
                        registry=reg,
                    )
                    block = imgs[:SB]
                    lock = _threading.Lock()
                    lats: list = []
                    errs: list = []

                    def run_worker(m, nreq):
                        try:
                            for _ in range(nreq):
                                t0 = time.perf_counter()
                                router.submit(block, model=m).result()
                                dt = time.perf_counter() - t0
                                with lock:
                                    lats.append(dt)
                        except Exception as e:  # noqa: BLE001
                            errs.append(e)

                    def storm(nreq):
                        threads = [
                            _threading.Thread(
                                target=run_worker, args=(m, nreq)
                            )
                            for m in ("a", "b")
                            for _ in range(PER_TENANT_WORKERS)
                        ]
                        t0 = time.perf_counter()
                        for t in threads:
                            t.start()
                        for t in threads:
                            t.join()
                        return time.perf_counter() - t0

                    try:
                        storm(3)  # warm the fused/group programs
                        lats.clear()
                        window = storm(SB_REQS)
                    finally:
                        router.close()
                    if errs:
                        raise errs[0]
                    fused_bins = int(
                        reg.counter("serve.router.fused_bins").value
                        if fused else 0
                    )
                    total = 2 * PER_TENANT_WORKERS * SB_REQS * SB
                    return total / window, fused_bins

                rate_f, fused_bins = _smallbatch_rate(True)
                rate_u, _ = _smallbatch_rate(False)
                flops1_per_image = (
                    serve_flops / eval_bs if serve_flops else None
                )
                _publish(
                    extras, "serve_smallbatch_images_per_sec", rate_f,
                    # A fused image is forwarded by BOTH tenants'
                    # members (useful rows halve the stacked program).
                    2 * flops1_per_image if flops1_per_image else None,
                    peak,
                    suffix=(f" (2 tenants x b{SB} requests fused into "
                            f"b{2 * SB} bins; {fused_bins} fused "
                            "bins)"),
                )
                _publish(
                    extras, "serve_smallbatch_unfused_images_per_sec",
                    rate_u, flops1_per_image, peak,
                    suffix=f" (same offered load, per-tenant b{SB} "
                           "bins)",
                )
                ratio = rate_f / rate_u
                extras["serve_smallbatch_fused_vs_unfused"] = round(
                    ratio, 2
                )
                extras["serve_smallbatch_fused_bins"] = fused_bins
                ok_sb = ratio >= 1.5 and fused_bins > 0
                extras["smallbatch_fusion_ok"] = ok_sb
                if not ok_sb:
                    _log(
                        f"SMALLBATCH FUSION VIOLATION: fused "
                        f"{rate_f:.1f} img/s is only {ratio:.2f}x the "
                        f"unfused {rate_u:.1f} ({fused_bins} fused "
                        "bins; acceptance >= 1.5x)"
                    )
                else:
                    _log(
                        f"smallbatch fusion: {rate_f:.1f} img/s fused "
                        f"vs {rate_u:.1f} unfused ({ratio:.2f}x, "
                        f"{fused_bins} fused bins)"
                    )
            else:
                _log(
                    "smallbatch fusion row skipped: serve/fusion.py "
                    f"fuses mesh-less engines only (n_dev={n_dev})"
                )
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"smallbatch fusion bench failed: "
                 f"{type(e).__name__}: {e}")

    # Front-door router scaling (ISSUE 12): off-device, no compiles.
    if not args.skip_router:
        try:
            _router_bench(extras)
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"router bench failed: {type(e).__name__}: {e}")
        # Interactive latency rows (ISSUE 16): off-device, no compiles.
        try:
            _interactive_bench(extras)
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"interactive bench failed: {type(e).__name__}: {e}")

    # Time-to-AUC rows (ISSUE 11): the north-star's FIRST clause lands
    # in the trajectory JSON instead of living only in the side script.
    # Two smoke-scale fit_ensemble runs (member-parallel, hbm loader)
    # through scripts/time_to_auc.py's own harness: fp32, then bf16 at
    # the same seed/recipe — wall seconds from trainer start to the
    # first ensemble-val crossing of the fixed target.
    if not args.skip_time_to_auc:
        try:
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "time_to_auc",
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "scripts", "time_to_auc.py"),
            )
            tta = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(tta)
            common = [
                "--smoke", "--k", "2", "--steps", "120",
                "--eval_every", "20", "--train_n", "256",
                "--val_n", "128", "--test_n", "128", "--bootstrap", "50",
                "--target", str(args.time_to_auc_target),
            ]
            extras["time_to_auc_target"] = args.time_to_auc_target
            r32 = tta.main(common, print_json=False)
            extras["time_to_auc_sec_fp32"] = r32["value"]
            _log(f"time_to_auc fp32: {r32['value']} s to AUC >= "
                 f"{args.time_to_auc_target} (crossed={r32['crossed']})")
            rbf = tta.main(common + ["--train_dtype", "bf16"],
                           print_json=False)
            extras["time_to_auc_sec_bf16"] = rbf["value"]
            _log(f"time_to_auc bf16: {rbf['value']} s to AUC >= "
                 f"{args.time_to_auc_target} (crossed={rbf['crossed']})")
            if r32["value"] and rbf["value"]:
                extras["time_to_auc_bf16_speedup"] = round(
                    r32["value"] / rbf["value"], 2
                )
            # The LAMB large-batch recipe (ISSUE 14): 2x the global
            # batch under linear-scaled LR + trust-ratio adaptation,
            # same seed/target — the first-class acceptance row is the
            # wall-clock ratio vs the adamw reference-batch run above.
            rlamb = tta.main(common + [
                "--optimizer", "lamb", "--global_batch", "64",
                "--lr_scale_ref_batch", "32",
            ], print_json=False)
            extras["time_to_auc_sec_lamb"] = rlamb["value"]
            _log(f"time_to_auc lamb (global batch 64, scaled LR): "
                 f"{rlamb['value']} s to AUC >= "
                 f"{args.time_to_auc_target} "
                 f"(crossed={rlamb['crossed']})")
            if r32["value"] and rlamb["value"]:
                extras["time_to_auc_lamb_speedup"] = round(
                    r32["value"] / rlamb["value"], 2
                )
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"time_to_auc bench failed: {type(e).__name__}: {e}")

    # Mesh-scaling rows (ISSUE 14): the pjit+LAMB train step and the
    # ASSEMBLED serving engine measured across simulated device counts
    # (scripts/dryrun_multichip.py — fresh subprocess per count; each
    # fake device computes single-threaded so the rows report device
    # parallelism, not intra-op thread count).
    if not args.skip_mesh:
        try:
            import importlib.util as _ilu

            spec = _ilu.spec_from_file_location(
                "dryrun_multichip_script",
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "scripts", "dryrun_multichip.py"),
            )
            drm = _ilu.module_from_spec(spec)
            spec.loader.exec_module(drm)
            mesh_rows = drm.run_counts(
                [1, 4], steps=8, batch_per_device=64, serve_rows=64
            )
            extras.update({
                k: v for k, v in mesh_rows.items()
                if "images_per_sec" in k or "_vs_d1" in k
            })
        except Exception as e:  # pragma: no cover - bench must emit JSON
            _log(f"mesh-scaling bench failed: {type(e).__name__}: {e}")

    # Post-run HBM high-water mark (ISSUE 19): the peak occupancy the
    # whole bench reached on any local device, as a fraction of that
    # device's limit — the trend row that catches a memory regression
    # before it becomes an OOM. Skipped quietly where the backend
    # exposes no memory_stats (CPU).
    try:
        fracs = []
        for d in jax.local_devices():
            stats_fn = getattr(d, "memory_stats", None)
            stats = stats_fn() if callable(stats_fn) else None
            if stats and stats.get("bytes_limit"):
                fracs.append(
                    float(stats.get("peak_bytes_in_use", 0))
                    / float(stats["bytes_limit"])
                )
        if fracs:
            extras["hbm_peak_frac"] = round(max(fracs), 4)
            _log(f"hbm_peak_frac: {extras['hbm_peak_frac']}")
    except Exception as e:  # pragma: no cover - bench must emit JSON
        _log(f"hbm peak sampling failed: {type(e).__name__}: {e}")

    extras["device_only"] = round(device_only, 2)
    print(json.dumps({
        "metric": "train_images_per_sec_per_chip",
        "value": round(device_only, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(device_only / BASELINE_IMAGES_PER_SEC_PER_CHIP, 3),
        **extras,
    }))


if __name__ == "__main__":
    main()
