#!/usr/bin/env python
"""Preprocess Kaggle EyePACS -> fundus-normalized 299x299 TFRecord shards
(reference entry point of the same name, SURVEY.md §3.3 / BASELINE.json:5).

Reads ``trainLabels.csv`` (columns image,level: ICDR grades 0-4), fundus-
normalizes every photograph (lib: jama16_retina_tpu.preprocess), and
writes stratified train/val/test shards. Grades are stored raw; binary
referable-DR binning (grade >= 2) happens online in the train pipeline,
so the same shards serve the binary and 5-class configs (BASELINE.json:7,9).

Example:
  python preprocess_eyepacs.py --data_dir=/data/eyepacs/train \
      --labels_csv=/data/eyepacs/trainLabels.csv --output_dir=/data/tfr
"""

from __future__ import annotations

import json

from absl import app, flags

_DATA_DIR = flags.DEFINE_string("data_dir", "", "directory of raw images")
_LABELS = flags.DEFINE_string("labels_csv", "", "trainLabels.csv path")
_OUT = flags.DEFINE_string("output_dir", "", "TFRecord output directory")
_SIZE = flags.DEFINE_integer("image_size", 299, "output diameter")
_VAL = flags.DEFINE_float("val_frac", 0.1, "validation fraction")
_TEST = flags.DEFINE_float("test_frac", 0.2, "test fraction")
_SHARDS = flags.DEFINE_integer("num_shards", 16, "shards per split")
_SEED = flags.DEFINE_integer("seed", 0, "partition shuffle seed")
_BEN_GRAHAM = flags.DEFINE_boolean(
    "ben_graham", False,
    "subtract-local-average contrast enhancement (quality option beyond "
    "the reference's plain normalization)",
)
_ENCODING = flags.DEFINE_enum(
    "encoding", "jpeg", ["jpeg", "raw"],
    "record encoding: jpeg (compact) or raw pre-decoded uint8 (~9x disk, "
    "removes the per-epoch host JPEG decode — see docs/PERF.md)",
)
_MIN_QUALITY = flags.DEFINE_float(
    "min_quality", 0.0,
    "drop images whose gradability score (fundus.gradability_stats) is "
    "below this [0,1] threshold — the executable form of the original "
    "study's image-quality grading (docs/QUALITY.md); every image's "
    "score lands in quality_<split>.csv regardless",
)
_WORKERS = flags.DEFINE_integer(
    "workers", 0,
    "CPU worker processes for the per-image stage (0 = in-process "
    "serial). Output shards and quality CSVs are byte-identical to the "
    "serial run at any worker count (SURVEY.md §3.3).",
)


def main(argv):
    del argv
    from jama16_retina_tpu.preprocess import datasets

    if not (_DATA_DIR.value and _LABELS.value and _OUT.value):
        raise app.UsageError("--data_dir, --labels_csv, --output_dir required")

    labels = datasets.parse_labels_csv(_LABELS.value)
    splits = datasets.stratified_split(
        labels, _VAL.value, _TEST.value, seed=_SEED.value
    )
    report = {}
    for split, items in splits.items():
        stats = datasets.process_split(
            items, _DATA_DIR.value, _OUT.value, split,
            image_size=_SIZE.value, num_shards=_SHARDS.value,
            ben_graham=_BEN_GRAHAM.value, encoding=_ENCODING.value,
            min_quality=_MIN_QUALITY.value, workers=_WORKERS.value,
        )
        report[split] = {"n_labeled": len(items), **stats.as_dict()}
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    app.run(main)
