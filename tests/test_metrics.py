"""Metrics layer vs scikit-learn (SURVEY.md §4.1)."""

import numpy as np
import pytest
import sklearn.metrics as skm

try:  # hypothesis is optional: only the property test below needs it,
    # and a host without it must still run the rest of this module.
    from hypothesis import given, settings, strategies as st

    HAVE_HYPOTHESIS = True
except ModuleNotFoundError:
    HAVE_HYPOTHESIS = False

from jama16_retina_tpu.eval import metrics


def _random_problem(seed, n=500):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 2, size=n)
    # scores correlated with labels but noisy, with ties sprinkled in
    scores = np.round(labels * 0.4 + rng.normal(0.3, 0.35, size=n), 2)
    if labels.min() == labels.max():  # ensure both classes present
        labels[0] = 1 - labels[0]
    return labels, scores


@pytest.mark.parametrize("seed", range(5))
def test_auc_matches_sklearn(seed):
    labels, scores = _random_problem(seed)
    assert metrics.roc_auc(labels, scores) == pytest.approx(
        skm.roc_auc_score(labels, scores), abs=1e-12
    )


if HAVE_HYPOTHESIS:
    @given(st.integers(min_value=0, max_value=10_000))
    @settings(max_examples=25, deadline=None)
    def test_auc_matches_sklearn_hypothesis(seed):
        labels, scores = _random_problem(seed, n=120)
        assert metrics.roc_auc(labels, scores) == pytest.approx(
            skm.roc_auc_score(labels, scores), abs=1e-12
        )
else:
    @pytest.mark.skip(reason="hypothesis not installed")
    def test_auc_matches_sklearn_hypothesis():
        # Visible skip, not silent non-collection: the property test's
        # absence must show in the report when the dep is missing.
        pass


def test_roc_curve_matches_sklearn():
    labels, scores = _random_problem(3)
    fpr, tpr, thr = metrics.roc_curve(labels, scores)
    sk_fpr, sk_tpr, sk_thr = skm.roc_curve(labels, scores, drop_intermediate=False)
    np.testing.assert_allclose(fpr, sk_fpr, atol=1e-12)
    np.testing.assert_allclose(tpr, sk_tpr, atol=1e-12)
    np.testing.assert_allclose(thr[1:], sk_thr[1:], atol=1e-12)


def test_perfect_and_inverted_auc():
    labels = np.array([0, 0, 1, 1])
    assert metrics.roc_auc(labels, np.array([0.1, 0.2, 0.8, 0.9])) == 1.0
    assert metrics.roc_auc(labels, np.array([0.9, 0.8, 0.2, 0.1])) == 0.0


def test_degenerate_labels_raise():
    with pytest.raises(ValueError):
        metrics.roc_auc(np.zeros(10), np.linspace(0, 1, 10))


def test_sensitivity_at_specificity_feasible():
    labels, scores = _random_problem(7, n=2000)
    for target in (0.87, 0.98):
        op = metrics.sensitivity_at_specificity(labels, scores, target)
        assert op.specificity >= target - 1e-12
        # achieved sens/spec must agree with a direct confusion recount
        cm = metrics.confusion_at_threshold(labels, scores, op.threshold)
        assert cm["sensitivity"] == pytest.approx(op.sensitivity, abs=1e-12)
        assert cm["specificity"] == pytest.approx(op.specificity, abs=1e-12)


def test_sens_at_spec_monotone_in_target():
    labels, scores = _random_problem(11, n=2000)
    ops = [
        metrics.sensitivity_at_specificity(labels, scores, t)
        for t in (0.5, 0.87, 0.98)
    ]
    assert ops[0].sensitivity >= ops[1].sensitivity >= ops[2].sensitivity


def test_ensemble_average():
    a = np.array([0.2, 0.8])
    b = np.array([0.4, 0.6])
    np.testing.assert_allclose(metrics.ensemble_average([a, b]), [0.3, 0.7])
    with pytest.raises(ValueError):
        metrics.ensemble_average([])


def test_quadratic_weighted_kappa_matches_sklearn():
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 5, size=400)
    preds = np.clip(labels + rng.integers(-1, 2, size=400), 0, 4)
    ours = metrics.quadratic_weighted_kappa(labels, preds, 5)
    theirs = skm.cohen_kappa_score(labels, preds, weights="quadratic")
    assert ours == pytest.approx(theirs, abs=1e-12)
    assert metrics.quadratic_weighted_kappa(labels, labels, 5) == 1.0


def test_referable_collapse():
    probs = np.array([[0.5, 0.3, 0.1, 0.05, 0.05], [0.0, 0.1, 0.4, 0.3, 0.2]])
    np.testing.assert_allclose(
        metrics.referable_probs_from_multiclass(probs), [0.2, 0.9]
    )


def test_evaluation_report_binary_and_multi():
    rng = np.random.default_rng(5)
    grades = rng.integers(0, 5, size=300)
    probs5 = rng.dirichlet(np.ones(5), size=300)
    # bias probs toward the true grade so AUC is informative
    probs5[np.arange(300), grades] += 1.0
    probs5 /= probs5.sum(axis=1, keepdims=True)
    rep = metrics.evaluation_report(grades, probs5)
    assert {"auc", "accuracy", "quadratic_weighted_kappa", "operating_points"} <= set(rep)
    assert len(rep["operating_points"]) == 2
    assert rep["auc"] > 0.6

    binary = (grades >= 2).astype(int)
    rep2 = metrics.evaluation_report(binary, probs5[:, 2:].sum(axis=1))
    assert rep2["auc"] == pytest.approx(rep["auc"], abs=1e-12)


def test_bootstrap_ci_contains_point_estimate_and_is_deterministic():
    rng = np.random.default_rng(11)
    labels = rng.integers(0, 2, 400).astype(float)
    scores = np.clip(labels * 0.4 + rng.normal(0.3, 0.25, 400), 0, 1)
    auc = metrics.roc_auc(labels, scores)
    lo, hi = metrics.bootstrap_ci(labels, scores, metrics.roc_auc, 500, seed=3)
    assert lo <= auc <= hi
    assert 0.0 < hi - lo < 0.3  # informative, not degenerate
    assert (lo, hi) == metrics.bootstrap_ci(
        labels, scores, metrics.roc_auc, 500, seed=3
    )
    # sklearn cross-check on one resample path: CI must bracket the
    # sklearn AUC too (same statistic).
    assert lo <= skm.roc_auc_score(labels, scores) <= hi


def test_bootstrap_ci_rejects_degenerate_sets_and_small_n_works():
    # One-class input: every resample is invalid -> hard error.
    labels = np.array([1.0, 1.0, 1.0])
    scores = np.array([0.9, 0.8, 0.1])
    with pytest.raises(ValueError, match="bootstrap"):
        metrics.bootstrap_ci(labels, scores, metrics.roc_auc, 120, seed=0)
    # Small n_samples must WORK on a healthy set (the floor is relative,
    # not a hard 100 — evaluate.py --bootstrap=50 is legal).
    rng = np.random.default_rng(0)
    l = rng.integers(0, 2, 200).astype(float)
    s = np.clip(l * 0.4 + rng.normal(0.3, 0.25, 200), 0, 1)
    lo, hi = metrics.bootstrap_ci(l, s, metrics.roc_auc, 50, seed=1)
    assert 0.0 <= lo <= hi <= 1.0


def test_bootstrap_ci_dict_statistic_single_pass():
    rng = np.random.default_rng(2)
    l = rng.integers(0, 2, 300).astype(float)
    s = np.clip(l * 0.5 + rng.normal(0.25, 0.2, 300), 0, 1)
    cis = metrics.bootstrap_ci(
        l, s,
        lambda a, b: {
            "sens": metrics.confusion_at_threshold(a, b, 0.5)["sensitivity"],
            "spec": metrics.confusion_at_threshold(a, b, 0.5)["specificity"],
        },
        300, seed=4,
    )
    assert set(cis) == {"sens", "spec"}
    for lo, hi in cis.values():
        assert 0.0 <= lo <= hi <= 1.0


def test_transferred_operating_points_use_tune_thresholds():
    rng = np.random.default_rng(7)
    tune_l = rng.integers(0, 2, 500).astype(float)
    tune_s = np.clip(tune_l * 0.5 + rng.normal(0.25, 0.2, 500), 0, 1)
    eval_l = rng.integers(0, 2, 400).astype(float)
    eval_s = np.clip(eval_l * 0.5 + rng.normal(0.25, 0.2, 400), 0, 1)
    rows = metrics.transferred_operating_points(
        tune_l, tune_s, eval_l, eval_s, (0.87, 0.98)
    )
    assert [r["target_specificity"] for r in rows] == [0.87, 0.98]
    for r in rows:
        # threshold comes from the TUNE split ...
        op = metrics.sensitivity_at_specificity(
            tune_l, tune_s, r["target_specificity"]
        )
        assert r["threshold"] == op.threshold
        # ... and the reported numbers are the EVAL-split confusion there.
        conf = metrics.confusion_at_threshold(eval_l, eval_s, r["threshold"])
        assert r["sensitivity"] == conf["sensitivity"]
        assert r["specificity"] == conf["specificity"]
        assert r["tp"] + r["fn"] == int(eval_l.sum())
    # achieved specificity on eval may drift from target — that is the
    # point of reporting the transfer; it must still be sane.
    assert all(0.5 <= r["specificity"] <= 1.0 for r in rows)


def test_evaluation_report_with_bootstrap():
    rng = np.random.default_rng(13)
    labels = rng.integers(0, 2, 300).astype(float)
    scores = np.clip(labels * 0.5 + rng.normal(0.25, 0.2, 300), 0, 1)
    rep = metrics.evaluation_report(labels, scores, bootstrap_samples=300)
    assert rep["auc_ci95"][0] <= rep["auc"] <= rep["auc_ci95"][1]
    for row in rep["operating_points"]:
        lo, hi = row["sensitivity_ci95"]
        assert 0.0 <= lo <= hi <= 1.0


def test_expected_calibration_error():
    # Perfectly calibrated by construction: P(y=1 | score s) == s for the
    # two score levels used.
    labels = np.array([1, 0, 0, 0] * 25 + [1, 1, 1, 0] * 25, np.float64)
    scores = np.array([0.25] * 100 + [0.75] * 100)
    assert metrics.expected_calibration_error(labels, scores) < 1e-12
    # Maximally miscalibrated: confident and always wrong.
    labels2 = np.array([0.0, 1.0] * 50)
    scores2 = np.array([0.99, 0.01] * 50)
    assert metrics.expected_calibration_error(labels2, scores2) > 0.9
    # Hand-check one two-bin case.
    l = np.array([1.0, 0.0, 1.0, 1.0])
    s = np.array([0.1, 0.1, 0.9, 0.9])
    # bin(0.1): acc 0.5 conf 0.1 -> 0.4 * 2/4 ; bin(0.9): acc 1.0 conf 0.9 -> 0.1 * 2/4
    expect = 0.5 * 0.4 + 0.5 * 0.1
    assert metrics.expected_calibration_error(l, s) == pytest.approx(expect)


def test_fit_temperature_recovers_known_miscalibration():
    """Generate calibrated probs, sharpen them by T_true (divide logits
    by 1/T_true), and check the fitted temperature undoes it."""
    rng = np.random.default_rng(21)
    p_true = rng.uniform(0.05, 0.95, 4000)
    labels = (rng.random(4000) < p_true).astype(np.float64)
    logits = np.log(p_true) - np.log1p(-p_true)
    t_true = 2.5
    miscal = 1.0 / (1.0 + np.exp(-logits * t_true))  # overconfident
    t_hat = metrics.fit_temperature(labels, miscal)
    assert t_hat == pytest.approx(t_true, rel=0.15)
    cal = metrics.apply_temperature(miscal, t_hat)
    assert metrics.expected_calibration_error(labels, cal) < \
        metrics.expected_calibration_error(labels, miscal)
    # Rank preservation: AUC identical before/after.
    assert metrics.roc_auc(labels, cal) == pytest.approx(
        metrics.roc_auc(labels, miscal), abs=1e-12
    )


def test_fit_temperature_near_one_for_calibrated_input():
    rng = np.random.default_rng(22)
    p_true = rng.uniform(0.05, 0.95, 4000)
    labels = (rng.random(4000) < p_true).astype(np.float64)
    assert metrics.fit_temperature(labels, p_true) == pytest.approx(1.0, abs=0.15)
