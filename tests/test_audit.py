"""Prediction provenance & audit plane (ISSUE 20): the sealed
per-request ledger, lineage queries, and deterministic replay.

The contract under test, layer by layer:

  * ``record()`` NEVER blocks or raises into serving: sampling is
    deterministic every-Nth, a full spool drops (counted
    ``audit.dropped``), and a failing segment seal (the ``audit.seal``
    chaos site) loses exactly that segment's records — counted, logged,
    writer alive, serving unaffected;
  * crash semantics: kill -9 mid-spool loses at most the unsealed
    tail; a restart resumes a FRESH segment number and never rewrites
    sealed history;
  * sealed segments carry the full record schema (per-row input
    digests, scores, per-threshold decisions, generation + member
    digests, cascade path, config identity) and graftfsck classifies a
    torn/corrupt one as ``audit`` (quarantine — not derivable), while
    retention GC prunes only beyond ``obs.audit.retention``;
  * the router demuxes a FUSED cross-request bin into one audit record
    per request slice, each carrying its own trace id (and the
    ``serve.router.bin.parts`` event mirrors the attribution into the
    stitched trace);
  * ``replay_record`` pins fp32 BIT-equality through a real assembled
    engine and returns typed verdicts (lineage_changed / no_capture /
    unreplayable / score_mismatch) on every refusal path;
  * /healthz and obs_report surface writer health (spool depth, seal
    age) and blame a wedged audit writer.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from jama16_retina_tpu.configs import get_config, override
from jama16_retina_tpu.integrity import artifact as artifact_lib
from jama16_retina_tpu.integrity import fsck as fsck_lib
from jama16_retina_tpu.integrity import retention as retention_lib
from jama16_retina_tpu.lifecycle.journal import Journal
from jama16_retina_tpu.obs import audit as audit_lib
from jama16_retina_tpu.obs import faultinject
from jama16_retina_tpu.obs import trace as obs_trace
from jama16_retina_tpu.obs.registry import Registry

pytestmark = pytest.mark.audit

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rows(n=4, size=2, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, (n, size, size, 3), np.uint8
    )


def _ledger(tmp_path, **kw):
    kw.setdefault("registry", Registry())
    kw.setdefault("seal_every", 2)
    return audit_lib.AuditLedger(str(tmp_path / "audit"), **kw)


# ---------------------------------------------------------------------------
# The record schema + serving-side surface
# ---------------------------------------------------------------------------


def test_record_roundtrip_schema_and_decisions(tmp_path):
    """A flushed record carries the full sealed schema: per-row input
    digests, float64 scores that roundtrip exactly through JSON,
    decisions at every configured threshold, lineage (member dirs +
    content digests), and the config identity replay rebuilds from."""
    member = tmp_path / "member_00"
    member.mkdir()
    (member / "weights.bin").write_bytes(b"\x01\x02\x03")
    reg = Registry()
    led = _ledger(tmp_path, registry=reg, thresholds=(0.3, 0.7),
                  config_name="smoke",
                  config_overrides=("model.image_size=64",),
                  policy_provenance={"path": "pol.json"})
    rows = _rows(3)
    scores = np.array([0.2, 0.5, 0.9])
    assert led.record(rows, scores, trace_id="t-1", model="m",
                      replica=2, generation=7,
                      member_dirs=[str(member)])
    led.close()
    recs = [r for r, _p in audit_lib.iter_records(str(tmp_path / "audit"),
                                                  strict=True)]
    assert len(recs) == 1
    rec = recs[0]
    assert rec["record_version"] == audit_lib.RECORD_VERSION
    assert rec["trace_id"] == "t-1" and rec["model"] == "m"
    assert rec["replica"] == 2 and rec["n"] == 3
    assert rec["input_sha256"] == audit_lib.row_digests(rows)
    # float64 -> JSON repr -> float64 is exact: the fp32 bit-equality
    # pin rides this roundtrip.
    np.testing.assert_array_equal(np.asarray(rec["scores"]), scores)
    assert rec["decisions"]["0.3"] == [False, True, True]
    assert rec["decisions"]["0.7"] == [False, False, True]
    assert rec["generation"] == 7
    assert rec["member_dirs"] == [str(member)]
    assert rec["member_digests"] == {
        str(member): audit_lib.checkpoint_digest(str(member))
    }
    assert rec["config"] == {"name": "smoke",
                             "overrides": ["model.image_size=64"]}
    assert rec["policy"] == {"path": "pol.json"}
    c = reg.snapshot()["counters"]
    assert c["audit.records"] == 1 and c["audit.rows"] == 3
    assert c["audit.sealed_segments"] == 1


def test_sampling_every_nth_deterministic(tmp_path):
    reg = Registry()
    led = _ledger(tmp_path, registry=reg, sample=0.5)
    accepted = [led.record(_rows(1), np.array([0.5])) for _ in range(10)]
    led.close()
    assert accepted == [False, True] * 5
    assert reg.snapshot()["counters"]["audit.records"] == 5


def test_spool_full_drops_counted_never_blocks(tmp_path, monkeypatch):
    """With the writer dead and the spool bounded at 2, the third
    record is DROPPED (counted) and the call returns immediately —
    serving never waits on audit durability."""
    monkeypatch.setattr(audit_lib.AuditLedger, "_writer_loop",
                        lambda self: None)
    reg = Registry()
    led = _ledger(tmp_path, registry=reg, queue_max=2)
    t0 = time.monotonic()
    got = [led.record(_rows(1), np.array([0.5])) for _ in range(3)]
    assert time.monotonic() - t0 < 1.0
    assert got == [True, True, False]
    c = reg.snapshot()["counters"]
    assert c["audit.dropped"] == 1 and c["audit.records"] == 2


@pytest.mark.chaos
def test_seal_fault_counts_losses_writer_survives(tmp_path):
    """The ``audit.seal`` chaos site: the first seal attempt fails —
    exactly that segment's records are lost (audit.seal_errors + one
    audit.dropped per record), the writer keeps draining, and the NEXT
    segment seals durably. record() never raised into the caller."""
    reg = Registry()
    led = _ledger(tmp_path, registry=reg, seal_every=2)
    prev = faultinject.arm({
        "audit.seal": {"kind": "error", "on_calls": [1]},
    })
    try:
        for i in range(4):
            assert led.record(_rows(2, seed=i), np.array([0.1, 0.9]))
        led.close()
    finally:
        faultinject.arm(prev)
    c = reg.snapshot()["counters"]
    assert c["audit.seal_errors"] == 1
    assert c["audit.dropped"] == 2       # the failed segment's records
    assert c["audit.sealed_segments"] == 1
    recs = [r for r, _p in audit_lib.iter_records(str(tmp_path / "audit"),
                                                  strict=True)]
    assert len(recs) == 2                # the surviving segment


def test_kill9_mid_spool_loses_only_unsealed_tail(tmp_path):
    """Crash semantics: SIGKILL with records in flight loses at most
    the unsealed tail; sealed segments replay cleanly; a restarted
    ledger resumes a FRESH segment number, never rewriting history."""
    audit_dir = str(tmp_path / "audit")
    child = textwrap.dedent(f"""
        import os, signal, sys, time
        sys.path.insert(0, {_REPO!r})
        import numpy as np
        from jama16_retina_tpu.obs import audit
        from jama16_retina_tpu.obs.registry import Registry
        led = audit.AuditLedger({audit_dir!r}, registry=Registry(),
                                seal_every=2)
        imgs = np.zeros((2, 2, 2, 3), np.uint8)
        for i in range(4):
            led.record(imgs, np.full(2, 0.5), generation=i)
        led.flush()                      # 2 sealed segments
        led.record(imgs, np.full(2, 0.5), generation=4)  # unsealed tail
        os.kill(os.getpid(), signal.SIGKILL)
    """)
    r = subprocess.run([sys.executable, "-c", child],
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == -signal.SIGKILL, r.stderr
    segs = audit_lib.segment_paths(audit_dir)
    assert [os.path.basename(p) for p in segs] == [
        "seg-000000.json", "seg-000001.json",
    ]
    before = [open(p, "rb").read() for p in segs]
    recs = [rec for rec, _p in audit_lib.iter_records(audit_dir,
                                                      strict=True)]
    assert [rec["generation"] for rec in recs] == [0, 1, 2, 3]
    # Restart: a fresh segment number after the existing maximum.
    led = audit_lib.AuditLedger(audit_dir, registry=Registry(),
                                seal_every=1)
    assert led.record(np.zeros((1, 2, 2, 3), np.uint8),
                      np.array([0.5]), generation=5)
    led.close()
    assert [os.path.basename(p)
            for p in audit_lib.segment_paths(audit_dir)] == [
        "seg-000000.json", "seg-000001.json", "seg-000002.json",
    ]
    after = [open(p, "rb").read() for p in segs]
    assert before == after               # sealed history untouched


# ---------------------------------------------------------------------------
# fsck classification + retention GC
# ---------------------------------------------------------------------------


@pytest.mark.integrity
def test_fsck_classifies_corrupt_audit_segment_quarantine(tmp_path):
    """A bit-flipped sealed audit segment classifies CORRUPT with
    artifact class ``audit`` (counted integrity.corrupt.audit) and
    repairs by QUARANTINE — an audit record is evidence, never a
    derivable corpse to delete; the clean segment is untouched."""
    wd = str(tmp_path)
    led = audit_lib.AuditLedger(os.path.join(wd, "audit"),
                                registry=Registry(), seal_every=1)
    led.record(_rows(2), np.array([0.1, 0.9]), trace_id="keep")
    led.record(_rows(2, seed=1), np.array([0.2, 0.8]), trace_id="flip")
    led.close()
    reg = Registry()
    assert fsck_lib.fsck_workdir(wd, registry=reg).clean
    seg1 = os.path.join(wd, "audit", "seg-000001.json")
    blob = bytearray(open(seg1, "rb").read())
    i = blob.find(b"flip")
    blob[i] ^= 0x01
    open(seg1, "wb").write(bytes(blob))
    # A torn (half-written lookalike) file in the audit dir classifies
    # too — the name-based walk needs no parseable payload.
    torn = os.path.join(wd, "audit", "seg-000099.json")
    open(torn, "w").write('{"kind": "audit_se')
    reg = Registry()
    report = fsck_lib.fsck_workdir(wd, registry=reg)
    bad = [f for f in report.findings if f.artifact == "audit"]
    assert {os.path.basename(f.path) for f in bad} \
        == {"seg-000001.json", "seg-000099.json"}
    assert all(f.status == "CORRUPT" and f.repair == "quarantine"
               for f in bad)
    assert reg.snapshot()["counters"]["integrity.corrupt.audit"] >= 1
    ledger = fsck_lib.repair_workdir(wd, report=report,
                                     registry=Registry())
    acts = {(a["action"], os.path.basename(a["path"]))
            for a in ledger["actions"]}
    assert ("quarantine", "seg-000001.json") in acts
    # The clean segment survived and still reads strict.
    recs = [r for r, _p in audit_lib.iter_records(
        os.path.join(wd, "audit"), strict=True)]
    assert [r["trace_id"] for r in recs] == ["keep"]


@pytest.mark.integrity
def test_retention_prunes_oldest_segments_with_captures(tmp_path):
    """obs.audit.retention=2 over 4 sealed segments: the 2 oldest are
    planned for deletion WITH their captured tensors; retention<=0
    (the default) keeps everything."""
    wd = str(tmp_path)
    led = audit_lib.AuditLedger(os.path.join(wd, "audit"),
                                registry=Registry(), seal_every=1,
                                capture=True)
    for i in range(4):
        led.record(_rows(1, seed=i), np.array([0.5]), trace_id=f"t{i}")
    led.close()
    segs = audit_lib.segment_paths(os.path.join(wd, "audit"))
    assert len(segs) == 4
    caps = sorted(os.listdir(os.path.join(wd, "audit", "capture")))
    assert len(caps) == 4

    cfg = get_config("smoke")
    plan = retention_lib.plan_retention(wd, cfg)  # retention=0 default
    assert not [a for a in plan.actions if a.cls == "audit"]

    cfg = override(cfg, ["obs.audit.retention=2"])
    plan = retention_lib.plan_retention(wd, cfg)
    planned = {os.path.basename(a.path) for a in plan.actions
               if a.cls == "audit"}
    assert planned == {"seg-000000.json", "seg-000001.json",
                       caps[0], caps[1]}
    retention_lib.apply_plan(plan, registry=Registry())
    assert [os.path.basename(p) for p in audit_lib.segment_paths(
        os.path.join(wd, "audit"))] == ["seg-000002.json",
                                        "seg-000003.json"]
    assert sorted(os.listdir(os.path.join(wd, "audit", "capture"))) \
        == caps[2:]


# ---------------------------------------------------------------------------
# Fused-batch attribution through the router (ISSUE 16 seam)
# ---------------------------------------------------------------------------


class _Stub:
    """Deterministic stub replica (test_router idiom)."""

    def __init__(self, rid, scale=1.0):
        self.rid = rid
        self.generation = 100 + rid
        self.scale = scale

    def probs(self, rows):
        return self.scale * rows.reshape(
            rows.shape[0], -1).astype(np.float64).sum(axis=1)


@pytest.mark.router
def test_fused_bin_demuxes_one_audit_record_per_request(tmp_path):
    """THE fused-batch audit pin: two tenants fused into ONE dispatch
    bin yield one audit record PER REQUEST SLICE — each carrying its
    own trace id, model, rows, and scores — and the
    serve.router.bin.parts event mirrors the same attribution into the
    stitched trace."""
    import dataclasses

    from jama16_retina_tpu.serve.router import Router

    cfg = get_config("smoke")
    cfg = cfg.replace(serve=dataclasses.replace(
        cfg.serve, max_batch=8, bucket_sizes=(8,), max_wait_ms=100.0,
        router_tick_ms=1.0, router_fusion=True,
    ))
    rows_a, rows_b = _rows(4, seed=1), _rows(4, seed=2)
    led = _ledger(tmp_path, seal_every=1)
    router = Router(cfg, engines={"a": [_Stub(0)], "b": [_Stub(1, 3.0)]},
                    registry=Registry())
    router.audit = led
    tracer = obs_trace.default_tracer()
    prev_enabled = tracer.enabled
    tracer.configure(enabled=True)
    try:
        fa = router.submit(rows_a, model="a")
        fb = router.submit(rows_b, model="b")
        out_a = np.asarray(fa.result(timeout=30))
        out_b = np.asarray(fb.result(timeout=30))
        events = [e for e in tracer.events()
                  if e["name"] == "serve.router.bin.parts"]
    finally:
        tracer.configure(enabled=prev_enabled)
        router.close()
        led.close()
    recs = [r for r, _p in audit_lib.iter_records(str(tmp_path / "audit"),
                                                  strict=True)]
    assert len(recs) == 2
    by_model = {r["model"]: r for r in recs}
    assert set(by_model) == {"a", "b"}
    tids = {r["trace_id"] for r in recs}
    assert None not in tids and len(tids) == 2
    np.testing.assert_array_equal(
        np.asarray(by_model["a"]["scores"]), out_a)
    np.testing.assert_array_equal(
        np.asarray(by_model["b"]["scores"]), out_b)
    assert by_model["a"]["input_sha256"] == audit_lib.row_digests(rows_a)
    assert by_model["b"]["input_sha256"] == audit_lib.row_digests(rows_b)
    # Satellite 1: the fused bin's trace event names every part.
    assert len(events) == 1
    parts = events[0]["args"]["parts"]
    assert {p["trace_id"] for p in parts} == tids
    assert {p["model"] for p in parts} == {"a", "b"}


# ---------------------------------------------------------------------------
# Lineage chain + replay verdicts
# ---------------------------------------------------------------------------


def test_lineage_chain_renders_promoting_cycle(tmp_path):
    jdir = str(tmp_path / "lifecycle")
    j = Journal(jdir)
    j.append("DRIFT_DETECTED", cycle=3, reason="psi",
             live_member_dirs=["/old/member_00"])
    j.append("RETRAIN", cycle=3, member_dirs=["/new/member_00"],
             data_manifest={"path": "/data/manifest.json",
                            "sha256": "abc"})
    j.append("GATE", cycle=3, verdicts=[{"gate": "auc", "passed": True}])
    j.append("STAGED_ROLLOUT", cycle=3, generation=9)
    j.append("COMMIT", cycle=3, generation=9)
    rec = {"trace_id": "t-9", "generation": 9,
           "member_dirs": ["/new/member_00"], "serve_dtype": "fp32"}
    chain = audit_lib.lineage_chain(rec, jdir)
    assert chain["cycle"] == 3
    assert chain["drift"]["reason"] == "psi"
    assert chain["warm_start_donors"] == ["/old/member_00"]
    assert chain["gate_verdicts"] == [{"gate": "auc", "passed": True}]
    assert chain["data_manifest"]["path"] == "/data/manifest.json"
    assert chain["commit"]["generation"] == 9
    # Journal-less: every present link renders, none is invented.
    bare = audit_lib.lineage_chain(rec, None)
    assert bare["cycle"] is None and bare["generation"] == 9


def test_replay_typed_refusal_verdicts(tmp_path):
    """The cheap verdict paths, no engine assembled: missing lineage,
    a swapped checkpoint (digest mismatch), capture-less records, and
    a cascade record without its sealed escalation mask."""
    audit_dir = str(tmp_path)
    member = tmp_path / "member_00"
    member.mkdir()
    (member / "w.bin").write_bytes(b"x")
    base = {"trace_id": "t", "serve_dtype": "fp32", "scores": [0.5],
            "input_sha256": [], "config": {"name": "smoke",
                                           "overrides": []}}
    v = audit_lib.replay_record({**base, "member_dirs": None},
                                audit_dir)
    assert (not v.ok) and v.kind == "lineage_changed"
    v = audit_lib.replay_record(
        {**base, "member_dirs": [str(member)],
         "member_digests": {str(member): "0" * 64}}, audit_dir)
    assert (not v.ok) and v.kind == "lineage_changed"
    good = {str(member): audit_lib.checkpoint_digest(str(member))}
    v = audit_lib.replay_record(
        {**base, "member_dirs": [str(member)], "member_digests": good},
        audit_dir)
    assert (not v.ok) and v.kind == "no_capture"
    v = audit_lib.replay_record(
        {**base, "member_dirs": [str(member)], "member_digests": good,
         "capture": {"file": "nope.npy", "sha256": "0" * 64},
         "cascade": {"student_dirs": ["/s"], "escalated": None}},
        audit_dir)
    assert (not v.ok) and v.kind == "unreplayable"


def test_capture_roundtrip_and_tamper_refused(tmp_path):
    led = _ledger(tmp_path, capture=True, seal_every=1)
    rows = _rows(2, seed=7)
    led.record(rows, np.array([0.1, 0.9]), trace_id="c-1")
    led.close()
    audit_dir = str(tmp_path / "audit")
    rec = audit_lib.find_records(audit_dir, "c-1")[0]
    got = audit_lib.load_captured(audit_dir, rec)
    np.testing.assert_array_equal(got, rows)
    cap = os.path.join(audit_dir, rec["capture"]["file"])
    blob = bytearray(open(cap, "rb").read())
    blob[-1] ^= 0xFF
    open(cap, "wb").write(bytes(blob))
    with pytest.raises(artifact_lib.ArtifactCorrupt):
        audit_lib.load_captured(audit_dir, rec)


def test_replay_real_engine_bit_equal_and_mismatch(tmp_path):
    """THE replay acceptance pin on a real XLA engine through the real
    router path: serve -> sealed record -> reassemble the recorded
    generation -> fp32 scores BIT-identical. A tampered sealed score
    then yields a typed score_mismatch and an audit_replay_mismatch
    blackbox dump."""
    import dataclasses

    import jax

    from jama16_retina_tpu import models, train_lib
    from jama16_retina_tpu.serve.router import Router
    from jama16_retina_tpu.utils import checkpoint as ckpt_lib

    size = 32
    overrides = (f"model.image_size={size}",)
    cfg = override(get_config("smoke"), list(overrides))
    cfg = cfg.replace(serve=dataclasses.replace(
        cfg.serve, max_batch=4, bucket_sizes=(4,), max_wait_ms=5.0,
        router_tick_ms=1.0,
    ))
    model = models.build(cfg.model)
    state, _ = train_lib.create_state(cfg, model, jax.random.key(0))
    member = str(tmp_path / "member_00")
    ck = ckpt_lib.Checkpointer(member)
    ck.save(1, jax.device_get(state), {"val_auc": 0.5})
    ck.wait()
    ck.close()
    from jama16_retina_tpu.serve.assemble import EngineSpec, assemble

    engine = assemble(EngineSpec(cfg=cfg, member_dirs=(member,),
                                 model=model))
    led = _ledger(tmp_path, seal_every=1, capture=True,
                  thresholds=(0.5,), config_name="smoke",
                  config_overrides=overrides)
    imgs = np.random.default_rng(3).integers(
        0, 256, (4, size, size, 3), np.uint8)
    router = Router(cfg, engines=[engine], registry=Registry())
    router.audit = led
    try:
        served = np.asarray(router.submit(imgs).result(timeout=120))
    finally:
        router.close()
        led.close()
    audit_dir = str(tmp_path / "audit")
    recs = [r for r, _p in audit_lib.iter_records(audit_dir,
                                                  strict=True)]
    assert len(recs) == 1
    rec = recs[0]
    assert rec["member_dirs"] == [member]
    np.testing.assert_array_equal(np.asarray(rec["scores"]), served)
    v = audit_lib.replay_record(rec, audit_dir,
                                workdir=str(tmp_path / "wd"))
    assert v.ok and v.kind == "bit_equal" and v.max_abs_dev == 0.0
    # Tampered sealed score: typed mismatch + blackbox forensics.
    tampered = dict(rec, scores=(np.asarray(rec["scores"]) + 1e-3
                                 ).tolist())
    v = audit_lib.replay_record(tampered, audit_dir,
                                workdir=str(tmp_path / "wd"))
    assert (not v.ok) and v.kind == "score_mismatch"
    dumps = [d for _b, dirs, _f in os.walk(str(tmp_path / "wd"))
             for d in dirs if "audit_replay_mismatch" in d]
    assert dumps


# ---------------------------------------------------------------------------
# Operator surfaces: /healthz, obs_report, audit_query CLI
# ---------------------------------------------------------------------------


@pytest.mark.obs
def test_healthz_carries_audit_writer_fields():
    from jama16_retina_tpu.obs.httpd import ObsHttp

    reg = Registry()
    srv = ObsHttp(reg, port=0)
    try:
        _status, detail = srv.health(now=1000.0)
        assert detail["audit_spool_depth"] is None
        assert detail["audit_last_seal_age_s"] is None
        reg.gauge("audit.spool_depth", help="t").set(3)
        reg.gauge("audit.last_seal_t", help="t").set(900.0)
        _status, detail = srv.health(now=1000.0)
        assert detail["audit_spool_depth"] == 3
        assert detail["audit_last_seal_age_s"] == 100.0
    finally:
        srv.close()


@pytest.mark.obs
def test_obs_report_audit_section_and_wedged_blame(tmp_path):
    sys.path.insert(0, os.path.join(_REPO, "scripts"))
    import obs_report

    telemetry = {"kind": "telemetry", "t": 1000.0, "process_index": 0,
                 "counters": {"audit.records": 10, "audit.rows": 40,
                              "audit.dropped": 2,
                              "audit.sealed_segments": 5,
                              "audit.seal_errors": 1,
                              "audit.captured": 10},
                 "gauges": {"audit.spool_depth": 4,
                            "audit.last_seal_t": 700.0}}
    records = [telemetry,
               {"kind": "audit_replay", "ok": True, "trace_id": "t"}]
    s = obs_report.audit_summary(records)
    assert s["records"] == 10 and s["rows"] == 40
    assert s["drop_rate"] == pytest.approx(2 / 12)
    assert s["seal_lag_s"] == 300.0
    assert s["replays"]["total"] == 1
    text = obs_report.render_audit(records)
    assert "Audit & provenance" in text and "records audited: 10" in text
    assert obs_report.audit_summary([{"kind": "train"}]) is None

    # Wedged-writer blame: heartbeats fresh, spool nonempty, nothing
    # sealed for longer than the threshold -> exit 1 naming the writer.
    from jama16_retina_tpu.utils.logging import RunLog

    wd = str(tmp_path)
    log = RunLog(wd)
    log.write("heartbeat", step=5, last_progress_t=990.0, t=995.0)
    log.write("telemetry", t=1000.0, counters={},
              gauges={"audit.spool_depth": 4,
                      "audit.last_seal_t": 100.0})
    log.close()
    code, msg = obs_report.check_heartbeats(wd, max_age_s=300.0,
                                            now=1000.0)
    assert code == 1 and "wedged audit writer" in msg
    # A drained spool clears the blame.
    log = RunLog(wd)
    log.write("telemetry", t=1001.0, counters={},
              gauges={"audit.spool_depth": 0,
                      "audit.last_seal_t": 100.0})
    log.close()
    code, msg = obs_report.check_heartbeats(wd, max_age_s=300.0,
                                            now=1000.0)
    assert code == 0, msg


def test_ledger_for_gating_and_dir_resolution(tmp_path):
    cfg = get_config("smoke")
    assert audit_lib.ledger_for(cfg, str(tmp_path)) is None  # disabled
    cfg = override(cfg, ["obs.audit.enabled=true"])
    assert audit_lib.ledger_for(cfg, None) is None    # no dir anywhere
    led = audit_lib.ledger_for(cfg, str(tmp_path), registry=Registry())
    assert led is not None
    assert led.dir == os.path.join(str(tmp_path), "audit")
    assert led.sample == 1.0 and led.seal_every == 64
    led.close()
    cfg = override(cfg, [f"obs.audit.dir={tmp_path}/elsewhere",
                         "obs.audit.sample=0.25",
                         "obs.audit.seal_every=8",
                         "obs.audit.queue_max=16"])
    led = audit_lib.ledger_for(cfg, None, registry=Registry())
    assert led.dir == f"{tmp_path}/elsewhere"
    assert led._every == 4 and led.seal_every == 8
    assert led._q.maxsize == 16
    led.close()


def test_audit_query_cli_list_trace_and_exit_codes(tmp_path):
    led = _ledger(tmp_path, seal_every=1, thresholds=(0.5,))
    led.record(_rows(2), np.array([0.2, 0.8]), trace_id="cli-1",
               generation=0)
    led.close()
    audit_dir = str(tmp_path / "audit")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    q = os.path.join(_REPO, "scripts", "audit_query.py")

    def run(*args):
        return subprocess.run([sys.executable, q, *args],
                              capture_output=True, text=True, env=env,
                              timeout=300)

    r = run("list", audit_dir, "--json")
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["records"][0]["trace_id"] == "cli-1"
    r = run("trace", "cli-1", f"--audit-dir={audit_dir}")
    assert r.returncode == 0 and "cli-1" in r.stdout
    assert "no promoting lifecycle cycle" in r.stdout
    r = run("trace", "missing-id", f"--audit-dir={audit_dir}")
    assert r.returncode == 1
