"""Runtime telemetry subsystem (jama16_retina_tpu/obs/; ISSUE 3): the
registry's thread-safety and quantile math, span/no-op semantics, the
StallClock's sum-to-window invariant, the Snapshotter's JSONL +
Prometheus + heartbeat exports, the serve path's close-observability
counters, obs_report's rendering and heartbeat exit codes, and a short
instrumented fit() producing every acceptance artifact end to end."""

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from jama16_retina_tpu.obs import export as obs_export
from jama16_retina_tpu.obs import registry as obs_registry
from jama16_retina_tpu.obs import trace as obs_trace
from jama16_retina_tpu.obs.spans import StallClock, span
from jama16_retina_tpu.serve.batcher import MicroBatcher
from jama16_retina_tpu.utils.logging import read_jsonl

pytestmark = pytest.mark.obs


def _load_obs_report():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(repo, "scripts", "obs_report.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


def test_registry_counter_gauge_histogram_basics():
    reg = obs_registry.Registry()
    c = reg.counter("c")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    g = reg.gauge("g")
    g.set(7)
    g.add(-2)
    assert g.value == 5.0
    h = reg.histogram("h", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 3.0, 100.0):
        h.observe(v)
    s = h.snapshot()
    assert s["count"] == 4 and s["sum"] == pytest.approx(105.0)
    # Overflow observations clamp quantiles to the largest finite bound.
    assert s["p99"] <= 4.0
    assert s["p50"] <= s["p95"] <= s["p99"]
    # Same name -> same object; same name, different kind -> loud.
    assert reg.counter("c") is c
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("c")


def test_registry_snapshot_shape():
    reg = obs_registry.Registry()
    reg.counter("a").inc()
    reg.gauge("b").set(2)
    reg.histogram("c").observe(0.01)
    snap = reg.snapshot()
    assert snap["counters"] == {"a": 1.0}
    assert snap["gauges"] == {"b": 2.0}
    assert snap["histograms"]["c"]["count"] == 1


def test_registry_disabled_is_noop_everywhere():
    """The explicit no-op mode: a disabled registry's metric handles
    stay valid but every op freezes — one branch, no state change."""
    reg = obs_registry.Registry(enabled=False)
    c, g, h = reg.counter("c"), reg.gauge("g"), reg.histogram("h")
    c.inc(10)
    g.set(5)
    h.observe(1.0)
    assert c.value == 0.0 and g.value == 0.0 and h.count == 0
    # span() with both sinks disabled returns the SHARED no-op context
    # (no allocation on the hot path). The tracer is injected for the
    # same reason the registry is: the process defaults are enabled by
    # any fit() earlier in the pytest session (ISSUE 4 upgraded span()
    # to also feed the event timeline).
    tr = obs_trace.Tracer(enabled=False)
    assert span("x", reg, tracer=tr) is span("y", reg, tracer=tr)
    reg.enabled = True
    c.inc()
    assert c.value == 1.0


def test_registry_ops_are_thread_safe():
    """8 threads hammering one counter + one histogram lose no updates
    (the serve path records from batcher worker + N submitters)."""
    reg = obs_registry.Registry()
    c = reg.counter("n")
    h = reg.histogram("h", buckets=(0.5, 1.0))
    n_threads, per = 8, 500

    def work():
        for _ in range(per):
            c.inc()
            h.observe(0.25)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * per
    s = h.snapshot()
    assert s["count"] == n_threads * per
    assert s["sum"] == pytest.approx(0.25 * n_threads * per)


def test_histogram_quantiles_interpolate_sanely():
    reg = obs_registry.Registry()
    h = reg.histogram("h", buckets=tuple(float(b) for b in range(1, 11)))
    for v in np.linspace(0.05, 9.95, 200):
        h.observe(float(v))
    s = h.snapshot()
    # Uniform on [0, 10): quantiles land near q*10 (bucket resolution 1).
    assert abs(s["p50"] - 5.0) < 1.0
    assert abs(s["p95"] - 9.5) < 1.0
    assert s["p50"] <= s["p95"] <= s["p99"]


def test_span_records_into_histogram():
    reg = obs_registry.Registry()
    with span("timed", reg):
        time.sleep(0.01)
    s = reg.histogram("timed").snapshot()
    assert s["count"] == 1
    assert s["sum"] >= 0.009


def test_registry_reset_zeroes_in_place():
    """reset() zeroes values but keeps handles valid — the run-scoping
    contract: metrics created at pipeline construction keep recording
    into the new run after the trainer's per-run reset."""
    reg = obs_registry.Registry()
    c, g = reg.counter("c"), reg.gauge("g")
    h = reg.histogram("h", buckets=(1.0,))
    c.inc(5)
    g.set(3)
    h.observe(0.5)
    reg.reset()
    assert c.value == 0.0 and g.value == 0.0 and h.count == 0
    c.inc()  # the pre-reset handle still feeds the registry
    assert reg.snapshot()["counters"]["c"] == 1.0
    assert reg.counter("c") is c


def test_obs_begin_run_scopes_default_registry_per_run():
    """Sequential ensemble members fit() one after another in one
    process: each run's entry resets the shared default registry, so
    member m's telemetry doesn't carry members 0..m-1's counts — and a
    prior obs.enabled=false run doesn't mute the next one."""
    from jama16_retina_tpu import trainer
    from jama16_retina_tpu.configs import get_config

    prev = obs_registry.set_default_registry(obs_registry.Registry())
    try:
        reg = obs_registry.default_registry()
        c = reg.counter("data.decode.records")
        c.inc(5)  # "member 0"'s leftovers
        reg.enabled = False  # a disabled run came before
        assert trainer._obs_begin_run(get_config("smoke")) is reg
        assert reg.enabled is True  # smoke's default obs.enabled
        assert c.value == 0.0
        c.inc()
        assert reg.snapshot()["counters"]["data.decode.records"] == 1.0
    finally:
        obs_registry.set_default_registry(prev)


def test_default_registry_is_injectable():
    prev = obs_registry.set_default_registry(obs_registry.Registry())
    try:
        obs_registry.default_registry().counter("x").inc()
        assert obs_registry.default_registry().counter("x").value == 1.0
    finally:
        obs_registry.set_default_registry(prev)
    assert "x" not in prev.snapshot()["counters"]


# ---------------------------------------------------------------------------
# StallClock: the trainer's window attribution
# ---------------------------------------------------------------------------


def test_stall_clock_fields_sum_to_window():
    """The acceptance invariant: input + dispatch + pause + other ==
    window wall time (disjoint measured segments; `other` is the exact
    remainder)."""
    reg = obs_registry.Registry()
    sc = StallClock(reg)
    with sc.measure("input"):
        time.sleep(0.02)
    with sc.measure("dispatch"):
        time.sleep(0.005)
    with sc.measure("pause"):
        time.sleep(0.01)
    with sc.measure("save"):
        time.sleep(0.002)
    time.sleep(0.005)  # unattributed host time -> other
    f = sc.fields()
    total = (f["input_wait_sec"] + f["dispatch_sec"] + f["pause_sec"]
             + f["save_sec"] + f["other_sec"])
    assert total == pytest.approx(f["window_sec"], abs=2e-3)
    assert f["input_wait_sec"] >= 0.018
    assert f["other_sec"] >= 0.003
    # Registry histograms saw each segment (cross-window quantiles).
    assert reg.histogram("trainer.input_s").count == 1
    # fields() resets the window.
    f2 = sc.fields()
    assert f2["input_wait_sec"] == 0.0 and f2["window_sec"] < f["window_sec"]


# ---------------------------------------------------------------------------
# Export: Snapshotter, prometheus text, heartbeat
# ---------------------------------------------------------------------------


def test_snapshotter_writes_telemetry_heartbeat_and_prom(tmp_path):
    reg = obs_registry.Registry()
    reg.counter("data.tiered.resident_rows").inc(70)
    reg.counter("data.tiered.streamed_rows").inc(10)
    reg.histogram("serve.request_latency_s").observe(0.012)
    snap = obs_export.Snapshotter(reg, str(tmp_path), every_s=1e9)
    snap.progress(42)
    snap.flush()
    snap.close()

    recs = read_jsonl(str(tmp_path / "metrics.jsonl"))
    telemetry = [r for r in recs if r["kind"] == "telemetry"]
    beats = [r for r in recs if r["kind"] == "heartbeat"]
    assert telemetry and beats
    assert telemetry[0]["counters"]["data.tiered.resident_rows"] == 70
    assert telemetry[0]["histograms"]["serve.request_latency_s"]["count"] == 1
    # The explicit heartbeat payload: step + last_progress_t, per process.
    assert beats[-1]["step"] == 42
    assert beats[-1]["last_progress_t"] is not None
    assert beats[-1]["process_index"] == 0

    prom = (tmp_path / "telemetry.prom").read_text()
    assert "# TYPE data_tiered_resident_rows counter" in prom
    assert "data_tiered_resident_rows 70" in prom
    assert 'serve_request_latency_s_bucket{le="+Inf"} 1' in prom
    assert "serve_request_latency_s_count 1" in prom
    # No torn temp file left behind (atomic publish).
    assert not (tmp_path / "telemetry.prom.tmp").exists()


def test_snapshotter_maybe_flush_honors_interval(tmp_path):
    reg = obs_registry.Registry()
    snap = obs_export.Snapshotter(reg, str(tmp_path), every_s=1e9)
    assert snap.maybe_flush() is None  # interval not elapsed
    assert snap.flushes == 0
    snap.every_s = 0.0
    assert snap.maybe_flush() is not None
    assert snap.flushes == 1
    snap.close()
    assert snap.flushes == 2  # close always flushes


def test_snapshotter_reuses_callers_runlog(tmp_path):
    """The trainer path: telemetry records land in the run's OWN
    metrics.jsonl, and close() does not close a log it doesn't own."""
    from jama16_retina_tpu.utils.logging import RunLog

    log = RunLog(str(tmp_path))
    reg = obs_registry.Registry()
    snap = obs_export.Snapshotter(reg, str(tmp_path), runlog=log,
                                  every_s=1e9)
    snap.flush()
    snap.close()
    log.write("train", step=1, loss=0.5)  # still open
    log.close()
    kinds = [r["kind"] for r in read_jsonl(str(tmp_path / "metrics.jsonl"))]
    assert kinds.count("telemetry") == 2  # flush + close
    assert kinds[-1] == "train"


def test_prometheus_text_histogram_is_cumulative():
    reg = obs_registry.Registry()
    h = reg.histogram("lat", buckets=(1.0, 2.0))
    for v in (0.5, 0.7, 1.5, 9.0):
        h.observe(v)
    text = obs_export.prometheus_text(reg.snapshot())
    assert 'lat_bucket{le="1"} 2' in text
    assert 'lat_bucket{le="2"} 3' in text
    assert 'lat_bucket{le="+Inf"} 4' in text
    assert "lat_count 4" in text


# ---------------------------------------------------------------------------
# obs_report: rendering + heartbeat exit codes
# ---------------------------------------------------------------------------


def test_obs_report_prom_roundtrip(tmp_path):
    rep = _load_obs_report()
    reg = obs_registry.Registry()
    reg.counter("data.tiered.resident_rows").inc(700)
    reg.counter("data.tiered.streamed_rows").inc(300)
    reg.gauge("serve.batcher.queue_depth").set(3)
    h = reg.histogram("serve.request_latency_s")
    for v in (0.01, 0.02, 0.04):
        h.observe(v)
    text = obs_export.prometheus_text(reg.snapshot())
    snap = rep.parse_prom(text)
    assert snap["counters"]["data_tiered_resident_rows"] == 700
    assert snap["gauges"]["serve_batcher_queue_depth"] == 3
    hh = snap["histograms"]["serve_request_latency_s"]
    assert hh["count"] == 3 and hh["p50"] <= hh["p99"]
    out = rep.render_snapshot(snap)
    assert "70.0%" in out  # cache hit rate 700/1000
    assert "serve request latency" in out


def test_obs_report_renders_stall_attribution():
    rep = _load_obs_report()
    records = [
        {"kind": "train", "step": s, "window_sec": 1.0,
         "input_wait_sec": 0.6, "dispatch_sec": 0.1, "pause_sec": 0.2,
         "other_sec": 0.1}
        for s in (10, 20)
    ]
    out = rep.render_stalls(records)
    assert "input wait" in out and "60.0%" in out
    assert "worst input-wait window" in out


def _write_heartbeats(workdir, entries):
    os.makedirs(workdir, exist_ok=True)
    by_file: dict = {}
    for proc, t, prog_t, step in entries:
        name = "metrics.jsonl" if proc == 0 else f"metrics.p{proc}.jsonl"
        by_file.setdefault(name, []).append(json.dumps({
            "kind": "heartbeat", "t": t, "process_index": proc,
            "step": step, "last_progress_t": prog_t,
        }))
    for name, lines in by_file.items():
        with open(os.path.join(workdir, name), "w") as f:
            f.write("\n".join(lines) + "\n")


def test_check_heartbeats_exit_codes(tmp_path):
    """The cron/CI one-liner (ISSUE 3 satellite): 0 fresh, 1 stale OR
    wedged (fresh heartbeat, stalled progress), 2 none."""
    rep = _load_obs_report()
    now = 1_000_000.0

    fresh = str(tmp_path / "fresh")
    _write_heartbeats(fresh, [(0, now - 10, now - 10, 100),
                              (1, now - 20, now - 20, 100)])
    code, msg = rep.check_heartbeats(fresh, 300.0, now=now)
    assert code == 0 and "ok" in msg

    stale = str(tmp_path / "stale")
    _write_heartbeats(stale, [(0, now - 10, now - 10, 100),
                              (1, now - 999, now - 999, 80)])
    code, msg = rep.check_heartbeats(stale, 300.0, now=now)
    assert code == 1 and "p1" in msg

    # Wedged: host keeps FLUSHING (fresh t) but stopped progressing —
    # the failure shape the old mtime probe could not see.
    wedged = str(tmp_path / "wedged")
    _write_heartbeats(wedged, [(0, now - 10, now - 999, 100)])
    code, msg = rep.check_heartbeats(wedged, 300.0, now=now)
    assert code == 1 and "wedged" in msg

    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    code, _ = rep.check_heartbeats(empty, 300.0, now=now)
    assert code == 2


def test_obs_report_cli_check_heartbeats(tmp_path):
    rep = _load_obs_report()
    w = str(tmp_path / "w")
    _write_heartbeats(w, [(0, time.time(), time.time(), 5)])
    assert rep.main(["--check-heartbeats", w, "--max-age-s", "300"]) == 0
    assert rep.main(["--check-heartbeats", w, "--max-age-s", "0"]) == 1


# ---------------------------------------------------------------------------
# MicroBatcher close observability (ISSUE 3 satellite)
# ---------------------------------------------------------------------------


def _row_sums(rows):
    return rows.reshape(rows.shape[0], -1).astype(np.float64).sum(axis=1)


def test_batcher_close_during_in_flight_window_keeps_coriders(tmp_path):
    """close() while a coalesced window is mid-inference neither
    deadlocks nor silently drops co-riders: every already-submitted
    future resolves with its own rows, the post-close submit is counted
    in rejected_at_close, and the sentinel-terminated window lands in
    close_flushed_windows."""
    reg = obs_registry.Registry()
    started = threading.Event()

    def infer(rows):
        started.set()
        time.sleep(0.05)  # close() arrives while this window is in flight
        return _row_sums(rows)

    rows = np.arange(12, dtype=np.float64).reshape(3, 4)
    b = MicroBatcher(infer, max_batch=2, max_wait_ms=5.0, registry=reg)
    f0 = b.submit(rows[0:1])
    f1 = b.submit(rows[1:2])
    f2 = b.submit(rows[2:3])
    assert started.wait(timeout=10)
    t0 = time.monotonic()
    b.close()  # joins the worker: must return, not deadlock
    assert time.monotonic() - t0 < 10
    for i, f in enumerate((f0, f1, f2)):
        np.testing.assert_array_equal(
            f.result(timeout=1), _row_sums(rows[i:i + 1])
        )
    with pytest.raises(RuntimeError, match="closed"):
        b.submit(rows[0:1])
    assert reg.counter("serve.batcher.rejected_at_close").value == 1
    # Queue drained back to empty; request latencies were recorded.
    assert reg.gauge("serve.batcher.queue_depth").value == 0
    assert reg.histogram("serve.request_latency_s").count == 3


def test_batcher_close_flush_counters_on_unstarted_drain():
    reg = obs_registry.Registry()
    b = MicroBatcher(
        lambda rows: _row_sums(rows), max_batch=8, autostart=False,
        registry=reg,
    )
    futs = [b.submit(np.ones((1, 4))) for _ in range(3)]
    b.close()  # never-started drain path
    for f in futs:
        np.testing.assert_array_equal(f.result(timeout=1), [4.0])
    assert reg.counter("serve.batcher.close_flushed_windows").value == 1
    assert reg.counter("serve.batcher.rows").value == 3
    s = reg.histogram("serve.batcher.window_fill").snapshot()
    assert s["count"] == 1 and s["sum"] == pytest.approx(3 / 8)


def test_batcher_queue_depth_and_fill_metrics():
    reg = obs_registry.Registry()
    b = MicroBatcher(
        lambda rows: _row_sums(rows), max_batch=4, max_wait_ms=50.0,
        autostart=False, registry=reg,
    )
    for _ in range(4):
        b.submit(np.ones((1, 4)))
    assert reg.gauge("serve.batcher.queue_depth").value == 4
    b.start()
    b.close()
    assert reg.gauge("serve.batcher.queue_depth").value == 0
    assert reg.counter("serve.batcher.batches").value >= 1
    assert reg.counter("serve.batcher.rows").value == 4


# ---------------------------------------------------------------------------
# ServingEngine telemetry + a serving-session export
# ---------------------------------------------------------------------------


def _make_engine():
    """A fresh k=2 smoke engine over an injected registry — each test
    builds its own so counter assertions never depend on test order."""
    import jax

    from jama16_retina_tpu import models, train_lib
    from jama16_retina_tpu.configs import ServeConfig, get_config, override
    from jama16_retina_tpu.serve.engine import ServingEngine

    cfg = override(get_config("smoke"), ["model.image_size=32"])
    cfg = cfg.replace(serve=ServeConfig(max_batch=8, bucket_sizes=(4, 8)))
    model = models.build(cfg.model)
    state, _ = train_lib.create_ensemble_state(cfg, model, [0, 1])
    state = jax.device_get(state)
    reg = obs_registry.Registry()
    engine = ServingEngine(cfg, model=model, state=state, registry=reg)
    imgs = np.random.default_rng(0).integers(
        0, 256, (6, 32, 32, 3), np.uint8
    )
    return engine, reg, imgs


def test_engine_pad_and_compile_counters():
    engine, reg, imgs = _make_engine()
    engine.member_probs(imgs)  # 6 rows -> bucket 8, pad 2
    assert reg.counter("serve.engine.rows").value == 6
    assert reg.counter("serve.engine.batches").value == 1
    assert reg.counter("serve.pad_rows_b8").value == 2
    assert reg.counter("serve.bucket_compiles_b8").value == 1
    engine.member_probs(imgs[:3])  # 3 rows -> bucket 4, pad 1
    assert reg.counter("serve.pad_rows_b4").value == 1
    assert reg.counter("serve.bucket_compiles_b4").value == 1
    # Same buckets again: pad waste grows, compile counters do NOT.
    engine.member_probs(imgs)
    assert reg.counter("serve.pad_rows_b8").value == 4
    assert reg.counter("serve.bucket_compiles_b8").value == 1
    assert reg.gauge("serve.engine.in_flight").value == 0  # drained


def test_engine_start_telemetry_defaults_to_config_cadence(tmp_path):
    """start_telemetry honors obs.flush_every_s (the knob the trainer
    uses) instead of a hardcoded cadence."""
    engine, _, _ = _make_engine()
    snap = engine.start_telemetry(str(tmp_path))
    try:
        assert snap.every_s == engine.cfg.obs.flush_every_s
    finally:
        snap.close()
    snap2 = engine.start_telemetry(str(tmp_path), every_s=5.0)
    try:
        assert snap2.every_s == 5.0
    finally:
        snap2.close()


def test_engine_session_produces_telemetry_artifacts(tmp_path):
    """ISSUE 3 acceptance: a ServingEngine session emits `telemetry`
    JSONL records AND <workdir>/telemetry.prom, renderable by
    obs_report."""
    engine, reg, imgs = _make_engine()
    with engine.make_batcher() as b:
        b.submit(imgs[:2]).result(timeout=60)
    snap = engine.start_telemetry(str(tmp_path), every_s=1e9)
    snap.close()  # final flush

    recs = read_jsonl(str(tmp_path / "metrics.jsonl"))
    telemetry = [r for r in recs if r["kind"] == "telemetry"]
    assert telemetry
    assert telemetry[-1]["counters"]["serve.engine.rows"] >= 2
    assert telemetry[-1]["histograms"]["serve.request_latency_s"]["count"] >= 1
    assert any(r["kind"] == "heartbeat" for r in recs)
    prom = (tmp_path / "telemetry.prom").read_text()
    assert "serve_engine_rows" in prom

    rep = _load_obs_report()
    out = rep.render_snapshot(rep.parse_prom(prom))
    assert "serve request latency" in out
    assert rep.main([str(tmp_path / "telemetry.prom")]) == 0


# ---------------------------------------------------------------------------
# End to end: an instrumented fit() produces every artifact
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def obs_fit(tmp_path_factory):
    from jama16_retina_tpu import trainer
    from jama16_retina_tpu.configs import get_config, override
    from jama16_retina_tpu.data import tfrecord

    data_dir = str(tmp_path_factory.mktemp("obs_data"))
    tfrecord.write_synthetic_split(data_dir, "train", 48, 32, 2, seed=1)
    tfrecord.write_synthetic_split(data_dir, "val", 16, 32, 1, seed=2)
    cfg = override(get_config("smoke"), [
        "model.image_size=32",
        "train.steps=8", "train.eval_every=4", "train.log_every=2",
        "data.batch_size=8", "data.augment=false", "eval.batch_size=8",
        "obs.flush_every_s=0",  # flush at every log boundary
    ])
    workdir = str(tmp_path_factory.mktemp("obs_run"))
    prev = obs_registry.set_default_registry(obs_registry.Registry())
    try:
        trainer.fit(cfg, data_dir, workdir, seed=0)
    finally:
        obs_registry.set_default_registry(prev)
    return workdir


def test_fit_train_records_carry_stall_attribution(obs_fit):
    """Acceptance: `train` records carry input-wait/pause/dispatch
    fields that sum consistently with window wall time."""
    recs = read_jsonl(os.path.join(obs_fit, "metrics.jsonl"))
    train = [r for r in recs if r["kind"] == "train"]
    assert train
    for r in train:
        for k in ("window_sec", "input_wait_sec", "dispatch_sec",
                  "pause_sec", "save_sec", "other_sec"):
            assert k in r, (k, r)
        total = (r["input_wait_sec"] + r["dispatch_sec"] + r["pause_sec"]
                 + r["save_sec"] + r["other_sec"])
        assert total == pytest.approx(r["window_sec"], abs=2e-3), r


def test_fit_emits_telemetry_heartbeat_and_prom(obs_fit):
    recs = read_jsonl(os.path.join(obs_fit, "metrics.jsonl"))
    telemetry = [r for r in recs if r["kind"] == "telemetry"]
    beats = [r for r in recs if r["kind"] == "heartbeat"]
    assert telemetry and beats
    # The prefetch-depth gauge and trainer stall histograms made it in.
    assert "data.prefetch.depth" in telemetry[-1]["gauges"]
    assert telemetry[-1]["histograms"]["trainer.input_s"]["count"] > 0
    assert beats[-1]["step"] == 8
    assert beats[-1]["last_progress_t"] is not None
    assert os.path.exists(os.path.join(obs_fit, "telemetry.prom"))


def test_obs_report_renders_a_real_run(obs_fit, capsys):
    rep = _load_obs_report()
    assert rep.main([obs_fit]) == 0
    out = capsys.readouterr().out
    assert "stall attribution" in out
    assert "heartbeat" in out
    # The run just finished, so its heartbeat is fresh.
    assert rep.main(["--check-heartbeats", obs_fit,
                     "--max-age-s", "600"]) == 0


def test_obs_disabled_run_writes_no_telemetry(tmp_path_factory):
    from jama16_retina_tpu import trainer
    from jama16_retina_tpu.configs import get_config, override
    from jama16_retina_tpu.data import tfrecord

    data_dir = str(tmp_path_factory.mktemp("obs_off_data"))
    tfrecord.write_synthetic_split(data_dir, "train", 16, 32, 1, seed=1)
    tfrecord.write_synthetic_split(data_dir, "val", 8, 32, 1, seed=2)
    cfg = override(get_config("smoke"), [
        "model.image_size=32",
        "train.steps=2", "train.eval_every=2", "train.log_every=1",
        "data.batch_size=8", "data.augment=false", "eval.batch_size=8",
        "obs.enabled=false",
    ])
    workdir = str(tmp_path_factory.mktemp("obs_off_run"))
    prev = obs_registry.set_default_registry(obs_registry.Registry())
    try:
        trainer.fit(cfg, data_dir, workdir, seed=0)
    finally:
        obs_registry.set_default_registry(prev)
        obs_registry.default_registry().enabled = True
    recs = read_jsonl(os.path.join(workdir, "metrics.jsonl"))
    assert not [r for r in recs if r["kind"] in ("telemetry", "heartbeat")]
    assert not os.path.exists(os.path.join(workdir, "telemetry.prom"))
    # Stall attribution stays (it is part of the train record contract,
    # not of the optional registry/export machinery).
    train = [r for r in recs if r["kind"] == "train"]
    assert train and all("input_wait_sec" in r for r in train)


# ---------------------------------------------------------------------------
# Ingest lease staleness blame + bench trend (ISSUE 18 satellites)
# ---------------------------------------------------------------------------


def _write_lease(workdir, cid, step, age_s, now, corrupt=False):
    from jama16_retina_tpu.ingest.leases import (LEASE_SCHEMA,
                                                 LEASE_VERSION)
    from jama16_retina_tpu.integrity import artifact as artifact_lib

    d = os.path.join(workdir, "leases")
    os.makedirs(d, exist_ok=True)
    p = os.path.join(d, f"lease-{cid}.json")
    artifact_lib.write_sealed_json(
        p, {"consumer_id": cid, "consumed_through": step},
        schema=LEASE_SCHEMA, version=LEASE_VERSION,
    )
    if corrupt:
        # Flip a payload byte UNDER the seal: digest mismatch, typed
        # ArtifactCorrupt on read.
        text = open(p).read().replace(f'"{cid}"', f'"{cid[:-1]}X"')
        with open(p, "w") as f:
            f.write(text)
    os.utime(p, (now - age_s, now - age_s))
    return p


def test_lease_staleness_blames_only_with_a_fresh_peer(tmp_path):
    """Mirrors the --check-heartbeats fleet semantics: a consumer is
    NAMED stale only while a peer still advances; when every lease is
    old the whole service is idle and nobody is blamed."""
    rep = _load_obs_report()
    wd = str(tmp_path)
    now = time.time()
    _write_lease(wd, "healthy", 40, 5.0, now)
    _write_lease(wd, "wedged", 7, 500.0, now)
    entries = rep.lease_staleness(wd, stale_s=120.0, now=now)
    assert [e["consumer_id"] for e in entries] == ["wedged", "healthy"]
    wedged, healthy = entries
    assert wedged["stale"] and wedged["blamed"]
    assert wedged["consumed_through"] == 7
    assert not healthy["stale"] and not healthy["blamed"]

    # All old -> idle service, blame nobody.
    wd2 = str(tmp_path / "idle")
    _write_lease(wd2, "a", 1, 500.0, now)
    _write_lease(wd2, "b", 2, 900.0, now)
    entries = rep.lease_staleness(wd2, stale_s=120.0, now=now)
    assert all(e["stale"] and not e["blamed"] for e in entries)

    # No lease files at all -> None (section stays quiet).
    assert rep.lease_staleness(str(tmp_path / "empty")) is None


def test_lease_staleness_renders_corrupt_and_blamed_rows(tmp_path):
    rep = _load_obs_report()
    wd = str(tmp_path)
    now = time.time()
    _write_lease(wd, "healthy", 12, 5.0, now)
    _write_lease(wd, "wedged", 3, 900.0, now)
    _write_lease(wd, "broken", 9, 10.0, now, corrupt=True)
    entries = rep.lease_staleness(wd, stale_s=120.0, now=now)
    by_cid = {e["consumer_id"]: e for e in entries}
    assert by_cid["broken"]["corrupt"]
    assert by_cid["broken"]["consumed_through"] is None
    assert not by_cid["healthy"]["corrupt"]

    # The Ingest section names the wedged consumer; the healthy
    # remainder stays quiet (fresh rows, no blame).
    records = [{"kind": "telemetry",
                "counters": {"ingest.batches_served": 10.0,
                             "ingest.rows_served": 80.0,
                             "ingest.consumer.healthy.rows": 80.0},
                "gauges": {}, "histograms": {}}]
    out = rep.render_ingest(records, workdir=wd, stale_lease_s=120.0)
    assert "wedged" in out and "STALE" in out
    assert "CORRUPT" in out
    assert "healthy" in out and "fresh" in out
    s = rep.ingest_summary(records, workdir=wd, stale_lease_s=120.0)
    assert [e["consumer_id"] for e in s["leases"]].count("wedged") == 1


def _load_bench_trend():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_trend", os.path.join(repo, "scripts", "bench_trend.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_trend_flags_regressions_by_direction(tmp_path, capsys):
    """The trajectory summarizer (ISSUE 18 satellite): BENCH rounds
    nest metrics under 'parsed', MULTICHIP rounds keep them top-level;
    a >10% move in the metric's BAD direction flags REGRESSED."""
    bt = _load_bench_trend()
    d = str(tmp_path)
    for rnd, rate, p99 in ((1, 1000.0, 10.0), (2, 800.0, 12.0)):
        with open(os.path.join(d, f"BENCH_r{rnd:02d}.json"), "w") as f:
            json.dump({"parsed": {"device_only": rate,
                                  "serve_p99_ms": p99}}, f)
    with open(os.path.join(d, "MULTICHIP_r01.json"), "w") as f:
        json.dump({"n": 1, "cmd": "x", "rc": 0, "tail": "", "ok": True,
                   "parsed": None, "eval_images_per_sec": 500.0}, f)
    with open(os.path.join(d, "MULTICHIP_r02.json"), "w") as f:
        json.dump({"n": 1, "cmd": "x", "rc": 0, "tail": "", "ok": True,
                   "parsed": None, "eval_images_per_sec": 510.0}, f)
    summary = bt.summarize(d, threshold=0.10)
    rows = {r["metric"]: r
            for r in summary["families"]["BENCH"]["trend"]}
    assert rows["device_only"]["direction"] == "higher_better"
    assert rows["device_only"]["regressed"]  # -20%
    assert rows["device_only"]["change_vs_previous"] == pytest.approx(
        -0.2)
    assert rows["serve_p99_ms"]["direction"] == "lower_better"
    assert rows["serve_p99_ms"]["regressed"]  # +20% latency
    mrows = {r["metric"]: r
             for r in summary["families"]["MULTICHIP"]["trend"]}
    assert not mrows["eval_images_per_sec"]["regressed"]  # +2%
    assert set(summary["regressions"]) == {"device_only",
                                           "serve_p99_ms"}

    # CLI: advisory exit 0 despite flags; --strict turns them into 1;
    # --json round-trips the same object.
    assert bt.main([d]) == 0
    assert "REGRESSED" in capsys.readouterr().out
    assert bt.main([d, "--strict"]) == 1
    capsys.readouterr()
    assert bt.main([d, "--json"]) == 0
    obj = json.loads(capsys.readouterr().out)
    assert obj["regressions"] == summary["regressions"]
    # An empty dir reports and exits 0 (advisory even when blind).
    empty = str(tmp_path / "none")
    os.makedirs(empty)
    assert bt.main([empty]) == 0


def test_bench_trend_direction_heuristic():
    bt = _load_bench_trend()
    assert not bt.lower_is_better("eval_images_per_sec")
    assert not bt.lower_is_better("device_only")
    assert not bt.lower_is_better("router_k4_vs_k1")
    assert bt.lower_is_better("hbm_load_sec")
    assert bt.lower_is_better("serve_p99_ms")
    assert bt.lower_is_better("fleet_overhead_pct")
    assert bt.lower_is_better("eval_stall_sec")
    assert bt.lower_is_better("spec_wasted_bytes")
