"""Tiered ingest tests (data/tiered_pipeline.py; ISSUE 1 tentpole).

Pins: residency planning at the 0% / partial / 100% boundaries, exact
epoch semantics per tier, (seed, step) determinism and O(1) resume at
every residency level, worker-count invariance of the parallel decode
stage, the bit-identical zero-budget fallback to the streamed path,
per-shard staged puts vs plain sharded puts, and trainer.fit end to end
on data.loader=tiered with interrupted+resumed ≡ uninterrupted.
"""

import os

import numpy as np
import pytest

from jama16_retina_tpu import trainer
from jama16_retina_tpu.configs import DataConfig, get_config, override
from jama16_retina_tpu.data import hbm_pipeline, tfrecord, tiered_pipeline
from jama16_retina_tpu.utils.logging import read_jsonl

ROW = hbm_pipeline.row_bytes(32)


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tiered_data"))
    tfrecord.write_synthetic_split(d, "train", 48, 32, 3, seed=1)
    tfrecord.write_synthetic_split(d, "val", 24, 32, 2, seed=2)
    return d


def _cfg(resident_bytes: int, **kw) -> DataConfig:
    return DataConfig(
        batch_size=8, tiered_resident_bytes=resident_bytes, **kw
    )


def test_plan_residency_boundaries():
    # 48 records, batch 8 -> 6 steps/epoch.
    assert tiered_pipeline.plan_residency(48, 8, 0) == (6, 0, 0)
    # Huge capacity: every batch fully resident, exactly one epoch pinned.
    assert tiered_pipeline.plan_residency(48, 8, 10**6) == (6, 8, 48)
    # Partial: 24 rows capacity -> 4 resident rows/batch, 24 pinned.
    assert tiered_pipeline.plan_residency(48, 8, 24) == (6, 4, 24)
    # Rounding: capacity that does not divide steps rounds DOWN so the
    # epoch never over-consumes the pinned set.
    steps, res_pb, n_res = tiered_pipeline.plan_residency(48, 8, 23)
    assert (steps, res_pb, n_res) == (6, 3, 18)
    # The streamed tier is always feasible: steps * (B - res_pb) <= n - n_res.
    assert steps * (8 - res_pb) <= 48 - n_res
    # Full residency with n % B != 0 pins ALL n rows (the per-epoch
    # permutation rotates the drop, hbm-style) — not just B*steps.
    assert tiered_pipeline.plan_residency(50, 8, 10**6) == (6, 8, 50)
    # Capacity short of n but rich enough for all-resident batches must
    # still reserve one streamed slot per batch: otherwise the rows
    # capacity cannot pin would be excluded from training PERMANENTLY.
    assert tiered_pipeline.plan_residency(50, 8, 49) == (6, 7, 42)
    # Oversized batch is refused like the hbm loader.
    with pytest.raises(ValueError, match="batch_size"):
        tiered_pipeline.plan_residency(4, 8, 0)


def test_no_record_is_permanently_excluded(tmp_path):
    """n=50 / batch 8 does not divide: at FULL residency the 2-record
    epoch drop must rotate (every record seen across a few epochs), and
    at capacity 49 (cannot pin all 50) the streamed slot must rotate
    the unpinned remainder through training."""
    d = str(tmp_path / "odd")
    tfrecord.write_synthetic_split(d, "train", 50, 32, 2, seed=4)
    all_imgs, _ = hbm_pipeline.load_split_numpy(d, "train", 32)
    everything = {im.tobytes() for im in all_imgs}
    assert len(everything) == 50
    for budget in (10**9, ROW * 49):
        it = tiered_pipeline.train_batches(
            d, "train", _cfg(budget), 32, seed=1
        )
        seen = set()
        for _ in range(6 * 8):  # 8 epochs of 6 steps
            seen |= {
                im.tobytes() for im in np.asarray(next(it)["image"])
            }
        assert seen == everything, f"budget={budget}"


def test_tiny_resident_set_pads_on_wide_mesh(tmp_path):
    """A resident set SMALLER than the mesh's data axis (res_pb=1 ->
    n_res=3 rows on 8 devices) must wrap-pad its device placement
    instead of crashing the sharded put."""
    from jama16_retina_tpu.parallel import mesh as mesh_lib

    d = str(tmp_path / "tiny")
    tfrecord.write_synthetic_split(d, "train", 24, 32, 2, seed=6)
    mesh = mesh_lib.make_mesh()
    it = tiered_pipeline.train_batches(
        d, "train", _cfg(ROW * 4), 32, seed=0, mesh=mesh
    )
    batch = next(it)
    assert batch["image"].shape == (8, 32, 32, 3)


@pytest.mark.parametrize(
    "resident_bytes", [0, ROW * 24, 10**9], ids=["0pct", "50pct", "100pct"]
)
def test_deterministic_and_resumes_o1_at_every_residency(
    data_dir, resident_bytes
):
    cfg = _cfg(resident_bytes)
    a = tiered_pipeline.train_batches(data_dir, "train", cfg, 32, seed=3)
    ref = [next(a) for _ in range(9)]
    # Same seed -> identical stream.
    b = tiered_pipeline.train_batches(data_dir, "train", cfg, 32, seed=3)
    for r in ref:
        got = next(b)
        np.testing.assert_array_equal(
            np.asarray(r["image"]), np.asarray(got["image"])
        )
    # skip_batches=k continues exactly where step k would be — across an
    # epoch boundary (6 steps/epoch, skip 7), at every residency level.
    resumed = tiered_pipeline.train_batches(
        data_dir, "train", cfg, 32, seed=3, skip_batches=7
    )
    for r in ref[7:]:
        got = next(resumed)
        np.testing.assert_array_equal(
            np.asarray(r["image"]), np.asarray(got["image"])
        )
        np.testing.assert_array_equal(
            np.asarray(r["grade"]), np.asarray(got["grade"])
        )


@pytest.mark.parametrize(
    "resident_bytes", [0, ROW * 24, 10**9], ids=["0pct", "50pct", "100pct"]
)
def test_epoch_covers_every_record_once_at_every_residency(
    data_dir, resident_bytes
):
    """48 records / batch 8 = 6 steps/epoch; at 0%, 50% and 100%
    residency each epoch must cover all 48 records exactly once (the
    48/8 fixture divides evenly, so both tiers' drop-remainders are
    empty), and epochs must reshuffle."""
    it = tiered_pipeline.train_batches(
        data_dir, "train", _cfg(resident_bytes), 32, seed=7
    )
    epochs = []
    for _ in range(2):
        batches = [np.asarray(next(it)["image"]) for _ in range(6)]
        epochs.append(np.concatenate(batches))
    for ep in epochs:
        assert len({im.tobytes() for im in ep}) == 48
    assert not np.array_equal(epochs[0], epochs[1])


def test_batch_composition_mixes_tiers(data_dir):
    """Partial residency serves a fixed per-batch quota from each tier:
    resident rows come from the pinned prefix [0, n_res) of the record
    index, streamed rows from the remainder — verified against a full
    host decode of the split."""
    images, grades = hbm_pipeline.load_split_numpy(data_dir, "train", 32)
    resident_keys = {im.tobytes() for im in images[:24]}
    streamed_keys = {im.tobytes() for im in images[24:]}
    it = tiered_pipeline.train_batches(
        data_dir, "train", _cfg(ROW * 24), 32, seed=11
    )
    for _ in range(6):
        batch = np.asarray(next(it)["image"])
        got_res = [im.tobytes() in resident_keys for im in batch]
        # Fixed layout: first res_pb rows resident, rest streamed.
        assert got_res == [True] * 4 + [False] * 4
        assert all(im.tobytes() in streamed_keys for im in batch[4:])


def test_zero_budget_falls_back_bit_identically_to_streamed(data_dir):
    """The acceptance contract: budget 0 -> the SAME batch sequence as
    the INDEPENDENT host-decoded reference (plan -> record ids ->
    direct decode, no staging/combine jit) — a check the loader's
    device plumbing can actually fail. streamed_batches (the public
    streamed mode) is held to the identical sequence."""
    tiered = tiered_pipeline.train_batches(
        data_dir, "train", _cfg(0), 32, seed=5
    )
    reference = tiered_pipeline.host_reference_batches(
        data_dir, "train", DataConfig(batch_size=8), 32, seed=5,
        capacity_rows=0,
    )
    streamed = tiered_pipeline.streamed_batches(
        data_dir, "train", DataConfig(batch_size=8), 32, seed=5
    )
    for _ in range(8):
        a, ref, c = next(tiered), next(reference), next(streamed)
        for got in (a, c):
            np.testing.assert_array_equal(
                np.asarray(got["image"]), ref["image"]
            )
            np.testing.assert_array_equal(
                np.asarray(got["grade"]), ref["grade"]
            )


def test_partial_residency_matches_host_reference(data_dir):
    """The mixed-tier device path (resident gather + staged streamed
    rows + combine jit) reproduces the host-decoded reference sequence
    bit for bit at 50% residency."""
    capacity = 24
    tiered = tiered_pipeline.train_batches(
        data_dir, "train", _cfg(ROW * capacity), 32, seed=13
    )
    reference = tiered_pipeline.host_reference_batches(
        data_dir, "train", DataConfig(batch_size=8), 32, seed=13,
        capacity_rows=capacity,
    )
    for _ in range(8):
        a, ref = next(tiered), next(reference)
        np.testing.assert_array_equal(np.asarray(a["image"]), ref["image"])
        np.testing.assert_array_equal(np.asarray(a["grade"]), ref["grade"])


def test_worker_count_invariance(data_dir):
    """decode_workers is a pure throughput knob: 1 worker and 8 workers
    must produce identical batches (the ParallelDecoder determinism
    contract the resume story rests on)."""
    i1 = tiered_pipeline.train_batches(
        data_dir, "train", _cfg(ROW * 24, decode_workers=1), 32, seed=9
    )
    i8 = tiered_pipeline.train_batches(
        data_dir, "train", _cfg(ROW * 24, decode_workers=8), 32, seed=9
    )
    for _ in range(7):
        a, b = next(i1), next(i8)
        np.testing.assert_array_equal(
            np.asarray(a["image"]), np.asarray(b["image"])
        )
        np.testing.assert_array_equal(
            np.asarray(a["grade"]), np.asarray(b["grade"])
        )


def test_parallel_decoder_matches_single_thread(data_dir):
    """decode_range/decode_batch are worker-count-invariant at the
    array level (each worker fills a disjoint slice)."""
    from jama16_retina_tpu.data.grain_pipeline import (
        ParallelDecoder,
        TFRecordIndex,
    )

    index = TFRecordIndex(tfrecord.list_split(data_dir, "train"))
    one = ParallelDecoder(index, 32, workers=1)
    many = ParallelDecoder(index, 32, workers=6)
    try:
        a_img, a_gr = one.decode_range(0, 48)
        b_img, b_gr = many.decode_range(0, 48)
        np.testing.assert_array_equal(a_img, b_img)
        np.testing.assert_array_equal(a_gr, b_gr)
        ids = [7, 3, 3, 41, 0]
        a = one.decode_batch(ids)
        b = many.decode_batch(ids)
        np.testing.assert_array_equal(a["image"], b["image"])
        np.testing.assert_array_equal(a["grade"], b["grade"])
    finally:
        one.close()
        many.close()


def test_batches_carry_mesh_sharding(data_dir):
    from jama16_retina_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh()  # all 8 fake devices
    it = tiered_pipeline.train_batches(
        data_dir, "train",
        DataConfig(batch_size=16, tiered_resident_bytes=ROW * 24),
        32, seed=0, mesh=mesh,
    )
    batch = next(it)
    assert batch["image"].sharding == mesh_lib.batch_sharding(mesh)
    assert batch["image"].shape == (16, 32, 32, 3)
    assert batch["grade"].shape == (16,)


def test_staged_put_matches_plain_put(data_dir):
    """pipeline.staged_put is a pure staging optimization: same values,
    same sharding as one whole-batch device_put."""
    import jax

    from jama16_retina_tpu.data import pipeline
    from jama16_retina_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh()
    sh = mesh_lib.batch_sharding(mesh)
    x = np.arange(16 * 4 * 3, dtype=np.uint8).reshape(16, 4, 3)
    staged = pipeline.staged_put(x, sh)
    plain = jax.device_put(x, mesh_lib._rank_sharding(x.ndim, sh))
    assert staged.sharding.is_equivalent_to(plain.sharding, x.ndim)
    np.testing.assert_array_equal(np.asarray(staged), np.asarray(plain))
    # Scalars fall back to a plain put instead of crashing.
    s = pipeline.staged_put(np.float32(3.5), sh)
    assert float(s) == 3.5


def test_fit_with_tiered_loader_resumes_exactly(data_dir, tmp_path):
    """trainer.fit end to end on data.loader=tiered at partial
    residency: interrupted+resumed == uninterrupted loss curves
    (SURVEY.md §5.4), resume O(1) by construction."""
    cfg = override(
        get_config("smoke"),
        ["data.loader=tiered", "train.steps=12", "train.eval_every=6",
         "train.log_every=1", "data.augment=true", "data.batch_size=8",
         "eval.batch_size=8", "train.lr_schedule=constant",
         # 24 of 48 rows resident at the smoke config's 64px images.
         f"data.tiered_resident_bytes={hbm_pipeline.row_bytes(64) * 24}"],
    )
    w_full = str(tmp_path / "full")
    trainer.fit(cfg, data_dir, w_full, seed=3)
    full = {
        r["step"]: r["loss"]
        for r in read_jsonl(os.path.join(w_full, "metrics.jsonl"))
        if r["kind"] == "train"
    }
    w_part = str(tmp_path / "part")
    trainer.fit(override(cfg, ["train.steps=6"]), data_dir, w_part, seed=3)
    trainer.fit(override(cfg, ["train.resume=true"]), data_dir, w_part, seed=3)
    part = {
        r["step"]: r["loss"]
        for r in read_jsonl(os.path.join(w_part, "metrics.jsonl"))
        if r["kind"] == "train"
    }
    assert set(full) == set(part) == set(range(1, 13))
    for s in full:
        assert full[s] == part[s], f"step {s}: {full[s]} != {part[s]}"


def test_fit_tf_refuses_tiered_loader(data_dir, tmp_path):
    cfg = override(get_config("smoke"), ["data.loader=tiered"])
    with pytest.raises(ValueError, match="tiered"):
        trainer.fit_tf(cfg, data_dir, str(tmp_path / "x"), seed=0)


def test_write_synthetic_split_rejects_mismatched_sizes(tmp_path):
    """ADVICE r5: synth_cfg.image_size must not silently override a
    disagreeing explicit image_size."""
    from jama16_retina_tpu.data import synthetic

    with pytest.raises(ValueError, match="image_size"):
        tfrecord.write_synthetic_split(
            str(tmp_path), "train", 4, image_size=64,
            synth_cfg=synthetic.SynthConfig(image_size=32),
        )
    # Matching sizes (and either alone) stay accepted.
    tfrecord.write_synthetic_split(
        str(tmp_path), "ok", 2, image_size=32, num_shards=1,
        synth_cfg=synthetic.SynthConfig(image_size=32),
    )
