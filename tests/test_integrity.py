"""Durable-state integrity (ISSUE 13): sealed artifacts, disk-fault
injection, repo-wide fsck/repair, and retention GC.

The contract under test, layer by layer:

  * every durable writer publishes through integrity/artifact.py's ONE
    seam — atomic, sealed (schema/version/env + sha256), and any
    corruption is a TYPED, COUNTED refusal on load, never silent;
  * injected disk faults (torn/bitflip/truncate/ENOSPC at the
    ``integrity.write`` family) and a literal kill -9 inside the
    commit window are all detectable or harmless — no torn artifact is
    ever readable;
  * ``fsck_workdir`` classifies CORRUPT/STALE/ORPHAN/REPAIRABLE and
    ``repair_workdir`` deletes derivable corpses / quarantines the
    rest with a sealed ledger, NEVER touching live.json-reachable or
    open-cycle state;
  * ``plan_retention`` is dry-run-first (plan == apply ledger) and the
    same protection pin holds for GC.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from jama16_retina_tpu.configs import get_config, override
from jama16_retina_tpu.integrity import artifact as artifact_lib
from jama16_retina_tpu.integrity import fsck as fsck_lib
from jama16_retina_tpu.integrity import retention as retention_lib
from jama16_retina_tpu.lifecycle.journal import Journal
from jama16_retina_tpu.obs import faultinject
from jama16_retina_tpu.obs import quality as quality_lib
from jama16_retina_tpu.obs.registry import Registry
from jama16_retina_tpu.serve import policy as policy_lib

pytestmark = pytest.mark.integrity

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GRAFTFSCK = os.path.join(REPO_ROOT, "scripts", "graftfsck.py")


def flip_byte(path: str, marker: "bytes | None" = None) -> None:
    """One flipped bit — inside ``marker`` for JSON artifacts (keeps
    the file parseable so the CHECKSUM, not the parser, must catch
    it); mid-file for binaries."""
    with open(path, "rb") as f:
        blob = bytearray(f.read())
    i = blob.find(marker) if marker else len(blob) // 2
    assert i >= 0
    blob[i] ^= 0x01
    with open(path, "wb") as f:
        f.write(bytes(blob))


def seed_policy(path: str) -> "policy_lib.ServePolicy":
    pol = policy_lib.derive_policy(
        [{"bucket": 8, "concurrency": 2, "images_per_sec": 50.0,
          "p50_ms": 2.0, "p99_ms": 4.0}],
        {"arch": "marker"},
    )
    policy_lib.save_policy(path, pol)
    return pol


# ---------------------------------------------------------------------------
# The sealed envelope
# ---------------------------------------------------------------------------


def test_sealed_roundtrip_and_seal_shape(tmp_path):
    p = str(tmp_path / "a.json")
    payload = {"format": "x", "version": 3, "vals": [1, 2, 3]}
    artifact_lib.write_sealed_json(p, payload, schema="serve.policy",
                                   version=3)
    doc, seal = artifact_lib.read_sealed_json(p, artifact="policy")
    assert doc == payload  # payload keys at the top level, seal stripped
    assert seal["schema"] == "serve.policy"
    assert seal["schema_version"] == 3
    assert seal["seal_version"] == artifact_lib.SEAL_VERSION
    assert len(seal["sha256"]) == 64
    assert set(seal["env"]) == {"python", "numpy", "platform"}
    # No temp leftovers: the write published via rename.
    assert not [n for n in os.listdir(tmp_path) if ".tmp" in n]


def test_sealing_is_deterministic(tmp_path):
    """Same payload -> byte-identical sealed file (no clocks in the
    seal): the lifecycle journal's byte-stability pins survive."""
    a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    payload = {"k": [1, 2], "s": "x"}
    artifact_lib.write_sealed_json(a, payload, schema="s", version=1)
    artifact_lib.write_sealed_json(b, payload, schema="s", version=1)
    assert open(a, "rb").read() == open(b, "rb").read()


def test_digest_mismatch_raises_typed_counted_with_rebuild(tmp_path):
    p = str(tmp_path / "pol.json")
    seed_policy(p)
    flip_byte(p, marker=b"marker")
    reg = Registry()
    with pytest.raises(artifact_lib.ArtifactCorrupt) as ei:
        artifact_lib.read_sealed_json(p, artifact="policy", registry=reg)
    msg = str(ei.value)
    assert p in msg                      # names the file
    assert ei.value.expected != ei.value.actual
    assert ei.value.expected in msg and ei.value.actual in msg
    assert "derive_serve_policy" in msg  # names the rebuild command
    assert reg.counter("integrity.corrupt").value == 1
    assert reg.counter("integrity.corrupt.policy").value == 1


def test_unsealed_legacy_file_loads_with_none_seal(tmp_path):
    p = str(tmp_path / "legacy.json")
    with open(p, "w") as f:
        json.dump({"format": "old", "v": 1}, f)
    doc, seal = artifact_lib.read_sealed_json(p)
    assert seal is None and doc["format"] == "old"


def test_sidecar_seal_detects_bitflip_and_size_change(tmp_path):
    p = str(tmp_path / "blob.bin")
    blob = bytes(range(256)) * 8
    artifact_lib.atomic_write_bytes(p, blob)
    artifact_lib.write_seal_sidecar(p, schema="quality.canary",
                                    version=1, blob=blob)
    assert artifact_lib.verify_sidecar(p, artifact="canary") == "ok"
    flip_byte(p)
    reg = Registry()
    with pytest.raises(artifact_lib.ArtifactCorrupt):
        artifact_lib.verify_sidecar(p, artifact="canary", registry=reg)
    assert reg.counter("integrity.corrupt.canary").value == 1
    with open(p, "ab") as f:  # size change is caught before hashing
        f.write(b"x")
    with pytest.raises(artifact_lib.ArtifactCorrupt, match="size"):
        artifact_lib.verify_sidecar(p, artifact="canary")
    # No sidecar = legacy, tolerated.
    q = str(tmp_path / "other.bin")
    artifact_lib.atomic_write_bytes(q, b"abc")
    assert artifact_lib.verify_sidecar(q) == "unsealed"


# ---------------------------------------------------------------------------
# Disk-fault injection at the integrity.write family
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind", ["torn", "bitflip", "truncate",
                                  "corrupt"])
def test_injected_disk_fault_is_always_detected(tmp_path, kind):
    """Every corrupt-family kind at the sealed writer's payload seam
    yields a file the reader REFUSES — typed ArtifactCorrupt when the
    damage preserves JSON, the loud unparseable refusal otherwise.
    Silent acceptance is the one outcome that must be impossible."""
    p = str(tmp_path / "a.json")
    payload = {"data": list(range(64)), "name": "drillvalue"}
    prev = faultinject.arm({
        "integrity.write": {"kind": kind, "on_calls": [1]},
    })
    try:
        artifact_lib.write_sealed_json(p, payload, schema="s", version=1)
    finally:
        faultinject.arm(prev)
    with pytest.raises((artifact_lib.ArtifactCorrupt, ValueError)):
        artifact_lib.read_sealed_json(p, artifact="journal",
                                      registry=Registry())


def test_enospc_style_write_failure_keeps_old_artifact(tmp_path):
    p = str(tmp_path / "a.json")
    artifact_lib.write_sealed_json(p, {"gen": 1}, schema="s", version=1)
    prev = faultinject.arm({
        "integrity.write": {"kind": "error", "on_calls": [1],
                            "error": "OSError",
                            "message": "No space left on device"},
    })
    try:
        with pytest.raises(OSError, match="No space left"):
            artifact_lib.write_sealed_json(p, {"gen": 2}, schema="s",
                                           version=1)
    finally:
        faultinject.arm(prev)
    doc, _ = artifact_lib.read_sealed_json(p)
    assert doc["gen"] == 1  # the old artifact is untouched and valid
    assert not [n for n in os.listdir(tmp_path) if ".tmp" in n]


def test_new_kinds_validated_in_spec():
    plan = faultinject.plan_from_spec({
        "integrity.write": {"kind": "torn", "on_calls": [1]},
    })
    assert plan.site("integrity.write").kind == "torn"
    with pytest.raises(ValueError, match="unknown kind"):
        faultinject.plan_from_spec({
            "integrity.write": {"kind": "shred", "on_calls": [1]},
        })


def test_kill9_in_commit_window_leaves_no_readable_torn_artifact(tmp_path):
    """THE torn-write drill: a writer SIGKILLed between fsync and the
    rename publish leaves the OLD artifact fully readable and only an
    inert .tmp behind."""
    kdir = str(tmp_path / "lc")
    Journal(kdir).append("DRIFT_DETECTED", cycle=0, reason="pre")
    child_src = (
        "import sys\n"
        f"sys.path.insert(0, {REPO_ROOT!r})\n"
        "from jama16_retina_tpu.obs import faultinject\n"
        "faultinject.arm_from_env_or_config()\n"
        "from jama16_retina_tpu.lifecycle.journal import Journal\n"
        f"Journal({kdir!r}).append('RETRAIN', cycle=0)\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAMA16_FAULTS=json.dumps({
        "integrity.write.commit": {"kind": "latency", "on_calls": [1],
                                   "delay_s": 60.0},
    }))
    child = subprocess.Popen([sys.executable, "-c", child_src], env=env)
    deadline = time.time() + 60
    tmp_seen = False
    while time.time() < deadline:
        if any(".tmp." in n for n in os.listdir(kdir)):
            tmp_seen = True
            break
        if child.poll() is not None:
            break
        time.sleep(0.02)
    child.send_signal(signal.SIGKILL)
    child.wait(timeout=30)
    assert tmp_seen, "the commit-window latency plan never held the write"
    j = Journal(kdir)  # loads cleanly: the OLD content, not a torn file
    assert j.state == "DRIFT_DETECTED"


# ---------------------------------------------------------------------------
# Every adopted writer refuses corruption typed
# ---------------------------------------------------------------------------


def test_journal_and_live_pointer_seal_detect_bitflip(tmp_path):
    d = str(tmp_path / "lc")
    j = Journal(d)
    j.append("DRIFT_DETECTED", cycle=0, reason="drift")
    flip_byte(os.path.join(d, "journal.json"), marker=b"drift")
    with pytest.raises(artifact_lib.ArtifactCorrupt):
        Journal(d)
    d2 = str(tmp_path / "lc2")
    j2 = Journal(d2)
    j2.write_live(["/ckpt/m0"])
    assert j2.read_live() == ["/ckpt/m0"]
    flip_byte(os.path.join(d2, "live.json"), marker=b"ckpt")
    with pytest.raises(artifact_lib.ArtifactCorrupt):
        j2.read_live()


def test_policy_profile_canary_seals_detect_corruption(tmp_path):
    ppath = str(tmp_path / "pol.json")
    seed_policy(ppath)
    assert policy_lib.load_policy(ppath).max_batch == 8  # intact loads
    flip_byte(ppath, marker=b"marker")
    with pytest.raises(artifact_lib.ArtifactCorrupt):
        policy_lib.load_policy(ppath)

    prof = quality_lib.build_profile(
        np.random.default_rng(0).random(64),
        thresholds=[{"threshold": 0.5}],
    )
    prpath = str(tmp_path / "prof.json")
    quality_lib.save_profile(prpath, prof)
    assert quality_lib.load_profile(prpath)["kind"] == "quality_profile"
    flip_byte(prpath, marker=b"threshold")
    with pytest.raises(artifact_lib.ArtifactCorrupt):
        quality_lib.load_profile(prpath)

    rng = np.random.default_rng(1)
    imgs = rng.integers(0, 256, (2, 8, 8, 3), np.uint8)
    cpath = quality_lib.save_canary(str(tmp_path / "canary"), imgs,
                                    scores=rng.random(2))
    assert cpath.endswith(".npz")
    assert os.path.exists(artifact_lib.sidecar_path(cpath))
    back, scores = quality_lib.load_canary_file(cpath)  # intact loads
    assert np.array_equal(back, imgs)
    flip_byte(cpath)
    with pytest.raises(artifact_lib.ArtifactCorrupt):
        quality_lib.load_canary_file(cpath)


@pytest.fixture(scope="module")
def shard_fixture(tmp_path_factory):
    from jama16_retina_tpu.data import rawshard as rawshard_lib
    from jama16_retina_tpu.data import tfrecord as tfrecord_lib

    root = tmp_path_factory.mktemp("rs")
    src = str(root / "data")
    tfrecord_lib.write_synthetic_split(src, "train", 12, image_size=16,
                                       num_shards=1, seed=0)
    rawshard_lib.transcode_split(src, "train", image_size=16,
                                 shard_records=4, workers=1)
    return src, rawshard_lib.default_shard_dir(src, 16)


def test_rawshard_manifest_sealed_with_per_shard_digests(shard_fixture):
    from jama16_retina_tpu.data import rawshard as rawshard_lib

    src, shard_dir = shard_fixture
    mpath = rawshard_lib.manifest_path(shard_dir, "train")
    with open(mpath) as f:
        m = json.load(f)
    assert artifact_lib.SEAL_KEY in m
    for e in m["shards"]:
        assert len(e["images_sha256"]) == 64
        assert len(e["grades_sha256"]) == 64
        blob = open(os.path.join(shard_dir, e["images"]), "rb").read()
        import hashlib

        assert hashlib.sha256(blob).hexdigest() == e["images_sha256"]
    # The loader verifies the manifest's seal.
    rawshard_lib.RawShardSplit(shard_dir, "train", image_size=16,
                               source_dir=src)


def test_corrupt_manifest_names_the_MANIFEST_rebuild(shard_fixture,
                                                     tmp_path):
    """Review regression: a corrupt manifest's error must name the
    manifest rebuild (re-run the transcode, it resumes), not the
    shard-pair deletion hint."""
    import shutil

    from jama16_retina_tpu.data import rawshard as rawshard_lib

    _src, shard_dir = shard_fixture
    copy = str(tmp_path / "rs")
    shutil.copytree(shard_dir, copy)
    flip_byte(rawshard_lib.manifest_path(copy, "train"),
              marker=b"train-00000")
    with pytest.raises(artifact_lib.ArtifactCorrupt) as ei:
        rawshard_lib.RawShardSplit(copy, "train", image_size=16)
    assert "it resumes" in str(ei.value)
    assert "delete the shard pair" not in str(ei.value)


def test_compile_cache_entry_corruption_degrades_counted(tmp_path):
    import jax
    import jax.numpy as jnp

    from jama16_retina_tpu.serve.compilecache import CompileCache

    reg = Registry()
    cache = CompileCache(str(tmp_path / "cc"), {"probe": 1}, registry=reg)
    probe = jax.jit(lambda x: x + 1).lower(
        jnp.zeros((2,), jnp.float32)
    ).compile()
    if not cache.save("probe", probe):
        pytest.skip("backend without executable serialization")
    assert cache.load("probe") is not None  # intact: a hit
    flip_byte(cache.entry_path("probe"))
    misses0 = reg.counter("serve.compile_cache.misses").value
    assert cache.load("probe") is None      # degrade, never raise
    assert reg.counter("serve.compile_cache.misses").value == misses0 + 1
    assert reg.counter("integrity.corrupt.compile_cache").value >= 1


# ---------------------------------------------------------------------------
# fsck: classify + repair + protection
# ---------------------------------------------------------------------------


def test_fsck_classifies_all_four_statuses(tmp_path):
    wd = str(tmp_path)
    # CORRUPT: a bit-flipped sealed policy.
    seed_policy(os.path.join(wd, "pol.json"))
    flip_byte(os.path.join(wd, "pol.json"), marker=b"marker")
    # STALE: an unsealed legacy profile.
    with open(os.path.join(wd, "prof.json"), "w") as f:
        json.dump({"version": 1, "kind": "quality_profile",
                   "bins": 20, "score_hist": [0] * 20}, f)
    # ORPHAN: a dead tmp leftover.
    with open(os.path.join(wd, "x.json.tmp.123"), "w") as f:
        f.write("{")
    # REPAIRABLE: a torn JSONL line.
    with open(os.path.join(wd, "metrics.jsonl"), "w") as f:
        f.write('{"kind": "train", "step": 1}\n{"kind": "tr')
    report = fsck_lib.fsck_workdir(wd, registry=Registry())
    by = report.by_status()
    assert {f.artifact for f in by["CORRUPT"]} == {"policy"}
    assert {f.artifact for f in by["STALE"]} == {"profile"}
    assert any(f.path.endswith(".tmp.123") for f in by["ORPHAN"])
    assert {f.artifact for f in by["REPAIRABLE"]} == {"jsonl"}

    ledger = fsck_lib.repair_workdir(wd, report=report,
                                     registry=Registry())
    acts = {a["action"] for a in ledger["actions"]}
    assert acts == {"delete", "rewrite"}
    # Post-repair: the corrupt policy is gone (derivable — rebuild on
    # demand), the torn line dropped losslessly, the STALE legacy
    # profile reported but untouched.
    report2 = fsck_lib.fsck_workdir(wd, registry=Registry())
    assert set(report2.by_status()) == {"STALE"}
    lines = open(os.path.join(wd, "metrics.jsonl")).read().splitlines()
    assert lines == ['{"kind": "train", "step": 1}']


def test_fsck_cross_refs_live_member_and_commit_pointer(tmp_path):
    wd = str(tmp_path)
    j = Journal(os.path.join(wd, "lifecycle"))
    j.write_live([os.path.join(wd, "member_00")])  # does not exist
    report = fsck_lib.fsck_workdir(wd)
    assert any(
        f.artifact == "checkpoint" and f.status == "CORRUPT"
        and "live.json" in f.detail
        for f in report.findings
    )
    # A restorable-looking member clears it.
    os.makedirs(os.path.join(wd, "member_00", "latest", "1"))
    assert fsck_lib.fsck_workdir(wd).clean


def test_repair_quarantines_journal_with_sealed_ledger(tmp_path):
    wd = str(tmp_path)
    d = os.path.join(wd, "lifecycle")
    j = Journal(d)
    j.append("DRIFT_DETECTED", cycle=0, reason="drift")
    j.append("ROLLBACK", cycle=0, cause="done")  # CLOSED cycle
    flip_byte(os.path.join(d, "journal.json"), marker=b"drift")
    report = fsck_lib.fsck_workdir(wd, registry=Registry())
    assert any(f.artifact == "journal" and f.status == "CORRUPT"
               for f in report.findings)
    ledger = fsck_lib.repair_workdir(wd, report=report,
                                     registry=Registry())
    assert any(a["action"] == "quarantine" for a in ledger["actions"])
    qdir = os.path.join(wd, "quarantine")
    assert os.path.exists(os.path.join(qdir, "journal.json"))
    # The ledger is itself a sealed artifact.
    doc, seal = artifact_lib.read_sealed_json(
        os.path.join(qdir, "ledger.json"), artifact="ledger"
    )
    assert seal is not None and doc["actions"]
    assert fsck_lib.fsck_workdir(wd, registry=Registry()).clean


def test_repair_never_touches_open_cycle_or_live_members(tmp_path):
    """THE protection pin: an open cycle's journal (even corrupt) and
    anything under a live.json member dir are skipped by repair."""
    wd = str(tmp_path)
    d = os.path.join(wd, "lifecycle")
    member = os.path.join(wd, "member_00")
    os.makedirs(os.path.join(member, "latest", "1"))
    j = Journal(d)
    j.write_live([member])
    j.append("DRIFT_DETECTED", cycle=0, reason="open")  # cycle OPEN
    flip_byte(os.path.join(d, "journal.json"), marker=b"open")
    # A corrupt artifact INSIDE the live member dir.
    seed_policy(os.path.join(member, "pol.json"))
    flip_byte(os.path.join(member, "pol.json"), marker=b"marker")
    report = fsck_lib.fsck_workdir(wd, registry=Registry())
    paths = {f.path for f in report.findings}
    assert any("journal.json" in p for p in paths)
    ledger = fsck_lib.repair_workdir(wd, report=report,
                                     registry=Registry())
    assert ledger["actions"] == []  # nothing touched
    assert {s["why"].split(" ")[0] for s in ledger["skipped"]} \
        == {"protected"}
    assert os.path.exists(os.path.join(d, "journal.json"))
    assert os.path.exists(os.path.join(member, "pol.json"))


def test_fsck_rawshard_bitflip_trim_then_resume_rebuilds(tmp_path):
    from jama16_retina_tpu.data import rawshard as rawshard_lib
    from jama16_retina_tpu.data import tfrecord as tfrecord_lib

    wd = str(tmp_path)
    src = os.path.join(wd, "data")
    tfrecord_lib.write_synthetic_split(src, "train", 8, image_size=16,
                                       num_shards=1, seed=0)
    rawshard_lib.transcode_split(src, "train", image_size=16,
                                 shard_records=4, workers=1)
    shard_dir = rawshard_lib.default_shard_dir(src, 16)
    victim = sorted(n for n in os.listdir(shard_dir)
                    if n.endswith(".images.npy"))[0]
    flip_byte(os.path.join(shard_dir, victim))
    reg = Registry()
    report = fsck_lib.fsck_workdir(wd, registry=reg)
    assert any(f.path.endswith(victim) and f.status == "CORRUPT"
               for f in report.findings)
    assert reg.counter("integrity.corrupt.rawshard").value >= 1
    fsck_lib.repair_workdir(wd, report=report, registry=reg)
    # Trimmed: the manifest is a valid PARTIAL transcode now...
    report2 = fsck_lib.fsck_workdir(wd)
    assert {f.status for f in report2.findings} == {"STALE"}
    # ...and the named rebuild command (resume) restores cleanliness.
    rawshard_lib.transcode_split(src, "train", image_size=16,
                                 shard_records=4, workers=1)
    assert fsck_lib.fsck_workdir(wd).clean
    rs = rawshard_lib.RawShardSplit(shard_dir, "train", image_size=16,
                                    source_dir=src)
    assert len(rs) == 8


def test_repair_trims_manifest_for_a_MISSING_shard(tmp_path):
    """Review regression: a shard that is GONE (not just damaged) must
    still be trimmed out of its manifest by --repair — the repair edits
    the manifest, so the missing target must not short-circuit it."""
    from jama16_retina_tpu.data import rawshard as rawshard_lib
    from jama16_retina_tpu.data import tfrecord as tfrecord_lib

    wd = str(tmp_path)
    src = os.path.join(wd, "data")
    tfrecord_lib.write_synthetic_split(src, "train", 8, image_size=16,
                                       num_shards=1, seed=0)
    rawshard_lib.transcode_split(src, "train", image_size=16,
                                 shard_records=4, workers=1)
    shard_dir = rawshard_lib.default_shard_dir(src, 16)
    victim = sorted(n for n in os.listdir(shard_dir)
                    if n.endswith(".images.npy"))[0]
    os.unlink(os.path.join(shard_dir, victim))
    report = fsck_lib.fsck_workdir(wd)
    assert any(f.repair == "trim-manifest" for f in report.findings)
    ledger = fsck_lib.repair_workdir(wd, report=report,
                                     registry=Registry())
    assert any(a["action"] == "trim-manifest" for a in ledger["actions"])
    # Post-repair: a valid PARTIAL manifest (STALE only), and the
    # resume command restores cleanliness.
    assert {f.status for f in fsck_lib.fsck_workdir(wd).findings} \
        == {"STALE"}
    rawshard_lib.transcode_split(src, "train", image_size=16,
                                 shard_records=4, workers=1)
    assert fsck_lib.fsck_workdir(wd).clean


def test_quarantine_takes_the_seal_sidecar_along(tmp_path):
    """Review regression: quarantining a sidecar-sealed binary must
    move the sidecar too — one --repair pass restores cleanliness."""
    wd = str(tmp_path)
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (2, 8, 8, 3), np.uint8)
    cpath = quality_lib.save_canary(os.path.join(wd, "canary.npz"),
                                    imgs, scores=rng.random(2))
    flip_byte(cpath)
    report = fsck_lib.fsck_workdir(wd, registry=Registry())
    ledger = fsck_lib.repair_workdir(wd, report=report,
                                     registry=Registry())
    q = [a for a in ledger["actions"] if a["action"] == "quarantine"]
    assert q and q[0]["sidecar_moved_to"]
    assert not os.path.exists(artifact_lib.sidecar_path(cpath))
    assert fsck_lib.fsck_workdir(wd, registry=Registry()).clean


def test_orphan_jex_sidecar_single_finding_right_class(tmp_path):
    """Review regression: a compile-cache sidecar without its entry is
    ONE finding, labeled compile_cache (not a duplicate 'canary')."""
    wd = str(tmp_path)
    cc = os.path.join(wd, "cc")
    os.makedirs(cc)
    artifact_lib.write_sealed_json(
        os.path.join(cc, "MANIFEST.json"),
        {"version": 1, "fingerprint": "f", "detail": {}},
        schema="compile_cache.manifest", version=1,
    )
    p = os.path.join(cc, "exec_b8.jex")
    artifact_lib.atomic_write_bytes(p, b"x" * 64)
    artifact_lib.write_seal_sidecar(p, schema="compile_cache.entry",
                                    version=1, blob=b"x" * 64)
    os.unlink(p)
    report = fsck_lib.fsck_workdir(wd)
    orphans = [f for f in report.findings if f.status == "ORPHAN"]
    assert len(orphans) == 1
    assert orphans[0].artifact == "compile_cache"


def test_retention_rotation_with_existing_rotation_keeps_fresh_log(
        tmp_path):
    """Review regression: rotating metrics.jsonl onto an existing .1
    must delete the OLD .1 first — never the freshly rotated log; and
    a .1 whose base is NOT rotating is the kept rotation."""
    wd = str(tmp_path)
    big = os.path.join(wd, "metrics.jsonl")
    with open(big, "w") as f:
        f.write('{"kind": "fresh"}\n' * 50)
    with open(big + ".1", "w") as f:
        f.write('{"kind": "old"}\n')
    keep = os.path.join(wd, "small.jsonl")
    with open(keep, "w") as f:
        f.write('{"kind": "k"}\n')
    with open(keep + ".1", "w") as f:
        f.write('{"kind": "kept rotation"}\n')
    plan = retention_lib.plan_retention(wd, _cfg(telemetry_max_bytes=100))
    kinds = [(a.kind, os.path.basename(a.path)) for a in plan.actions]
    assert kinds.index(("delete", "metrics.jsonl.1")) \
        < kinds.index(("rotate", "metrics.jsonl"))
    assert ("delete", "small.jsonl.1") not in kinds  # base not rotating
    retention_lib.apply_plan(plan, registry=Registry())
    assert not os.path.exists(big)
    assert "fresh" in open(big + ".1").read()  # the CURRENT log survived
    assert "kept rotation" in open(keep + ".1").read()


def test_check_integrity_does_not_page_on_stale_counters(tmp_path):
    """Review regression: cumulative integrity.corrupt counters flushed
    BEFORE a clean fsck verdict are repaired history, not evidence —
    the cron probe must return 0 after repair + re-fsck."""
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    wd = str(tmp_path)
    with open(os.path.join(wd, "metrics.jsonl"), "w") as f:
        f.write(json.dumps({
            "kind": "telemetry", "t": 100.0,
            "counters": {"integrity.corrupt": 3}, "gauges": {},
            "histograms": {},
        }) + "\n")
    os.makedirs(os.path.join(wd, "integrity"))
    artifact_lib.write_sealed_json(
        os.path.join(wd, "integrity", "fsck-last.json"),
        {"kind": "integrity_fsck", "t": 200.0, "clean": True,
         "corrupt_at_verdict": 3.0,
         "counts": {}, "findings": [], "checked": {}, "repaired": None},
        schema="integrity.fsck", version=1,
    )
    code, msg = obs_report.check_integrity(wd)
    assert code == 0, msg  # stale counters predate the clean verdict
    # A LIVE run keeps flushing its cumulative pre-repair count with
    # newer timestamps: still repaired history, still quiet.
    with open(os.path.join(wd, "metrics.jsonl"), "a") as f:
        f.write(json.dumps({
            "kind": "telemetry", "t": 300.0,
            "counters": {"integrity.corrupt": 3}, "gauges": {},
            "histograms": {},
        }) + "\n")
    code, msg = obs_report.check_integrity(wd)
    assert code == 0, msg
    # Only the counter GROWING past the verdict's pinned value pages.
    with open(os.path.join(wd, "metrics.jsonl"), "a") as f:
        f.write(json.dumps({
            "kind": "telemetry", "t": 400.0,
            "counters": {"integrity.corrupt": 4}, "gauges": {},
            "histograms": {},
        }) + "\n")
    code, msg = obs_report.check_integrity(wd)
    assert code == 1 and "grew" in msg


def test_graftfsck_cli_exit_codes_and_verdict(tmp_path):
    wd = str(tmp_path)
    ppath = os.path.join(wd, "pol.json")
    seed_policy(ppath)
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def run(*args):
        return subprocess.run([sys.executable, GRAFTFSCK, wd, *args],
                              capture_output=True, text=True, env=env,
                              timeout=300)

    assert run().returncode == 0
    flip_byte(ppath, marker=b"marker")
    r = run("--json")
    assert r.returncode == 1
    doc = json.loads(r.stdout)
    assert any(ppath in f["path"] for f in doc["findings"])
    assert run("--repair").returncode == 0
    assert run().returncode == 0
    # Every run wrote the sealed verdict obs_report reads.
    verdict, seal = artifact_lib.read_sealed_json(
        os.path.join(wd, "integrity", "fsck-last.json")
    )
    assert seal is not None and verdict["clean"] is True


# ---------------------------------------------------------------------------
# Retention GC
# ---------------------------------------------------------------------------


def _cfg(**integrity_over):
    cfg = get_config("smoke")
    over = [f"integrity.{k}={v}" for k, v in integrity_over.items()]
    return override(cfg, over) if over else cfg


def _mk_dump(bb: str, name: str, mtime: float) -> str:
    d = os.path.join(bb, name)
    os.makedirs(d)
    with open(os.path.join(d, "meta.json"), "w") as f:
        json.dump({"reason": name}, f)
    os.utime(d, (mtime, mtime))
    return d


def test_retention_dry_run_ledger_matches_apply(tmp_path):
    wd = str(tmp_path)
    bb = os.path.join(wd, "blackbox")
    for i in range(25):
        _mk_dump(bb, f"{i:02d}-r", 1_000_000 + i)
    with open(os.path.join(wd, "metrics.jsonl.prev"), "w") as f:
        f.write("{}\n")
    cfg = _cfg()
    plan1 = retention_lib.plan_retention(wd, cfg)
    plan2 = retention_lib.plan_retention(wd, cfg)
    assert plan1.ledger() == plan2.ledger()  # pure over fs state
    dry = plan1.ledger()
    applied = retention_lib.apply_plan(plan1, registry=Registry())
    assert applied["actions"] == dry["actions"]
    assert applied["executed"] == dry["actions"]  # apply == dry run
    # The sealed GC ledger landed.
    doc, seal = artifact_lib.read_sealed_json(
        os.path.join(wd, "integrity", "gc-ledger.json")
    )
    assert seal is not None and len(doc["runs"]) == 1


def test_retention_blackbox_cap_oldest_first(tmp_path):
    wd = str(tmp_path)
    bb = os.path.join(wd, "blackbox")
    for i in range(25):
        _mk_dump(bb, f"{i:02d}-r", 1_000_000 + i)
    reg = Registry()
    plan = retention_lib.plan_retention(wd, _cfg())
    deleted = {os.path.basename(a.path) for a in plan.actions
               if a.cls == "blackbox"}
    assert deleted == {f"{i:02d}-r" for i in range(5)}  # the 5 OLDEST
    retention_lib.apply_plan(plan, registry=reg)
    assert sorted(os.listdir(bb)) == [f"{i:02d}-r" for i in range(5, 25)]
    assert reg.counter("integrity.gc.deleted.blackbox").value == 5
    assert reg.counter("integrity.gc.deleted").value == 5


def test_retention_cache_lru_and_telemetry_rotation(tmp_path):
    wd = str(tmp_path)
    cc = os.path.join(wd, "cache")
    os.makedirs(cc)
    artifact_lib.write_sealed_json(
        os.path.join(cc, "MANIFEST.json"),
        {"version": 1, "fingerprint": "f", "detail": {}},
        schema="compile_cache.manifest", version=1,
    )
    for i in range(4):
        p = os.path.join(cc, f"exec_b{i}.jex")
        artifact_lib.atomic_write_bytes(p, b"x" * 1000)
        os.utime(p, (2_000_000 + i, 2_000_000 + i))
    big = os.path.join(wd, "metrics.jsonl")
    with open(big, "w") as f:
        f.write('{"kind": "t"}\n' * 200)
    cfg = _cfg(cache_max_bytes=2500, telemetry_max_bytes=100)
    plan = retention_lib.plan_retention(wd, cfg)
    cache_dels = sorted(os.path.basename(a.path) for a in plan.actions
                        if a.cls == "compile_cache")
    assert cache_dels == ["exec_b0.jex", "exec_b1.jex"]  # LRU first
    assert any(a.kind == "rotate" and a.path == big
               for a in plan.actions)
    retention_lib.apply_plan(plan, registry=Registry())
    assert os.path.exists(big + ".1") and not os.path.exists(big)
    assert os.path.exists(os.path.join(cc, "MANIFEST.json"))


def test_retention_never_collects_live_or_open_cycle(tmp_path):
    """THE GC protection pin: candidate sets of closed cycles beyond
    the keep window are collected — but NEVER one named by live.json,
    and NEVER the open cycle's, regardless of age."""
    wd = str(tmp_path)
    lc = os.path.join(wd, "lifecycle")
    j = Journal(lc)
    # Cycles 0..3 closed, 4 open. Candidate roots for each.
    for c in range(5):
        cand = os.path.join(lc, f"candidate-{c:04d}")
        os.makedirs(os.path.join(cand, "member_00"))
        j.append("DRIFT_DETECTED", cycle=c, reason="r")
        j.append("RETRAIN", cycle=c,
                 member_dirs=[os.path.join(cand, "member_00")])
        if c < 4:
            j.append("ROLLBACK", cycle=c, cause="x")
    # live.json points INTO the OLDEST candidate (a promoted-then-
    # committed set that later cycles never replaced).
    j.write_live([os.path.join(lc, "candidate-0000", "member_00")])
    plan = retention_lib.plan_retention(wd, _cfg(keep_candidate_cycles=1))
    planned = {os.path.basename(a.path) for a in plan.actions}
    # Closed cycles beyond keep=1 are 0, 1, 2 — but 0 is live-pinned
    # and 4 is open: only 1 and 2 are collectible.
    assert planned == {"candidate-0001", "candidate-0002"}
    retention_lib.apply_plan(plan, registry=Registry())
    left = sorted(n for n in os.listdir(lc) if n.startswith("candidate"))
    assert left == ["candidate-0000", "candidate-0003",
                    "candidate-0004"]


def test_flightrec_prunes_blackbox_across_runs(tmp_path):
    from jama16_retina_tpu.obs.flightrec import FlightRecorder
    from jama16_retina_tpu.obs.trace import Tracer

    wd = str(tmp_path)
    bb = os.path.join(wd, "blackbox")
    for i in range(6):  # dumps left behind by PREVIOUS runs
        _mk_dump(bb, f"old-{i}", 1_000_000 + i)
    reg = Registry()
    fr = FlightRecorder(wd, registry=reg, tracer=Tracer(),
                        blackbox_keep=4)
    fr.dump("drill")
    kept = sorted(os.listdir(bb))
    assert len(kept) == 4
    assert "01-drill" in kept            # this run's dump survives
    assert "old-0" not in kept and "old-1" not in kept  # oldest pruned
    assert reg.counter("obs.blackbox_pruned").value == 3


# ---------------------------------------------------------------------------
# obs_report: Integrity section + --check-integrity
# ---------------------------------------------------------------------------


def test_check_integrity_exit_codes(tmp_path):
    wd = str(tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    report_py = os.path.join(REPO_ROOT, "scripts", "obs_report.py")

    def probe():
        return subprocess.run(
            [sys.executable, report_py, "--check-integrity", wd],
            capture_output=True, text=True, env=env, timeout=300,
        )

    r = probe()
    assert r.returncode == 2 and "blind" in r.stdout  # never fsck'd
    seed_policy(os.path.join(wd, "pol.json"))
    run_fsck = lambda *a: subprocess.run(  # noqa: E731
        [sys.executable, GRAFTFSCK, wd, *a], capture_output=True,
        text=True, env=env, timeout=300)
    run_fsck()
    assert probe().returncode == 0
    flip_byte(os.path.join(wd, "pol.json"), marker=b"marker")
    run_fsck()
    r = probe()
    assert r.returncode == 1 and "fsck found" in r.stdout
    run_fsck("--repair")
    assert probe().returncode == 0


def test_obs_report_integrity_section_json(tmp_path):
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    wd = str(tmp_path)
    seed_policy(os.path.join(wd, "pol.json"))
    with open(os.path.join(wd, "metrics.jsonl"), "w") as f:
        f.write(json.dumps({
            "kind": "telemetry", "t": 1.0,
            "counters": {"integrity.corrupt": 2,
                         "integrity.corrupt.policy": 2,
                         "integrity.gc.deleted": 1,
                         "integrity.repaired": 1},
            "gauges": {}, "histograms": {},
        }) + "\n")
    records = obs_report.load_records(wd)
    s = obs_report.integrity_summary(wd, records)
    assert s["corrupt_counters"]["integrity.corrupt"] == 2
    assert s["repaired"] == 1
    assert s["gc_counters"]["integrity.gc.deleted"] == 1
    assert s["fsck"] is None  # never fsck'd
    assert s["bytes_by_class"]["telemetry"]["count"] == 1
    text = obs_report.render_integrity(wd, records)
    assert "Integrity" in text and "NEVER RUN" in text


# ---------------------------------------------------------------------------
# graftlint rule ``artifacts``
# ---------------------------------------------------------------------------


def test_artifacts_rule_flags_bare_writes_and_passes_routed(tmp_path):
    from jama16_retina_tpu.analysis.core import Corpus
    from jama16_retina_tpu.analysis.rule_artifacts import ArtifactsRule

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        "import json, os, pickle\n"
        "import numpy as np\n"
        "def w(path, obj, arr, fh):\n"
        "    json.dump(obj, fh)\n"
        "    os.replace(path + '.tmp', path)\n"
        "    np.save(path, arr)\n"
        "    pickle.dump(obj, fh)\n"
    )
    (pkg / "integrity").mkdir()
    (pkg / "integrity" / "artifact.py").write_text(
        "import os\n"
        "def atomic(path):\n"
        "    os.replace(path + '.tmp', path)\n"
    )
    (pkg / "good.py").write_text(
        "from pkg.integrity import artifact\n"
        "def w(path, obj):\n"
        "    artifact.write_sealed_json(path, obj, schema='s', version=1)\n"
    )
    corpus = Corpus(str(tmp_path), package="pkg")
    found = ArtifactsRule().run(corpus)
    by_code = {}
    for f in found:
        by_code.setdefault(f.code, []).append(f)
    assert len(by_code["artifacts.bare-replace"]) == 1  # not artifact.py
    assert len(by_code["artifacts.bare-json-dump"]) == 1
    assert len(by_code["artifacts.bare-binary-dump"]) == 2
    assert all(f.path == "pkg/bad.py" for f in found)
    # Stable, name-based suppression keys.
    assert by_code["artifacts.bare-replace"][0].key \
        == "pkg/bad.py::w.os.replace"


def test_reliability_rules_include_artifact_corrupt():
    from jama16_retina_tpu.obs import alerts as obs_alerts

    rules = obs_alerts.reliability_rules(get_config("smoke"))
    by_metric = {r.metric: r for r in rules}
    assert by_metric["rate(integrity.corrupt)"].reason \
        == "artifact_corrupt"
    assert by_metric["rate(integrity.corrupt)"].threshold == 0.0
