"""Critical-path analyzer pins (ISSUE 18): typed verdicts over
synthetic Chrome-shaped timelines — every verdict reachable, the
double-count discipline (nested serve.engine.* spans, server-lane
decode vs the consumer decomposition, trainer.input residual), the
waterfall shapes, and the as_dict schema the FlightRecorder dump and
obs_report --json both serialize."""

import pytest

from jama16_retina_tpu.obs import criticalpath


def ev(name, ts_s, dur_s, **args):
    """One complete event in the tracer's Chrome shape (µs)."""
    return {"ph": "X", "name": name, "ts": ts_s * 1e6,
            "dur": dur_s * 1e6, "args": args}


def test_verdict_codes_are_append_only_stable():
    # Append-only: 0..5 are the ISSUE 18 originals, 6..8 the ISSUE 19
    # device refinements — existing codes never renumber.
    assert criticalpath.VERDICT_CODES == {
        "balanced": 0, "device_bound": 1, "decode_bound": 2,
        "credit_starved": 3, "h2d_bound": 4, "queue_bound": 5,
        "device_compute_bound": 6, "device_membw_bound": 7,
        "device_underutilized": 8,
    }


def test_empty_window_is_balanced_at_zero_confidence():
    v = criticalpath.diagnose([])
    assert v.verdict == "balanced" and v.code == 0
    assert v.confidence == 0.0
    assert v.n_events == 0
    assert v.request_waterfalls == [] and v.step_waterfalls == []


def test_device_bound():
    events = [ev("trainer.input", 0.0, 0.01),
              ev("trainer.dispatch", 0.01, 0.09)]
    v = criticalpath.diagnose(events)
    assert v.verdict == "device_bound" and v.code == 1
    assert v.confidence == pytest.approx(0.9)


def test_decode_bound_from_consumer_segments():
    events = [
        ev("ingest.batch.credit_wait", 0.0, 0.001, trace_id="t1"),
        ev("ingest.batch.decode", 0.001, 0.08, trace_id="t1"),
        ev("ingest.batch.ring_dwell", 0.081, 0.001, trace_id="t1"),
        ev("ingest.batch.read", 0.082, 0.002, trace_id="t1"),
        ev("trainer.dispatch", 0.084, 0.01),
    ]
    v = criticalpath.diagnose(events)
    assert v.verdict == "decode_bound" and v.code == 2
    assert v.evidence["decode"] > 0.8


def test_credit_starved():
    events = [
        ev("ingest.batch.credit_wait", 0.0, 0.08, trace_id="t1"),
        ev("ingest.batch.cache", 0.08, 0.001, trace_id="t1"),
        ev("trainer.dispatch", 0.081, 0.01),
    ]
    v = criticalpath.diagnose(events)
    assert v.verdict == "credit_starved" and v.code == 3


def test_h2d_bound_by_name_substring():
    events = [ev("trainer.h2d_copy", 0.0, 0.08),
              ev("trainer.dispatch", 0.08, 0.01)]
    v = criticalpath.diagnose(events)
    assert v.verdict == "h2d_bound" and v.code == 4


def test_queue_bound():
    events = [
        ev("serve.request.queue_wait", 0.0, 0.08, trace_id="r1"),
        ev("serve.request.device", 0.08, 0.01, trace_id="r1"),
    ]
    v = criticalpath.diagnose(events)
    assert v.verdict == "queue_bound" and v.code == 5


def test_balanced_below_dominant_fraction():
    # 3-way near-even split: no category reaches DOMINANT_FRACTION.
    events = [ev("trainer.dispatch", 0.0, 0.03),
              ev("trainer.input", 0.03, 0.035),
              ev("serve.request.queue_wait", 0.08, 0.035, trace_id="r")]
    v = criticalpath.diagnose(events)
    assert v.verdict == "balanced" and v.code == 0
    assert 0.0 < v.confidence < criticalpath.DOMINANT_FRACTION


def test_nested_engine_spans_do_not_double_count():
    # serve.engine.* nests inside serve.request.device — counting both
    # would double the device wall and flip a queue verdict.
    events = [
        ev("serve.request.queue_wait", 0.0, 0.06, trace_id="r"),
        ev("serve.request.device", 0.06, 0.04, trace_id="r"),
        ev("serve.engine.infer", 0.06, 0.04, trace_id="r"),
    ]
    v = criticalpath.diagnose(events)
    assert v.verdict == "queue_bound"
    assert v.totals_s["device"] == pytest.approx(0.04)


def test_server_lane_decode_counts_only_without_consumer_segments():
    server_only = [ev("ingest.decode.batch", 0.0, 0.08, trace_id="t")]
    v = criticalpath.diagnose(server_only)
    assert v.verdict == "decode_bound"
    # With the consumer decomposition present the server lane is the
    # SAME wall seen from the other process — it must not add.
    both = server_only + [
        ev("ingest.batch.decode", 0.0, 0.08, trace_id="t"),
    ]
    v2 = criticalpath.diagnose(both)
    assert v2.totals_s["decode"] == pytest.approx(0.08)


def test_trainer_input_residual_goes_to_other():
    # trainer.input measured 0.1s; the ingest.batch.* segments explain
    # 0.08 of it — only the unexplained 0.02 lands in "other".
    events = [
        ev("trainer.input", 0.0, 0.1),
        ev("ingest.batch.decode", 0.0, 0.08, trace_id="t"),
        ev("trainer.dispatch", 0.1, 0.01),
    ]
    totals = criticalpath.attribute(events)
    assert totals["decode"] == pytest.approx(0.08)
    assert totals["other"] == pytest.approx(0.02)
    # No decomposition: input-bound IS decode-bound in these terms.
    totals2 = criticalpath.attribute([ev("trainer.input", 0.0, 0.1)])
    assert totals2["decode"] == pytest.approx(0.1)


def test_request_waterfalls_group_by_trace_slowest_first():
    events = [
        ev("ingest.batch.credit_wait", 0.0, 0.01, trace_id="slow"),
        ev("ingest.batch.decode", 0.01, 0.05, trace_id="slow"),
        ev("ingest.batch.decode", 0.1, 0.002, trace_id="fast"),
    ]
    wf = criticalpath.request_waterfalls(events)
    assert [w["trace_id"] for w in wf] == ["slow", "fast"]
    assert wf[0]["total_s"] == pytest.approx(0.06)
    assert wf[0]["dominant"] == "ingest.batch.decode"
    segs = wf[0]["segments"]
    assert [s["name"] for s in segs] == [
        "ingest.batch.credit_wait", "ingest.batch.decode"]
    assert sum(s["frac"] for s in segs) == pytest.approx(1.0, abs=1e-3)


def test_step_waterfalls_split_at_dispatch():
    events = [
        ev("trainer.input", 0.0, 0.01),
        ev("trainer.dispatch", 0.01, 0.02),
        ev("trainer.input", 0.03, 0.04),
        ev("trainer.dispatch", 0.07, 0.02),
    ]
    wf = criticalpath.step_waterfalls(events)
    assert len(wf) == 2
    # Slowest first: the second step (0.06 total) outranks the first.
    assert wf[0]["step_index"] == 1
    assert wf[0]["dominant"] == "trainer.input"
    assert wf[1]["dominant"] == "trainer.dispatch"


def test_as_dict_schema():
    v = criticalpath.diagnose(
        [ev("trainer.dispatch", 0.0, 0.1)], top_k=1)
    d = v.as_dict()
    assert set(d) == {"verdict", "code", "confidence", "evidence",
                      "totals_s", "n_events", "request_waterfalls",
                      "step_waterfalls", "device"}
    assert set(d["evidence"]) == {"device", "decode", "credit", "h2d",
                                  "queue", "other"}
    assert d["code"] == criticalpath.VERDICT_CODES[d["verdict"]]
    assert d["device"] is None  # no device summary offered
