"""Device-utilization plane (ISSUE 19): HBM accounting by owner,
MFU/roofline attribution, and the compile ledger (obs/device.py), plus
its consumers — the criticalpath verdict refinement, /healthz probe
fields, fleet memory-pressure blame, the reliability rule, and
obs_report's Device section.

Numpy-cheap pins run everywhere; the real-engine compile-ledger test
(XLA compiles) stays out of the quick tier.
"""

from __future__ import annotations

import dataclasses
import importlib.util
import json
import os
import time

import numpy as np
import pytest

from jama16_retina_tpu.configs import get_config, override
from jama16_retina_tpu.obs import alerts as obs_alerts
from jama16_retina_tpu.obs import criticalpath
from jama16_retina_tpu.obs import device as device_lib
from jama16_retina_tpu.obs import fleet as fleet_lib
from jama16_retina_tpu.obs import trace as trace_lib
from jama16_retina_tpu.obs.registry import Registry

pytestmark = pytest.mark.device


@pytest.fixture(autouse=True)
def _clean_ledgers():
    device_lib.reset_for_tests()
    yield
    device_lib.reset_for_tests()


class FakeDev:
    def __init__(self, in_use, peak, limit):
        self._stats = {"bytes_in_use": in_use,
                       "peak_bytes_in_use": peak,
                       "bytes_limit": limit}

    def memory_stats(self):
        return dict(self._stats)


def _load_obs_report():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(repo, "scripts", "obs_report.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- monitor sampling ------------------------------------------------------


def test_monitor_samples_hbm_gauges():
    reg = Registry()
    mon = device_lib.DeviceMonitor(
        reg, devices=[FakeDev(6000, 7000, 10000),
                      FakeDev(9000, 9000, 10000)],
        ledger=device_lib.ProgramLedger(),
    )
    out = mon.sample()
    g = reg.snapshot()["gauges"]
    # max in_use / max peak across devices, min headroom.
    assert g["device.hbm.bytes_in_use"] == 9000.0
    assert g["device.hbm.peak_bytes"] == 9000.0
    assert g["device.hbm.bytes_limit"] == 10000.0
    assert g["device.hbm.headroom_frac"] == pytest.approx(0.1)
    assert out["bytes_in_use"] == 9000


def test_monitor_hbm_gauges_declare_fleet_reductions():
    """Merging two processes' device gauges must take the TIGHTEST
    view: summed headrooms would hide the pressured process."""
    snaps = []
    for in_use in (2000, 9000):
        reg = Registry()
        device_lib.DeviceMonitor(
            reg, devices=[FakeDev(in_use, in_use, 10000)],
            ledger=device_lib.ProgramLedger(),
        ).sample()
        snaps.append((f"s-{in_use}", reg.snapshot()))
    m = fleet_lib.merge_snapshots(snaps)
    assert m["gauges"]["device.hbm.bytes_in_use"] == 9000.0   # max
    assert m["gauges"]["device.hbm.headroom_frac"] == pytest.approx(
        0.1)                                                  # min
    assert m["gauges"]["device.hbm.bytes_limit"] == 10000.0   # min


def test_cpu_device_without_memory_stats_publishes_nothing():
    class Bare:
        pass

    reg = Registry()
    mon = device_lib.DeviceMonitor(reg, devices=[Bare()],
                                   ledger=device_lib.ProgramLedger())
    out = mon.sample()
    assert "bytes_in_use" not in out
    assert not any(k.startswith("device.hbm.")
                   for k in reg.snapshot()["gauges"])


def test_disabled_monitor_is_one_branch():
    reg = Registry()
    mon = device_lib.DeviceMonitor(
        reg, enabled=False, devices=[FakeDev(1, 1, 2)],
        ledger=device_lib.ProgramLedger(),
    )
    assert mon.sample() is None
    assert reg.snapshot()["gauges"] == {}


def test_monitor_for_gates_on_config():
    cfg = get_config("smoke")
    assert device_lib.monitor_for(cfg) is not None
    off = cfg.replace(obs=dataclasses.replace(cfg.obs,
                                              device_enabled=False))
    assert device_lib.monitor_for(off) is None
    obs_off = cfg.replace(obs=dataclasses.replace(cfg.obs,
                                                  enabled=False))
    assert device_lib.monitor_for(obs_off) is None


# -- owner ledger ----------------------------------------------------------


def test_owner_ledger_arithmetic_and_untracked_gap():
    device_lib.set_hbm_owner("serve_live", 4000)
    device_lib.add_hbm_owner("ingest_rings", 1500)
    device_lib.add_hbm_owner("ingest_rings", 500)
    device_lib.add_hbm_owner("ingest_rings", -500)
    reg = Registry()
    mon = device_lib.DeviceMonitor(
        reg, devices=[FakeDev(9000, 9000, 10000)],
        ledger=device_lib.ProgramLedger(),
    )
    out = mon.sample()
    g = reg.snapshot()["gauges"]
    assert g["device.hbm.owner.serve_live"] == 4000.0
    assert g["device.hbm.owner.ingest_rings"] == 1500.0
    assert g["device.hbm.untracked_bytes"] == 3500.0
    assert out["untracked_bytes"] == 3500.0
    # Over-claimed owners clamp the gap at 0 instead of going negative.
    device_lib.set_hbm_owner("serve_live", 99999)
    mon.sample()
    assert reg.snapshot()["gauges"]["device.hbm.untracked_bytes"] == 0.0
    # Subtracting below zero clamps; clearing removes the key.
    device_lib.add_hbm_owner("ingest_rings", -99999)
    assert device_lib.hbm_owners()["ingest_rings"] == 0.0
    device_lib.clear_hbm_owner("ingest_rings")
    assert "ingest_rings" not in device_lib.hbm_owners()


def test_hbm_budget_cross_check_gauge():
    device_lib.note_hbm_budget(8000)
    reg = Registry()
    device_lib.DeviceMonitor(
        reg, devices=[FakeDev(6000, 6000, 10000)],
        ledger=device_lib.ProgramLedger(),
    ).sample()
    g = reg.snapshot()["gauges"]
    assert g["device.hbm.derived_budget_bytes"] == 8000.0
    assert g["device.hbm.budget_occupancy_frac"] == pytest.approx(0.75)


def test_hbm_pipeline_notes_its_derived_budget():
    from jama16_retina_tpu.data import hbm_pipeline

    budget = hbm_pipeline.hbm_budget_bytes(0.6)
    assert budget > 0
    reg = Registry()
    device_lib.DeviceMonitor(
        reg, devices=[FakeDev(100, 100, 10**12)],
        ledger=device_lib.ProgramLedger(),
    ).sample()
    assert reg.snapshot()["gauges"][
        "device.hbm.derived_budget_bytes"] == float(budget)


def test_tree_device_bytes_host_arrays():
    tree = {"a": np.zeros((4, 4), np.float32),
            "b": np.zeros(8, np.uint8)}
    assert device_lib.tree_device_bytes(tree) == 4 * 4 * 4 + 8
    assert device_lib.tree_device_bytes({}) == 0


# -- MFU / roofline --------------------------------------------------------


def test_mfu_window_math_with_injected_clock():
    import jax

    clock = iter([10.0, 12.0])
    ledger = device_lib.ProgramLedger()
    e = ledger.register("train_step", flops_per_call=2e9,
                        bytes_per_call=1e7)
    reg = Registry()
    mon = device_lib.DeviceMonitor(
        reg, devices=[], ledger=ledger, peak_flops_per_s=1e12,
        peak_bw_bytes_per_s=1e11, clock=lambda: next(clock),
    )
    mon.sample()  # baseline tick
    for _ in range(10):
        e.note_call()
    out = mon.sample()
    n_dev = max(1, jax.local_device_count())
    want = 10 * 2e9 / (2.0 * 1e12 * n_dev)
    assert out["mfu"] == pytest.approx(want)
    g = reg.snapshot()["gauges"]
    assert g["device.mfu"] == pytest.approx(want, abs=1e-6)
    assert g["device.mfu.train_step"] == pytest.approx(want, abs=1e-6)
    assert reg.snapshot()["counters"][
        "device.program.calls.train_step"] == 10.0


def test_roofline_classes_against_injected_ridge():
    # ridge = 1e12 / 1e11 = 10 flops/byte.
    ledger = device_lib.ProgramLedger()
    ledger.register("dense", flops_per_call=1e9, bytes_per_call=1e7)
    ledger.register("streamy", flops_per_call=1e9, bytes_per_call=1e9)
    reg = Registry()
    mon = device_lib.DeviceMonitor(
        reg, devices=[], ledger=ledger, peak_flops_per_s=1e12,
        peak_bw_bytes_per_s=1e11, clock=iter([0.0, 1.0]).__next__,
    )
    mon.sample()
    g = reg.snapshot()["gauges"]
    assert g["device.roofline.dense"] == 1.0      # 100 >= 10: compute
    assert g["device.roofline.streamy"] == 2.0    # 1 < 10: memory
    # The dominant class follows the program carrying the window FLOPs.
    ledger.get("streamy").note_call(5)
    out = mon.sample()
    assert out["dominant_class"] == 2.0
    assert reg.snapshot()["gauges"][
        "device.roofline.dominant_class"] == 2.0


def test_one_flops_source_trainer_ceiling_is_ledger_entry():
    """aot_compile_step's returned FLOPs (the trainer throughput
    ceiling's numerator) IS the program-ledger entry's — one parse
    site, no second cost_analysis path to drift."""
    import jax
    import jax.numpy as jnp

    from jama16_retina_tpu import train_lib

    @jax.jit
    def prog(x):
        return (x @ x.T).sum()

    x = jnp.ones((16, 16), jnp.float32)
    compiled, flops = train_lib.aot_compile_step(prog, x,
                                                 program="train_step")
    entry = device_lib.program_ledger().get("train_step")
    assert entry is not None
    if flops is not None:  # cost analysis availability is backend-luck
        assert entry.flops == flops
    # The compile itself landed in the compile ledger.
    snap = device_lib.compile_ledger().snapshot()
    assert snap["count"] >= 1
    assert any(e["signature"] == "train_step" for e in snap["entries"])


# -- compile ledger --------------------------------------------------------


def test_compile_timed_records_even_on_raise():
    reg = Registry()
    with pytest.raises(ValueError):
        with device_lib.compile_timed("boom", registry=reg):
            raise ValueError("compile OOM")
    snap = device_lib.compile_ledger().snapshot()
    assert snap["count"] == 1
    assert reg.snapshot()["counters"]["device.compile.count"] == 1.0


def test_compile_ledger_slowest_and_exemplar():
    reg = Registry()
    device_lib.record_compile("serve_b8", 0.5, registry=reg)
    device_lib.record_compile("train_step", 2.5, registry=reg)
    device_lib.record_compile("serve_b8", 0.25, registry=reg)
    snap = device_lib.compile_ledger().snapshot()
    assert snap["count"] == 3
    assert snap["sec"] == pytest.approx(3.25)
    assert snap["slowest"] == {"signature": "train_step", "sec": 2.5}
    assert snap["entries"][0]["signature"] == "train_step"
    by_sig = {e["signature"]: e for e in snap["entries"]}
    assert by_sig["serve_b8"]["count"] == 2
    assert by_sig["serve_b8"]["max_sec"] == 0.5
    # Histogram exemplar names the slowest compile of the window.
    hist = reg.snapshot()["histograms"]["device.compile.sec_hist"]
    assert hist["exemplar"]["trace_id"] == "train_step"
    assert hist["exemplar"]["value"] == 2.5
    counters = reg.snapshot()["counters"]
    assert counters["device.compile.count"] == 3.0
    assert counters["device.compile.sec"] == pytest.approx(3.25)


def test_note_compile_saved_counter_and_zero_noop():
    reg = Registry()
    device_lib.note_compile_saved(1.25, registry=reg)
    device_lib.note_compile_saved(0.0, registry=reg)
    assert reg.snapshot()["counters"][
        "device.compile.saved_sec"] == pytest.approx(1.25)


def test_last_compile_age_and_healthz_fields():
    from jama16_retina_tpu.obs.httpd import ObsHttp

    assert device_lib.compile_ledger().last_compile_age_s() is None
    reg = Registry()
    device_lib.DeviceMonitor(
        reg, devices=[FakeDev(9500, 9500, 10000)],
        ledger=device_lib.ProgramLedger(),
    ).sample()
    device_lib.record_compile("serve_b4", 1.0, registry=reg)
    http = ObsHttp(reg, port=0)
    try:
        status, detail = http.health()
        assert status == 2  # no snapshotter: still carries device fields
        assert detail["hbm_headroom_frac"] == pytest.approx(0.05)
        assert detail["last_compile_age_s"] is not None
        assert detail["last_compile_age_s"] < 60.0
    finally:
        http.close()


# -- Snapshotter wiring ----------------------------------------------------


def test_snapshotter_flush_samples_monitor_into_telemetry(tmp_path):
    from jama16_retina_tpu.obs.export import Snapshotter

    reg = Registry()
    mon = device_lib.DeviceMonitor(
        reg, devices=[FakeDev(6000, 7000, 10000)],
        ledger=device_lib.ProgramLedger(),
    )
    device_lib.record_compile("train_step", 1.5, registry=reg)
    snapper = Snapshotter(reg, workdir=str(tmp_path), device=mon)
    snap = snapper.flush()
    assert snap["gauges"]["device.hbm.headroom_frac"] == pytest.approx(
        0.4)
    records = [json.loads(ln) for ln in
               open(tmp_path / "metrics.jsonl")]
    telem = [r for r in records if r["kind"] == "telemetry"]
    assert telem[0]["gauges"]["device.hbm.bytes_in_use"] == 6000.0
    ledgers = [r for r in records if r["kind"] == "compile_ledger"]
    assert ledgers and ledgers[0]["count"] == 1
    assert ledgers[0]["slowest"]["signature"] == "train_step"
    # No new compiles -> no duplicate compile_ledger record.
    snapper.flush()
    records = [json.loads(ln) for ln in
               open(tmp_path / "metrics.jsonl")]
    assert sum(r["kind"] == "compile_ledger" for r in records) == 1


# -- verdict refinement ----------------------------------------------------


def _dispatch_dominant_events():
    tr = trace_lib.Tracer(enabled=True)
    for _ in range(6):
        t0 = time.perf_counter()
        time.sleep(0.001)
        t1 = time.perf_counter()
        tr.complete("trainer.input", t0, t1, {})
        time.sleep(0.01)
        t2 = time.perf_counter()
        tr.complete("trainer.dispatch", t1, t2, {})
    return tr.events()


def test_refine_device_verdict_pure():
    assert criticalpath.refine_device_verdict(None) is None
    assert criticalpath.refine_device_verdict({}) is None
    assert criticalpath.refine_device_verdict(
        {"mfu": None, "dominant_class": None}) is None
    assert criticalpath.refine_device_verdict(
        {"mfu": 0.9, "dominant_class": "memory"}
    ) == "device_membw_bound"
    assert criticalpath.refine_device_verdict(
        {"mfu": device_lib.SATURATED_MFU, "dominant_class": "compute"}
    ) == "device_compute_bound"
    assert criticalpath.refine_device_verdict(
        {"mfu": 0.05, "dominant_class": "compute"}
    ) == "device_underutilized"


def test_diagnose_refines_device_bound_only():
    events = _dispatch_dominant_events()
    base = criticalpath.diagnose(events)
    assert base.verdict == "device_bound"
    assert base.device is None

    low = criticalpath.diagnose(events, device={
        "mfu": 0.03, "dominant_class": "compute"})
    assert low.verdict == "device_underutilized"
    assert low.code == criticalpath.VERDICT_CODES[
        "device_underutilized"]
    assert low.device == {"mfu": 0.03, "dominant_class": "compute"}

    mem = criticalpath.diagnose(events, device={
        "mfu": 0.6, "dominant_class": "memory"})
    assert mem.verdict == "device_membw_bound"

    hot = criticalpath.diagnose(events, device={
        "mfu": 0.55, "dominant_class": "compute"})
    assert hot.verdict == "device_compute_bound"

    # A summary that cannot commit keeps the unrefined verdict.
    vague = criticalpath.diagnose(events, device={"mfu": None})
    assert vague.verdict == "device_bound" and vague.device is None


def test_diagnose_ignores_device_for_other_verdicts():
    tr = trace_lib.Tracer(enabled=True)
    for _ in range(4):
        t0 = time.perf_counter()
        time.sleep(0.01)
        t1 = time.perf_counter()
        tr.complete("ingest.batch.decode", t0, t1, {})
    v = criticalpath.diagnose(tr.events(), device={
        "mfu": 0.01, "dominant_class": "compute"})
    assert v.verdict == "decode_bound"
    assert v.device is None


def test_summary_from_gauges():
    assert device_lib.summary_from_gauges(None) is None
    assert device_lib.summary_from_gauges({"x": 1.0}) is None
    s = device_lib.summary_from_gauges({
        "device.mfu": 0.12,
        "device.mfu.train_step": 0.12,
        "device.roofline.dominant_class": 2.0,
        "device.bw_frac": 0.7,
        "device.hbm.headroom_frac": 0.3,
    })
    assert s == {
        "mfu": 0.12, "dominant_class": "memory", "bw_frac": 0.7,
        "hbm_headroom_frac": 0.3,
        "programs": {"train_step": 0.12},
    }


# -- alerts + fleet blame --------------------------------------------------


def test_reliability_rules_include_hbm_pressure_and_latch():
    cfg = get_config("smoke")
    rules = obs_alerts.reliability_rules(cfg)
    rule = next(r for r in rules if r.reason == "hbm_pressure")
    assert rule.metric == "device.hbm.headroom_frac"
    assert rule.op == "<" and rule.for_seconds == 60.0
    assert rule.threshold == cfg.obs.device_hbm_headroom_alert

    reg = Registry()
    device_lib.DeviceMonitor(
        reg, devices=[FakeDev(9500, 9500, 10000)],
        ledger=device_lib.ProgramLedger(),
    ).sample()
    mgr = obs_alerts.AlertManager(rules, registry=reg)
    assert not [f for f in mgr.evaluate(now=1000.0)
                if f["reason"] == "hbm_pressure"]  # for-60s not held yet
    firing = mgr.evaluate(now=1061.0)
    assert any(f["reason"] == "hbm_pressure" for f in firing)


def test_zero_threshold_disables_hbm_pressure_rule():
    cfg = get_config("smoke")
    cfg = cfg.replace(obs=dataclasses.replace(
        cfg.obs, device_hbm_headroom_alert=0.0))
    assert not [r for r in obs_alerts.reliability_rules(cfg)
                if r.reason == "hbm_pressure"]


def test_fleet_heartbeats_blame_memory_pressured_process(tmp_path):
    fdir = str(tmp_path / "fleet")
    now = time.time()
    for role, in_use in (("train", 2000), ("serve", 9500)):
        reg = Registry()
        device_lib.DeviceMonitor(
            reg, devices=[FakeDev(in_use, in_use, 10000)],
            ledger=device_lib.ProgramLedger(),
        ).sample()
        bus = fleet_lib.FleetBus(fdir, role, registry=reg)
        bus.publish(reg.snapshot(), heartbeat={"step": 1})
    code, msg = fleet_lib.check_fleet_heartbeats(fdir, 300.0, now=now)
    assert code == 0
    # Only the 5%-headroom process is named memory-pressured.
    pressured = [ln for ln in msg.splitlines()
                 if "memory-pressured" in ln]
    assert len(pressured) == 1 and "serve" in pressured[0]
    assert "5.0%" in pressured[0]
    # A stale pressured process keeps the annotation on its blame line.
    code, msg = fleet_lib.check_fleet_heartbeats(
        fdir, 0.001, now=now + 100)
    assert code == 1
    assert any("memory-pressured" in ln for ln in msg.splitlines()
               if "serve" in ln)


# -- bench trend directions ------------------------------------------------


def test_bench_trend_device_row_directions():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_trend", os.path.join(repo, "scripts", "bench_trend.py")
    )
    bt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bt)
    assert bt.lower_is_better("train_mfu") is False
    assert bt.lower_is_better("serve_mfu_b64") is False
    assert bt.lower_is_better("hbm_peak_frac") is True
    # The device rows must not disturb the existing shapes.
    assert bt.lower_is_better("devicemon_overhead_pct") is True
    assert bt.lower_is_better("train_images_per_sec_per_chip") is False


# -- obs_report Device section ---------------------------------------------


def _device_records():
    return [
        {"kind": "telemetry", "t": 1.0,
         "counters": {"device.compile.count": 3,
                      "device.compile.sec": 4.5,
                      "device.compile.saved_sec": 2.0,
                      "device.program.calls.train_step": 50},
         "gauges": {"device.hbm.bytes_in_use": 6.0e9,
                    "device.hbm.peak_bytes": 7.0e9,
                    "device.hbm.bytes_limit": 8.0e9,
                    "device.hbm.headroom_frac": 0.25,
                    "device.hbm.untracked_bytes": 1.0e9,
                    "device.hbm.owner.serve_live": 4.0e9,
                    "device.hbm.owner.ingest_rings": 1.0e9,
                    "device.mfu": 0.31,
                    "device.mfu.train_step": 0.31,
                    "device.bw_gbps.train_step": 123.4,
                    "device.bw_frac": 0.4,
                    "device.roofline.train_step": 1.0,
                    "device.roofline.dominant_class": 1.0}},
        {"kind": "compile_ledger", "t": 2.0, "count": 3, "sec": 4.5,
         "slowest": {"signature": "train_step", "sec": 3.0},
         "entries": [{"signature": "train_step", "count": 1,
                      "sec": 3.0, "max_sec": 3.0},
                     {"signature": "serve_b8", "count": 2,
                      "sec": 1.5, "max_sec": 1.0}]},
    ]


def test_obs_report_device_summary_and_render():
    obs_report = _load_obs_report()
    s = obs_report.device_summary(_device_records())
    assert s["hbm"]["headroom_frac"] == 0.25
    assert s["owners"] == {"serve_live": 4.0e9, "ingest_rings": 1.0e9}
    assert s["mfu"] == 0.31
    assert s["dominant_class"] == "compute"
    assert s["programs"]["train_step"]["mfu"] == 0.31
    assert s["programs"]["train_step"]["roofline"] == "compute"
    assert s["programs"]["train_step"]["calls"] == 50
    assert s["compile"]["count"] == 3
    assert s["compile"]["saved_sec"] == 2.0
    assert s["compile"]["ledger"]["slowest"]["signature"] == "train_step"
    text = obs_report.render_device(_device_records())
    assert "device utilization:" in text
    assert "(untracked)" in text
    assert "serve_live" in text
    assert "MFU: 31.0%" in text
    assert "2.00s saved by cache" in text
    assert "slowest train_step" in text
    # A stream with no device signals renders nothing new.
    assert obs_report.device_summary(
        [{"kind": "telemetry", "counters": {"x": 1}, "gauges": {}}]
    ) is None


def test_obs_report_diagnosis_summary_accepts_device():
    obs_report = _load_obs_report()
    events = _dispatch_dominant_events()
    s = obs_report.diagnosis_summary(
        events, device={"mfu": 0.02, "dominant_class": "compute"})
    assert s["verdict"] == "device_underutilized"
    text = obs_report.render_diagnosis(s)
    assert "device_underutilized" in text
    assert "device evidence" in text and "MFU 2.0%" in text


# -- real-engine compile ledger (full tier: XLA compiles) ------------------


def test_engine_warm_and_cache_hit_miss_compile_ledger(tmp_path):
    import jax

    from jama16_retina_tpu import models, train_lib
    from jama16_retina_tpu.serve.engine import ServingEngine

    cfg = override(get_config("smoke"), ["model.image_size=32"])
    cfg = cfg.replace(serve=dataclasses.replace(
        cfg.serve, max_batch=4, bucket_sizes=(4,),
        compile_cache_dir=str(tmp_path / "cc"),
    ))
    model = models.build(cfg.model)
    state, _ = train_lib.create_ensemble_state(cfg, model, [0])

    reg1 = Registry()
    eng1 = ServingEngine(cfg, model=model, state=state, registry=reg1)
    c1 = reg1.snapshot()["counters"]
    assert c1.get("serve.compile_cache.misses", 0) == 1
    assert c1.get("device.compile.count", 0) >= 1
    snap = device_lib.compile_ledger().snapshot()
    assert any(e["signature"] == "serve_b4" for e in snap["entries"])
    imgs = np.zeros((4, 32, 32, 3), np.uint8)
    ref = eng1.probs(imgs)

    # Same cache dir, fresh registry: the warm is a HIT — no serve_b4
    # miss-compile, and the stored compile seconds are credited.
    device_lib.reset_for_tests()
    reg2 = Registry()
    eng2 = ServingEngine(cfg, model=model, state=state, registry=reg2)
    c2 = reg2.snapshot()["counters"]
    assert c2.get("serve.compile_cache.hits", 0) == 1
    assert c2.get("serve.compile_cache.misses", 0) == 0
    assert c2.get("device.compile.saved_sec", 0) > 0
    snap2 = device_lib.compile_ledger().snapshot()
    assert not any(e["signature"] == "serve_b4"
                   for e in snap2["entries"])
    # The deserialized program is registered for dispatch counting and
    # serves the same math.
    np.testing.assert_array_equal(eng2.probs(imgs), ref)
    entry = device_lib.program_ledger().get("serve_b4")
    assert entry is not None and entry.calls >= 1
    del eng1, eng2
    jax.clear_caches()
