"""graftlint (jama16_retina_tpu/analysis/) — ISSUE 9.

Per rule: at least one purpose-built BAD fixture that must fire and one
GOOD fixture that must stay quiet, exercised through the real Corpus
loader over tmp mini-repos. Plus the CLI exit-code contract (0/1/2),
the suppression/justification machinery, THE tier-1 gate
``test_lint_repo_clean`` (the repo itself must lint clean forever), and
the consolidated ``configs.override()`` dotted-path edge cases the
config rule's grammar checking depends on.
"""

from __future__ import annotations

import json
import os

import pytest

from jama16_retina_tpu import configs
from jama16_retina_tpu.analysis import (
    ConfigRule,
    Corpus,
    FaultsRule,
    LocksRule,
    MetricsRule,
    PurityRule,
    PytestMarksRule,
    default_rules,
)
from jama16_retina_tpu.analysis import core as lint_core
from jama16_retina_tpu.analysis.__main__ import main as lint_main

pytestmark = pytest.mark.analysis

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_repo(tmp_path, files: dict, package: str = "pkg") -> Corpus:
    """A mini-repo on disk -> Corpus (same loader the CLI uses)."""
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    return Corpus(str(tmp_path), package=package)


def run_rule(rule, corpus):
    return rule.run(corpus)


def codes(findings) -> set:
    return {f.code for f in findings}


GLOSSARY_HEADER = "| Metric | Kind | Meaning |\n|---|---|---|\n"


# ---------------------------------------------------------------------------
# metrics rule
# ---------------------------------------------------------------------------


def test_metrics_fires_on_missing_help_and_undocumented(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/mod.py": (
            "reg.counter('layer.thing')\n"
            "reg.gauge('layer.other', help='fine')\n"
        ),
        "docs/OBSERVABILITY.md": (
            "# obs\n\n" + GLOSSARY_HEADER
            + "| `layer.other` | gauge | ok |\n"
        ),
    })
    found = run_rule(MetricsRule(), corpus)
    assert "metrics.help-missing" in codes(found)
    assert "metrics.undocumented" in codes(found)
    # file:line pointing at the offending registration
    f = next(x for x in found if x.code == "metrics.help-missing")
    assert f.path == "pkg/mod.py" and f.line == 1


def test_metrics_quiet_on_documented_helped_metrics(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/mod.py": (
            "for k in ('a', 'b'):\n"
            "    reg.counter(f'layer.sub.{k}', help='per-k count')\n"
            "reg.histogram('layer.lat_s', help='latency')\n"
        ),
        "docs/OBSERVABILITY.md": (
            "# obs\n\n" + GLOSSARY_HEADER
            + "| `layer.sub.{key}` | counter | per-key |\n"
            + "| `layer.lat_s` | histogram | latency |\n"
        ),
    })
    assert run_rule(MetricsRule(), corpus) == []


def test_metrics_kind_conflict_and_grammar(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/mod.py": (
            "reg.counter('layer.x', help='h')\n"
            "reg.gauge('layer.x', help='h')\n"
            "reg.counter('NotDotted', help='h')\n"
        ),
    })
    found = run_rule(MetricsRule(), corpus)
    assert "metrics.kind-conflict" in codes(found)
    assert "metrics.name-grammar" in codes(found)


def test_metrics_doc_orphan_and_help_conflict(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/mod.py": (
            "reg.counter('layer.x', help='one meaning')\n"
            "reg.counter('layer.x', help='another meaning')\n"
        ),
        "docs/RELIABILITY.md": (
            "# rel\n\n" + GLOSSARY_HEADER
            + "| `layer.x` | counter | ok |\n"
            + "| `layer.gone` | counter | stale row |\n"
        ),
    })
    found = run_rule(MetricsRule(), corpus)
    assert "metrics.doc-orphan" in codes(found)
    assert "metrics.help-conflict" in codes(found)
    orphan = next(x for x in found if x.code == "metrics.doc-orphan")
    assert orphan.path == "docs/RELIABILITY.md" and "layer.gone" in \
        orphan.message


def test_metrics_non_literal_name_fires(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/mod.py": "def f(name):\n    return reg.histogram(name)\n",
    })
    assert "metrics.non-literal-name" in codes(
        run_rule(MetricsRule(), corpus))


def test_metrics_ignores_non_registry_receivers(tmp_path):
    """np.histogram() and friends are numeric code, not metric
    registrations — the rule pins the receiver to registry-like names
    (review fix: a stray numpy call must never fail CI)."""
    corpus = make_repo(tmp_path, {
        "pkg/mod.py": (
            "import numpy as np\n"
            "def f(xs, registry):\n"
            "    h, edges = np.histogram(xs, bins=50)\n"
            "    stats.counter(xs)\n"
            "    registry.counter('layer.x', help='h')\n"
            "    lib.default_registry().gauge('layer.y', help='h')\n"
            "    return h, edges\n"
        ),
        "docs/OBSERVABILITY.md": (
            "# obs\n\n" + GLOSSARY_HEADER
            + "| `layer.x` | counter | ok |\n"
            + "| `layer.y` | gauge | ok |\n"
        ),
    })
    assert run_rule(MetricsRule(), corpus) == []


# ---------------------------------------------------------------------------
# config rule
# ---------------------------------------------------------------------------

_CONFIGS_SRC = """\
import dataclasses

@dataclasses.dataclass(frozen=True)
class SubConfig:
    used_knob: int = 1
    dead_knob: int = 2

@dataclasses.dataclass(frozen=True)
class Config:
    sub: SubConfig = dataclasses.field(default_factory=SubConfig)
    alert_rules: tuple = ()
    watch_rules: tuple = ("m.ok < 1",)
"""


def test_config_dead_and_undocumented_knob(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/configs.py": _CONFIGS_SRC,
        "pkg/user.py": (
            "def f(cfg):\n"
            "    return (cfg.sub.used_knob, cfg.alert_rules, "
            "cfg.watch_rules)\n"
        ),
        "docs/X.md": "documents used_knob and sub and alert_rules "
                     "and watch_rules\n",
    })
    found = run_rule(ConfigRule(), corpus)
    dead = [f for f in found if f.code == "config.dead-knob"]
    assert [f.key for f in dead] == ["knob::SubConfig.dead_knob"]
    undoc = [f for f in found if f.code == "config.undocumented-knob"]
    assert {f.key for f in undoc} == {"knob::SubConfig.dead_knob"}


def test_config_quiet_when_knobs_consumed_and_documented(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/configs.py": _CONFIGS_SRC.replace("dead_knob", "live_knob"),
        "pkg/user.py": (
            "def f(cfg):\n"
            "    _ = (cfg.alert_rules, cfg.watch_rules)\n"
            "    return cfg.sub.used_knob + getattr(cfg.sub, 'live_knob')\n"
        ),
        "docs/X.md": "used_knob live_knob sub alert_rules watch_rules\n",
    })
    assert run_rule(ConfigRule(), corpus) == []


def test_config_alert_grammar_in_defaults_and_docs(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/configs.py": _CONFIGS_SRC.replace(
            'alert_rules: tuple = ()',
            'alert_rules: tuple = ("quality.x >> 3",)',
        ),
        "pkg/user.py": "def f(c): return (c.sub.used_knob, c.sub.dead_knob,"
                       " c.sub, c.alert_rules, c.watch_rules)\n",
        "docs/X.md": (
            "used_knob dead_knob sub alert_rules watch_rules\n"
            "A good rule: `m.lat > 0.5 for 60 -> slo`\n"
            "A bad rule: `m.lat > 0.5 oops`\n"
        ),
    })
    found = run_rule(ConfigRule(), corpus)
    bad = [f for f in found if f.code == "config.alert-grammar"]
    assert {f.path for f in bad} == {"pkg/configs.py", "docs/X.md"}
    assert all("cannot parse" in f.message for f in bad)


def test_config_watch_context_rejects_rate_and_for(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/configs.py": _CONFIGS_SRC.replace(
            'watch_rules: tuple = ("m.ok < 1",)',
            'watch_rules: tuple = ("rate(m.x) > 0", "m.ok < 1 for 30")',
        ),
        "pkg/user.py": "def f(c): return (c.sub.used_knob, c.sub.dead_knob,"
                       " c.sub, c.alert_rules, c.watch_rules)\n",
        "docs/X.md": "used_knob dead_knob sub alert_rules watch_rules\n",
    })
    found = run_rule(ConfigRule(), corpus)
    watch = [f for f in found if f.code == "config.watch-context"]
    assert len(watch) == 2
    assert any("rate()" in f.message for f in watch)
    assert any("for N" in f.message or "'for" in f.message for f in watch)


def test_config_watch_context_quiet_on_plain_rule(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/mod.py": "mgr = Controller(watch_rules=('m.ok < 1',))\n",
    })
    assert run_rule(ConfigRule(), corpus) == []


# ---------------------------------------------------------------------------
# faults rule
# ---------------------------------------------------------------------------

_FAULTS_DECL = (
    "SITES = {\n"
    "    'a.read': 'seam a',\n"
    "    'b.step': 'seam b',\n"
    "}\n"
)

_RELIABILITY_DOC = (
    "# rel\n\n## Fault injection howto\n\n"
    "Sites: `a.read`, `b.step`.\n"
)


def test_faults_quiet_when_populations_agree(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/obs/faultinject.py": _FAULTS_DECL,
        "pkg/mod.py": (
            "from pkg.obs import faultinject\n"
            "def f():\n"
            "    faultinject.check('a.read')\n"
            "    faultinject.corrupt('b.step', b'x')\n"
        ),
        "docs/RELIABILITY.md": _RELIABILITY_DOC,
    })
    assert run_rule(FaultsRule(), corpus) == []


def test_faults_fires_on_undeclared_and_unfired_sites(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/obs/faultinject.py": _FAULTS_DECL,
        "pkg/mod.py": (
            "from pkg.obs import faultinject\n"
            "def f():\n"
            "    faultinject.check('a.read')\n"
            "    faultinject.check('c.ghost')\n"
            "    faultinject.arm({'d.ghost': {'kind': 'error'}})\n"
        ),
        "docs/RELIABILITY.md": _RELIABILITY_DOC + "Also `e.ghost`.\n",
    })
    found = run_rule(FaultsRule(), corpus)
    unknown = {f.key for f in found if f.code == "faults.unknown-site"}
    assert unknown == {"site::c.ghost", "site::d.ghost"}
    assert {f.key for f in found if f.code == "faults.doc-unknown-site"} \
        == {"site::e.ghost"}
    # b.step is declared + documented but never fired
    assert {f.key for f in found if f.code == "faults.never-fired"} \
        == {"site::b.step"}


def test_faults_undocumented_declared_site(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/obs/faultinject.py": _FAULTS_DECL,
        "pkg/mod.py": (
            "from pkg.obs import faultinject\n"
            "def f():\n"
            "    faultinject.check('a.read')\n"
            "    faultinject.check('b.step')\n"
        ),
        "docs/RELIABILITY.md": (
            "# rel\n\n## Fault injection howto\n\nSites: `a.read`.\n"
        ),
    })
    found = run_rule(FaultsRule(), corpus)
    assert {f.key for f in found if f.code == "faults.undocumented-site"} \
        == {"site::b.step"}


# ---------------------------------------------------------------------------
# locks rule
# ---------------------------------------------------------------------------


def test_locks_fires_on_unguarded_cross_thread_write(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/mod.py": (
            "import threading\n"
            "class Shared:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0\n"
            "    def safe(self):\n"
            "        with self._lock:\n"
            "            self._n += 1\n"
            "    def racy(self):\n"
            "        self._n = 0\n"
        ),
    })
    found = run_rule(LocksRule(), corpus)
    assert [f.code for f in found] == ["locks.unguarded-write"]
    assert found[0].key == "pkg/mod.py::Shared.racy._n"
    assert found[0].line == 10


def test_locks_quiet_on_disciplined_class(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/mod.py": (
            "import threading\n"
            "class Shared:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0\n"
            "        self._free = 0\n"
            "    def safe(self):\n"
            "        with self._lock:\n"
            "            self._n += 1\n"
            "    def _bump_locked(self):\n"
            "        self._n += 1\n"  # caller-holds-the-lock convention
            "    def single_writer(self):\n"
            "        self._free = 1\n"  # never lock-guarded: not judged
        ),
    })
    assert run_rule(LocksRule(), corpus) == []


def test_locks_subscript_write_counts(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/mod.py": (
            "import threading\n"
            "class Shared:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._d = {}\n"
            "    def safe(self, k):\n"
            "        with self._lock:\n"
            "            self._d[k] = 1\n"
            "    def racy(self, k):\n"
            "        self._d[k] = 2\n"
        ),
    })
    found = run_rule(LocksRule(), corpus)
    assert [f.key for f in found] == ["pkg/mod.py::Shared.racy._d"]


# ---------------------------------------------------------------------------
# purity rule
# ---------------------------------------------------------------------------


def test_purity_fires_on_clock_and_entropy_calls(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/sched.py": (
            "import time, random\n"
            "def decide(x):\n"
            "    return x + time.time() + random.random()\n"
        ),
    })
    found = run_rule(
        PurityRule(targets=("pkg/sched.py::decide",)), corpus)
    assert {f.key.split("::")[-1] for f in found} \
        == {"time.time", "random.random"}
    assert all(f.code == "purity.impure-call" for f in found)


def test_purity_quiet_with_injected_clock(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/sched.py": (
            "import time\n"
            "def decide(x, now_fn=time.time):\n"
            "    return x + now_fn()\n"  # call rides the injected seam
        ),
    })
    assert run_rule(
        PurityRule(targets=("pkg/sched.py::decide",)), corpus) == []


def test_purity_module_target_and_pragma(tmp_path):
    corpus = make_repo(tmp_path, {
        "pkg/journal.py": (
            "import os\n"
            "def stamp():\n"
            "    return os.urandom(8)\n"
        ),
        "pkg/other.py": (
            "from datetime import datetime\n"
            "def tagged():  # graftlint: deterministic\n"
            "    return datetime.now()\n"
        ),
    })
    found = run_rule(PurityRule(targets=("pkg/journal.py",)), corpus)
    assert {f.key.split("::")[-1] for f in found} \
        == {"os.urandom", "datetime.datetime.now"}


# ---------------------------------------------------------------------------
# pytest-marks rule
# ---------------------------------------------------------------------------

_PYTEST_INI = (
    "[pytest]\n"
    "markers =\n"
    "    tier_a: registered marker\n"
)


def test_pytest_marks_fires_on_unregistered(tmp_path):
    corpus = make_repo(tmp_path, {
        "pytest.ini": _PYTEST_INI,
        "tests/test_x.py": (
            "import pytest\n"
            "@pytest.mark.tier_b\n"
            "def test_a():\n    pass\n"
        ),
    })
    found = run_rule(PytestMarksRule(), corpus)
    assert [f.key for f in found] == ["mark::tier_b"]


def test_pytest_marks_quiet_on_registered_and_builtin(tmp_path):
    corpus = make_repo(tmp_path, {
        "pytest.ini": _PYTEST_INI,
        "tests/test_x.py": (
            "import pytest\n"
            "@pytest.mark.tier_a\n"
            "@pytest.mark.parametrize('v', [1])\n"
            "def test_a(v):\n    pass\n"
        ),
    })
    assert run_rule(PytestMarksRule(), corpus) == []


# ---------------------------------------------------------------------------
# suppressions + baseline
# ---------------------------------------------------------------------------


def _lock_fixture_files(racy: bool) -> dict:
    body = (
        "import threading\n"
        "class Shared:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "    def safe(self):\n"
        "        with self._lock:\n"
        "            self._n += 1\n"
    )
    if racy:
        body += "    def racy(self):\n        self._n = 0\n"
    return {"jama16_retina_tpu/mod.py": body}


def test_suppression_needs_reason_and_tracks_usage(tmp_path):
    files = _lock_fixture_files(racy=True)
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    corpus = Corpus(str(tmp_path))
    sup_path = tmp_path / ".graftlint.json"
    # With a justified suppression: finding is hidden.
    sup_path.write_text(json.dumps({"suppressions": [{
        "code": "locks.unguarded-write",
        "key": "jama16_retina_tpu/mod.py::Shared.racy._n",
        "reason": "single-threaded setup path, documented",
    }]}))
    found = lint_core.run_rules(corpus, [LocksRule()],
                                suppressions_path=str(sup_path))
    assert found == []
    # Without a reason: the suppression itself is the finding and the
    # original violation still reports.
    sup_path.write_text(json.dumps({"suppressions": [{
        "code": "locks.unguarded-write",
        "key": "jama16_retina_tpu/mod.py::Shared.racy._n",
    }]}))
    found = lint_core.run_rules(corpus, [LocksRule()],
                                suppressions_path=str(sup_path))
    assert codes(found) == {"core.suppression-no-reason",
                            "locks.unguarded-write"}
    # A suppression matching nothing is reported as unused.
    sup_path.write_text(json.dumps({"suppressions": [{
        "code": "locks.unguarded-write",
        "key": "jama16_retina_tpu/mod.py::Shared.gone._n",
        "reason": "stale",
    }]}))
    found = lint_core.run_rules(corpus, [LocksRule()],
                                suppressions_path=str(sup_path))
    assert codes(found) == {"core.suppression-unused",
                            "locks.unguarded-write"}


def test_rule_subset_does_not_misreport_other_rules_suppressions(tmp_path):
    """A --rules subset run must not flag the whole-set suppression
    file as unused (only suppressions of rules that RAN are judged)."""
    files = _lock_fixture_files(racy=False)
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    sup_path = tmp_path / ".graftlint.json"
    sup_path.write_text(json.dumps({"suppressions": [{
        "code": "metrics.non-literal-name",
        "key": "jama16_retina_tpu/other.py::helper",
        "reason": "generic helper",
    }]}))
    corpus = Corpus(str(tmp_path))
    # locks-only run: the metrics suppression is out of scope -> quiet.
    assert lint_core.run_rules(corpus, [LocksRule()],
                               suppressions_path=str(sup_path)) == []
    # Full run (metrics included): now it IS unused.
    found = lint_core.run_rules(corpus, [LocksRule(), MetricsRule()],
                                suppressions_path=str(sup_path))
    assert codes(found) == {"core.suppression-unused"}


def test_baseline_subtracts_accepted_findings(tmp_path):
    files = _lock_fixture_files(racy=True)
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    corpus = Corpus(str(tmp_path))
    found = lint_core.run_rules(corpus, [LocksRule()])
    assert len(found) == 1
    base = tmp_path / "baseline.json"
    lint_core.write_baseline(str(base), found)
    again = lint_core.run_rules(
        corpus, [LocksRule()],
        baseline=lint_core.load_baseline(str(base)),
    )
    assert again == []


# ---------------------------------------------------------------------------
# CLI exit codes (the acceptance bullets: each class of violation
# flips a clean fixture repo's exit code to 1 with a file:line finding
# naming the violated rule)
# ---------------------------------------------------------------------------


def _write(tmp_path, files):
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)


def _cli(tmp_path, *args) -> int:
    return lint_main(["--root", str(tmp_path), *args])


def test_cli_clean_repo_exits_0_and_json_shape(tmp_path, capsys):
    _write(tmp_path, _lock_fixture_files(racy=False))
    assert _cli(tmp_path, "--json") == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["findings"] == [] and "locks" in doc["rules"]


def test_cli_deleting_a_glossary_line_flips_to_1(tmp_path, capsys):
    files = {
        "jama16_retina_tpu/mod.py": (
            "reg.counter('layer.x', help='h')\n"
            "reg.gauge('layer.y', help='h')\n"
        ),
        "docs/OBSERVABILITY.md": (
            "# obs\n\n" + GLOSSARY_HEADER
            + "| `layer.x` | counter | ok |\n"
            + "| `layer.y` | gauge | ok |\n"
        ),
    }
    _write(tmp_path, files)
    assert _cli(tmp_path) == 0
    capsys.readouterr()
    # Delete one glossary row -> exit 1 with a finding naming the rule.
    (tmp_path / "docs/OBSERVABILITY.md").write_text(
        "# obs\n\n" + GLOSSARY_HEADER
        + "| `layer.y` | gauge | ok |\n")
    assert _cli(tmp_path) == 1
    out = capsys.readouterr().out
    assert "metrics.undocumented" in out
    assert "jama16_retina_tpu/mod.py:1" in out


def test_cli_unregistered_fault_site_flips_to_1(tmp_path, capsys):
    files = {
        "jama16_retina_tpu/obs/faultinject.py": _FAULTS_DECL,
        "jama16_retina_tpu/mod.py": (
            "from jama16_retina_tpu.obs import faultinject\n"
            "def f():\n"
            "    faultinject.check('a.read')\n"
            "    faultinject.check('b.step')\n"
        ),
        "docs/RELIABILITY.md": _RELIABILITY_DOC,
    }
    _write(tmp_path, files)
    assert _cli(tmp_path) == 0
    capsys.readouterr()
    (tmp_path / "jama16_retina_tpu/mod.py").write_text(
        "from jama16_retina_tpu.obs import faultinject\n"
        "def f():\n"
        "    faultinject.check('a.read')\n"
        "    faultinject.check('b.step')\n"
        "    faultinject.check('never.declared')\n"
    )
    assert _cli(tmp_path) == 1
    out = capsys.readouterr().out
    assert "faults.unknown-site" in out
    assert "jama16_retina_tpu/mod.py:5" in out


def test_cli_unguarded_write_flips_to_1(tmp_path, capsys):
    _write(tmp_path, _lock_fixture_files(racy=False))
    assert _cli(tmp_path) == 0
    capsys.readouterr()
    _write(tmp_path, _lock_fixture_files(racy=True))
    assert _cli(tmp_path) == 1
    out = capsys.readouterr().out
    assert "locks.unguarded-write" in out
    assert "jama16_retina_tpu/mod.py:" in out


def test_cli_unknown_rule_exits_2(tmp_path):
    assert _cli(tmp_path, "--rules", "nonsense") == 2


def test_cli_empty_corpus_exits_2_not_clean(tmp_path):
    """A mis-pointed --root must be loud (review fix): zero scanned
    files would make every rule vacuously pass."""
    (tmp_path / "empty").mkdir()
    assert _cli(tmp_path / "empty") == 2


def test_cli_rule_subset_and_list_rules(tmp_path, capsys):
    _write(tmp_path, _lock_fixture_files(racy=True))
    assert _cli(tmp_path, "--rules", "purity") == 0  # locks not selected
    capsys.readouterr()
    assert lint_main(["--list-rules"]) == 0
    names = capsys.readouterr().out.split()
    assert {"metrics", "config", "faults", "locks", "purity"} <= set(names)


# ---------------------------------------------------------------------------
# THE tier-1 gate: this repository lints clean, forever
# ---------------------------------------------------------------------------


def test_lint_repo_clean():
    corpus = Corpus(REPO_ROOT)
    found = lint_core.run_rules(corpus, default_rules())
    assert found == [], (
        "graftlint found contract violations:\n"
        + "\n".join(f.render() for f in found)
    )


def test_repo_fault_sites_registry_matches_wired_seams():
    """The declared vocabulary is exactly the seams PR 6/8/10/11/12/13
    (+ the ISSUE 17 ingest service, + the ISSUE 18 decode-throttle
    diagnosis drill, + the ISSUE 20 audit-segment seal) wired."""
    from jama16_retina_tpu.obs import faultinject

    assert set(faultinject.SITES) == {
        "tfrecord.read", "host.decode", "ckpt.restore", "ckpt.save",
        "engine.dispatch", "serve.router.dispatch",
        "serve.compile_cache.load", "trainer.step",
        "lifecycle.retrain", "lifecycle.gate", "lifecycle.swap",
        "integrity.write", "integrity.write.commit",
        "ingest.attach", "ingest.ring.write", "ingest.decode",
        "audit.seal",
    }
    assert all(desc for desc in faultinject.SITES.values())


# ---------------------------------------------------------------------------
# configs.override() dotted-path edge cases (ISSUE 9 satellite —
# consolidated here because the config rule's grammar/context checks
# ride the same override surface)
# ---------------------------------------------------------------------------


class TestOverrideEdgeCases:
    def test_empty_default_int_tuple_parses_ints(self):
        cfg = configs.get_config("smoke")
        out = configs.override(cfg, ["serve.bucket_sizes=8,16,32"])
        assert out.serve.bucket_sizes == (8, 16, 32)

    def test_empty_default_str_tuple_stays_str(self):
        cfg = configs.get_config("smoke")
        out = configs.override(cfg, ["eval.ensemble_dirs=20260801,ckpt2"])
        assert out.eval.ensemble_dirs == ("20260801", "ckpt2")

    def test_nonempty_float_tuple_uses_element_type(self):
        cfg = configs.get_config("smoke")
        out = configs.override(cfg, ["data.contrast_range=0.5,1.5"])
        assert out.data.contrast_range == (0.5, 1.5)

    def test_nested_unknown_key_did_you_mean(self):
        cfg = configs.get_config("smoke")
        with pytest.raises(ValueError) as e:
            configs.override(cfg, ["obs.quality.enabledd=true"])
        assert "did you mean 'enabled'" in str(e.value)
        assert "QualityConfig" in str(e.value)  # valid-field listing

    def test_unknown_middle_segment_did_you_mean(self):
        cfg = configs.get_config("smoke")
        with pytest.raises(ValueError) as e:
            configs.override(cfg, ["obs.qualiti.enabled=true"])
        assert "did you mean 'quality'" in str(e.value)

    def test_section_assignment_rejected(self):
        cfg = configs.get_config("smoke")
        with pytest.raises(ValueError, match="set its fields individually"):
            configs.override(cfg, ["obs.quality=on"])

    def test_over_deep_path_is_clean_valueerror(self):
        cfg = configs.get_config("smoke")
        with pytest.raises(ValueError, match="remove the extra segment"):
            configs.override(cfg, ["train.steps.x=1"])

    def test_property_is_not_a_field(self):
        cfg = configs.get_config("smoke")
        with pytest.raises(ValueError, match="unknown config field"):
            configs.override(cfg, ["model.num_classes=3"])

    def test_bad_value_names_the_override(self):
        cfg = configs.get_config("smoke")
        with pytest.raises(ValueError, match="train.steps=banana"):
            configs.override(cfg, ["train.steps=banana"])

    def test_nested_override_applies(self):
        cfg = configs.get_config("smoke")
        out = configs.override(
            cfg, ["obs.quality.enabled=true", "obs.quality.window_scores=64"]
        )
        assert out.obs.quality.enabled is True
        assert out.obs.quality.window_scores == 64
        # untouched siblings survive the frozen-chain rebuild
        assert out.obs.flush_every_s == cfg.obs.flush_every_s
