"""Serving subsystem (jama16_retina_tpu/serve/): the engine's stacked
forward is BIT-IDENTICAL to the sequential restore+forward path it
replaced (the predict.py rewire contract), bucket padding is exact at
every partial batch size, the micro-batcher coalesces concurrent
submitters and returns correct per-request futures under any arrival
interleaving, and the parallel host stage is worker-count-invariant."""

import dataclasses
import threading
import time

import jax
import numpy as np
import pytest

from jama16_retina_tpu import models, train_lib, trainer
from jama16_retina_tpu.configs import ServeConfig, get_config, override
from jama16_retina_tpu.eval import metrics
from jama16_retina_tpu.serve import MicroBatcher, ServingEngine, resolve_buckets
from jama16_retina_tpu.utils import checkpoint as ckpt_lib

K = 2  # ensemble members in the fixture
N_IMGS = 12
SIZE = 32


@pytest.fixture(scope="module")
def serve_setup(tmp_path_factory):
    """Smoke-model ensemble checkpoints + an engine over them.

    Buckets (4, 8) with max_batch 8: small enough that every partial
    size and the chunk boundary are exercised by a 12-image request.
    """
    root = tmp_path_factory.mktemp("serve")
    cfg = override(get_config("smoke"), [f"model.image_size={SIZE}"])
    cfg = cfg.replace(serve=ServeConfig(
        max_batch=8, max_wait_ms=20.0, bucket_sizes=(4, 8),
    ))
    model = models.build(cfg.model)
    dirs = []
    for m in range(K):
        state, _ = train_lib.create_state(cfg, model, jax.random.key(m))
        d = str(root / f"member_{m:02d}")
        ck = ckpt_lib.Checkpointer(d)
        ck.save(1, jax.device_get(state), {"val_auc": 0.5})
        ck.wait()
        ck.close()
        dirs.append(d)
    engine = ServingEngine(cfg, dirs, model=model)
    imgs = np.random.default_rng(0).integers(
        0, 256, (N_IMGS, SIZE, SIZE, 3), np.uint8
    )
    return cfg, model, dirs, engine, imgs


@pytest.fixture(scope="module")
def sequential_ref(serve_setup):
    """The pre-engine path predict.py ran: each member restored
    individually, forwarded through the single-member jit eval step.
    One restore + one jit instance for the whole module (the references
    below call it at several shapes)."""
    cfg, model, dirs, _, _ = serve_setup
    states = [trainer.restore_for_eval(cfg, model, d) for d in dirs]
    eval_step = train_lib.make_eval_step(cfg, model)

    def member_probs(padded):
        return np.stack([
            np.asarray(eval_step(s, {"image": padded})) for s in states
        ])

    return member_probs


def _pad(rows, bucket):
    if rows.shape[0] == bucket:
        return rows
    fill = np.zeros((bucket - rows.shape[0], *rows.shape[1:]), rows.dtype)
    return np.concatenate([rows, fill])


# ---------------------------------------------------------------------------
# Engine: stacked state, bit-identity, bucket padding
# ---------------------------------------------------------------------------


def test_engine_bit_identical_to_sequential_path(serve_setup, sequential_ref):
    """The acceptance contract of the rewire: one stacked lax.map
    forward == k sequential restore+forward passes, bit for bit, at the
    same padded shapes (12 rows -> chunks of 8 and 4 on this engine)."""
    _, _, _, engine, imgs = serve_setup
    got = engine.member_probs(imgs)
    assert got.shape[:2] == (K, N_IMGS)
    ref = np.concatenate([
        sequential_ref(imgs[:8]),
        sequential_ref(_pad(imgs[8:], 4))[:, :4],
    ], axis=1)
    np.testing.assert_array_equal(got, ref)


def test_engine_probs_match_ensemble_average_exactly(serve_setup):
    cfg, model, dirs, engine, imgs = serve_setup
    member = engine.member_probs(imgs)
    np.testing.assert_array_equal(
        engine.probs(imgs), metrics.ensemble_average(list(member))
    )


def test_bucket_padding_exact_at_every_partial_size(serve_setup,
                                                    sequential_ref):
    """For every n in [1, max_batch]: the engine pads n rows to the
    smallest covering bucket with zero rows, and the kept rows are
    bitwise what the sequential path computes at that padded shape —
    zero-fill neighbors are provably inert (row-independent eval)."""
    _, _, _, engine, imgs = serve_setup
    refs = {
        b: sequential_ref(_pad(imgs[:b], b)) for b in engine.buckets
    }
    for n in range(1, engine.max_batch + 1):
        bucket = next(b for b in engine.buckets if b >= n)
        got = engine.member_probs(imgs[:n])
        ref = sequential_ref(_pad(imgs[:n], bucket))[:, :n]
        np.testing.assert_array_equal(got, ref, err_msg=f"n={n}")
        # Rows shared with the full-bucket reference agree too: a kept
        # row's value never depends on whether its neighbors were real
        # images or padding.
        np.testing.assert_array_equal(
            got, refs[bucket][:, :n], err_msg=f"n={n} vs full bucket"
        )


def test_multi_chunk_requests_bounded_in_flight_stay_exact(serve_setup):
    """Requests spanning more chunks than the engine's in-flight window
    (12 rows at max_batch 4 -> 3 chunks vs window 2) produce exactly the
    per-chunk results, in order — the bounded-residency drain loses no
    rows and reorders nothing."""
    cfg, model, dirs, engine, imgs = serve_setup
    small = cfg.replace(serve=ServeConfig(max_batch=4, bucket_sizes=(4,)))
    chunked = ServingEngine(small, dirs, model=model)
    ref = np.concatenate(
        [engine.member_probs(imgs[i:i + 4]) for i in range(0, N_IMGS, 4)],
        axis=1,
    )
    np.testing.assert_array_equal(chunked.member_probs(imgs), ref)


def test_vmapped_member_parallel_mode_is_float_equivalent(serve_setup):
    """serve.member_parallel=true (the pod-topology vmapped form) is
    documented float-equivalent, not bit-equal: batching convs across
    members reassociates their reductions, which at the smoke model's
    bf16 compute dtype drifts probabilities by up to ~4e-4 (well inside
    bf16's ~8e-3 resolution; float32 configs sit at ~1e-7). This pin is
    exactly why the engine's default is the bit-exact lax.map form."""
    cfg, model, dirs, engine, imgs = serve_setup
    vm_cfg = cfg.replace(
        serve=dataclasses.replace(cfg.serve, member_parallel=True)
    )
    vm_engine = ServingEngine(vm_cfg, dirs, model=model)
    got, ref = vm_engine.member_probs(imgs), engine.member_probs(imgs)
    np.testing.assert_allclose(got, ref, rtol=0, atol=2e-3)


def test_engine_rejects_empty_and_misshapen_requests(serve_setup):
    _, _, _, engine, imgs = serve_setup
    with pytest.raises(ValueError, match="empty"):
        engine.member_probs(imgs[:0])
    with pytest.raises(ValueError, match="expected images"):
        engine.member_probs(imgs[0])  # missing the row dim


def test_stack_states_drops_opt_state_and_inverts_unstack(serve_setup):
    cfg, model, dirs, engine, _ = serve_setup
    states = [trainer.restore_for_eval(cfg, model, d) for d in dirs]
    stacked = train_lib.stack_states(states)
    assert stacked.opt_state is None
    assert int(stacked.step.shape[0]) == K
    for m, s in enumerate(states):
        member = train_lib.unstack_member(stacked, m)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            member.params, s.params,
        )
    with pytest.raises(ValueError, match="at least one"):
        train_lib.stack_states([])


def test_resolve_buckets():
    assert resolve_buckets(ServeConfig(max_batch=64)) == (8, 16, 32, 64)
    assert resolve_buckets(ServeConfig(max_batch=48)) == (8, 16, 32, 48)
    assert resolve_buckets(ServeConfig(max_batch=5)) == (5,)
    assert resolve_buckets(
        ServeConfig(max_batch=8, bucket_sizes=(8, 4, 4))
    ) == (4, 8)
    with pytest.raises(ValueError, match="largest bucket"):
        resolve_buckets(ServeConfig(max_batch=16, bucket_sizes=(4, 8)))
    with pytest.raises(ValueError, match="max_batch"):
        resolve_buckets(ServeConfig(max_batch=0))


def test_resolve_buckets_respects_mesh_divisor():
    """Serving meshes shard batch rows over the data axis: auto buckets
    round UP to the axis size, explicit non-dividing buckets are
    rejected at construction instead of at first dispatch."""
    assert resolve_buckets(
        ServeConfig(max_batch=64), divisor=16
    ) == (16, 32, 64)
    assert resolve_buckets(
        ServeConfig(max_batch=20), divisor=16
    ) == (16, 32)  # 8 and 20 both round up
    with pytest.raises(ValueError, match="data axis"):
        resolve_buckets(
            ServeConfig(max_batch=16, bucket_sizes=(4, 16)), divisor=8
        )


def test_engine_on_mesh_rounds_buckets_and_shards(serve_setup):
    """An engine over the 8-fake-device data mesh auto-rounds its
    buckets to the axis size and still scores a lone image correctly
    (bit-identical to the meshless engine: lax.map at an 8-row shape
    either way)."""
    cfg, model, dirs, engine, imgs = serve_setup
    from jama16_retina_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh()
    auto_cfg = cfg.replace(serve=ServeConfig(max_batch=8))
    mesh_engine = ServingEngine(auto_cfg, dirs, model=model, mesh=mesh)
    assert all(b % mesh.devices.size == 0 for b in mesh_engine.buckets)
    # Same compiled row shape (bucket 8) on both engines -> bitwise.
    np.testing.assert_array_equal(
        mesh_engine.member_probs(imgs[:8]), engine.member_probs(imgs[:8])
    )
    # A lone request still serves (padded to a full mesh-divisible
    # bucket under the hood).
    assert mesh_engine.member_probs(imgs[:1]).shape[:2] == (K, 1)


def test_serve_config_overrides_parse_numeric_tuples():
    from jama16_retina_tpu import configs

    cfg = configs.override(get_config("smoke"), [
        "serve.max_batch=16", "serve.max_wait_ms=2.5",
        "serve.bucket_sizes=4,16", "serve.member_parallel=true",
    ])
    assert cfg.serve.max_batch == 16
    assert cfg.serve.max_wait_ms == 2.5
    assert cfg.serve.bucket_sizes == (4, 16)  # ints, not strings
    assert cfg.serve.member_parallel is True
    # Element types come from the ANNOTATION, not from what the value
    # happens to parse as: a date-named checkpoint dir stays a string.
    cfg = configs.override(
        get_config("smoke"), ["eval.ensemble_dirs=20260801,/ckpt/b"]
    )
    assert cfg.eval.ensemble_dirs == ("20260801", "/ckpt/b")


# ---------------------------------------------------------------------------
# Micro-batcher: coalescing, ordering, determinism, failure paths
# ---------------------------------------------------------------------------


def _row_sums(rows):
    return rows.reshape(rows.shape[0], -1).astype(np.float64).sum(axis=1)


def test_batcher_coalesces_queued_requests():
    """16 staged single-row requests flush as ONE coalesced batch (the
    window drains the whole queue before its deadline)."""
    calls = []

    def infer(rows):
        calls.append(rows.shape[0])
        return _row_sums(rows)

    rng = np.random.default_rng(1)
    rows = rng.normal(size=(16, 3))
    with MicroBatcher(
        infer, max_batch=64, max_wait_ms=50.0, autostart=False
    ) as b:
        futs = [b.submit(rows[i:i + 1]) for i in range(16)]
        b.start()
        got = [f.result(timeout=30) for f in futs]
    assert calls == [16]
    assert b.batches_run == 1 and b.rows_run == 16
    for i, g in enumerate(got):
        np.testing.assert_array_equal(g, _row_sums(rows[i:i + 1]))


def test_batcher_window_closes_at_max_batch():
    calls = []

    def infer(rows):
        calls.append(rows.shape[0])
        return _row_sums(rows)

    rows = np.arange(40, dtype=np.float64).reshape(10, 4)
    with MicroBatcher(
        infer, max_batch=4, max_wait_ms=200.0, autostart=False
    ) as b:
        futs = [b.submit(rows[i:i + 1]) for i in range(10)]
        b.start()
        for f in futs:
            f.result(timeout=30)
    # 10 single-row requests at max_batch 4: windows close at 4 rows
    # without ever waiting out the 200 ms deadline.
    assert calls == [4, 4, 2]


def test_batcher_concurrent_submitters_coalesce_and_stay_correct():
    """Concurrent submitters: every future resolves to its own rows'
    results, and the batcher runs FEWER batches than requests (i.e. it
    actually coalesced) while a slow infer holds the engine."""
    calls = []

    def infer(rows):
        calls.append(rows.shape[0])
        time.sleep(0.03)  # while the engine is busy, submitters pile up
        return _row_sums(rows)

    rng = np.random.default_rng(2)
    rows = rng.normal(size=(24, 5))
    results = {}
    barrier = threading.Barrier(8)

    def submitter(w, batcher):
        barrier.wait()
        for i in range(w * 3, w * 3 + 3):
            results[i] = batcher.submit(rows[i:i + 1])

    with MicroBatcher(infer, max_batch=16, max_wait_ms=20.0) as b:
        threads = [
            threading.Thread(target=submitter, args=(w, b))
            for w in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        got = {i: f.result(timeout=30) for i, f in results.items()}
    assert sum(calls) == 24
    assert len(calls) < 24, f"no coalescing happened: {calls}"
    for i in range(24):
        np.testing.assert_array_equal(got[i], _row_sums(rows[i:i + 1]))


def test_batcher_multi_row_requests_split_in_submission_order():
    """Requests of mixed sizes resolve to exactly their own row slices
    of the coalesced result, in submission order."""
    def infer(rows):
        return _row_sums(rows)

    rng = np.random.default_rng(3)
    reqs = [rng.normal(size=(n, 4)) for n in (3, 1, 5)]
    with MicroBatcher(
        infer, max_batch=16, max_wait_ms=50.0, autostart=False
    ) as b:
        futs = [b.submit(r) for r in reqs]
        # close() without start(): the drain path flushes everything
        # still queued, so no future is left hanging.
    for r, f in zip(reqs, futs):
        np.testing.assert_array_equal(f.result(timeout=30), _row_sums(r))
    with pytest.raises(RuntimeError, match="closed"):
        b.submit(reqs[0])


def test_batcher_deterministic_under_arrival_interleaving(serve_setup):
    """Single-bucket engine: a row's probabilities are bit-identical
    whether it was submitted alone, coalesced with strangers, or
    replayed in a different interleaving — every row always runs at the
    same compiled shape with inert zero padding."""
    cfg, model, dirs, _, imgs = serve_setup
    one_bucket = cfg.replace(serve=ServeConfig(
        max_batch=8, max_wait_ms=5.0, bucket_sizes=(8,),
    ))
    engine = ServingEngine(one_bucket, dirs, model=model)
    ref = {i: engine.probs(imgs[i:i + 1]) for i in range(N_IMGS)}

    for seed in (0, 1):
        results = {}
        lock = threading.Lock()

        def submitter(idx_list, batcher):
            for i in idx_list:
                time.sleep(0.001 * ((i + seed) % 3))
                f = batcher.submit(imgs[i:i + 1])
                with lock:
                    results[i] = f

        order = np.random.default_rng(seed).permutation(N_IMGS)
        with engine.make_batcher() as b:
            threads = [
                threading.Thread(
                    target=submitter, args=(order[w::3], b)
                )
                for w in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            got = {i: f.result(timeout=60) for i, f in results.items()}
        for i in range(N_IMGS):
            np.testing.assert_array_equal(
                got[i], ref[i], err_msg=f"seed={seed} img={i}"
            )


def test_batcher_rejects_malformed_rows_at_submit():
    """With a pinned row shape/dtype, a malformed request fails ITS OWN
    submit() and never reaches a coalesced window where it would take
    innocent co-riders' futures down."""
    def infer(rows):
        return _row_sums(rows)

    good = np.zeros((1, 4, 4, 3), np.uint8)
    with MicroBatcher(
        infer, max_batch=8, max_wait_ms=50.0, autostart=False,
        row_shape=(4, 4, 3), row_dtype=np.uint8,
    ) as b:
        f_good = b.submit(good)
        with pytest.raises(ValueError, match="co-riders"):
            b.submit(np.zeros((1, 8, 8, 3), np.uint8))  # wrong size
        with pytest.raises(ValueError, match="uint8"):
            b.submit(np.zeros((1, 4, 4, 3), np.float32))  # wrong dtype
    np.testing.assert_array_equal(
        f_good.result(timeout=30), _row_sums(good)
    )


def test_batcher_cancelled_future_does_not_poison_window():
    """A request cancel()ed before its window flushes must not corrupt
    co-riders: their futures still resolve with their own results."""
    def infer(rows):
        return _row_sums(rows)

    rows = np.arange(12, dtype=np.float64).reshape(3, 4)
    with MicroBatcher(
        infer, max_batch=8, max_wait_ms=50.0, autostart=False
    ) as b:
        f0 = b.submit(rows[0:1])
        f1 = b.submit(rows[1:2])
        f2 = b.submit(rows[2:3])
        assert f1.cancel()  # not yet running: cancellable
        b.start()
        np.testing.assert_array_equal(
            f0.result(timeout=30), _row_sums(rows[0:1])
        )
        np.testing.assert_array_equal(
            f2.result(timeout=30), _row_sums(rows[2:3])
        )
        assert f1.cancelled()


def test_batcher_propagates_infer_errors_and_survives():
    boom = [True]

    def infer(rows):
        if boom[0]:
            raise ValueError("engine exploded")
        return _row_sums(rows)

    rows = np.ones((2, 3))
    with MicroBatcher(infer, max_batch=4, max_wait_ms=1.0) as b:
        f1 = b.submit(rows)
        with pytest.raises(ValueError, match="engine exploded"):
            f1.result(timeout=30)
        boom[0] = False  # the worker must have survived the failure
        f2 = b.submit(rows)
        np.testing.assert_array_equal(f2.result(timeout=30), _row_sums(rows))
    with pytest.raises(ValueError, match="n >= 1"):
        b2 = MicroBatcher(infer, max_batch=4, autostart=False)
        b2.submit(rows[:0])


# ---------------------------------------------------------------------------
# Host stage: parallel fundus normalization
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def photo_dir(tmp_path_factory):
    import cv2

    from jama16_retina_tpu.data import synthetic

    d = tmp_path_factory.mktemp("photos")
    for i in range(4):
        img = synthetic.render_fundus(
            np.random.default_rng(i), i % 5,
            synthetic.SynthConfig(image_size=96),
        )
        cv2.imwrite(str(d / f"eye_{i}.jpeg"), img[..., ::-1])
    (d / "junk.jpeg").write_bytes(b"not a jpeg")
    # A readable frame with no fundus in it (all-black): FundusNotFound.
    cv2.imwrite(str(d / "zz_black.png"), np.zeros((96, 96, 3), np.uint8))
    return d


def test_host_preprocess_is_worker_count_invariant(photo_dir):
    from jama16_retina_tpu.serve import host as serve_host

    paths = sorted(str(p) for p in photo_dir.iterdir())
    runs = [
        serve_host.preprocess_paths(paths, 64, workers=w)
        for w in (1, 4)
    ]
    a, b = runs
    assert a.kept == b.kept and len(a.kept) == 4
    assert a.skipped == b.skipped and len(a.skipped) == 2
    reasons = dict(a.skipped)
    assert "unreadable" in reasons[str(photo_dir / "junk.jpeg")]
    assert "no fundus" in reasons[str(photo_dir / "zz_black.png")]
    np.testing.assert_array_equal(a.images, b.images)
    assert a.qualities == b.qualities
    # Kept rows come back in input order (the _expand contract predict
    # relies on for row<->path alignment).
    assert a.kept == [p for p in paths if "eye_" in p]


def test_host_preprocess_empty_keeps_shape():
    from jama16_retina_tpu.serve import host as serve_host

    res = serve_host.preprocess_paths([], 64, workers=2)
    assert res.images.shape == (0, 64, 64, 3)
    assert res.kept == [] and res.skipped == [] and res.qualities == []


# ---------------------------------------------------------------------------
# Engine vs predict.py CLI: JSONL parity on CPU
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_engine_matches_predict_cli_jsonl(serve_setup, photo_dir):
    """The rewired predict.py CLI emits exactly what the engine +
    parallel host stage compute in-process: same rows, same rounded
    probabilities, same skip ledger — the subsystem and its CLI face
    cannot drift apart."""
    import json
    import os
    import subprocess
    import sys

    cfg, model, dirs, _, _ = serve_setup
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = subprocess.run(
        [
            sys.executable, os.path.join(repo, "predict.py"),
            "--config=smoke", "--set", f"model.image_size={SIZE}",
            *[f"--ensemble_dir={d}" for d in dirs],
            f"--images={photo_dir}", "--device=cpu", "--batch_size=2",
        ],
        capture_output=True, text=True, cwd=repo, timeout=900,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    detail = f"stdout:\n{res.stdout[-2000:]}\nstderr:\n{res.stderr[-2000:]}"
    assert res.returncode == 0, detail
    rows = [json.loads(l) for l in res.stdout.splitlines() if l.strip()]
    cli = {r["image"]: r for r in rows if "prob" in r}
    cli_errors = {r["image"] for r in rows if "error" in r}

    from jama16_retina_tpu.serve import host as serve_host

    paths = sorted(
        str(p) for p in photo_dir.iterdir()
        if str(p).lower().endswith((".jpg", ".jpeg", ".png"))
    )
    pre = serve_host.preprocess_paths(paths, SIZE, workers=2)
    # The CLI pins a single bucket at --batch_size: reproduce it.
    ecfg = cfg.replace(serve=ServeConfig(max_batch=2, bucket_sizes=(2,)))
    engine = ServingEngine(ecfg, dirs, model=model)
    probs = engine.probs(pre.images)
    assert set(cli) == set(pre.kept)
    assert cli_errors == {p for p, _ in pre.skipped}
    for p, pr in zip(pre.kept, probs):
        assert cli[p]["prob"] == round(float(pr), 6), p
        assert cli[p]["n_models"] == K
