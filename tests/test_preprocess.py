"""Offline-preprocessing tests (SURVEY.md §4.1): fundus normalization on
synthetic circles with known geometry, label parsing, stratified splits,
and the full raw-images -> TFRecords -> train pipeline round trip."""

import csv
import os

import numpy as np
import pytest

from jama16_retina_tpu.data import pipeline, synthetic
from jama16_retina_tpu.configs import DataConfig
from jama16_retina_tpu.preprocess import (
    FundusNotFound,
    find_fundus_circle,
    fundus,
    resize_and_center_fundus,
)
from jama16_retina_tpu.preprocess import datasets


def draw_disc(size_hw, cx, cy, r, value=120):
    h, w = size_hw
    img = np.zeros((h, w, 3), np.uint8)
    yy, xx = np.mgrid[0:h, 0:w]
    img[((xx - cx) ** 2 + (yy - cy) ** 2) <= r * r] = value
    return img


class TestFundusCircle:
    def test_detects_known_circle(self):
        img = draw_disc((400, 600), cx=310, cy=190, r=150)
        c = find_fundus_circle(img)
        assert abs(c.cx - 310) <= 2 and abs(c.cy - 190) <= 2
        assert abs(c.radius - 150) <= 2

    def test_blank_image_raises(self):
        with pytest.raises(FundusNotFound):
            find_fundus_circle(np.zeros((100, 100, 3), np.uint8))

    def test_tiny_speck_raises(self):
        img = np.zeros((200, 200, 3), np.uint8)
        img[99:101, 99:101] = 200
        with pytest.raises(FundusNotFound):
            find_fundus_circle(img)

    def test_vertically_cropped_frame_uses_width(self):
        # EyePACS-style: circle top/bottom cut by the frame.
        img = draw_disc((300, 500), cx=250, cy=150, r=200)
        c = find_fundus_circle(img)
        assert abs(c.radius - 200) <= 2
        assert abs(c.cx - 250) <= 2


class TestResizeAndCenter:
    @pytest.mark.parametrize("cx,cy,r", [(310, 190, 150), (150, 150, 60),
                                          (500, 260, 220)])
    def test_output_centered_fixed_radius(self, cx, cy, r):
        img = draw_disc((480, 720), cx, cy, r)
        out = resize_and_center_fundus(img, diameter=128)
        assert out.shape == (128, 128, 3) and out.dtype == np.uint8
        c = find_fundus_circle(out, threshold=12)
        # Centered within a couple px, radius ~= 128*0.98/2.
        assert abs(c.cx - 64) <= 3 and abs(c.cy - 64) <= 3
        assert abs(c.radius - 128 * 0.98 / 2) <= 3

    def test_corners_are_black_with_mask(self):
        img = np.full((300, 300, 3), 200, np.uint8)  # fully lit frame
        out = resize_and_center_fundus(img, diameter=100, circular_mask=True)
        assert out[0, 0].sum() == 0 and out[-1, -1].sum() == 0
        assert out[50, 50].sum() > 0

    def test_ben_graham_preserves_shape_and_range(self):
        img = draw_disc((300, 300), 150, 150, 120)
        out = resize_and_center_fundus(img, diameter=96, ben_graham=True)
        assert out.shape == (96, 96, 3)
        assert out.max() <= 255 and out.min() >= 0

    def test_synthetic_fundus_roundtrip(self):
        # The synthetic renderer's discs normalize cleanly too.
        imgs, _ = synthetic.make_dataset(2, synthetic.SynthConfig(image_size=160))
        for im in imgs:
            out = resize_and_center_fundus(im, diameter=96)
            c = find_fundus_circle(out)
            assert abs(c.cx - 48) <= 4 and abs(c.cy - 48) <= 4


class TestLabelsCsv:
    def _write(self, tmp_path, rows, name="labels.csv", delim=","):
        p = os.path.join(tmp_path, name)
        with open(p, "w", newline="") as fh:
            csv.writer(fh, delimiter=delim).writerows(rows)
        return p

    def test_eyepacs_format(self, tmp_path):
        p = self._write(tmp_path, [["image", "level"], ["10_left", "0"],
                                   ["10_right", "3"], ["13_left", "2"]])
        labels = datasets.parse_labels_csv(p)
        assert labels == {"10_left": 0, "10_right": 3, "13_left": 2}

    def test_messidor_semicolon_format(self, tmp_path):
        p = self._write(
            tmp_path,
            [["Image name", "Retinopathy grade", "Macular edema"],
             ["20051020_43808_0100_PP.tif", "2", "0"],
             ["20051020_43832_0100_PP.tif", "0", "1"]],
            delim=";",
        )
        labels = datasets.parse_labels_csv(p)
        assert labels == {
            "20051020_43808_0100_PP": 2,
            "20051020_43832_0100_PP": 0,
        }

    def test_headerless(self, tmp_path):
        p = self._write(tmp_path, [["img_a", "1"], ["img_b", "4"]])
        assert datasets.parse_labels_csv(p) == {"img_a": 1, "img_b": 4}

    def test_empty_raises(self, tmp_path):
        p = self._write(tmp_path, [])
        with pytest.raises(ValueError):
            datasets.parse_labels_csv(p)


class TestStratifiedSplit:
    def test_fractions_and_stratification(self):
        labels = {f"g{g}_{i}": g for g in range(5) for i in range(40)}
        splits = datasets.stratified_split(labels, 0.1, 0.2, seed=0)
        assert len(splits["test"]) == 40 and len(splits["val"]) == 20
        assert len(splits["train"]) == 140
        for split in splits.values():
            grades = [g for _, g in split]
            assert set(grades) == set(range(5))  # every grade in every split
        # Disjoint and complete.
        names = [n for s in splits.values() for n, _ in s]
        assert len(names) == len(set(names)) == 200

    def test_deterministic_given_seed(self):
        labels = {f"im{i}": i % 5 for i in range(50)}
        a = datasets.stratified_split(labels, 0.2, 0.2, seed=3)
        b = datasets.stratified_split(labels, 0.2, 0.2, seed=3)
        assert a == b


def test_end_to_end_raw_images_to_train_pipeline(tmp_path):
    """Raw synthetic photos on disk + CSV -> process_split -> TFRecords
    readable by the online pipeline (the full reference preprocessing
    contract, SURVEY.md §3.3)."""
    import cv2

    raw = tmp_path / "raw"
    raw.mkdir()
    rng = np.random.default_rng(0)
    items = []
    for i in range(8):
        grade = int(rng.integers(0, 5))
        # Rectangular frame with off-center disc, like a real photograph.
        img = draw_disc((240, 320), cx=140 + i * 5, cy=120, r=90 + i,
                        value=100 + i * 10)
        cv2.imwrite(str(raw / f"im_{i}.jpeg"), img[..., ::-1])
        items.append((f"im_{i}", grade))

    out = tmp_path / "tfr"
    stats = datasets.process_split(
        items, str(raw), str(out), "train", image_size=96, num_shards=2
    )
    assert stats.written == 8 and stats.skipped_missing == 0
    batch = next(
        pipeline.train_batches(str(out), "train", DataConfig(batch_size=4), 96)
    )
    assert batch["image"].shape == (4, 96, 96, 3)
    # Every stored image is a normalized centered disc.
    c = find_fundus_circle(batch["image"][0])
    assert abs(c.cx - 48) <= 4 and abs(c.cy - 48) <= 4


def test_process_split_workers_byte_identical(tmp_path):
    """--workers=N must be a pure wall-clock lever: the 2-worker pool
    produces byte-identical shards AND quality CSV to the serial run
    (VERDICT r3 #6 — order preserved by imap, all writing in the one
    consumer). One image is missing and one is blank so the skip
    bookkeeping crosses the process boundary too."""
    import cv2

    raw = tmp_path / "raw"
    raw.mkdir()
    rng = np.random.default_rng(1)
    items = []
    for i in range(6):
        grade = int(rng.integers(0, 5))
        img = draw_disc((200, 260), cx=120 + i * 4, cy=100, r=80 + i,
                        value=90 + i * 12)
        cv2.imwrite(str(raw / f"im_{i}.jpeg"), img[..., ::-1])
        items.append((f"im_{i}", grade))
    cv2.imwrite(str(raw / "blank.jpeg"), np.zeros((200, 260, 3), np.uint8))
    items.append(("blank", 0))          # -> skipped_no_fundus
    items.append(("gone", 1))           # -> skipped_missing

    outs = {}
    for label, workers in (("serial", 0), ("pool", 2)):
        out = tmp_path / label
        stats = datasets.process_split(
            items, str(raw), str(out), "train", image_size=96,
            num_shards=2, workers=workers,
        )
        assert stats.written == 6 and stats.skipped_no_fundus == 1
        assert stats.skipped_missing == 1
        outs[label] = out

    serial_files = sorted(p.name for p in outs["serial"].iterdir())
    assert serial_files == sorted(p.name for p in outs["pool"].iterdir())
    for name in serial_files:
        a = (outs["serial"] / name).read_bytes()
        b = (outs["pool"] / name).read_bytes()
        assert a == b, f"{name} differs between serial and 2-worker runs"


class TestGradability:
    """fundus.gradability_stats: the image-quality lever (VERDICT r2 #4).
    Synthetic fundus images carry vessel/lesion texture, so heavy blur,
    under- and over-exposure must separate cleanly from clean renders."""

    def _images(self, n=4):
        imgs, _ = synthetic.make_dataset(
            n, synthetic.SynthConfig(image_size=128), seed=0
        )
        return imgs

    def test_blur_collapses_score(self):
        import cv2

        sharp = [fundus.gradability_stats(im)["quality"]
                 for im in self._images()]
        blurred = [
            fundus.gradability_stats(cv2.GaussianBlur(im, (0, 0), 6))["quality"]
            for im in self._images()
        ]
        assert min(sharp) > 2 * max(blurred), (sharp, blurred)

    def test_exposure_penalized(self):
        im = self._images(1)[0]
        good = fundus.gradability_stats(im)["quality"]
        dark = fundus.gradability_stats((im * 0.08).astype(np.uint8))["quality"]
        washed = fundus.gradability_stats(
            np.clip(im.astype(np.int32) + 215, 0, 255).astype(np.uint8)
        )["quality"]
        assert good > 2 * dark
        assert good > 2 * washed

    def test_min_quality_filter_and_report(self, tmp_path):
        """process_split with --min_quality: blurred photographs are
        dropped and counted, every image (kept or not) lands in the
        quality report CSV, and written records carry their score in
        image/quality (read back via read_quality_by_name)."""
        import cv2

        from jama16_retina_tpu.data import tfrecord

        raw = tmp_path / "raw"
        raw.mkdir()
        items = []
        for i, im in enumerate(self._images(6)):
            if i >= 3:  # last three: heavy defocus
                im = cv2.GaussianBlur(im, (0, 0), 6)
            # PNG: JPEG ringing would re-sharpen the blurred frames.
            cv2.imwrite(str(raw / f"q_{i}.png"), im[..., ::-1])
            items.append((f"q_{i}", i % 5))

        # Threshold between the two clusters, computed from the data so
        # the test pins SEPARATION, not absolute constants.
        def score(name):
            bgr = cv2.imread(str(raw / f"{name}.png"), cv2.IMREAD_COLOR)
            norm, q = resize_and_center_fundus(
                bgr[..., ::-1], diameter=96, with_quality=True
            )
            return q["quality"]

        sharp_min = min(score(f"q_{i}") for i in range(3))
        blur_max = max(score(f"q_{i}") for i in range(3, 6))
        assert sharp_min > blur_max
        thresh = (sharp_min + blur_max) / 2

        out = tmp_path / "tfr"
        stats = datasets.process_split(
            items, str(raw), str(out), "train", image_size=96,
            num_shards=1, min_quality=thresh,
        )
        assert stats.written == 3
        assert stats.skipped_low_quality == 3
        assert stats.quality_mean >= thresh > 0

        with open(out / "quality_train.csv") as f:
            rows = list(csv.DictReader(f))
        assert len(rows) == 6
        assert sum(int(r["written"]) for r in rows) == 3
        assert all(float(r["quality"]) >= 0 for r in rows)

        from jama16_retina_tpu.data.tfrecord import (
            list_split,
            read_quality_by_name,
        )

        q = read_quality_by_name(list_split(str(out), "train"))
        assert len(q) == 3
        assert all(v >= thresh for v in q.values())


def test_process_split_counts_missing_and_blank(tmp_path):
    import cv2

    raw = tmp_path / "raw"
    raw.mkdir()
    cv2.imwrite(str(raw / "good.jpeg"),
                draw_disc((200, 200), 100, 100, 80)[..., ::-1])
    cv2.imwrite(str(raw / "blank.jpeg"), np.zeros((200, 200, 3), np.uint8))
    items = [("good", 1), ("blank", 0), ("absent", 2)]
    stats = datasets.process_split(items, str(raw), str(tmp_path / "o"),
                                   "test", image_size=64, num_shards=1)
    assert stats.written == 1
    assert stats.skipped_no_fundus == 1
    assert stats.skipped_missing == 1
