"""Grain loader tests (data/grain_pipeline.py; SURVEY.md N4/§5.4).

Pins: the TFRecord random-access index decodes the same records the
tf.data parser does; the derived O(1) resume state equals the state a
really-consumed iterator reports (sharded and unsharded); per-process
shards are disjoint; and a full trainer.fit with data.loader=grain
reproduces an uninterrupted loss curve across an interrupt/resume.
"""

import json
import os

import numpy as np
import pytest

from jama16_retina_tpu import trainer
from jama16_retina_tpu.configs import DataConfig, get_config, override
from jama16_retina_tpu.data import grain_pipeline, tfrecord
from jama16_retina_tpu.utils.logging import read_jsonl


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("grain_data"))
    tfrecord.write_synthetic_split(d, "train", 48, 32, 3, seed=1)
    tfrecord.write_synthetic_split(d, "val", 24, 32, 2, seed=2)
    return d


def test_index_matches_tfdata_parse(data_dir):
    """Every record the pure-host index reads decodes BIT-EXACTLY to what
    tf.data's parse_fn produces: parse_fn pins dct_method=INTEGER_ACCURATE,
    the islow DCT OpenCV also uses, so switching data.loader can never
    change the pixel stream. Raw-encoded records are exact by construction
    — also pinned."""
    import tensorflow as tf

    paths = tfrecord.list_split(data_dir, "train")
    source = grain_pipeline.FundusSource(data_dir, "train", 32)
    parse = tfrecord.parse_fn()
    ref = [
        (image.numpy(), int(grade.numpy()))
        for image, grade, _ in map(
            parse, tf.data.TFRecordDataset(paths).take(len(source))
        )
    ]
    assert len(source) == 48 == len(ref)
    for i in range(len(source)):
        row = source[i]
        np.testing.assert_array_equal(row["image"], ref[i][0])
        assert int(row["grade"]) == ref[i][1]

    # Raw encoding: byte-exact round trip through the index.
    rng = np.random.default_rng(0)
    img = rng.integers(0, 256, (32, 32, 3), dtype=np.uint8)
    raw_dir = os.path.join(data_dir, "rawenc")
    tfrecord.write_example_shards(
        [tfrecord.make_raw_example(img, 3, "x")], raw_dir, "train", 1
    )
    src = grain_pipeline.FundusSource(raw_dir, "train", 32)
    np.testing.assert_array_equal(src[0]["image"], img)
    assert int(src[0]["grade"]) == 3


@pytest.mark.parametrize("p_cnt", [1, 2])
def test_derived_state_matches_consumed_state(data_dir, p_cnt):
    cfg = DataConfig(batch_size=8)
    for p_idx in range(p_cnt):
        it = grain_pipeline.make_train_iterator(
            data_dir, "train", cfg, 32, seed=5,
            process_index=p_idx, process_count=p_cnt,
        )
        for _ in range(3):
            next(it)
        real = json.loads(it.get_state().decode())
        fresh = grain_pipeline.make_train_iterator(
            data_dir, "train", cfg, 32, seed=5,
            process_index=p_idx, process_count=p_cnt,
        )
        derived = json.loads(
            grain_pipeline.state_at_step(
                fresh, 3, 8 // p_cnt, p_idx, p_cnt
            ).decode()
        )
        assert real["last_seen_indices"] == derived["last_seen_indices"]
        assert real["last_worker_index"] == derived["last_worker_index"]
        # And the restored stream continues with the identical batch.
        resumed = grain_pipeline.train_batches(
            data_dir, "train", cfg, 32, seed=5,
            process_index=p_idx, process_count=p_cnt, skip_batches=3,
        )
        a, b = next(it), next(resumed)
        np.testing.assert_array_equal(a["image"], b["image"])
        np.testing.assert_array_equal(a["grade"], b["grade"])


def test_process_shards_are_disjoint_and_cover_epoch(data_dir):
    """One epoch across 2 processes: no record seen twice, and together
    they cover all records the drop-remainder shard admits."""
    cfg = DataConfig(batch_size=8)
    blobs = []
    for p in range(2):
        it = grain_pipeline.make_train_iterator(
            data_dir, "train", cfg, 32, seed=9,
            process_index=p, process_count=2,
        )
        # 48 records / 2 shards / local batch 4 = 6 batches per epoch
        for _ in range(6):
            blobs.append(next(it)["image"].tobytes())
    imgs = np.concatenate([
        np.frombuffer(b, np.uint8).reshape(-1, 32, 32, 3) for b in blobs
    ])
    # Pixel payloads are unique per synthetic record, so byte-identity
    # detects duplicates across and within shards.
    uniq = {im.tobytes() for im in imgs}
    assert len(imgs) == 48
    assert len(uniq) == 48  # every record exactly once across the epoch


@pytest.mark.slow  # worker-process startup dominates on a 1-vCPU host
def test_worker_parallelism_is_deterministic_and_covers_epoch(data_dir):
    """The practical race check for loader parallelism (SURVEY.md §5.2,
    the grain analogue of the tf.data determinism test). grain worker
    PROCESSES interleave whole batches round-robin, so their stream is a
    known reordering of in-process loading (state_at_step documents why
    there is no closed-form resume for it) — what must hold is:
    (a) two independent worker_count=2 runs with one seed are
    bit-identical (no scheduling nondeterminism leaks into batches), and
    (b) one epoch still yields every record exactly once."""
    cfg = DataConfig(batch_size=8)
    run_a, run_b = (
        grain_pipeline.make_train_iterator(
            data_dir, "train", cfg, 32, seed=11, worker_count=2
        )
        for _ in range(2)
    )
    seen = []
    for _ in range(9):  # past one 6-batch epoch: reshuffle must agree too
        a, b = next(run_a), next(run_b)
        np.testing.assert_array_equal(a["image"], b["image"])
        np.testing.assert_array_equal(a["grade"], b["grade"])
        seen.append(a["image"])
    epoch = np.concatenate(seen[:6])
    assert len({im.tobytes() for im in epoch}) == 48  # each record once


def test_fit_with_grain_loader_resumes_exactly(data_dir, tmp_path):
    """trainer.fit end to end on data.loader=grain: interrupted+resumed
    == uninterrupted, with augmentation on — §5.4's contract, now with
    O(1) state restore instead of replay."""
    cfg = override(
        get_config("smoke"),
        ["data.loader=grain", "train.steps=12", "train.eval_every=6",
         "train.log_every=1", "data.augment=true", "data.batch_size=8",
         "eval.batch_size=8", "train.lr_schedule=constant"],
    )
    w_full = str(tmp_path / "full")
    trainer.fit(cfg, data_dir, w_full, seed=3)
    full = {
        r["step"]: r["loss"]
        for r in read_jsonl(os.path.join(w_full, "metrics.jsonl"))
        if r["kind"] == "train"
    }
    w_part = str(tmp_path / "part")
    trainer.fit(override(cfg, ["train.steps=6"]), data_dir, w_part, seed=3)
    trainer.fit(override(cfg, ["train.resume=true"]), data_dir, w_part, seed=3)
    part = {
        r["step"]: r["loss"]
        for r in read_jsonl(os.path.join(w_part, "metrics.jsonl"))
        if r["kind"] == "train"
    }
    assert set(full) == set(part) == set(range(1, 13))
    for s in full:
        assert full[s] == part[s], f"step {s}: {full[s]} != {part[s]}"


@pytest.mark.slow  # worker-process startup dominates on a 1-vCPU host
def test_fit_with_grain_workers_resumes_exactly(data_dir, tmp_path):
    """Worker-mode resume (VERDICT r2 #5): with data.grain_workers=2
    positions have no (seed, step) closed form, so the trainer persists
    iterator.get_state() next to each checkpoint (grain_state/<step>)
    and restores it on --resume. Interrupted+resumed == uninterrupted,
    both in worker mode."""
    cfg = override(
        get_config("smoke"),
        ["data.loader=grain", "data.grain_workers=2", "train.steps=12",
         "train.eval_every=6", "train.log_every=1", "data.augment=true",
         "data.batch_size=8", "eval.batch_size=8",
         "train.lr_schedule=constant"],
    )
    w_full = str(tmp_path / "full")
    trainer.fit(cfg, data_dir, w_full, seed=3)
    full = {
        r["step"]: r["loss"]
        for r in read_jsonl(os.path.join(w_full, "metrics.jsonl"))
        if r["kind"] == "train"
    }
    w_part = str(tmp_path / "part")
    trainer.fit(override(cfg, ["train.steps=6"]), data_dir, w_part, seed=3)
    assert os.path.exists(os.path.join(w_part, "grain_state", "6.json"))
    trainer.fit(override(cfg, ["train.resume=true"]), data_dir, w_part, seed=3)
    part = {
        r["step"]: r["loss"]
        for r in read_jsonl(os.path.join(w_part, "metrics.jsonl"))
        if r["kind"] == "train"
    }
    assert set(full) == set(part) == set(range(1, 13))
    for s in full:
        assert full[s] == part[s], f"step {s}: {full[s]} != {part[s]}"


def test_grain_worker_resume_without_state_file_fails_loudly(
    data_dir, tmp_path
):
    """A worker-mode resume with no persisted state (legacy workdir)
    must hit grain's documented NotImplementedError, not silently
    fabricate a position."""
    cfg = override(
        get_config("smoke"),
        ["data.loader=grain", "train.steps=6", "train.eval_every=3",
         "data.batch_size=8", "eval.batch_size=8"],
    )
    w = str(tmp_path / "legacy")
    trainer.fit(cfg, data_dir, w, seed=0)  # in-process run: no state files
    resumed = override(cfg, [
        "train.resume=true", "train.steps=9", "data.grain_workers=2",
    ])
    with pytest.raises(NotImplementedError, match="grain_state"):
        trainer.fit(resumed, data_dir, w, seed=0)


def test_unknown_loader_raises(data_dir, tmp_path):
    cfg = override(get_config("smoke"), ["data.loader=dali"])
    with pytest.raises(ValueError, match="unknown data.loader"):
        trainer.fit(cfg, data_dir, str(tmp_path / "x"), seed=0)
