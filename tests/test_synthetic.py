"""Synthetic fundus fixture sanity (SURVEY.md §4 fixtures)."""

import numpy as np

from jama16_retina_tpu.data import synthetic


def test_shapes_dtype_and_determinism():
    imgs, grades = synthetic.make_dataset(8, synthetic.SynthConfig(image_size=64), seed=3)
    assert imgs.shape == (8, 64, 64, 3) and imgs.dtype == np.uint8
    assert grades.shape == (8,) and set(np.unique(grades)) <= set(range(5))
    imgs2, grades2 = synthetic.make_dataset(8, synthetic.SynthConfig(image_size=64), seed=3)
    np.testing.assert_array_equal(imgs, imgs2)
    np.testing.assert_array_equal(grades, grades2)


def test_fundus_structure():
    cfg = synthetic.SynthConfig(image_size=128)
    rng = np.random.default_rng(0)
    img = synthetic.render_fundus(rng, 0, cfg)
    # corners are (near-)black background; center is bright retina
    assert img[:8, :8].mean() < 20
    assert img[60:68, 60:68].mean() > 60


def test_grade_signal_present():
    """Higher grades must carry more dark-lesion pixels — the learnable
    signal integration tests rely on."""
    cfg = synthetic.SynthConfig(image_size=128)

    def lesion_frac(grade, seed):
        rng = np.random.default_rng(seed)
        img = synthetic.render_fundus(rng, grade, cfg).astype(np.int32)
        # lesions are dark red: low green+blue, moderate red
        mask = (img[..., 0] < 130) & (img[..., 0] > 50) & (img[..., 1] < 40)
        return mask.mean()

    g0 = np.mean([lesion_frac(0, s) for s in range(10)])
    g4 = np.mean([lesion_frac(4, s) for s in range(10)])
    assert g4 > g0 * 2


def test_binary_labels():
    np.testing.assert_array_equal(
        synthetic.binary_labels(np.array([0, 1, 2, 3, 4])), [0, 0, 1, 1, 1]
    )


def test_flip_binary_labels_rate_and_boundary():
    grades = synthetic.sample_grades(20_000, np.random.default_rng(0))
    flipped = synthetic.flip_binary_labels(
        grades, 0.1, np.random.default_rng(1)
    )
    y, y_noisy = synthetic.binary_labels(grades), synthetic.binary_labels(flipped)
    rate = (y != y_noisy).mean()
    assert 0.08 < rate < 0.12  # ~p of labels flipped
    # flips land exactly one grade across the boundary; unflipped
    # grades are untouched
    assert set(np.unique(flipped[y != y_noisy])) <= {1, 2}
    np.testing.assert_array_equal(grades[y == y_noisy], flipped[y == y_noisy])
    # p=0 is the identity
    np.testing.assert_array_equal(
        synthetic.flip_binary_labels(grades, 0.0, np.random.default_rng(2)),
        grades,
    )


def test_noisy_auc_ceiling_matches_monte_carlo():
    """The analytic ceiling (published in the time_to_auc artifact) must
    match a direct simulation: score = true label + tiny within-class
    jitter (a perfect scorer), AUC measured against flipped labels."""
    from sklearn.metrics import roc_auc_score

    p, q, n = 0.05, 0.30, 200_000
    rng = np.random.default_rng(0)
    truth = (rng.random(n) < q).astype(np.int32)
    noisy = truth ^ (rng.random(n) < p)
    score = truth + rng.random(n) * 1e-3  # perfect ranking, no exact ties
    mc = roc_auc_score(noisy, score)
    assert abs(synthetic.noisy_auc_ceiling(p, q) - mc) < 0.003
    # clean labels -> perfect AUC
    assert synthetic.noisy_auc_ceiling(0.0, q) == 1.0


def test_write_synthetic_split_label_noise(tmp_path):
    from jama16_retina_tpu.data import tfrecord
    from jama16_retina_tpu.data.grain_pipeline import FundusSource

    d = str(tmp_path)
    tfrecord.write_synthetic_split(
        d, "clean", 64, image_size=32, num_shards=1, seed=5, encoding="raw"
    )
    tfrecord.write_synthetic_split(
        d, "noisy", 64, image_size=32, num_shards=1, seed=5, encoding="raw",
        label_noise=0.25,
    )
    clean = FundusSource(d, "clean", 32)
    noisy = FundusSource(d, "noisy", 32)
    n_flip = 0
    for i in range(64):
        c, n = clean[i], noisy[i]
        np.testing.assert_array_equal(c["image"], n["image"])
        if (c["grade"] >= 2) != (n["grade"] >= 2):
            n_flip += 1
    assert 0 < n_flip < 64


def test_write_synthetic_split_shifted_distribution(tmp_path):
    """The shift knobs behind the cross-dataset transfer artifact
    (scripts/cross_dataset_transfer.py): custom grade marginals move the
    written prevalence, a custom SynthConfig changes the rendered
    images, and malformed marginals are refused loudly."""
    import pytest

    from jama16_retina_tpu.data import synthetic, tfrecord
    from jama16_retina_tpu.data.grain_pipeline import FundusSource

    d = str(tmp_path)
    marg = (0.2, 0.1, 0.3, 0.2, 0.2)  # prevalence 0.70 vs default 0.30
    tfrecord.write_synthetic_split(
        d, "shift", 200, image_size=32, num_shards=1, seed=5,
        encoding="raw", grade_marginals=marg,
        synth_cfg=synthetic.SynthConfig(
            image_size=32, lesions_per_grade=2, lesion_radius=1
        ),
    )
    tfrecord.write_synthetic_split(
        d, "base", 200, image_size=32, num_shards=1, seed=5, encoding="raw"
    )
    shift, base = FundusSource(d, "shift", 32), FundusSource(d, "base", 32)
    prev = np.mean([shift[i]["grade"] >= 2 for i in range(200)])
    assert 0.55 < prev < 0.85  # binomial(200, 0.70) comfortably inside
    # Same seed, different SynthConfig+grades: images must differ.
    assert any(
        not np.array_equal(shift[i]["image"], base[i]["image"])
        for i in range(10)
    )
    # One-stream discipline: explicitly passing the DEFAULT marginals
    # must reproduce the default path byte-identically (the grade draw
    # stays first on the seed's rng; labels and render noise never
    # share stream positions).
    tfrecord.write_synthetic_split(
        d, "ctrl", 200, image_size=32, num_shards=1, seed=5,
        encoding="raw", grade_marginals=synthetic.GRADE_MARGINALS,
    )
    ctrl = FundusSource(d, "ctrl", 32)
    for i in range(0, 200, 37):
        np.testing.assert_array_equal(ctrl[i]["image"], base[i]["image"])
        assert ctrl[i]["grade"] == base[i]["grade"]
    with pytest.raises(ValueError, match="grade_marginals"):
        tfrecord.write_synthetic_split(
            d, "bad", 4, image_size=32, grade_marginals=(0.5, 0.5)
        )


def test_sample_grades_is_make_datasets_first_draw():
    """The realized-ceiling path (scripts/time_to_auc.py) reproduces a
    split's grades from its seed via sample_grades — which must stay the
    FIRST draw make_dataset performs, or the gate silently computes the
    ceiling for different labels than the written split's."""
    _, grades = synthetic.make_dataset(
        32, synthetic.SynthConfig(image_size=32), seed=12
    )
    np.testing.assert_array_equal(
        grades, synthetic.sample_grades(32, np.random.default_rng(12))
    )


def test_realized_ceiling_converges_to_analytic():
    p, n = 0.05, 300_000
    true = synthetic.sample_grades(n, np.random.default_rng(0))
    noisy = synthetic.flip_binary_labels(
        true, p, np.random.default_rng([0, synthetic.FLIP_STREAM_KEY])
    )
    realized = synthetic.realized_noisy_auc_ceiling(true >= 2, noisy >= 2)
    analytic = synthetic.noisy_auc_ceiling(p, synthetic.REFERABLE_PREVALENCE)
    assert abs(realized - analytic) < 0.002
    # degenerate split refuses loudly
    import pytest

    with pytest.raises(ValueError):
        synthetic.realized_noisy_auc_ceiling(
            np.ones(4, bool), np.ones(4, bool)
        )
