"""Synthetic fundus fixture sanity (SURVEY.md §4 fixtures)."""

import numpy as np

from jama16_retina_tpu.data import synthetic


def test_shapes_dtype_and_determinism():
    imgs, grades = synthetic.make_dataset(8, synthetic.SynthConfig(image_size=64), seed=3)
    assert imgs.shape == (8, 64, 64, 3) and imgs.dtype == np.uint8
    assert grades.shape == (8,) and set(np.unique(grades)) <= set(range(5))
    imgs2, grades2 = synthetic.make_dataset(8, synthetic.SynthConfig(image_size=64), seed=3)
    np.testing.assert_array_equal(imgs, imgs2)
    np.testing.assert_array_equal(grades, grades2)


def test_fundus_structure():
    cfg = synthetic.SynthConfig(image_size=128)
    rng = np.random.default_rng(0)
    img = synthetic.render_fundus(rng, 0, cfg)
    # corners are (near-)black background; center is bright retina
    assert img[:8, :8].mean() < 20
    assert img[60:68, 60:68].mean() > 60


def test_grade_signal_present():
    """Higher grades must carry more dark-lesion pixels — the learnable
    signal integration tests rely on."""
    cfg = synthetic.SynthConfig(image_size=128)

    def lesion_frac(grade, seed):
        rng = np.random.default_rng(seed)
        img = synthetic.render_fundus(rng, grade, cfg).astype(np.int32)
        # lesions are dark red: low green+blue, moderate red
        mask = (img[..., 0] < 130) & (img[..., 0] > 50) & (img[..., 1] < 40)
        return mask.mean()

    g0 = np.mean([lesion_frac(0, s) for s in range(10)])
    g4 = np.mean([lesion_frac(4, s) for s in range(10)])
    assert g4 > g0 * 2


def test_binary_labels():
    np.testing.assert_array_equal(
        synthetic.binary_labels(np.array([0, 1, 2, 3, 4])), [0, 0, 1, 1, 1]
    )
