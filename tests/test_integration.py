"""Integration tests (SURVEY.md §4.4): tiny synthetic TFRecords -> full
fit() -> checkpoint round-trip -> evaluate with operating points; plus the
k=2 ensemble path. Runs through the real compiler on 8 fake CPU devices."""

import os

import jax
import numpy as np
import pytest

from jama16_retina_tpu import models, train_lib, trainer
from jama16_retina_tpu.configs import get_config, override
from jama16_retina_tpu.data import tfrecord
from jama16_retina_tpu.eval import metrics
from jama16_retina_tpu.utils import checkpoint as ckpt_lib
from jama16_retina_tpu.utils.logging import read_jsonl


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("synth_data"))
    # Learnable set: lesion count correlates with grade (see data/synthetic).
    tfrecord.write_synthetic_split(d, "train", 96, 64, 4, seed=1)
    tfrecord.write_synthetic_split(d, "val", 48, 64, 2, seed=2)
    tfrecord.write_synthetic_split(d, "test", 48, 64, 2, seed=3)
    return d


@pytest.fixture(scope="module")
def smoke_cfg():
    cfg = get_config("smoke")
    return override(
        cfg,
        [
            "train.steps=60",
            "train.eval_every=20",
            "train.log_every=10",
            "train.learning_rate=0.005",
            "eval.batch_size=16",
            "data.batch_size=16",
            "data.augment=false",
        ],
    )


@pytest.fixture(scope="module")
def fitted(smoke_cfg, data_dir, tmp_path_factory):
    workdir = str(tmp_path_factory.mktemp("run"))
    res = trainer.fit(smoke_cfg, data_dir, workdir, seed=0)
    return workdir, res


def test_fit_improves_and_checkpoints(fitted, smoke_cfg):
    workdir, res = fitted
    # The synthetic task is learnable: 60 steps of tiny_cnn must beat chance.
    assert res["best_auc"] > 0.65, res
    assert res["best_step"] > 0
    log = read_jsonl(os.path.join(workdir, "metrics.jsonl"))
    kinds = {r["kind"] for r in log}
    assert {"config", "train", "eval"} <= kinds
    train_recs = [r for r in log if r["kind"] == "train"]
    assert all(np.isfinite(r["loss"]) for r in train_recs)
    # Window rates may be None (physics-guard refusal) but never an
    # impossible number; the pause-aware average must be present+positive.
    assert all(
        r["images_per_sec_window"] is None or r["images_per_sec_window"] > 0
        for r in train_recs
    )
    assert all(
        r.get("images_per_sec_avg") is None
        or r.get("images_per_sec_avg", 1) > 0
        for r in train_recs
    )
    # Loss went down over the run.
    assert train_recs[-1]["loss"] < train_recs[0]["loss"]


def test_checkpoint_roundtrip_bitwise(fitted, smoke_cfg):
    workdir, _ = fitted
    model = models.build(smoke_cfg.model)
    state, _ = train_lib.create_state(smoke_cfg, model, jax.random.key(0))
    ckpt = ckpt_lib.Checkpointer(workdir)
    best = ckpt.restore(ckpt_lib.abstract_like(jax.device_get(state)))
    again = ckpt.restore(ckpt_lib.abstract_like(jax.device_get(state)))
    ckpt.close()
    for a, b in zip(jax.tree.leaves(best), jax.tree.leaves(again)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(best.step) == ckpt_lib_best_step(workdir)


def ckpt_lib_best_step(workdir):
    c = ckpt_lib.Checkpointer(workdir)
    try:
        return c.best_step
    finally:
        c.close()


def test_evaluate_checkpoints_report(fitted, smoke_cfg, data_dir):
    workdir, res = fitted
    report = trainer.evaluate_checkpoints(smoke_cfg, data_dir, [workdir])
    assert report["n_models"] == 1 and report["split"] == "test"
    assert 0.0 <= report["auc"] <= 1.0
    assert report["n_examples"] == 48
    ops = report["operating_points"]
    assert [o["target_specificity"] for o in ops] == [0.87, 0.98]
    for o in ops:
        assert o["specificity"] >= o["target_specificity"] - 1e-9


def test_evaluate_checkpoints_threshold_transfer_and_ci(fitted, smoke_cfg, data_dir):
    """The paper protocol end to end: thresholds tuned on val, applied
    to test, with bootstrap CIs (evaluate.py --threshold_split --bootstrap)."""
    workdir, _ = fitted
    report = trainer.evaluate_checkpoints(
        smoke_cfg, data_dir, [workdir],
        threshold_split="val", bootstrap=200,
    )
    assert report["threshold_split"] == "val"
    rows = report["operating_points_transferred"]
    assert [r["target_specificity"] for r in rows] == [0.87, 0.98]
    for r, chosen in zip(rows, report["operating_points"]):
        assert {"tp", "fp", "fn", "tn", "sensitivity", "specificity"} <= set(r)
        assert r["tp"] + r["fp"] + r["fn"] + r["tn"] == report["n_examples"]
        # transferred thresholds come from val, not from the test split
        # (they may coincide numerically only by accident; just check the
        # transferred rows carry a threshold and full confusion).
        assert 0.0 <= r["threshold"] <= 1.0 or np.isinf(r["threshold"])
        # the protocol's headline rows carry the uncertainty too
        assert r["sensitivity_ci95"][0] <= r["sensitivity"] <= r["sensitivity_ci95"][1]
        assert r["specificity_ci95"][0] <= r["specificity"] <= r["specificity_ci95"][1]
    lo, hi = report["auc_ci95"]
    assert lo <= report["auc"] <= hi


def test_evaluate_checkpoints_calibration(fitted, smoke_cfg, data_dir):
    """--calibrate: temperature fit on val, calibrated Brier/ECE on test;
    refuses to run without a tuning split."""
    workdir, _ = fitted
    report = trainer.evaluate_checkpoints(
        smoke_cfg, data_dir, [workdir],
        threshold_split="val", calibrate=True,
    )
    cal = report["calibration"]
    assert cal["temperature"] > 0
    assert 0.0 <= cal["ece"] <= 1.0 and 0.0 <= cal["brier"] <= 1.0
    with pytest.raises(ValueError, match="tuning split"):
        trainer.evaluate_checkpoints(
            smoke_cfg, data_dir, [workdir], calibrate=True
        )


def test_evaluate_checkpoints_cross_dataset_thresholds(
    fitted, smoke_cfg, data_dir, tmp_path
):
    """The actual JAMA protocol shape: tuning split in ANOTHER dataset
    dir (EyePACS val -> Messidor-2 test). Same split name on a different
    dir must pass the self-tuning guard."""
    other = str(tmp_path / "tune_ds")
    tfrecord.write_synthetic_split(other, "test", 32, 64, 2, seed=9)
    workdir, _ = fitted
    report = trainer.evaluate_checkpoints(
        smoke_cfg, data_dir, [workdir],
        threshold_split="test", threshold_data_dir=other,
    )
    assert report["threshold_data_dir"] == other
    assert len(report["operating_points_transferred"]) == 2
    with pytest.raises(ValueError, match="eval set itself"):
        trainer.evaluate_checkpoints(
            smoke_cfg, data_dir, [workdir], threshold_split="test"
        )


def test_resume_continues_from_checkpoint(smoke_cfg, data_dir, tmp_path):
    cfg = override(smoke_cfg, ["train.steps=20", "train.eval_every=10"])
    workdir = str(tmp_path / "resume_run")
    trainer.fit(cfg, data_dir, workdir, seed=0)
    cfg2 = override(cfg, ["train.steps=30", "train.resume=true"])
    trainer.fit(cfg2, data_dir, workdir, seed=0)
    log = read_jsonl(os.path.join(workdir, "metrics.jsonl"))
    resumes = [r for r in log if r["kind"] == "resume"]
    assert resumes and resumes[0]["step"] == 20
    # Resume reconstructs best tracking from the best manager's on-disk
    # metrics — the pre-interruption peak, not a -inf reset.
    pre_best = max(
        r["val_auc"] for r in log if r["kind"] == "eval" and r["step"] <= 20
    )
    assert resumes[0]["best_auc"] == pytest.approx(pre_best, abs=1e-5)
    evals = [r for r in log if r["kind"] == "eval"]
    assert evals[-1]["step"] == 30
    assert evals[-1]["best_auc"] >= pre_best - 1e-9


def test_ensemble_k2_beats_or_matches_members(smoke_cfg, data_dir, tmp_path):
    cfg = override(smoke_cfg, ["train.ensemble_size=2", "train.steps=40",
                               "train.eval_every=20"])
    workdir = str(tmp_path / "ens")
    results = trainer.fit_ensemble(cfg, data_dir, workdir)
    assert len(results) == 2
    assert results[0]["workdir"] != results[1]["workdir"]
    member_dirs = [r["workdir"] for r in results]
    ens_report = trainer.evaluate_checkpoints(cfg, data_dir, member_dirs)
    assert ens_report["n_models"] == 2
    # Ensemble-averaged probs produce a valid report; AUC sane.
    assert 0.3 <= ens_report["auc"] <= 1.0


def test_legacy_checkpoint_without_ema_field_restores(
    smoke_cfg, data_dir, tmp_path
):
    """Checkpoints written BEFORE TrainState grew ema_params (the round-2
    on-disk population) must keep restoring: Checkpointer.restore falls
    back to a four-field dict restore and rebuilds the state with
    ema_params=None when the saved tree has no ema key at all."""
    import orbax.checkpoint as ocp

    model = models.build(smoke_cfg.model)
    state, _ = train_lib.create_state(smoke_cfg, model, jax.random.key(0))
    state = jax.device_get(state)
    legacy = {f: getattr(state, f)
              for f in ("step", "params", "batch_stats", "opt_state")}
    legacy["step"] = np.asarray(7, np.int32)
    workdir = str(tmp_path / "legacy")
    mngr = ocp.CheckpointManager(
        os.path.join(workdir, "latest"),
        options=ocp.CheckpointManagerOptions(max_to_keep=1, create=True),
    )
    mngr.save(7, args=ocp.args.StandardSave(legacy))
    mngr.wait_until_finished()
    mngr.close()

    ckpt = ckpt_lib.Checkpointer(workdir)
    assert not ckpt.saved_with_ema()
    restored = ckpt.restore(ckpt_lib.abstract_like(state))
    ckpt.close()
    assert restored.ema_params is None
    assert int(restored.step) == 7
    for a, b in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_save_probs_csv_matches_report(fitted, smoke_cfg, data_dir, tmp_path):
    """--save_probs: one CSV row per eval example, names from the
    TFRecords, and recomputing AUC from the file reproduces the report."""
    import csv

    workdir, _ = fitted
    out = str(tmp_path / "probs.csv")
    report = trainer.evaluate_checkpoints(
        smoke_cfg, data_dir, [workdir], save_probs=out
    )
    assert report["probs_file"] == out
    with open(out) as f:
        rows = list(csv.DictReader(f))
    assert len(rows) == report["n_examples"] == 48
    assert len({r["name"] for r in rows}) == 48
    assert all(r["name"] for r in rows)
    # Synthetic fixtures predate quality scoring: the joined quality
    # column is present and -1 for every row (QUALITY.md step 4 join).
    assert all(float(r["quality"]) == -1.0 for r in rows)
    labels = np.array([int(r["grade"]) >= 2 for r in rows], np.float64)
    probs = np.array([float(r["prob_referable"]) for r in rows])
    auc = metrics.roc_auc(labels, probs)
    assert auc == pytest.approx(report["auc"], abs=2e-6)


def test_fit_with_ema_checkpoints_shadow_and_evaluates(
    smoke_cfg, data_dir, tmp_path
):
    """train.ema_decay end to end: the saved state carries the shadow,
    restore keeps it, evaluate scores with it, and fit_tf rejects it."""
    cfg = override(
        smoke_cfg,
        ["train.ema_decay=0.95", "train.steps=20", "train.eval_every=10"],
    )
    workdir = str(tmp_path / "ema_run")
    res = trainer.fit(cfg, data_dir, workdir, seed=0)
    assert res["best_auc"] is not None

    model = models.build(cfg.model)
    state, _ = train_lib.create_state(cfg, model, jax.random.key(0))
    ckpt = ckpt_lib.Checkpointer(workdir)
    restored = ckpt.restore(ckpt_lib.abstract_like(jax.device_get(state)))
    ckpt.close()
    assert restored.ema_params is not None
    # Shadow differs from raw params (training moved them apart) ...
    diffs = [
        float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
        for a, b in zip(
            jax.tree.leaves(restored.params),
            jax.tree.leaves(restored.ema_params),
        )
    ]
    assert max(diffs) > 0
    # ... and evaluation runs through the shadow without structure errors.
    report = trainer.evaluate_checkpoints(cfg, data_dir, [workdir])
    assert 0.0 <= report["auc"] <= 1.0

    with pytest.raises(ValueError, match="ema_decay"):
        trainer.fit_tf(cfg, data_dir, str(tmp_path / "ema_tf"), seed=0)

    # THE operational case: evaluating an EMA-trained checkpoint under a
    # preset that never mentions ema (restore adapts to the checkpoint's
    # saved structure, not the eval config).
    report_default_cfg = trainer.evaluate_checkpoints(
        smoke_cfg, data_dir, [workdir]
    )
    assert 0.0 <= report_default_cfg["auc"] <= 1.0
    # And resuming with a mismatched ema config fails loudly.
    with pytest.raises(ValueError, match="matching config"):
        trainer.fit(
            override(smoke_cfg, ["train.resume=true", "train.steps=25"]),
            data_dir, workdir, seed=0,
        )


def test_early_stopping_triggers(smoke_cfg, data_dir, tmp_path):
    cfg = override(
        smoke_cfg,
        ["train.steps=60", "train.eval_every=10",
         "train.early_stop_patience=1", "train.learning_rate=0.0",
         "train.min_delta=0.5"],
    )
    res = trainer.fit(cfg, data_dir, str(tmp_path / "es"), seed=0)
    assert res["stopped_early"]


def test_resume_reproduces_uninterrupted_run_exactly(smoke_cfg, data_dir, tmp_path):
    """VERDICT r1 #7 / SURVEY.md §5.4: a run interrupted at step k and
    resumed must produce the SAME loss sequence as one uninterrupted run
    — pins (a) bitwise checkpoint restore, (b) step-derived PRNG keys,
    (c) the pipeline's skip-to-position resume, with augmentation on."""
    # Constant LR: cosine's decay horizon depends on train.steps, and the
    # interrupted run is simulated by a shorter steps= — with a
    # steps-dependent schedule the two runs would (correctly) differ for
    # schedule reasons, masking what this test pins.
    cfg = override(
        smoke_cfg,
        ["train.steps=16", "train.eval_every=8", "train.log_every=1",
         "data.augment=true", "train.lr_schedule=constant"],
    )
    w_full = str(tmp_path / "full")
    trainer.fit(cfg, data_dir, w_full, seed=3)
    losses_full = {
        r["step"]: r["loss"]
        for r in read_jsonl(os.path.join(w_full, "metrics.jsonl"))
        if r["kind"] == "train"
    }

    w_part = str(tmp_path / "part")
    trainer.fit(override(cfg, ["train.steps=8"]), data_dir, w_part, seed=3)
    trainer.fit(
        override(cfg, ["train.resume=true"]), data_dir, w_part, seed=3
    )
    losses_part = {
        r["step"]: r["loss"]
        for r in read_jsonl(os.path.join(w_part, "metrics.jsonl"))
        if r["kind"] == "train"
    }
    assert set(losses_full) == set(losses_part) == set(range(1, 17))
    for s in range(1, 17):
        assert losses_full[s] == losses_part[s], (
            f"step {s}: uninterrupted {losses_full[s]} != resumed {losses_part[s]}"
        )


def test_run_meta_seed_wins_on_resume(smoke_cfg, data_dir, tmp_path):
    """The persisted run_meta seed overrides a different CLI seed on
    resume — stream continuity beats the (likely accidental) new seed."""
    cfg = override(smoke_cfg, ["train.steps=8", "train.eval_every=8"])
    w = str(tmp_path / "meta")
    trainer.fit(cfg, data_dir, w, seed=5)
    import json
    with open(os.path.join(w, "run_meta.json")) as f:
        assert json.load(f)["seed"] == 5
    res = trainer.fit(
        override(cfg, ["train.steps=12", "train.resume=true"]),
        data_dir, w, seed=99,
    )
    assert res["best_step"] >= 8
    with open(os.path.join(w, "run_meta.json")) as f:
        assert json.load(f)["seed"] == 5  # unchanged


def test_fit_save_every_evals_gates_checkpoints(smoke_cfg, data_dir, tmp_path):
    """train.save_every_evals on the single-model loop: evals run at
    every interval (the JSONL record is the early-stop/resume source),
    but checkpoints land only at the FIRST eval (so a crash early in
    the run never resumes from step 0 — ADVICE r4), every Nth eval, and
    the final step — each skipped save skips the full device->host
    state fetch."""
    cfg = override(smoke_cfg, [
        "train.steps=60", "train.eval_every=10", "train.save_every_evals=3",
    ])
    workdir = str(tmp_path / "sparse")
    trainer.fit(cfg, data_dir, workdir, seed=0)
    evals = [r["step"] for r in read_jsonl(os.path.join(workdir, "metrics.jsonl"))
             if r.get("kind") == "eval"]
    assert evals == [10, 20, 30, 40, 50, 60]
    ck = ckpt_lib.Checkpointer(workdir)
    # due: ordinal 1 -> 10; (step // 10) % 3 == 0 -> 30, 60 (final
    # always due anyway)
    assert ck.all_steps() == {10, 30, 60}
    ck.close()


def test_save_due_first_eval_flag():
    """train.save_first_eval: on (default) the first eval is always
    due (no crash window that resumes from step 0 — ADVICE r4); off,
    the pre-round-5 pure-ordinal cadence holds (scripts/time_to_auc.py
    opts out so the measured crossing never pays an early state
    fetch). Pure-function pin — the end-to-end default-on behavior is
    covered by test_fit_save_every_evals_gates_checkpoints."""
    from jama16_retina_tpu.configs import get_config, override

    base = override(get_config("smoke"), [
        "train.steps=60", "train.eval_every=10", "train.save_every_evals=3",
    ])
    due = [s for s in range(10, 61, 10) if trainer._save_due(base, s)]
    assert due == [10, 30, 60]
    off = override(base, ["train.save_first_eval=false"])
    due_off = [s for s in range(10, 61, 10) if trainer._save_due(off, s)]
    assert due_off == [30, 60]


def test_fit_stopping_eval_saves_even_when_not_due(smoke_cfg, data_dir, tmp_path):
    """An early-stopping eval must checkpoint even if its ordinal is not
    save-due — the run has to end durable (best + latest exist)."""
    cfg = override(smoke_cfg, [
        "train.steps=60", "train.eval_every=10", "train.save_every_evals=100",
        "train.early_stop_patience=1", "train.learning_rate=0.0",
        "train.min_delta=0.5",
    ])
    workdir = str(tmp_path / "stop")
    res = trainer.fit(cfg, data_dir, workdir, seed=0)
    assert res["stopped_early"]
    ck = ckpt_lib.Checkpointer(workdir)
    assert ck.all_steps()  # the stopping eval saved despite save_every_evals
    ck.close()
