"""Model-zoo tests (SURVEY.md §4.2 — model parity + shape census).

Heavy backbones are checked with ``jax.eval_shape`` (abstract init — no
XLA compile, critical on this 1-vCPU host); numeric forward/backward
behavior is exercised through ``tiny_cnn``, which shares the same ConvBN
cell and call contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jama16_retina_tpu import models
from jama16_retina_tpu.configs import ModelConfig


def abstract_variables(model, image_size, batch=2):
    x = jnp.zeros((batch, image_size, image_size, 3))
    return jax.eval_shape(
        lambda k, x: model.init({"params": k, "dropout": k}, x, train=False),
        jax.random.key(0),
        x,
    )


def n_leaves(tree):
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(tree))


# Golden trainable-parameter counts. inception_v3 is independently
# verified against tf.keras below; the others pin against regression.
EXPECTED_PARAMS = {
    "inception_v3": 24_327_970,  # binary head + slim aux head
    "resnet50": 23_510_081,  # == keras ResNet50 minus its 1000-class head
    "efficientnet_b4": 17_550_409,
    "tiny_cnn": 23_649,
}


@pytest.mark.parametrize("arch", sorted(EXPECTED_PARAMS))
def test_param_census(arch):
    cfg = ModelConfig(arch=arch, compute_dtype="float32")
    size = 64 if arch == "tiny_cnn" else 299
    variables = abstract_variables(models.build(cfg), size)
    assert n_leaves(variables["params"]) == EXPECTED_PARAMS[arch]
    assert n_leaves(variables["batch_stats"]) > 0


@pytest.mark.slow
def test_inception_param_parity_with_keras():
    """Weight-match check vs the locally available TF twin (SURVEY.md §4.2):
    same trainable-parameter count as tf.keras InceptionV3 when configured
    identically (1000 classes, no aux head)."""
    tf = pytest.importorskip("tensorflow")
    keras_model = tf.keras.applications.InceptionV3(
        weights=None, include_top=True, classes=1000
    )
    keras_trainable = sum(int(tf.size(w)) for w in keras_model.trainable_weights)

    from jama16_retina_tpu.models.inception_v3 import InceptionV3

    m = InceptionV3(num_classes=1000, aux_head=False, dtype=jnp.float32)
    variables = abstract_variables(m, 299, batch=1)
    assert n_leaves(variables["params"]) == keras_trainable == 23_817_352


@pytest.mark.parametrize(
    "arch,num_aux", [("inception_v3", 1), ("resnet50", 0), ("efficientnet_b4", 0)]
)
def test_output_shapes_binary_and_multi(arch, num_aux):
    for head, classes in [("binary", 1), ("multi", 5)]:
        cfg = ModelConfig(arch=arch, head=head, compute_dtype="bfloat16")
        m = models.build(cfg)
        out = jax.eval_shape(
            lambda k, x: m.apply(
                m.init({"params": k, "dropout": k}, x, train=False),
                x,
                train=False,
            ),
            jax.random.key(0),
            jnp.zeros((4, 299, 299, 3)),
        )
        logits, aux = out
        assert logits.shape == (4, classes)
        assert logits.dtype == jnp.float32  # head always f32
        if num_aux:
            assert aux.shape == (4, classes)
        else:
            assert aux is None


def test_tiny_cnn_trains_bn_and_dropout():
    """Numeric forward: BN stats mutate in train mode, dropout is rng-driven,
    logits differ between train and eval modes."""
    cfg = ModelConfig(arch="tiny_cnn", compute_dtype="float32", image_size=32)
    m = models.build(cfg)
    x = jax.random.normal(jax.random.key(1), (8, 32, 32, 3))
    variables = m.init({"params": jax.random.key(0), "dropout": jax.random.key(0)}, x, train=False)

    (logits, aux), mutated = m.apply(
        variables,
        x,
        train=True,
        mutable=["batch_stats"],
        rngs={"dropout": jax.random.key(2)},
    )
    assert aux is None and logits.shape == (8, 1)
    before = jax.tree.leaves(variables["batch_stats"])
    after = jax.tree.leaves(mutated["batch_stats"])
    assert any(not np.allclose(b, a) for b, a in zip(before, after))

    eval_logits, _ = m.apply(variables, x, train=False)
    assert not np.allclose(np.asarray(eval_logits), np.asarray(logits))


def test_stem_s2d_exact_same_params_and_close_logits():
    """ModelConfig.stem_s2d (VERDICT r3 #2 lever a): the space-to-depth
    stem is a REWRITE, not a new model — identical parameter tree (same
    checkpoints/transplant), and logits matching the baseline stem to
    bf16 reduction-order noise on f32 compute exactly."""
    # f32 compute: the weight-rearrangement equivalence is exact in f32
    # (the sums are the same terms), so the pin can be tight.
    kw = dict(arch="inception_v3", compute_dtype="float32", image_size=147)
    base = models.build(ModelConfig(**kw))
    s2d = models.build(ModelConfig(stem_s2d=True, **kw))
    x = jax.random.uniform(jax.random.key(1), (2, 147, 147, 3)) * 2 - 1

    v_base = base.init({"params": jax.random.key(0)}, x, train=False)
    assert jax.tree.structure(v_base) == jax.tree.structure(
        jax.eval_shape(
            lambda k: s2d.init({"params": k}, x, train=False),
            jax.random.key(0),
        )
    )
    lb, _ = base.apply(v_base, x, train=False)
    ls, _ = s2d.apply(v_base, x, train=False)  # SAME variables
    np.testing.assert_allclose(np.asarray(lb), np.asarray(ls),
                               rtol=1e-4, atol=1e-4)


def test_remat_stem_identical_logits():
    """ModelConfig.remat_stem (VERDICT r3 #2 lever b): rematerialization
    changes scheduling only — same params, bitwise-same forward."""
    kw = dict(arch="inception_v3", compute_dtype="float32", image_size=147)
    base = models.build(ModelConfig(**kw))
    remat = models.build(ModelConfig(remat_stem=True, **kw))
    x = jax.random.uniform(jax.random.key(1), (2, 147, 147, 3)) * 2 - 1
    v = base.init({"params": jax.random.key(0)}, x, train=False)
    lb, _ = base.apply(v, x, train=False)
    lr, _ = remat.apply(v, x, train=False)
    np.testing.assert_array_equal(np.asarray(lb), np.asarray(lr))
    # And the gradient path works (the point of remat is backward).
    def loss(params, m):
        out, _ = m.apply({**v, "params": params}, x, train=False)
        return jnp.sum(out ** 2)
    gb = jax.grad(loss)(v["params"], base)
    gr = jax.grad(loss)(v["params"], remat)
    for a, b in zip(jax.tree.leaves(gb), jax.tree.leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)


def test_bfloat16_policy_param_dtype():
    """Params stay float32 even when compute dtype is bfloat16."""
    cfg = ModelConfig(arch="tiny_cnn", compute_dtype="bfloat16", image_size=32)
    m = models.build(cfg)
    variables = abstract_variables(m, 32)
    for leaf in jax.tree.leaves(variables["params"]):
        assert leaf.dtype == jnp.float32


def test_build_rejects_unknown_arch():
    with pytest.raises(ValueError, match="unknown arch"):
        models.build(ModelConfig(arch="vgg19"))


@pytest.mark.slow
def test_inception_forward_parity_after_keras_transplant():
    """VERDICT r1 #5 / SURVEY.md §4.2: transplant RANDOM keras weights
    into the Flax tree and pin forward-output closeness — 'weight-matched'
    as a measured fact, not a docstring. f32, eval mode, no aux."""
    tf = pytest.importorskip("tensorflow")
    from jama16_retina_tpu.models import transplant
    from jama16_retina_tpu.models.inception_v3 import InceptionV3

    keras_model = tf.keras.applications.InceptionV3(
        weights=None, include_top=True, classes=1000
    )
    # Perturb BN stats/betas away from the (0, 1) init so the transplant
    # of moving statistics is actually load-bearing in the comparison.
    rng = np.random.default_rng(0)
    for layer in keras_model.layers:
        if isinstance(layer, tf.keras.layers.BatchNormalization):
            layer.beta.assign(rng.normal(0, 0.05, layer.beta.shape))
            layer.moving_mean.assign(rng.normal(0, 0.1, layer.moving_mean.shape))
            layer.moving_variance.assign(
                rng.uniform(0.5, 1.5, layer.moving_variance.shape)
            )

    m = InceptionV3(num_classes=1000, aux_head=False, dtype=jnp.float32)
    x = rng.uniform(-1, 1, (2, 299, 299, 3)).astype(np.float32)
    variables = m.init(
        {"params": jax.random.key(0), "dropout": jax.random.key(0)},
        jnp.asarray(x), train=False,
    )
    params, batch_stats = transplant.transplant_from_keras(
        keras_model, variables["params"], variables["batch_stats"]
    )
    # TPU f32 convs default to bf16 passes (~4e-5 drift over 94 layers vs
    # TF's CPU f32); pin highest precision for an apples-to-apples compare.
    with jax.default_matmul_precision("highest"):
        logits, aux = m.apply(
            {"params": params, "batch_stats": batch_stats},
            jnp.asarray(x), train=False,
        )
    assert aux is None
    flax_probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    keras_probs = keras_model(x, training=False).numpy()
    np.testing.assert_allclose(flax_probs, keras_probs, atol=1e-5)
    # And the raw pooled-logit scale agrees (softmax can mask offsets).
    np.testing.assert_allclose(
        np.asarray(logits).std(), np.log(keras_probs + 1e-30).std(), rtol=0.2
    )
