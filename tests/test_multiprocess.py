"""True multi-process distributed training test (SURVEY.md §3.5, N9).

The unit tests pin per-process input sharding in isolation; this test
runs the REAL thing: two OS processes, each with 2 fake CPU devices,
brought up via jax.distributed (Gloo collectives) through train.py's own
entry point — coordinator env trio, per-process record striding,
``make_array_from_process_local_data`` batch assembly, GSPMD gradient
mean across processes, orbax multi-host checkpointing, process-0-only
JSONL — and pins the result against a single-process 4-device run.

Numeric note: with P processes the global batch holds the SAME record
set as the 1-process stream (stride partition over the deterministic
interleave), permuted process-major. Loss/grads/BN are permutation-
invariant, so the runs must agree — but only with the per-POSITION
randomness off (augment, dropout), which the config here disables.
"""

import json
import os
import socket
import subprocess
import sys

import jax
import numpy as np
import pytest

from jama16_retina_tpu import models, train_lib
from jama16_retina_tpu.configs import get_config, override
from jama16_retina_tpu.data import tfrecord
from jama16_retina_tpu.utils import checkpoint as ckpt_lib
from jama16_retina_tpu.utils.logging import read_jsonl

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

COMMON_ARGS = [
    "--config=smoke", "--device=cpu",
    "--set", "train.steps=4", "--set", "train.eval_every=2",
    "--set", "train.log_every=1",
    "--set", "data.batch_size=16", "--set", "eval.batch_size=8",
    "--set", "data.augment=false", "--set", "model.dropout_rate=0.0",
    "--set", "data.shuffle_buffer=1", "--set", "train.lr_schedule=constant",
    # sgd, NOT adam: adam's first-step update is ~sign(grad), which
    # amplifies reduce-order fp noise (different device grouping of the
    # same rows) into +-2*lr param flips — sgd keeps the divergence
    # linear in the ~1e-7 grad noise, so allclose is a meaningful pin.
    "--set", "train.optimizer=sgdm",
]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _child_env(extra=None) -> dict:
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env.update(extra or {})
    return env


def _run_train(data_dir, workdir, fake_devices, log_path, env=None,
               extra_args=()):
    # Child output goes to a FILE: with pipes, a process blocked on a
    # full pipe buffer while its peer waits at the jax.distributed
    # shutdown barrier deadlocks the whole group.
    log = open(log_path, "w")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "train.py"),
         f"--data_dir={data_dir}", f"--workdir={workdir}",
         f"--fake_devices={fake_devices}", *COMMON_ARGS, *extra_args],
        env=_child_env(env), cwd=REPO,
        stdout=log, stderr=subprocess.STDOUT,
    )
    proc._log_path = log_path  # type: ignore[attr-defined]
    proc._log_file = log  # type: ignore[attr-defined]
    return proc


def _wait(proc) -> str:
    proc.wait(timeout=600)
    proc._log_file.close()
    with open(proc._log_path) as f:
        return f.read()


@pytest.mark.slow
def test_two_process_training_matches_single_process(tmp_path):
    data_dir = str(tmp_path / "data")
    # ONE train shard: with fewer files than processes the pipeline
    # stride-partitions the record stream, which is what gives the
    # same-set/permuted global-batch property the equality relies on.
    tfrecord.write_synthetic_split(data_dir, "train", 48, 64, 1, seed=1)
    tfrecord.write_synthetic_split(data_dir, "val", 24, 64, 1, seed=2)

    w1 = str(tmp_path / "one_proc")
    p = _run_train(data_dir, w1, 4, str(tmp_path / "one.log"))
    out = _wait(p)
    assert p.returncode == 0, f"single-process run failed:\n{out[-3000:]}"

    w2 = str(tmp_path / "two_proc")
    port = _free_port()
    procs = [
        _run_train(
            data_dir, w2, 2, str(tmp_path / f"p{i}.log"),
            env={
                "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                "JAX_NUM_PROCESSES": "2",
                "JAX_PROCESS_ID": str(i),
            },
        )
        for i in range(2)
    ]
    outs = [_wait(p) for p in procs]
    assert all(p.returncode == 0 for p in procs), (
        f"two-process run failed:\np0:\n{outs[0][-3000:]}\n"
        f"p1:\n{outs[1][-3000:]}"
    )

    # Both processes print the same final result JSON (same global eval).
    finals = [json.loads(o.strip().splitlines()[-1]) for o in outs]
    assert finals[0]["results"] == finals[1]["results"]

    # Process-0-only JSONL: records parse cleanly (no torn/duplicated
    # lines from concurrent appends) and cover the full run.
    log = read_jsonl(os.path.join(w2, "metrics.jsonl"))
    steps = [r["step"] for r in log if r["kind"] == "eval"]
    assert steps == sorted(set(steps)), f"duplicated eval records: {steps}"
    assert steps[-1] == 4
    # Process 1 mirrors its records to the per-process heartbeat file
    # (stall detection, SURVEY.md §5.3) instead of the system of record.
    hb = read_jsonl(os.path.join(w2, "metrics.p1.jsonl"))
    assert [r["step"] for r in hb if r["kind"] == "eval"] == steps

    # The distributed run must train the same model: restore both latest
    # checkpoints and compare (2-proc reduce order differs -> allclose).
    cfg = override(get_config("smoke"), [
        "train.steps=4", "data.augment=false", "model.dropout_rate=0.0",
        "train.optimizer=sgdm",  # must match COMMON_ARGS: opt_state tree
    ])
    model = models.build(cfg.model)
    states = []
    for w in (w1, w2):
        st, _ = train_lib.create_state(cfg, model, jax.random.key(0))
        ck = ckpt_lib.Checkpointer(w)
        states.append(ck.restore(
            ckpt_lib.abstract_like(jax.device_get(st)), ck.latest_step
        ))
        ck.close()
    # The tight pin is the FIRST step's loss (identical record set, one
    # reduce of noise ~1e-6); after that, BatchNorm's small-variance
    # divisions amplify reduce-order noise chaotically, so the final
    # params get only an envelope — a sharding/data-partition bug is
    # O(1) there, orders beyond it.
    first = {
        w: next(r["loss"] for r in read_jsonl(os.path.join(w, "metrics.jsonl"))
                if r["kind"] == "train" and r["step"] == 1)
        for w in (w1, w2)
    }
    assert abs(first[w1] - first[w2]) < 5e-5, first
    for a, b in zip(jax.tree.leaves(states[0]), jax.tree.leaves(states[1])):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-2, atol=1e-3
        )


@pytest.mark.slow
def test_two_process_hbm_loader_matches_single_process(tmp_path):
    """Multi-HOST HBM-resident loader (VERDICT r3 #3): each process
    decodes only its own devices' row shards and uploads them in place;
    the per-step gather is one global GSPMD program, so batch selection
    — a pure function of (seed, step) over the SAME global row order —
    must make the 2-process run match the single-process 4-device run
    to reduce-order noise."""
    data_dir = str(tmp_path / "data")
    tfrecord.write_synthetic_split(data_dir, "train", 48, 64, 1, seed=1)
    tfrecord.write_synthetic_split(data_dir, "val", 24, 64, 1, seed=2)
    hbm_args = ["--set", "data.loader=hbm"]

    w1 = str(tmp_path / "one_proc")
    p = _run_train(data_dir, w1, 4, str(tmp_path / "one.log"),
                   extra_args=hbm_args)
    out = _wait(p)
    assert p.returncode == 0, f"single-process hbm run failed:\n{out[-3000:]}"

    w2 = str(tmp_path / "two_proc")
    port = _free_port()
    procs = [
        _run_train(
            data_dir, w2, 2, str(tmp_path / f"hp{i}.log"),
            env={
                "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                "JAX_NUM_PROCESSES": "2",
                "JAX_PROCESS_ID": str(i),
            },
            extra_args=hbm_args,
        )
        for i in range(2)
    ]
    outs = [_wait(p) for p in procs]
    assert all(p.returncode == 0 for p in procs), (
        f"two-process hbm run failed:\np0:\n{outs[0][-3000:]}\n"
        f"p1:\n{outs[1][-3000:]}"
    )
    finals = [json.loads(o.strip().splitlines()[-1]) for o in outs]
    assert finals[0]["results"] == finals[1]["results"]

    # Each process decoded only its half of the rows (the 1/P-decode
    # property itself, from the loader's own log line).
    for i in range(2):
        with open(str(tmp_path / f"hp{i}.log")) as f:
            assert "decoded 24 of 48 rows" in f.read(), f"p{i} log"

    # Identical global batches (pure (seed, step) selection) -> the
    # first-step loss pin is as tight as the single-model stream test's.
    first = {
        w: next(r["loss"] for r in read_jsonl(os.path.join(w, "metrics.jsonl"))
                if r["kind"] == "train" and r["step"] == 1)
        for w in (w1, w2)
    }
    assert abs(first[w1] - first[w2]) < 5e-5, first

    cfg = override(get_config("smoke"), [
        "train.steps=4", "data.augment=false", "model.dropout_rate=0.0",
        "train.optimizer=sgdm",
    ])
    model = models.build(cfg.model)
    states = []
    for w in (w1, w2):
        st, _ = train_lib.create_state(cfg, model, jax.random.key(0))
        ck = ckpt_lib.Checkpointer(w)
        states.append(ck.restore(
            ckpt_lib.abstract_like(jax.device_get(st)), ck.latest_step
        ))
        ck.close()
    for a, b in zip(jax.tree.leaves(states[0]), jax.tree.leaves(states[1])):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-2, atol=1e-3
        )


ENSEMBLE_ARGS = [
    "--set", "train.ensemble_size=2",
    "--set", "train.ensemble_parallel=true",
]


def _first_member_losses(w):
    """Per-member losses of train step 1 from a workdir's JSONL — the
    tight cross-run pin (identical global batches, one reduce of
    noise)."""
    losses = next(
        r["loss_per_member"]
        for r in read_jsonl(os.path.join(w, "metrics.jsonl"))
        if r["kind"] == "train" and r["step"] == 1
    )
    assert len(losses) == 2
    return losses


def _compare_member_checkpoints(w1, w2, k=2):
    """Restore both runs' final per-member checkpoints and compare to
    the reduce-order envelope (a sharding/data-partition bug is O(1),
    orders beyond it). The restore cfg must mirror COMMON_ARGS'
    numeric fields (optimizer choice shapes the opt_state tree)."""
    cfg = override(get_config("smoke"), [
        "train.steps=4", "data.augment=false", "model.dropout_rate=0.0",
        "train.optimizer=sgdm",
    ])
    model = models.build(cfg.model)
    for m in range(k):
        states = []
        for w in (w1, w2):
            st, _ = train_lib.create_state(cfg, model, jax.random.key(0))
            ck = ckpt_lib.Checkpointer(ckpt_lib.member_dir(w, m))
            states.append(ck.restore(
                ckpt_lib.abstract_like(jax.device_get(st)), ck.latest_step
            ))
            ck.close()
        for a, b in zip(jax.tree.leaves(states[0]), jax.tree.leaves(states[1])):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-2, atol=1e-3
            )


@pytest.mark.slow
def test_two_process_member_parallel_hbm_loader_runs(tmp_path):
    """Member-parallel + hbm loader on multi-host: the hbm batch is born
    as a global array over the ('member','data') mesh, so
    device_prefetch's full_local path must pass it through untouched
    (the already-sharded check runs BEFORE the full_local host assembly
    — a code-review catch on the round-4 diff). Pins the 2-process run
    against the single-process stacked run."""
    data_dir = str(tmp_path / "data")
    tfrecord.write_synthetic_split(data_dir, "train", 48, 64, 1, seed=1)
    tfrecord.write_synthetic_split(data_dir, "val", 24, 64, 1, seed=2)
    args = ENSEMBLE_ARGS + ["--set", "data.loader=hbm"]

    w1 = str(tmp_path / "one_proc")
    p = _run_train(data_dir, w1, 4, str(tmp_path / "one.log"),
                   extra_args=args)
    out = _wait(p)
    assert p.returncode == 0, f"single-process run failed:\n{out[-3000:]}"

    w2 = str(tmp_path / "two_proc")
    port = _free_port()
    procs = [
        _run_train(
            data_dir, w2, 2, str(tmp_path / f"ehp{i}.log"),
            env={
                "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                "JAX_NUM_PROCESSES": "2",
                "JAX_PROCESS_ID": str(i),
            },
            extra_args=args,
        )
        for i in range(2)
    ]
    outs = [_wait(p) for p in procs]
    assert all(p.returncode == 0 for p in procs), (
        f"two-process run failed:\np0:\n{outs[0][-3000:]}\n"
        f"p1:\n{outs[1][-3000:]}"
    )
    finals = [json.loads(o.strip().splitlines()[-1]) for o in outs]
    assert finals[0]["results"] == finals[1]["results"]

    np.testing.assert_allclose(
        _first_member_losses(w1), _first_member_losses(w2), atol=5e-5
    )


@pytest.mark.slow
def test_two_process_manual_data_matches_single_process(tmp_path):
    """The fully-manual shard_map form (train.ensemble_manual_data,
    round 5) under REAL multi-process collectives: its explicit
    loss/BN pmeans ride Gloo across two OS processes over the
    ('member': 2, 'data': 2) mesh. Pinned against the single-process
    4-device manual run — a wrong-recipe gradient (the shard_map
    psum-self-transpose trap, MULTIHOST.md §Full-manual) or a
    mis-sharded batch would diverge at step 1."""
    data_dir = str(tmp_path / "data")
    tfrecord.write_synthetic_split(data_dir, "train", 48, 64, 1, seed=1)
    tfrecord.write_synthetic_split(data_dir, "val", 24, 64, 1, seed=2)
    args = ENSEMBLE_ARGS + ["--set", "train.ensemble_manual_data=true"]

    w1 = str(tmp_path / "one_proc")
    p = _run_train(data_dir, w1, 4, str(tmp_path / "one.log"),
                   extra_args=args)
    out = _wait(p)
    assert p.returncode == 0, f"single-process manual run failed:\n{out[-3000:]}"

    w2 = str(tmp_path / "two_proc")
    port = _free_port()
    procs = [
        _run_train(
            data_dir, w2, 2, str(tmp_path / f"mp{i}.log"),
            env={
                "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                "JAX_NUM_PROCESSES": "2",
                "JAX_PROCESS_ID": str(i),
            },
            extra_args=args,
        )
        for i in range(2)
    ]
    outs = [_wait(p) for p in procs]
    assert all(p.returncode == 0 for p in procs), (
        f"two-process manual run failed:\np0:\n{outs[0][-3000:]}\n"
        f"p1:\n{outs[1][-3000:]}"
    )
    finals = [json.loads(o.strip().splitlines()[-1]) for o in outs]
    assert finals[0]["results"] == finals[1]["results"]

    np.testing.assert_allclose(
        _first_member_losses(w1), _first_member_losses(w2), atol=5e-5
    )
    _compare_member_checkpoints(w1, w2)


@pytest.mark.slow
def test_two_process_member_parallel_matches_single_process(tmp_path):
    """Multi-HOST member-parallel ensembles (VERDICT r2 #3): a 2-process
    x 2-fake-device run over the ('member': 2, 'data': 2) mesh — each
    host reads the full batch stream, full-local assembly places the
    interleaved data columns, the member-sharded state gathers through
    the replicated reshard for checkpointing — pinned against the
    single-process 4-device stacked run."""
    data_dir = str(tmp_path / "data")
    tfrecord.write_synthetic_split(data_dir, "train", 48, 64, 1, seed=1)
    tfrecord.write_synthetic_split(data_dir, "val", 24, 64, 1, seed=2)

    w1 = str(tmp_path / "one_proc")
    p = _run_train(data_dir, w1, 4, str(tmp_path / "one.log"),
                   extra_args=ENSEMBLE_ARGS)
    out = _wait(p)
    assert p.returncode == 0, f"single-process ensemble failed:\n{out[-3000:]}"

    w2 = str(tmp_path / "two_proc")
    port = _free_port()
    procs = [
        _run_train(
            data_dir, w2, 2, str(tmp_path / f"ep{i}.log"),
            env={
                "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                "JAX_NUM_PROCESSES": "2",
                "JAX_PROCESS_ID": str(i),
            },
            extra_args=ENSEMBLE_ARGS,
        )
        for i in range(2)
    ]
    outs = [_wait(p) for p in procs]
    assert all(p.returncode == 0 for p in procs), (
        f"two-process ensemble failed:\np0:\n{outs[0][-3000:]}\n"
        f"p1:\n{outs[1][-3000:]}"
    )
    finals = [json.loads(o.strip().splitlines()[-1]) for o in outs]
    assert finals[0]["results"] == finals[1]["results"]

    # Same global batches (full stream on every host) -> per-member
    # first-step losses match the single-process stacked run tightly;
    # both members' final checkpoints agree across the two runs.
    np.testing.assert_allclose(
        _first_member_losses(w1), _first_member_losses(w2), atol=5e-5
    )
    _compare_member_checkpoints(w1, w2)
