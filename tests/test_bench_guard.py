"""bench.py trust machinery (VERDICT r2 #1), testable off-chip: the
physics guard refuses impossible rates, _publish omits refused keys, the
fence reduces the LARGEST leaf (a step counter must never serve as the
completion fence), and the peak table resolves this fleet's chips."""

import numpy as np
import pytest

import bench


def test_physics_guard_refuses_impossible_rates():
    peak = 197e12
    flops_per_image = 33.3e9
    ok = bench._physics_guard("x", 1400.0, flops_per_image, peak)
    assert ok == 1400.0
    # 41313 img/s at 33.3 GFLOP/img implies ~1.38 PFLOP/s — the actual
    # BENCH_r02 garbage row; must be refused.
    assert bench._physics_guard("x", 41313.97, flops_per_image, peak) is None
    # Unknown cost analysis: cannot judge, must not refuse.
    assert bench._physics_guard("x", 1e9, None, peak) == 1e9


def test_publish_stores_only_possible_rates():
    extras = {}
    out = bench._publish(extras, "good", 1000.0, 33.3e9, 197e12)
    assert out == 1000.0 and extras["good"] == 1000.0
    out = bench._publish(extras, "bad", 83121.54, 33.3e9, 197e12)
    assert out is None and "bad" not in extras


def test_fence_reduces_largest_leaf():
    import jax.numpy as jnp

    tree = {
        # Leaf order puts the counter first — the round-3 fix must pick
        # the LARGE leaf, whose producing computation is the real work.
        "a_step": jnp.asarray(7, jnp.int32),
        "params": jnp.full((64, 64), 2.0, jnp.float32),
    }
    assert bench._fence(tree) == pytest.approx(64 * 64 * 2.0)


def test_peak_flops_table():
    class FakeDev:
        def __init__(self, kind):
            self.device_kind = kind

    import jax

    real = jax.devices
    try:
        jax.devices = lambda: [FakeDev("TPU v5 lite")]
        assert bench._peak_flops() == pytest.approx(197e12)
        jax.devices = lambda: [FakeDev("TPU v4")]
        assert bench._peak_flops() == pytest.approx(275e12)
        jax.devices = lambda: [FakeDev("warp drive")]
        # Unknown hardware: deliberately generous, never over-refuses.
        assert bench._peak_flops() >= 1e15
    finally:
        jax.devices = real


def test_ensemble_speedup_gate_withholds_slowdowns():
    """A stacked-ensemble rate below the sequential member rate must
    never be published as ensemble4_parallel_speedup — it lands in the
    _gated key with a logged reason (ISSUE 1 satellite; BENCH_r05
    shipped 0.85 as a 'speedup')."""
    extras = {}
    bench._gate_ensemble_speedup(extras, rate=1182.4, device_only=1397.8)
    assert "ensemble4_parallel_speedup" not in extras
    assert extras["ensemble4_parallel_gated"] == 0.85
    extras = {}
    bench._gate_ensemble_speedup(extras, rate=1600.0, device_only=1397.8)
    assert extras["ensemble4_parallel_speedup"] == 1.14
    assert "ensemble4_parallel_gated" not in extras


def test_tiered_bench_plan_is_partial_residency():
    """The pipeline_fed_tiered section must measure a genuinely MIXED
    batch: its pinned budget yields a residency fraction strictly
    between 0 and 1 on the bench fixture, and the published rate rides
    the same physics guard as every other key."""
    frac = bench.tiered_residency_plan(bench.BENCH_N_IMAGES, 299)
    assert 0.0 < frac < 1.0
    # 7/8 nominal, rounded down by per-batch quota planning.
    assert 0.5 <= frac <= 0.875
    extras = {}
    out = bench._publish(
        extras, "pipeline_fed_tiered", 83121.54, 33.3e9, 197e12
    )
    assert out is None and "pipeline_fed_tiered" not in extras
    out = bench._publish(
        extras, "pipeline_fed_tiered", 1000.0, 33.3e9, 197e12
    )
    assert out == 1000.0 and extras["pipeline_fed_tiered"] == 1000.0


def test_serve_rates_ride_the_same_physics_guard():
    """Every serve_* img/s key publishes through the SAME guard as the
    training rates: an impossible rate (implied FLOP/s above chip peak)
    is refused and omitted, a physical one lands rounded."""
    flops_per_image = 4 * 33.3e9  # k=4 ensemble: every image pays 4 passes
    extras = {}
    for key in (
        "serve_images_per_sec",
        "serve_ensemble4_images_per_sec",
        "serve_offered_images_per_sec_c8",
    ):
        out = bench._publish(extras, key, 83121.54, flops_per_image, 197e12)
        assert out is None and key not in extras
        out = bench._publish(extras, key, 1000.0, flops_per_image, 197e12)
        assert out == 1000.0 and extras[key] == 1000.0


def test_latency_summary_p50_le_p99():
    """The offered-load latency summary's percentile pair comes from one
    sorted sample, so p50 <= p99 must hold on ANY input — including the
    degenerate single-sample window — and an empty window is refused
    rather than summarized."""
    rng = np.random.default_rng(0)
    s = bench._latency_summary(rng.gamma(2.0, 10.0, size=500))
    assert s["p50_ms"] <= s["p99_ms"]
    assert s["n"] == 500
    assert s["p50_ms"] <= s["mean_ms"] <= s["p99_ms"] * 2  # sane ballpark
    one = bench._latency_summary([5.0])
    assert one["p50_ms"] == one["p99_ms"] == one["mean_ms"] == 5.0
    # Unsorted input must not corrupt the percentiles (the summary
    # sorts internally).
    rev = bench._latency_summary([30.0, 1.0, 2.0, 3.0])
    assert rev["p50_ms"] <= rev["p99_ms"]
    with pytest.raises(ValueError, match="empty"):
        bench._latency_summary([])


def test_offered_load_closed_loop_counts_every_request():
    """The offered-load harness returns one latency per request across
    all submitters and a positive window (CPU-only: a resolved-future
    fake stands in for the batcher)."""
    from concurrent.futures import Future

    calls = []

    def submit(payload):
        calls.append(payload)
        f = Future()
        f.set_result(np.zeros(1))
        return f

    lats, window = bench._offered_load(
        submit, concurrency=4, requests_per_worker=5,
        payload=lambda w, i: (w, i),
    )
    assert len(lats) == 20 == len(calls)
    assert window > 0
    assert all(l >= 0 for l in lats)
    # Every (worker, request) pair was offered exactly once.
    assert sorted(calls) == [(w, i) for w in range(4) for i in range(5)]


def test_telemetry_overhead_guard_pins_two_percent():
    """The ISSUE 3 overhead pin: an instrumented rate more than 2%
    below the uninstrumented one flags telemetry_overhead_ok=false
    loudly; within 2% (or faster — tunnel noise) passes with the
    measured percentage published either way."""
    extras = {}
    assert bench._telemetry_overhead_guard(extras, 990.0, 1000.0)
    assert extras["telemetry_overhead_ok"] is True
    assert extras["telemetry_overhead_pct"] == pytest.approx(1.0)
    extras = {}
    assert not bench._telemetry_overhead_guard(extras, 950.0, 1000.0)
    assert extras["telemetry_overhead_ok"] is False
    assert extras["telemetry_overhead_pct"] == pytest.approx(5.0)
    extras = {}
    # Noise made the instrumented run FASTER: clamp to 0%, still ok.
    assert bench._telemetry_overhead_guard(extras, 1010.0, 1000.0)
    assert extras["telemetry_overhead_pct"] == 0.0


def test_quality_overhead_guard_pins_two_percent():
    """The ISSUE 5 pin, same math as the telemetry/tracing guards: the
    quality-monitor-instrumented device_only rate more than 2% below
    the uninstrumented headline flags quality_overhead_ok=false; within
    2% (or noise-faster, clamped to 0%) passes with the percentage
    published either way."""
    extras = {}
    assert bench._quality_overhead_guard(extras, 985.0, 1000.0)
    assert extras["quality_overhead_ok"] is True
    assert extras["quality_overhead_pct"] == pytest.approx(1.5)
    extras = {}
    assert not bench._quality_overhead_guard(extras, 960.0, 1000.0)
    assert extras["quality_overhead_ok"] is False
    assert extras["quality_overhead_pct"] == pytest.approx(4.0)
    extras = {}
    assert bench._quality_overhead_guard(extras, 1005.0, 1000.0)
    assert extras["quality_overhead_pct"] == 0.0


def test_quality_observe_is_hot_path_cheap():
    """Per-batch bound backing the bench pin off-chip: one observe()
    over a serving-sized batch (score binning + per-image input stats +
    amortized window publication) must stay far under a per-step
    budget, and the DISABLED monitor must be branch-cheap."""
    import dataclasses
    import time

    from jama16_retina_tpu.configs import QualityConfig
    from jama16_retina_tpu.obs import quality as quality_lib
    from jama16_retina_tpu.obs.registry import Registry

    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (32, 64, 64, 3), np.uint8)
    scores = rng.random(32)
    prof = quality_lib.build_profile(
        rng.random(4096),
        stat_values=quality_lib.input_stat_values(imgs),
        thresholds=[{"threshold": 0.5}],
    )
    mon = quality_lib.QualityMonitor(
        dataclasses.replace(QualityConfig(), enabled=True,
                            window_scores=128),
        registry=Registry(), profile=prof,
    )
    n = 200
    t0 = time.perf_counter()
    for _ in range(n):
        mon.observe(imgs, scores)
    per_batch = (time.perf_counter() - t0) / n
    # ~50x headroom over the measured cost on this host; a 32-row
    # observe above 10 ms/batch would blow the 2% bench budget anyway.
    assert per_batch < 10e-3, f"{per_batch * 1e3:.2f} ms per observe"
    off = quality_lib.QualityMonitor(
        dataclasses.replace(QualityConfig(), enabled=False),
        registry=Registry(),
    )
    t0 = time.perf_counter()
    for _ in range(5000):
        off.observe(imgs, scores)
    per_off = (time.perf_counter() - t0) / 5000
    assert per_off < 20e-6, f"{per_off * 1e6:.1f} us disabled observe"


def test_instrumented_step_preserves_results_and_counts():
    """_instrumented_step (the overhead bench's workload) must change
    NOTHING about the step's math — only record around it — and its
    registry must see every step and every batch fetch."""
    import jax
    import jax.numpy as jnp

    from jama16_retina_tpu.obs.registry import Registry

    @jax.jit
    def step(state, batch, key):
        return state + batch.sum(), {"loss": state}

    reg = Registry()
    wrapped, wrap_iter = bench._instrumented_step(step, reg)
    batch = jnp.ones((4,))
    it = wrap_iter(lambda i: batch)
    state = jnp.zeros(())
    for i in range(5):
        state, _ = wrapped(state, it(i), None)
    assert float(state) == pytest.approx(20.0)
    assert reg.counter("bench.steps").value == 5
    assert reg.histogram("trainer.dispatch_s").count == 5
    assert reg.histogram("trainer.input_s").count == 5


def test_telemetry_ops_are_hot_path_cheap():
    """Per-op bound backing the 2% pin off-chip: one counter inc plus
    one histogram observe — the trainer's per-step telemetry cost —
    must average far below the microseconds-per-step budget (bound is
    ~50x the measured cost, so CI scheduler noise cannot flake it)."""
    import time

    from jama16_retina_tpu.obs.registry import Registry
    from jama16_retina_tpu.obs.spans import StallClock

    reg = Registry()
    c = reg.counter("n")
    h = reg.histogram("h")
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        c.inc()
        h.observe(0.001)
    per_op = (time.perf_counter() - t0) / n
    assert per_op < 100e-6, f"{per_op * 1e6:.1f} us per inc+observe"
    # The StallClock segment (2 perf_counter calls + histogram feed).
    sc = StallClock(reg)
    t0 = time.perf_counter()
    for _ in range(n):
        with sc.measure("dispatch"):
            pass
    per_seg = (time.perf_counter() - t0) / n
    assert per_seg < 100e-6, f"{per_seg * 1e6:.1f} us per segment"


def test_robustness_overhead_guard_pins_two_percent():
    """The ISSUE 6 pin, same shared guard math: device_only with the
    reliability seams live-but-disabled (unarmed fault point +
    shedding-off admission branches) must stay within 2%."""
    extras = {}
    assert bench._robustness_overhead_guard(extras, 990.0, 1000.0)
    assert extras["robustness_overhead_ok"] is True
    assert extras["robustness_overhead_pct"] == pytest.approx(1.0)
    extras = {}
    assert not bench._robustness_overhead_guard(extras, 950.0, 1000.0)
    assert extras["robustness_overhead_ok"] is False
    assert extras["robustness_overhead_pct"] == pytest.approx(5.0)
    extras = {}
    assert bench._robustness_overhead_guard(extras, 1010.0, 1000.0)
    assert extras["robustness_overhead_pct"] == 0.0


def test_integrity_overhead_guard_pins_two_percent():
    """The ISSUE 13 pin, same shared guard math: device_only with the
    sealed-artifact layer's hot-path residue (unarmed integrity.write
    seam branch per step + a full sealed publish every 25 steps) must
    stay within 2% — checksum cost rides writes, never the hot loop."""
    extras = {}
    assert bench._integrity_overhead_guard(extras, 990.0, 1000.0)
    assert extras["integrity_overhead_ok"] is True
    assert extras["integrity_overhead_pct"] == pytest.approx(1.0)
    extras = {}
    assert not bench._integrity_overhead_guard(extras, 950.0, 1000.0)
    assert extras["integrity_overhead_ok"] is False
    assert extras["integrity_overhead_pct"] == pytest.approx(5.0)
    extras = {}
    assert bench._integrity_overhead_guard(extras, 1010.0, 1000.0)
    assert extras["integrity_overhead_pct"] == 0.0


def test_fleet_overhead_guard_pins_two_percent():
    """The ISSUE 15 pin, same shared guard math: device_only with the
    fleet plane's residue (one disabled-bus branch per flush check + a
    sealed segment publish every 25 steps) must stay within 2% — a
    process joining the fleet dir must not tax its own hot loop."""
    extras = {}
    assert bench._fleet_overhead_guard(extras, 990.0, 1000.0)
    assert extras["fleet_overhead_ok"] is True
    assert extras["fleet_overhead_pct"] == pytest.approx(1.0)
    extras = {}
    assert not bench._fleet_overhead_guard(extras, 950.0, 1000.0)
    assert extras["fleet_overhead_ok"] is False
    assert extras["fleet_overhead_pct"] == pytest.approx(5.0)
    extras = {}
    assert bench._fleet_overhead_guard(extras, 1010.0, 1000.0)
    assert extras["fleet_overhead_pct"] == 0.0


def test_diagnosis_overhead_guard_pins_two_percent():
    """The ISSUE 18 pin, same shared guard math: device_only with the
    causal-diagnosis plane's residue (per-step provenance stamp + the
    disabled-analyzer branch) must stay within 2% — the contract that
    lets ingest.provenance default on."""
    extras = {}
    assert bench._diagnosis_overhead_guard(extras, 990.0, 1000.0)
    assert extras["diagnosis_overhead_ok"] is True
    assert extras["diagnosis_overhead_pct"] == pytest.approx(1.0)
    extras = {}
    assert not bench._diagnosis_overhead_guard(extras, 950.0, 1000.0)
    assert extras["diagnosis_overhead_ok"] is False
    assert extras["diagnosis_overhead_pct"] == pytest.approx(5.0)
    extras = {}
    assert bench._diagnosis_overhead_guard(extras, 1010.0, 1000.0)
    assert extras["diagnosis_overhead_pct"] == 0.0


def test_router_overhead_guard_pins_two_percent():
    """The ISSUE 12 pin, same shared guard math: the workload routed
    through a 1-replica Router must stay within 2% of calling the
    replica directly."""
    extras = {}
    assert bench._router_overhead_guard(extras, 990.0, 1000.0)
    assert extras["router_overhead_ok"] is True
    assert extras["router_overhead_pct"] == pytest.approx(1.0)
    extras = {}
    assert not bench._router_overhead_guard(extras, 950.0, 1000.0)
    assert extras["router_overhead_ok"] is False
    extras = {}
    assert bench._router_overhead_guard(extras, 1010.0, 1000.0)
    assert extras["router_overhead_pct"] == 0.0


def test_unarmed_fault_site_costs_one_branch():
    """Per-op bound backing the robustness pin off-chip (ISSUE 6): an
    UNARMED faultinject.check — what every seam (tfrecord.read,
    host.decode, ckpt.restore, engine.dispatch, trainer.step) pays in
    production — must cost one global read + branch, bounded like the
    disabled tracer's record. An armed-but-other-site check stays cheap
    too (one dict probe), and the armed+firing path is correctness-land,
    not hot-path-land."""
    import time

    from jama16_retina_tpu.obs import faultinject

    faultinject.disarm()
    n = 50_000
    t0 = time.perf_counter()
    for _ in range(n):
        faultinject.check("tfrecord.read")
    per_unarmed = (time.perf_counter() - t0) / n
    assert per_unarmed < 20e-6, f"{per_unarmed * 1e6:.2f} us unarmed check"

    # A REAL declared site that is not the seam being measured: the
    # armed-but-elsewhere cost (arm validates against SITES now).
    faultinject.arm({"host.decode": {"kind": "error", "on_calls": [1]}})
    try:
        t0 = time.perf_counter()
        for _ in range(n):
            faultinject.check("tfrecord.read")
        per_other = (time.perf_counter() - t0) / n
    finally:
        faultinject.disarm()
    assert per_other < 20e-6, f"{per_other * 1e6:.2f} us armed-other check"


def test_chaos_smoke_recovers_every_path():
    """bench.py --chaos off-chip: the deterministic chaos drive must
    report ok with every site's injection delivered (the bench-level
    proof each recovery path actually ran) — including the three
    ISSUE 8 lifecycle sites (transient retrain, fail-closed gate that
    must end in ROLLBACK with the journal intact, transient swap)."""
    extras = {}
    bench._chaos_smoke(extras)
    assert extras["chaos_ok"] is True
    assert extras["chaos_injections"]["tfrecord.read"] == 1
    assert extras["chaos_injections"]["engine.dispatch"] == 1
    assert extras["chaos_injections"]["lifecycle.retrain"] == 1
    assert extras["chaos_injections"]["lifecycle.gate"] == 1
    assert extras["chaos_injections"]["lifecycle.swap"] == 1
    # ISSUE 12 + ISSUE 16: the replica-death drill AND the
    # mid-speculation replica-death drill each delivered one router
    # dispatch failure into the merged ledger, zero dropped requests
    # in both.
    assert extras["chaos_injections"]["serve.router.dispatch"] == 2
    assert extras["chaos_router_zero_drops"] is True
    assert extras["chaos_speculation_zero_drops"] is True


def test_lifecycle_overhead_guard_pins_two_percent():
    """The ISSUE 8 pin, same shared guard math: device_only with the
    self-healing layer attached but idle (unarmed lifecycle fault
    site + idle-shadow branch + on_fire-carrying alert evaluate at a
    10-step cadence) must stay within 2%."""
    extras = {}
    assert bench._lifecycle_overhead_guard(extras, 990.0, 1000.0)
    assert extras["lifecycle_overhead_ok"] is True
    assert extras["lifecycle_overhead_pct"] == pytest.approx(1.0)
    extras = {}
    assert not bench._lifecycle_overhead_guard(extras, 950.0, 1000.0)
    assert extras["lifecycle_overhead_ok"] is False
    assert extras["lifecycle_overhead_pct"] == pytest.approx(5.0)
    extras = {}
    assert bench._lifecycle_overhead_guard(extras, 1010.0, 1000.0)
    assert extras["lifecycle_overhead_pct"] == 0.0


def test_idle_alert_evaluate_with_on_fire_is_cheap():
    """Per-op bound backing the lifecycle pin off-chip: one
    AlertManager.evaluate over a small registry with an installed (but
    never firing) on_fire callback — the per-window cost the idle
    controller adds at flush cadence — stays well under a millisecond,
    and the callback is never invoked while quiet."""
    import time

    from jama16_retina_tpu.obs import alerts as obs_alerts
    from jama16_retina_tpu.obs.registry import Registry

    reg = Registry()
    reg.gauge("quality.canary_ok").set(1.0)
    fired = []
    mgr = obs_alerts.AlertManager(
        [obs_alerts.AlertRule("quality.canary_ok", "<", 1.0)],
        registry=reg, on_fire=fired.append,
    )
    n = 2_000
    t0 = time.perf_counter()
    for i in range(n):
        mgr.evaluate(now=float(i))
    per_eval = (time.perf_counter() - t0) / n
    assert not fired
    assert per_eval < 1e-3, f"{per_eval * 1e6:.1f} us per idle evaluate"


def test_tracing_overhead_guard_pins_two_percent():
    """The ISSUE 4 twin of the telemetry pin: device_only with the
    event tracer on must stay within 2% of the uninstrumented
    headline, published under tracing_overhead_pct/_ok."""
    extras = {}
    assert bench._tracing_overhead_guard(extras, 990.0, 1000.0)
    assert extras["tracing_overhead_ok"] is True
    assert extras["tracing_overhead_pct"] == pytest.approx(1.0)
    extras = {}
    assert not bench._tracing_overhead_guard(extras, 950.0, 1000.0)
    assert extras["tracing_overhead_ok"] is False
    assert extras["tracing_overhead_pct"] == pytest.approx(5.0)
    extras = {}
    assert bench._tracing_overhead_guard(extras, 1010.0, 1000.0)
    assert extras["tracing_overhead_pct"] == 0.0


def test_tracing_ops_are_hot_path_cheap():
    """Per-op bound backing the tracing pin off-chip (ISSUE 4): one
    ring-buffer event append — the cost span()/StallClock call sites
    add when tracing is on — must stay far under the per-step budget,
    and the disabled path must cost one branch (bounded well below the
    enabled path's own generous bound)."""
    import time

    from jama16_retina_tpu.obs.registry import Registry
    from jama16_retina_tpu.obs.spans import StallClock
    from jama16_retina_tpu.obs.trace import Tracer

    tr = Tracer(enabled=True, buffer_events=4096)
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        tr.complete("x", 0.0, 1.0)
        tr.instant("i")
    per_op = (time.perf_counter() - t0) / (2 * n)
    assert per_op < 100e-6, f"{per_op * 1e6:.1f} us per trace append"

    # A StallClock segment with BOTH sinks live (histogram + ring).
    reg = Registry()
    sc = StallClock(reg, tracer=tr)
    t0 = time.perf_counter()
    for _ in range(n):
        with sc.measure("dispatch"):
            pass
    per_seg = (time.perf_counter() - t0) / n
    assert per_seg < 100e-6, f"{per_seg * 1e6:.1f} us per traced segment"

    off = Tracer(enabled=False)
    t0 = time.perf_counter()
    for _ in range(n):
        off.complete("x", 0.0, 1.0)
        off.instant("i")
    per_off = (time.perf_counter() - t0) / (2 * n)
    assert per_off < 20e-6, f"{per_off * 1e6:.1f} us per disabled record"


def test_instrumented_step_with_tracer_preserves_results():
    """The tracing-overhead bench's workload (_instrumented_step with a
    tracer) must change NOTHING about the step's math — only add ring
    events alongside the registry observations."""
    import jax
    import jax.numpy as jnp

    from jama16_retina_tpu.obs.registry import Registry
    from jama16_retina_tpu.obs.trace import Tracer

    @jax.jit
    def step(state, batch, key):
        return state + batch.sum(), {"loss": state}

    reg = Registry()
    tr = Tracer(enabled=True, buffer_events=256)
    wrapped, wrap_iter = bench._instrumented_step(step, reg, tracer=tr)
    batch = jnp.ones((4,))
    it = wrap_iter(lambda i: batch)
    state = jnp.zeros(())
    for i in range(5):
        state, _ = wrapped(state, it(i), None)
    assert float(state) == pytest.approx(20.0)
    assert reg.counter("bench.steps").value == 5
    assert reg.histogram("trainer.dispatch_s").count == 5
    names = [e["name"] for e in tr.events()]
    assert names.count("trainer.dispatch") == 5
    assert names.count("trainer.input") == 5


def test_timed_steps_counts_all_steps():
    """_timed_steps' fence discipline on CPU: a step that chains state
    through iterations yields a sane rate and the final state reflects
    every step (no early window close)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(state, batch, key):
        return state + batch.sum(), {"loss": state}

    state = jnp.zeros(())
    batch = jnp.ones((4,))
    rate, final = bench._timed_steps(
        step, state, lambda i: batch, None, n_steps=10, batch_size=4,
        n_dev=1, warmup=2,
    )
    # warmup 2 + timed 10 = 12 accumulations of 4.
    assert float(final) == pytest.approx(48.0)
    assert rate > 0


def test_gated_ensemble_reason_lands_in_json():
    """ISSUE 7 satellite: a withheld ensemble4_parallel_speedup must
    carry its gating reason IN the bench JSON record, not only in a
    stderr log — and a published speedup must carry no reason key."""
    extras = {}
    bench._gate_ensemble_speedup(extras, rate=1182.4, device_only=1397.8,
                                 n_dev=1)
    assert extras["ensemble4_parallel_gated"] == 0.85
    reason = extras["ensemble4_parallel_gated_reason"]
    assert "1-device" in reason and "HBM" in reason
    assert "0.846" in reason  # the measured ratio, unrounded to 3 dp
    extras = {}
    bench._gate_ensemble_speedup(extras, rate=1600.0, device_only=1397.8,
                                 n_dev=1)
    assert "ensemble4_parallel_gated_reason" not in extras


def test_ensemble_speedup_ungated_on_wide_mesh():
    """ISSUE 14 satellite: on a >= 4-device mesh with a genuinely
    MEMBER-SHARDED step the REAL ratio publishes whatever it measures
    — member-sharded stacking is the production path there, so a <1.0
    value is a regression the trajectory must show, never a gated row
    — and the gated/reason keys never appear. 1-device behavior (the
    previous test) is pinned unchanged."""
    extras = {}
    bench._gate_ensemble_speedup(extras, rate=1182.4, device_only=1397.8,
                                 n_dev=4, member_sharded=True)
    assert extras["ensemble4_parallel_speedup"] == 0.85
    assert "ensemble4_parallel_gated" not in extras
    assert "ensemble4_parallel_gated_reason" not in extras
    extras = {}
    bench._gate_ensemble_speedup(extras, rate=4200.0, device_only=1397.8,
                                 n_dev=8, member_sharded=True)
    assert extras["ensemble4_parallel_speedup"] == 3.0
    assert "ensemble4_parallel_gated_reason" not in extras


def test_ensemble_speedup_gated_on_fake_wide_replicated_mesh():
    """ISSUE 17 satellite: device count alone must not un-gate. Bench's
    in-process ensemble step is replicated (mesh=None), and on a
    fake-device CPU host jax reports 8 'devices' — the old
    ``n_dev >= 4`` rule published a 0.85 slowdown ungated there. A
    sub-1.0 ratio from a NON-member-sharded step is withheld to the
    _gated key with its reason, at every width."""
    for n_dev in (1, 4, 8):
        extras = {}
        bench._gate_ensemble_speedup(extras, rate=1182.4,
                                     device_only=1397.8, n_dev=n_dev,
                                     member_sharded=False)
        assert "ensemble4_parallel_speedup" not in extras
        assert extras["ensemble4_parallel_gated"] == 0.85
        assert "0.846" in extras["ensemble4_parallel_gated_reason"]
    # A real >= 1.0 speedup still publishes even when replicated.
    extras = {}
    bench._gate_ensemble_speedup(extras, rate=1600.0, device_only=1397.8,
                                 n_dev=8, member_sharded=False)
    assert extras["ensemble4_parallel_speedup"] == 1.14
    assert "ensemble4_parallel_gated" not in extras


def test_disabled_tuner_is_one_branch():
    """ISSUE 7's overhead pin off-chip: with data.autotune off the
    loaders carry no tuner — their poll sites reduce to one
    ``knobs is not None`` branch per batch (tiered fill loop,
    device_prefetch queue). Bound that branch like the unarmed fault
    check; the enabled path's per-window decide() is O(1) math at log
    cadence, not per step, so the hot path never pays more."""
    import time as _time

    knobs = None
    depth_default = 2
    n = 50_000
    t0 = _time.perf_counter()
    acc = 0
    for _ in range(n):
        # The exact disabled-path shape of the loader poll sites.
        depth = depth_default if knobs is None else knobs.stage_depth
        acc += depth
    per_op = (_time.perf_counter() - t0) / n
    assert acc == n * depth_default
    assert per_op < 20e-6, f"{per_op * 1e6:.2f} us disabled knob poll"


def test_autotune_window_observe_is_cheap_and_deterministic():
    """The enabled tuner's per-WINDOW cost (counter reads + pure
    decide): bounded well under a log-window's budget, and the same
    stats produce the same decision — the bench's converged-knob
    record is reproducible."""
    import time as _time

    from jama16_retina_tpu.data import autotune
    from jama16_retina_tpu.obs.registry import Registry

    reg = Registry()
    knobs = autotune.Knobs(1, 1, 1)
    tuner = autotune.IngestAutotuner(
        knobs, autotune.Limits(hbm_headroom_bytes=10**9,
                               batch_bytes=10**6),
        registry=reg,
    )
    t0 = _time.perf_counter()
    n = 200
    for _ in range(n):
        tuner.observe(window_sec=1.0, input_wait_sec=0.0)
    per_window = (_time.perf_counter() - t0) / n
    assert per_window < 2e-3, f"{per_window * 1e3:.2f} ms per window"
    # Deterministic: two tuners fed the same stat sequence land on the
    # same knobs (the autotune_final_knobs key is a pure function of
    # the observed windows).
    def drive():
        r = Registry()
        k = autotune.Knobs(1, 1, 1)
        t = autotune.IngestAutotuner(
            k, autotune.Limits(hbm_headroom_bytes=10**9,
                               batch_bytes=10**6), registry=r,
        )
        waits = [0.5, 0.5, 0.4, 0.2, 0.0, 0.0, 0.0, 0.0, 0.0]
        for w in waits:
            r.counter("data.decode.busy_s").inc(0.9 if w > 0.1 else 0.05)
            t.observe(window_sec=1.0, input_wait_sec=w)
        return k.as_dict()

    assert drive() == drive()


def test_graftlint_full_repo_under_ten_seconds():
    """ISSUE 9 bench-guard satellite: the contract checker rides the
    tier-1 suite (test_lint_repo_clean), so a full-repo run must stay
    fast — one shared AST parse per file, no imports of the heavy
    stack. Pinned at < 10 s on this container (measured ~2 s); a rule
    that regresses this budget slows EVERY future PR's gate."""
    import os
    import time as _time

    from jama16_retina_tpu.analysis import Corpus, default_rules
    from jama16_retina_tpu.analysis import core as lint_core

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    t0 = _time.perf_counter()
    corpus = Corpus(root)
    findings = lint_core.run_rules(corpus, default_rules())
    elapsed = _time.perf_counter() - t0
    assert elapsed < 10.0, (
        f"graftlint full-repo run took {elapsed:.2f}s (budget 10s)"
    )
    # The runtime pin must measure a REAL run: the corpus saw the
    # package and the rules produced a (clean) verdict.
    assert len(corpus.py) > 40
    assert findings == []


def test_autotune_overhead_guard_pins_two_percent():
    """ISSUE 7's pin rides the shared guard math: the device_only
    window with the tuner's steady-state costs live must sit within 2%
    of uninstrumented, flagged loudly otherwise."""
    extras = {}
    assert bench._autotune_overhead_guard(extras, 990.0, 1000.0)
    assert extras["autotune_overhead_ok"] is True
    assert extras["autotune_overhead_pct"] == pytest.approx(1.0)
    extras = {}
    assert not bench._autotune_overhead_guard(extras, 950.0, 1000.0)
    assert extras["autotune_overhead_ok"] is False
