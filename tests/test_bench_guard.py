"""bench.py trust machinery (VERDICT r2 #1), testable off-chip: the
physics guard refuses impossible rates, _publish omits refused keys, the
fence reduces the LARGEST leaf (a step counter must never serve as the
completion fence), and the peak table resolves this fleet's chips."""

import numpy as np
import pytest

import bench


def test_physics_guard_refuses_impossible_rates():
    peak = 197e12
    flops_per_image = 33.3e9
    ok = bench._physics_guard("x", 1400.0, flops_per_image, peak)
    assert ok == 1400.0
    # 41313 img/s at 33.3 GFLOP/img implies ~1.38 PFLOP/s — the actual
    # BENCH_r02 garbage row; must be refused.
    assert bench._physics_guard("x", 41313.97, flops_per_image, peak) is None
    # Unknown cost analysis: cannot judge, must not refuse.
    assert bench._physics_guard("x", 1e9, None, peak) == 1e9


def test_publish_stores_only_possible_rates():
    extras = {}
    out = bench._publish(extras, "good", 1000.0, 33.3e9, 197e12)
    assert out == 1000.0 and extras["good"] == 1000.0
    out = bench._publish(extras, "bad", 83121.54, 33.3e9, 197e12)
    assert out is None and "bad" not in extras


def test_fence_reduces_largest_leaf():
    import jax.numpy as jnp

    tree = {
        # Leaf order puts the counter first — the round-3 fix must pick
        # the LARGE leaf, whose producing computation is the real work.
        "a_step": jnp.asarray(7, jnp.int32),
        "params": jnp.full((64, 64), 2.0, jnp.float32),
    }
    assert bench._fence(tree) == pytest.approx(64 * 64 * 2.0)


def test_peak_flops_table():
    class FakeDev:
        def __init__(self, kind):
            self.device_kind = kind

    import jax

    real = jax.devices
    try:
        jax.devices = lambda: [FakeDev("TPU v5 lite")]
        assert bench._peak_flops() == pytest.approx(197e12)
        jax.devices = lambda: [FakeDev("TPU v4")]
        assert bench._peak_flops() == pytest.approx(275e12)
        jax.devices = lambda: [FakeDev("warp drive")]
        # Unknown hardware: deliberately generous, never over-refuses.
        assert bench._peak_flops() >= 1e15
    finally:
        jax.devices = real


def test_timed_steps_counts_all_steps():
    """_timed_steps' fence discipline on CPU: a step that chains state
    through iterations yields a sane rate and the final state reflects
    every step (no early window close)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(state, batch, key):
        return state + batch.sum(), {"loss": state}

    state = jnp.zeros(())
    batch = jnp.ones((4,))
    rate, final = bench._timed_steps(
        step, state, lambda i: batch, None, n_steps=10, batch_size=4,
        n_dev=1, warmup=2,
    )
    # warmup 2 + timed 10 = 12 accumulations of 4.
    assert float(final) == pytest.approx(48.0)
    assert rate > 0
